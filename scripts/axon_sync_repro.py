"""Repro: ``jax.block_until_ready`` does not synchronize on the axon TPU
platform (VERDICT r2 / ADVICE r2) — the experiment behind bench.py's
host-fetch timing discipline.

Times a chain of 20 dependent 4096^3 bf16 matmuls two ways:

  1. ``block_until_ready`` only — on axon this returns while the remote
     execution is still in flight, so the "measured" TFLOP/s exceeds the
     chip's physical bf16 peak by orders of magnitude;
  2. the same chain followed by a host fetch of one element (which is
     data-dependent on the whole chain), giving a physically sane number.

Run on the TPU machine: ``python scripts/axon_sync_repro.py``. If (1) is
at or below peak, the platform bug is gone and bench.py's ``_fetch`` sync
could be relaxed back to ``block_until_ready``.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

N = 4096
CHAIN = 20
FLOPS = 2 * N**3 * CHAIN


def chain(x):
    for _ in range(CHAIN):
        x = x @ x
        x = x / jnp.sqrt(jnp.float32(N))  # keep values finite
    return x


def main():
    import json
    import sys
    as_json = "--json" in sys.argv
    if not as_json:
        print("backend:", jax.default_backend(), jax.devices())
    x = jax.random.normal(jax.random.PRNGKey(0), (N, N), jnp.bfloat16)
    f = jax.jit(chain)
    y = f(x)
    _ = float(np.asarray(y[0, 0]))            # compile + settle

    t0 = time.perf_counter()
    y = f(x)
    jax.block_until_ready(y)
    dt_block = time.perf_counter() - t0

    t0 = time.perf_counter()
    y = f(x)
    _ = float(np.asarray(y[0, 0]))
    dt_fetch = time.perf_counter() - t0

    peak = 197.0  # v5e bf16
    if as_json:
        # machine-readable line for scripts/tpu_smoke.sh
        print(json.dumps({
            "backend": jax.default_backend(),
            "block_ms": round(dt_block * 1e3, 1),
            "fetch_ms": round(dt_fetch * 1e3, 1),
            "block_tflops": round(FLOPS / dt_block / 1e12, 1),
            "fetch_tflops": round(FLOPS / dt_fetch / 1e12, 1),
            "peak_tflops": peak,
            "block_sync_broken": FLOPS / dt_block / 1e12 > peak * 1.5,
        }))
        return
    print(f"block_until_ready: {dt_block*1e3:8.1f} ms  "
          f"-> {FLOPS/dt_block/1e12:9.1f} TFLOP/s")
    print(f"host fetch:        {dt_fetch*1e3:8.1f} ms  "
          f"-> {FLOPS/dt_fetch/1e12:9.1f} TFLOP/s")
    if FLOPS / dt_block / 1e12 > peak * 1.5:
        print("CONFIRMED: block_until_ready returned before execution "
              "finished (apparent TFLOP/s above physical peak) — timed "
              "regions must end with a host fetch.")
    else:
        print("NOT reproduced: block_until_ready appears to synchronize "
              "on this platform/version.")


if __name__ == "__main__":
    main()
