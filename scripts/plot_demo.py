"""Render the demo's training loss curves (docs/demo/*.jsonl) to one PNG.

The JSONLs are appended across resumed tunnel windows with a
per-invocation step counter, so curves are aggregated per EPOCH, and
when an epoch appears in more than one invocation (a window died
mid-epoch and the resume retrained it) only the NEWEST invocation's
records count — stale partial-epoch records from the aborted attempt
are dropped. VAE and DALLE losses live on different scales, so they get
two panels (never a dual axis).

Run: python scripts/plot_demo.py [--dir docs/demo]
"""

import argparse
import json
import os


def epoch_series(path):
    """epoch -> mean loss over that epoch's records from the newest run.

    A run boundary is a step-counter reset (each invocation counts steps
    from 0, monotonically); per epoch, only records from the latest run
    that touched it are kept, so an aborted attempt's partial records
    don't blend into the retrained epoch's point."""
    if not os.path.exists(path):
        return [], []
    by_epoch = {}                          # epoch -> run -> [losses]
    run, prev_step = 0, None
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not ("loss" in rec and "epoch" in rec and "step" in rec):
                continue
            if prev_step is not None and rec["step"] <= prev_step:
                run += 1
            prev_step = rec["step"]
            by_epoch.setdefault(rec["epoch"], {}).setdefault(
                run, []).append(rec["loss"])
    epochs = sorted(by_epoch)
    means = []
    for e in epochs:
        losses = by_epoch[e][max(by_epoch[e])]
        means.append(sum(losses) / len(losses))
    return epochs, means


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default="docs/demo")
    ap.add_argument("--out", default=None,
                    help="default: <dir>/loss_curves.png")
    args = ap.parse_args()

    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        print("matplotlib not installed (it is not a package dependency); "
              "pip install matplotlib to render loss curves")
        return

    panels = []
    for fname, title in (("vae_loss.jsonl", "DiscreteVAE recon loss"),
                         ("dalle_loss.jsonl", "DALLE token CE loss")):
        ep, loss = epoch_series(os.path.join(args.dir, fname))
        if ep:
            panels.append((title, ep, loss))
    if not panels:
        print("no loss JSONLs found; nothing to plot")
        return

    ink, muted, series = "#0b0b0b", "#52514e", "#2a78d6"
    fig, axes = plt.subplots(1, len(panels), figsize=(5.2 * len(panels), 3.4),
                             facecolor="#fcfcfb")
    if len(panels) == 1:
        axes = [axes]
    for ax, (title, ep, loss) in zip(axes, panels):
        ax.set_facecolor("#fcfcfb")
        ax.plot(ep, loss, color=series, linewidth=2)
        ax.set_title(title, color=ink, fontsize=11, loc="left")
        ax.set_xlabel("epoch", color=muted, fontsize=9)
        ax.set_ylabel("loss", color=muted, fontsize=9)
        ax.tick_params(colors=muted, labelsize=8)
        ax.grid(True, color="#e8e7e2", linewidth=0.6)
        for side in ("top", "right"):
            ax.spines[side].set_visible(False)
        for side in ("left", "bottom"):
            ax.spines[side].set_color("#e8e7e2")
        # direct label on the final point (selective, not every point)
        ax.annotate(f"{loss[-1]:.3f}", (ep[-1], loss[-1]),
                    textcoords="offset points", xytext=(4, 4),
                    color=ink, fontsize=8)
    fig.tight_layout()
    out = args.out or os.path.join(args.dir, "loss_curves.png")
    fig.savefig(out, dpi=150)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
