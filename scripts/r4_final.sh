#!/usr/bin/env bash
# Round-4 closing agenda: the window-4 micro-sweep, then a full-bench
# re-record (the 04:19 mid-run wedge killed the last one after five
# configs had measured) plus a fresh kernel/sync smoke papertrail.
# Safe to launch any time:
#   nohup bash scripts/r4_final.sh > /tmp/r4_final.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
. scripts/window_lib.sh

# never compete with the window-2/3 chain for the chip or the single CPU
# core (the etiquette in .claude/skills/verify/SKILL.md) — wait it out
while pgrep -f 'r4_window[23]\.sh' > /dev/null; do
  echo "[$(stamp)] window-2/3 chain still running; waiting 120s"
  sleep 120
done

start_ts=$(date +%s)
bash scripts/r4_window4.sh

# window4's step 4 already re-records the bench when its sweep improved
# the tuned best; only run the closing bench if that didn't happen
# (healthy windows are 17-35 min — don't spend one on a duplicate pass)
newest=$(ls -t docs/BENCH_TPU_*.json 2>/dev/null | head -1)
if [ -n "$newest" ] && \
   [ "$(stat -c %Y "$newest")" -ge "$start_ts" ]; then
  echo "[$(stamp)] window-4 already recorded $newest; skipping the closing bench"
else
  wait_healthy_tunnel
  echo "[$(stamp)] == closing full bench =="
  run_full_bench final
fi

echo "[$(stamp)] == closing tpu_smoke =="
bash scripts/tpu_smoke.sh && echo "[$(stamp)] smoke OK" \
  || echo "[$(stamp)] smoke FAILED"
echo "[$(stamp)] round-4 closing agenda complete — inspect and commit"
