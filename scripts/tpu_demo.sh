#!/usr/bin/env bash
# End-to-end trained proof on the real chip (VERDICT r3 item 4) — this
# repo's answer to the reference's images/landscape.png moment (reference
# README.md:9-13: 6-layer DALLE on 2000 landscape images).
#
# One command, run from the repo root on the TPU machine when the tunnel
# is healthy (probe first: scripts/tpu_smoke.sh):
#
#   bash scripts/tpu_demo.sh
#
# Builds the download-free real-photo dataset (600 augmented 128px crops
# of three photographs, 12 captions), trains the VAE, trains a 6-layer
# DALLE on the VAE's codes, then generates samples for three held
# prompts. Artifacts land in docs/demo/: loss-curve JSONL for both
# trainings, per-epoch recon grids, generated sample grids.
set -euo pipefail
cd "$(dirname "$0")/.."
# OUT/DATA/MODELS overridable so a CPU rehearsal can run in a scratch dir
# without touching the committed docs/demo artifacts
OUT=${OUT:-docs/demo}
DATA=${DATA:-data/demo}
MODELS=${MODELS:-models}
mkdir -p "$OUT"

# Scale knobs (defaults = the real chip run; the CPU rehearsal in CI-ish
# form is IMG_N=48 IMG_SIZE=32 VAE_EPOCHS=1 DALLE_EPOCHS=1 DIM=32 DEPTH=2
# TOKENS=64 CDIM=32 HID=16 LAYERS=2)
VAE_EPOCHS=${VAE_EPOCHS:-16}
DALLE_EPOCHS=${DALLE_EPOCHS:-24}
IMG_N=${IMG_N:-600}
IMG_SIZE=${IMG_SIZE:-128}
DIM=${DIM:-256}
DEPTH=${DEPTH:-6}
TOKENS=${TOKENS:-1024}
CDIM=${CDIM:-256}
HID=${HID:-64}
LAYERS=${LAYERS:-3}
# Backend bring-up discipline for every training invocation: a wedged
# tunnel claim ends the attempt after this many seconds (with backoff+
# jitter retries inside the CLI) instead of pending away the healthy
# window — the r5 failure mode (docs/TPU_OUTAGE_2026-07-30.md, ROADMAP).
INIT_DEADLINE_S=${INIT_DEADLINE_S:-300}
INIT_FLAGS="--init_deadline_s $INIT_DEADLINE_S"

# rebuild the dataset whenever the size/count knobs differ from what the
# existing one was built with (a 32px rehearsal set must not feed a 128px
# training run)
stamp="$DATA/.stamp_${IMG_N}_${IMG_SIZE}"
if [ ! -f "$stamp" ]; then
  rm -rf "$DATA"
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python scripts/make_demo_dataset.py --out "$DATA" --n "$IMG_N" \
    --size "$IMG_SIZE"
  touch "$stamp"
fi

# Resume support: healthy tunnel windows have been ~16-20 min (2026-07-31)
# while the full demo needs longer, so each invocation continues from the
# newest per-epoch checkpoint instead of restarting — successive windows
# make incremental progress. Loss-curve JSONLs are APPENDED across
# invocations; records carry epoch + wall time, so plot loss vs epoch (or
# sort by time), not vs the per-invocation step counter.
#
# Same guard as the dataset stamp, for models/: resumed runs take their
# config from the checkpoint manifest, so a leftover rehearsal checkpoint
# (different arch knobs) must not hijack a real run via --loadVAE.
mstamp="$MODELS/.demo_stamp_${IMG_SIZE}_${DIM}_${DEPTH}_${TOKENS}_${CDIM}_${HID}_${LAYERS}"
mkdir -p "$MODELS"
if [ ! -f "$mstamp" ]; then
  rm -rf "$MODELS"/demovae-* "$MODELS"/demodalle_dalle-* \
         "$MODELS"/democfg_dalle-* "$MODELS"/democlip-* \
         "$MODELS"/.demo_stamp_*
  rm -f "$OUT/vae_loss.jsonl" "$OUT/dalle_loss.jsonl" \
        "$OUT/cfg_loss.jsonl" "$OUT/clip_loss.jsonl"   # curves restart too
  touch "$mstamp"
fi

# `latest_epoch NAME` prints the newest checkpoint's epoch for NAME under
# $MODELS/, or -1.
latest_epoch() {
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python - "$1" "$MODELS" <<'EOF'
import sys
from dalle_pytorch_tpu import checkpoint as ckpt
found = ckpt.latest(sys.argv[2], sys.argv[1])
print(-1 if found is None else found[1])
EOF
}

vae_done=$(latest_epoch demovae)
if [ "$vae_done" -ge "$((VAE_EPOCHS - 1))" ]; then
  echo "== train_vae: complete at epoch $vae_done, skipping =="
else
  resume_flags=""
  remaining="$VAE_EPOCHS"
  if [ "$vae_done" -ge 0 ]; then
    resume_flags="--loadVAE demovae"
    remaining="$((VAE_EPOCHS - vae_done - 1))"
  fi
  echo "== train_vae ($remaining of $VAE_EPOCHS epochs) =="
  python -m dalle_pytorch_tpu.cli.train_vae \
    --dataPath "$DATA/images" --imageSize "$IMG_SIZE" --batchSize 16 \
    --n_epochs "$remaining" --name demovae --num_tokens "$TOKENS" \
    --codebook_dim "$CDIM" --hidden_dim "$HID" --num_layers "$LAYERS" \
    --lr 3e-4 --tempsched --models_dir "$MODELS" --results_dir "$OUT" \
    --metrics "$OUT/vae_loss.jsonl" --log_interval 10 $INIT_FLAGS $resume_flags
fi

dalle_done=$(latest_epoch demodalle_dalle)
if [ "$dalle_done" -ge "$((DALLE_EPOCHS - 1))" ]; then
  echo "== train_dalle: complete at epoch $dalle_done, skipping =="
else
  resume_flags=""
  remaining="$DALLE_EPOCHS"
  if [ "$dalle_done" -ge 0 ]; then
    resume_flags="--load_dalle demodalle"
    remaining="$((DALLE_EPOCHS - dalle_done - 1))"
  fi
  echo "== train_dalle ($remaining of $DALLE_EPOCHS epochs) =="
  python -m dalle_pytorch_tpu.cli.train_dalle \
    --dataPath "$DATA/images" --imageSize "$IMG_SIZE" --batchSize 16 \
    --captions_only "$DATA/only.txt" --captions "$DATA/captions.txt" \
    --vaename demovae --vae_epoch "$((VAE_EPOCHS - 1))" --name demodalle \
    --n_epochs "$remaining" --dim "$DIM" --depth "$DEPTH" --heads 8 \
    --dim_head "$((DIM / 8))" --num_text_tokens 64 --text_seq_len 32 \
    --attn_dropout 0.1 --ff_dropout 0.1 --lr 3e-4 --models_dir "$MODELS" \
    --results_dir "$OUT" --metrics "$OUT/dalle_loss.jsonl" \
    --log_interval 10 --sample_every 8 $INIT_FLAGS $resume_flags
fi

echo "== gen_dalle =="
for prompt in "a photo of a purple flower" \
              "a photo of an ancient chinese temple" \
              "a portrait of a woman in uniform"; do
  python -m dalle_pytorch_tpu.cli.gen_dalle "$prompt" --name demodalle \
    --dalle_epoch "$((DALLE_EPOCHS - 1))" --num_images 8 \
    --models_dir "$MODELS" --results_dir "$OUT"
done

# -- classifier-free-guidance proof (VERDICT r4 item 6) ---------------------
# A second DALLE trained WITH caption dropout (the unconditional stream CFG
# needs), then the same prompt sampled at guidance 1/2/4 — the committed
# grids are the end-to-end evidence that guidance actually sharpens prompt
# adherence, not just that the math is parity-tested at s=1.
CFG_EPOCHS=${CFG_EPOCHS:-$DALLE_EPOCHS}
cfg_done=$(latest_epoch democfg_dalle)
if [ "$cfg_done" -ge "$((CFG_EPOCHS - 1))" ]; then
  echo "== train_dalle (cfg): complete at epoch $cfg_done, skipping =="
else
  resume_flags=""
  remaining="$CFG_EPOCHS"
  if [ "$cfg_done" -ge 0 ]; then
    resume_flags="--load_dalle democfg"
    remaining="$((CFG_EPOCHS - cfg_done - 1))"
  fi
  echo "== train_dalle with --caption_drop 0.1 ($remaining of $CFG_EPOCHS epochs) =="
  python -m dalle_pytorch_tpu.cli.train_dalle \
    --dataPath "$DATA/images" --imageSize "$IMG_SIZE" --batchSize 16 \
    --captions_only "$DATA/only.txt" --captions "$DATA/captions.txt" \
    --vaename demovae --vae_epoch "$((VAE_EPOCHS - 1))" --name democfg \
    --n_epochs "$remaining" --dim "$DIM" --depth "$DEPTH" --heads 8 \
    --dim_head "$((DIM / 8))" --num_text_tokens 64 --text_seq_len 32 \
    --attn_dropout 0.1 --ff_dropout 0.1 --caption_drop 0.1 --lr 3e-4 \
    --models_dir "$MODELS" --results_dir "$OUT" \
    --metrics "$OUT/cfg_loss.jsonl" --log_interval 10 $INIT_FLAGS $resume_flags
fi

# A small CLIP on the same captions scores the guidance sweep — mean
# CLIP score per scale is the QUANTITATIVE prompt-adherence evidence
# (VERDICT r4 item 6 asks CFG to demonstrably improve adherence).
CLIP_EPOCHS=${CLIP_EPOCHS:-8}
clip_done=$(latest_epoch democlip)
if [ "$clip_done" -ge "$((CLIP_EPOCHS - 1))" ]; then
  echo "== train_clip: complete at epoch $clip_done, skipping =="
else
  resume_flags=""
  remaining="$CLIP_EPOCHS"
  if [ "$clip_done" -ge 0 ]; then
    resume_flags="--load_clip democlip"
    remaining="$((CLIP_EPOCHS - clip_done - 1))"
  fi
  echo "== train_clip ($remaining of $CLIP_EPOCHS epochs) =="
  python -m dalle_pytorch_tpu.cli.train_clip \
    --dataPath "$DATA/images" --imageSize "$IMG_SIZE" --batchSize 16 \
    --captions_only "$DATA/only.txt" --captions "$DATA/captions.txt" \
    --name democlip --n_epochs "$remaining" \
    --dim_text "$DIM" --dim_image "$DIM" --dim_latent "$DIM" \
    --num_text_tokens 64 --text_seq_len 32 --lr 3e-4 \
    --models_dir "$MODELS" --results_dir "$OUT" \
    --metrics "$OUT/clip_loss.jsonl" --log_interval 10 $INIT_FLAGS $resume_flags
fi

echo "== gen_dalle guidance sweep (CLIP-scored) =="
rm -f "$OUT/guidance_scores.jsonl"
for g in 1 2 4; do
  for prompt in "a photo of a purple flower" \
                "a portrait of a woman in uniform"; do
    python -m dalle_pytorch_tpu.cli.gen_dalle "$prompt" --name democfg \
      --dalle_epoch "$((CFG_EPOCHS - 1))" --num_images 8 --guidance "$g" \
      --clip_name democlip --clip_epoch "$((CLIP_EPOCHS - 1))" \
      --scores_json "$OUT/guidance_scores.jsonl" \
      --models_dir "$MODELS" --results_dir "$OUT/guidance_$g"
  done
done
python - "$OUT/guidance_scores.jsonl" <<'EOF'
import json, sys
from collections import defaultdict
by_g = defaultdict(list)
for line in open(sys.argv[1]):
    r = json.loads(line)
    by_g[r["guidance"]].extend(r["scores"])
print("mean CLIP score by guidance scale:")
for g in sorted(by_g):
    s = by_g[g]
    print(f"  guidance {g}: {sum(s)/len(s):.4f}  (n={len(s)})")
EOF
PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
python scripts/plot_demo.py --dir "$OUT" || true
echo "demo artifacts in $OUT/"
