# Shared helpers for the healthy-window orchestrator scripts. Source from
# a script that already did `cd` to the repo root:
#   . "$(dirname "$0")/window_lib.sh"
# NEVER edit a script that is currently executing (bash reads scripts
# incrementally — rewriting one mid-run corrupts it); editing THIS file
# while sourcing scripts run is safe, since sourcing loads it whole.

stamp() { date -u +"%H:%M:%S"; }

# Block until a chip claim succeeds, probing with a deadline per try
# (default 600 s, override via BENCH_INIT_DEADLINE_S) and sleeping 120 s
# between failed probes. The 2026-07-30/31 outage pattern: the tunnel
# wedges for hours with claims blocking indefinitely, then recovers
# without notice.
wait_healthy_tunnel() {
  echo "[$(stamp)] waiting for a healthy tunnel (probe deadline/try: ${BENCH_INIT_DEADLINE_S:-600}s)"
  # `timeout` belt over the in-process deadline: when the relay is FULLY
  # wedged, python blocks at interpreter startup (sitecustomize claim)
  # before the deadline thread ever starts, and the probe would hang the
  # orchestrator forever.
  # BENCH_INIT_DEADLINE_S is a float elsewhere (bench.py float()s it;
  # tests export 0.01) — truncate before the integer shell arithmetic or
  # the probe command itself errors and the loop spins forever.
  local deadline_int
  deadline_int=$(printf '%.0f' "${BENCH_INIT_DEADLINE_S:-600}")
  until BENCH_INIT_DEADLINE_S=${BENCH_INIT_DEADLINE_S:-600} \
        timeout -k 30 $(( deadline_int + 60 )) \
        python - <<'EOF'
import os, sys, threading
# A claim alone is not health: the 2026-07-31 07:16 window claimed fine,
# then wedged on the first real dispatch. Prove EXECUTION: compile + run
# a small matmul and fetch the result, all under the same deadline.
ok = {}
def probe():
    try:
        import jax, jax.numpy as jnp
        x = jnp.ones((256, 256), jnp.bfloat16)
        y = jax.jit(lambda a: (a @ a).sum())(x)
        ok["v"] = float(y)
    except Exception:
        pass
t = threading.Thread(target=probe, daemon=True)
t.start()
t.join(float(os.environ.get("BENCH_INIT_DEADLINE_S", "600")))
sys.stdout.flush()
os._exit(0 if "v" in ok else 1)
EOF
  do
    echo "[$(stamp)] still wedged; sleeping 120s"
    sleep 120
  done
  echo "[$(stamp)] tunnel healthy"
}

# Print the committed tuned best (tokens/sec/chip), or 0 if none.
tuned_best() {
  python -c "
import json
try: print(json.load(open('docs/TUNE_NORTH.json'))['best']['tokens_sec_chip'])
except Exception: print(0)"
}

# run_full_bench SCRATCH_TAG — run the full bench and save its JSON to
# docs/BENCH_TPU_<utc date_time>.json (the committed-artifact convention).
run_full_bench() {
  local tag=${1:-window} out tmp
  out="docs/BENCH_TPU_$(date -u +%Y-%m-%d_%H%M).json"
  tmp="/tmp/bench_${tag}.json"
  if python bench.py > "$tmp" 2>"/tmp/bench_${tag}.err"; then
    python -c "
import json, sys
d = json.load(open('$tmp'))
json.dump(d, open('$out', 'w'), indent=2)
print('wrote $out')" && echo "[$(stamp)] bench OK"
  else
    echo "[$(stamp)] bench FAILED"; tail -3 "/tmp/bench_${tag}.err"
  fi
}

# rebench_if_improved BEST_BEFORE SCRATCH_TAG — re-record the full bench
# iff the committed tuned best now exceeds BEST_BEFORE.
rebench_if_improved() {
  local before=$1 tag=${2:-window} after
  after=$(tuned_best)
  if python -c "exit(0 if float('$after') > float('$before') else 1)"; then
    echo "[$(stamp)] tuned best improved: $before -> $after; re-recording bench"
    run_full_bench "$tag"
  else
    echo "[$(stamp)] tuned best unchanged ($after); skipping re-bench"
  fi
}
