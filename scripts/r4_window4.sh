#!/usr/bin/env bash
# Round-4 fourth-wave agenda: cheap micro-sweep around the measured
# optimum, informed by the 2026-07-31 03:44 window's answer that MFU
# FALLS with batch (115.0k@8 > 92.4k@16 > every 32/64 point):
#   1. probe BELOW batch 8 (4, 6) — the trend says smaller may win
#   2. the 4x128 head split at the batch-8 winner point (its window-1
#      leg ran only at batches 32/64 which OOM'd; never measured)
#   3. loss_chunk 128/512 around the winning 256
#   4. re-record the full bench iff the tuned best moved
# Usage (after r4_window2/r4_window3 finish, or standalone):
#   nohup bash scripts/r4_window4.sh > /tmp/r4_window4.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
. scripts/window_lib.sh

wait_healthy_tunnel
echo "[$(stamp)] running the window-4 agenda"
best_before=$(tuned_best)

echo "[$(stamp)] == 1/4 small-batch probe (best so far: $best_before) =="
python scripts/tune_north.py --attns flash --batches 4,6 \
  --loss_chunks 256 --claim_retries 2 \
  && echo "[$(stamp)] small-batch leg OK" \
  || echo "[$(stamp)] small-batch leg FAILED"

echo "[$(stamp)] == 2/4 4x128 head split at batch 8 =="
python scripts/tune_north.py --attns flash,xla --batches 8 \
  --loss_chunks 256 --head_cfgs 4x128 --claim_retries 2 \
  && echo "[$(stamp)] head-split leg OK" \
  || echo "[$(stamp)] head-split leg FAILED"

echo "[$(stamp)] == 3/4 loss_chunk 128/512 at batch 8 =="
python scripts/tune_north.py --attns flash --batches 8 \
  --loss_chunks 128,512 --claim_retries 2 \
  && echo "[$(stamp)] loss-chunk leg OK" \
  || echo "[$(stamp)] loss-chunk leg FAILED"

echo "[$(stamp)] == 4/4 conditional re-bench =="
rebench_if_improved "$best_before" w4
echo "[$(stamp)] window-4 agenda complete"
