#!/usr/bin/env bash
# Round-5 banking agenda, priority-ordered per VERDICT r4 "Next round":
#   1. full bench at tuned defaults  -> docs/BENCH_TPU_<ts>.json  (item 1:
#      the rc=0 artifact every perf claim should route through)
#   2. long-context probe            -> docs/LONGCTX.json         (item 4:
#      the flash kernel's memory-crossover existence proof)
#   3. int8 quantized generation     -> docs/QUANTGEN_TPU_*.json  (item 5)
#   4. MFU micro-sweeps (batch 4/6, heads 4x128, loss_chunk 128/512,
#      flash tiles)                  -> docs/TUNE_NORTH.json      (item 2)
#   5. conditional re-bench if the sweeps moved the tuned best
# Every leg is independent (|| continues); artifacts merge incrementally,
# so a window that closes mid-chain still banks whatever finished.
# Launch any time (waits for a healthy tunnel itself):
#   nohup bash scripts/r5_agenda.sh > /tmp/r5_agenda.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
. scripts/window_lib.sh

wait_healthy_tunnel
echo "[$(stamp)] == 1/5 full bench (tuned defaults) =="
run_full_bench r5

echo "[$(stamp)] == 2/5 long-context probe =="
python scripts/longctx_probe.py --seqs 2560,5120,10240 \
  && echo "[$(stamp)] longctx OK" || echo "[$(stamp)] longctx FAILED"

echo "[$(stamp)] == 3/5 quantized generation =="
out="docs/QUANTGEN_TPU_$(date -u +%Y-%m-%d_%H%M).json"
if python bench.py --config north --gen_quant --gen_batches 1,4 \
     > /tmp/r5_quantgen.json 2>/tmp/r5_quantgen.err; then
  python -c "
import json
d = json.load(open('/tmp/r5_quantgen.json'))
json.dump(d, open('$out', 'w'), indent=2)
print('wrote $out')" && echo "[$(stamp)] quantgen OK"
else
  echo "[$(stamp)] quantgen FAILED"; tail -3 /tmp/r5_quantgen.err
fi

best_before=$(tuned_best)
echo "[$(stamp)] == 4/5 micro-sweeps (best so far: $best_before) =="
python scripts/tune_north.py --attns flash --batches 4,6 \
  --loss_chunks 256 --claim_retries 3 \
  && echo "[$(stamp)] small-batch leg OK" \
  || echo "[$(stamp)] small-batch leg FAILED"
python scripts/tune_north.py --attns flash,xla --batches 8 \
  --loss_chunks 256 --head_cfgs 4x128 --claim_retries 3 \
  && echo "[$(stamp)] head-split leg OK" \
  || echo "[$(stamp)] head-split leg FAILED"
python scripts/tune_north.py --attns flash --batches 8 \
  --loss_chunks 128,512 --claim_retries 3 \
  && echo "[$(stamp)] loss-chunk leg OK" \
  || echo "[$(stamp)] loss-chunk leg FAILED"
python scripts/tune_north.py --attns flash --batches 8 \
  --loss_chunks 256 --flash_blocks 256x256,128x256,256x128,640x128 \
  --claim_retries 3 \
  && echo "[$(stamp)] tile sweep OK" || echo "[$(stamp)] tile sweep FAILED"
# the new surgical remat lever (drop ONLY the f32 layernorm saves):
# r4's sweep showed batch>=16 loses to 8 because of activation traffic —
# save_ln reclaims the dominant bytes at the cost of a layernorm
# recompute, so the 16/32 points get one more honest shot
python scripts/tune_north.py --attns flash --batches 16,32 \
  --loss_chunks 256 --remats save_ln --claim_retries 3 \
  && echo "[$(stamp)] save_ln leg OK" || echo "[$(stamp)] save_ln leg FAILED"

echo "[$(stamp)] == 5/5 conditional re-bench =="
rebench_if_improved "$best_before" r5b
echo "[$(stamp)] r5 banking agenda complete — inspect and commit"
