#!/usr/bin/env bash
# Bank the next healthy TPU-tunnel window (2026-07-30 outage pattern: the
# tunnel wedges for hours, then recovers without notice — r3 lost a whole
# round's hardware evidence to this; r4 runs this orchestrator detached).
#
#   nohup bash scripts/healthy_window.sh > /tmp/healthy_window.log 2>&1 &
#
# Probes the chip claim cheaply in a loop (bench's claim deadline applies
# inside each step anyway), then runs the round's hardware agenda in
# priority order, continuing past per-step failures:
#   1. scripts/tune_north.py  — sweep, writes docs/TUNE_NORTH.json
#   2. python bench.py        — full artifact with tuned defaults,
#                               saved to docs/BENCH_TPU_<date>.json
#   3. scripts/tpu_smoke.sh   — compiled-kernel + sync papertrail
#   4. scripts/profile_north.py — where the step time goes
#   5. scripts/tpu_demo.sh    — end-to-end trained proof
# Nothing is committed automatically — inspect and commit the artifacts.
set -u
cd "$(dirname "$0")/.."
. scripts/window_lib.sh

wait_healthy_tunnel
echo "[$(stamp)] running the agenda"

echo "[$(stamp)] == 1/5 tune_north =="
python scripts/tune_north.py --attns xla,flash,flash_pallas \
  --batches 16,32,64 --loss_chunks 0,256 --claim_retries 2 \
  && echo "[$(stamp)] tune OK" || echo "[$(stamp)] tune FAILED"
# follow-up: the 4x128 head split fills the MXU's 128-wide contraction in
# attention (same 512 inner dim / same FLOPs); TUNE_NORTH.json keeps
# whichever best wins across both sweeps
python scripts/tune_north.py --attns flash,xla --batches 32,64 \
  --loss_chunks 0 --head_cfgs 4x128 --claim_retries 2 \
  && echo "[$(stamp)] head-split tune OK" \
  || echo "[$(stamp)] head-split tune FAILED"

echo "[$(stamp)] == 2/5 full bench =="
run_full_bench window

echo "[$(stamp)] == 3/5 tpu_smoke =="
bash scripts/tpu_smoke.sh && echo "[$(stamp)] smoke OK" \
  || echo "[$(stamp)] smoke FAILED"

echo "[$(stamp)] == 4/5 profile_north =="
if python scripts/profile_north.py > /tmp/profile_north.json \
     2>/tmp/profile_north.err; then
  cp /tmp/profile_north.json docs/PROFILE_NORTH.json
  cat docs/PROFILE_NORTH.json; echo "[$(stamp)] profile OK"
else
  echo "[$(stamp)] profile FAILED"; tail -3 /tmp/profile_north.err
fi

echo "[$(stamp)] == 5/5 tpu_demo =="
bash scripts/tpu_demo.sh && echo "[$(stamp)] demo OK" \
  || echo "[$(stamp)] demo FAILED"
echo "[$(stamp)] agenda complete — inspect artifacts and commit"
