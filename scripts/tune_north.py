"""North-star tuning sweep (VERDICT r2 item 9): measure train
tokens/sec/chip and MFU for the depth-12 dim-512 DALLE across attention
impls and batch sizes on the real chip, host-synced timing. Prints one JSON
line per point plus a best-config summary; use it to pick bench defaults.

Run: python scripts/tune_north.py [--steps N]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def cfg_key(r):
    """Identity of a sweep point: the full tunable tuple. Records written
    before a dimension existed default to the value those runs actually
    used (e.g. pre-remat records ran remat='none')."""
    return (r.get("attn"), r.get("batch"), r.get("loss_chunk"),
            r.get("heads", 8), r.get("dim_head", 64),
            r.get("remat", "none"), r.get("reversible", False),
            r.get("flash_block_q", 128), r.get("flash_block_k", 128))


def merge_tune_payload(prev, results, backend="tpu"):
    """Fold this run's ``results`` into the previously committed payload
    (bench.merge_keyed_records: latest measurement wins per cfg_key,
    foreign-backend payloads discarded). ``best`` is recomputed over the
    MERGED set, so a prior winner survives until beaten — but a
    re-measurement of that same config replaces its number (a noisy best
    is correctable, never pinned forever)."""
    from bench import merge_keyed_records
    merged = merge_keyed_records(prev, results, cfg_key, backend)
    best = max(merged, key=lambda r: r["tokens_sec_chip"])
    return {"best": best, "results": merged, "backend": backend}


def _write_merged(results, out=None):
    """Merge ``results`` into docs/TUNE_NORTH.json (latest-wins per config,
    best recomputed over the merged set) and return the path. ``out``
    overrides the destination (tests)."""
    out = out or os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "TUNE_NORTH.json")
    from bench import atomic_write_json
    prev = None
    try:
        with open(out) as f:
            prev = json.load(f)
    except (OSError, ValueError):
        pass
    return atomic_write_json(out, merge_tune_payload(prev, results))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=15)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--attns", default="xla,flash")
    ap.add_argument("--batches", default="8,16,32")
    ap.add_argument("--loss_chunks", default="0",
                    help="comma list; 0 = dense CE head")
    ap.add_argument("--head_cfgs", default="8x64",
                    help="comma list of headsxdim_head splits of the 512 "
                         "inner dim (e.g. '8x64,4x128'; 4x128 fills the "
                         "MXU's 128-wide contraction)")
    ap.add_argument("--remats", default="none",
                    help="comma list of layer-body remat modes "
                         "('none,dots,full'); 'full' trades ~1/3 more "
                         "FLOPs for per-layer activation memory, 'dots' "
                         "recomputes only vector work (matmul outputs stay "
                         "saved, ~2/3 of activation bytes reclaimed at "
                         "near-zero FLOP cost) — both unlock batches that "
                         "OOM a 16G v5e chip un-rematerialized")
    ap.add_argument("--flash_blocks", default="128x128",
                    help="comma list of flash-kernel block_q x block_k tile "
                         "sizes (e.g. '128x128,256x256,128x256'); only "
                         "affects attn impls with a flash forward")
    ap.add_argument("--reversibles", default="0",
                    help="comma list of 0/1: run the reversible engine as a "
                         "sweep dimension (O(1) activation memory by "
                         "inversion instead of recompute-by-checkpoint; "
                         "measured FASTER than the sequential stack at "
                         "batch 8 on 2026-07-30: 110.2k vs 105.2k tok/s)")
    ap.add_argument("--claim_retries", type=int, default=20,
                    help="re-exec for a fresh chip claim this many times "
                         "when backend init stalls/errors (wedged-tunnel "
                         "resilience, same pattern as bench.py)")
    args = ap.parse_args()

    # Backend init via bench.py's shared deadline + re-exec helper; retry
    # timeouts too, with long backoff — the sweep is a background job that
    # should wait out a tunnel outage rather than give up.
    from bench import claim_backend
    claim = claim_backend(args.claim_retries, attempt_env="TUNE_ATTEMPT",
                          retry_on_timeout=True,
                          backoff=lambda a: min(60 * (a + 1), 300))
    if claim is not None:
        print(json.dumps({"error": claim[0], "claim_attempts": claim[1]}),
              flush=True)
        os._exit(1)

    import jax

    import bench
    from bench import (_bf16_peak, build_cfg, dalle_train_flops_per_token,
                       setup_train, time_steps)
    from dalle_pytorch_tpu.parallel import make_mesh

    # Mid-sweep stall protection (same wedge pattern bench guards against):
    # measured points are flushed to TUNE_NORTH.json as they land (below),
    # so on stall just report and exit — nothing is lost, and the detached
    # window orchestrator's next step isn't blocked forever.
    def _on_stall(failure):
        print(json.dumps({"sweep_stalled": True, **failure}), flush=True)
        os._exit(1)

    bench.start_stall_watchdog(on_stall=_on_stall)

    n_dev = len(jax.devices())
    mesh = make_mesh({"dp": n_dev})
    peak = _bf16_peak()
    results = []
    for hc in args.head_cfgs.split(","):
      heads, dim_head = (int(v) for v in hc.split("x"))
      for remat in args.remats.split(","):
       for rev in (bool(int(r)) for r in args.reversibles.split(",")):
        if rev and remat != "none":
            # the reversible engine's early-return branch never reaches the
            # remat logic (transformer.py): rev x remat=full would re-time
            # a byte-identical config under a false label
            continue
        for attn in args.attns.split(","):
         for i_fb, fb in enumerate(args.flash_blocks.split(",")):
          if not attn.startswith("flash") and i_fb > 0:
              continue                  # block sizes don't affect xla attn
          bq, bk = ((int(v) for v in fb.split("x"))
                    if attn.startswith("flash") else (128, 128))
          for chunk in (int(c) for c in args.loss_chunks.split(",")):
           for batch in (int(b) for b in args.batches.split(",")):
            cfg = build_cfg(False, depth=12, attn_impl=attn,
                            loss_chunk=chunk, heads=heads,
                            dim_head=dim_head, remat=remat,
                            reversible=rev, flash_block_q=bq,
                            flash_block_k=bk)
            bench.beat(f"point attn={attn} b={batch} chunk={chunk} "
                       f"remat={remat} rev={rev} {heads}x{dim_head} "
                       f"{bq}x{bk}")
            t0 = time.perf_counter()   # duration math — not wall-clock
            try:
                step, params, opt_state, data, key = setup_train(
                    cfg, batch, mesh)
                dt, loss, _ = time_steps(step, params, opt_state, data, key,
                                         args.warmup, args.steps)
            except Exception as e:
                msg = f"{type(e).__name__}: {e}"
                kind = bench.classify_error_kind(msg)
                print(json.dumps({"attn": attn, "batch": batch,
                                  "heads": heads, "dim_head": dim_head,
                                  "loss_chunk": chunk, "remat": remat,
                                  "reversible": rev,
                                  "flash_block_q": cfg.flash_block_q,
                                  "flash_block_k": cfg.flash_block_k,
                                  "kind": kind, "error": msg[:300]}),
                      flush=True)
                continue
            tps = args.steps * batch * cfg.seq_len / dt / n_dev
            mfu = tps * dalle_train_flops_per_token(cfg) / peak
            rec = {"attn": attn, "batch": batch,
                   "batch_per_chip": batch // n_dev, "loss_chunk": chunk,
                   "heads": heads, "dim_head": dim_head, "remat": remat,
                   "reversible": rev,
                   "flash_block_q": cfg.flash_block_q,
                   "flash_block_k": cfg.flash_block_k,
                   "tokens_sec_chip": round(tps, 1), "mfu": round(mfu, 4),
                   "loss": round(loss, 4),
                   "setup_s": round(time.perf_counter() - t0 - dt, 1)}
            results.append(rec)
            print(json.dumps(rec), flush=True)
            # flush the merged record NOW: a later stall/wedge (or a kill)
            # must not cost the points already measured. bench.py reads
            # this as its north-config defaults (bench_north); committing
            # it is how a sweep's winner becomes the recorded config.
            # Successive sweeps only ever IMPROVE the record: merge keeps
            # the existing best until beaten.
            if jax.default_backend() == "tpu":
                _write_merged(results)

    if results:
        best = max(results, key=lambda r: r["tokens_sec_chip"])
        print(json.dumps({"best": best}), flush=True)
        if jax.default_backend() == "tpu":
            print(json.dumps({"wrote": _write_merged(results)}), flush=True)


if __name__ == "__main__":
    main()
