"""Long-context attention probe: fwd+bwd throughput of the transformer
stack at sequence lengths past the flagship's 1280, xla vs flash.

The point (SURVEY §5.7 build note; VERDICT r3 calls long-context
first-class): the flash kernel's claim to exist is MEMORY — it never
materializes the (n, n) score matrix, so it keeps training at context
lengths where the xla path's quadratic buffers exhaust a 16G chip. This
probe measures both impls at growing seq lengths and records, for each
point, tokens/sec or the classified OOM — the committed evidence for
that crossover (docs/LONGCTX.json, merged incrementally like
TUNE_NORTH).

Run: python scripts/longctx_probe.py [--seqs 2560,5120,10240]
     [--impls xla,flash] [--depth 2] [--batch 1] [--steps 5]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def point_key(r):
    return (r.get("impl"), r.get("seq"), r.get("depth"), r.get("batch"))


def merge_longctx_payload(prev, results, backend="tpu"):
    """Latest-wins merge per (impl, seq, depth, batch) via
    bench.merge_keyed_records (same discipline as TUNE_NORTH), sorted for
    a stable committed diff."""
    from bench import merge_keyed_records
    merged = merge_keyed_records(prev, results, point_key, backend)
    return {"results": sorted(merged, key=lambda r: (r["impl"], r["seq"])),
            "backend": backend}


def _write_merged(results, out=None):
    from bench import atomic_write_json
    out = out or os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "LONGCTX.json")
    prev = None
    try:
        with open(out) as f:
            prev = json.load(f)
    except (OSError, ValueError):
        pass
    return atomic_write_json(out, merge_longctx_payload(prev, results))


def run_point(impl, seq, depth, batch, steps, warmup):
    """tokens/sec for fwd+bwd through a depth-layer stack at (batch, seq),
    or raises (caller classifies OOM vs error).

    ``impl`` 'xla'/'flash' compare the SAME dense attention (the memory
    crossover); 'sparse_windowed' runs the VariableSparsity stack via the
    windowed decomposition instead — a different (sparse) attention
    function, recorded as the long-context capability of the sparse
    training path, not as a dense-attention comparison point."""
    import jax
    import jax.numpy as jnp

    from dalle_pytorch_tpu.ops.transformer import (TransformerConfig,
                                                   transformer_apply,
                                                   transformer_init)
    if impl == "sparse_windowed":
        cfg = TransformerConfig(dim=512, depth=depth, seq_len=seq,
                                causal=True, sparse_attn=True,
                                sparse_impl="windowed")
    else:
        cfg = TransformerConfig(dim=512, depth=depth, seq_len=seq,
                                attn_impl=impl, causal=True)
    params = transformer_init(jax.random.PRNGKey(0), cfg, jnp.bfloat16)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, seq, 512),
                          jnp.bfloat16)

    def loss(p, x):
        return transformer_apply(p, x, cfg=cfg).astype(jnp.float32).mean()

    step = jax.jit(jax.grad(loss))
    from bench import _fetch
    g = None
    for _ in range(max(warmup, 1)):
        g = step(params, x)
    _fetch(jax.tree.leaves(g)[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        g = step(params, x)
    _fetch(jax.tree.leaves(g)[0])
    dt = time.perf_counter() - t0
    return steps * batch * seq / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="2560,5120,10240")
    ap.add_argument("--impls", default="xla,flash")
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--claim_retries", type=int, default=3)
    args = ap.parse_args()

    from bench import claim_backend
    claim = claim_backend(args.claim_retries, attempt_env="LONGCTX_ATTEMPT",
                          retry_on_timeout=True,
                          backoff=lambda a: min(60 * (a + 1), 300))
    if claim is not None:
        print(json.dumps({"error": claim[0], "claim_attempts": claim[1]}),
              flush=True)
        os._exit(1)

    import jax

    import bench

    def _on_stall(failure):
        print(json.dumps({"probe_stalled": True, **failure}), flush=True)
        os._exit(1)

    bench.start_stall_watchdog(on_stall=_on_stall)

    results = []
    # seq-major so each length yields its xla-vs-flash pair together — a
    # window that closes mid-run still leaves comparable points
    for seq in (int(s) for s in args.seqs.split(",")):
        for impl in args.impls.split(","):
            bench.beat(f"longctx {impl} seq={seq}")
            rec = {"impl": impl, "seq": seq, "depth": args.depth,
                   "batch": args.batch}
            try:
                tps = run_point(impl, seq, args.depth, args.batch,
                                args.steps, args.warmup)
                rec["tokens_sec"] = round(tps, 1)
            except Exception as e:
                msg = f"{type(e).__name__}: {e}"
                rec["kind"] = bench.classify_error_kind(msg)
                rec["error"] = msg[:300]
            results.append(rec)
            print(json.dumps(rec), flush=True)
            if jax.default_backend() == "tpu":
                _write_merged(results)

    if results and jax.default_backend() == "tpu":
        print(json.dumps({"wrote": _write_merged(results)}), flush=True)


if __name__ == "__main__":
    main()
