#!/usr/bin/env bash
# Run the long-context probe after the closing agenda finishes — the
# probe's committed artifact (docs/LONGCTX.json) is the xla-vs-flash
# crossover evidence at long sequence lengths. Safe to launch any time:
#   nohup bash scripts/r4_probe.sh > /tmp/r4_probe.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
. scripts/window_lib.sh

# serialize behind ANY chip-claiming work, not just the closing agenda —
# and re-check after the tunnel wait, since an agenda may have started
# while we were blocked in the probe (the residual race is the few
# seconds between the final check and our own claim)
chip_busy() {
  pgrep -f 'scripts/(r4_window[0-9]|r4_closing[0-9]*|r4_final|healthy_window)\.sh|scripts/(tune_north|profile_north)\.py|bench\.py' \
    > /dev/null
}
until ! chip_busy; do
  echo "[$(stamp)] chip-claiming work still running; waiting 120s"
  sleep 120
done

wait_healthy_tunnel
while chip_busy; do
  echo "[$(stamp)] an agenda claimed the chip during the wait; waiting 120s"
  sleep 120
done
echo "[$(stamp)] == long-context probe =="
python scripts/longctx_probe.py --claim_retries 10 \
  && echo "[$(stamp)] probe OK" || echo "[$(stamp)] probe FAILED"
echo "[$(stamp)] probe agenda complete — inspect and commit docs/LONGCTX.json"
