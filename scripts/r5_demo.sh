#!/usr/bin/env bash
# Round-5 CFG demo leg (VERDICT r4 item 6): waits for the banking agenda
# (scripts/r5_agenda.sh) to finish so the two never compete for the chip,
# then trains the caption-dropout DALLE and samples the guidance sweep
# via scripts/tpu_demo.sh (resume-aware: short windows make incremental
# progress).
#   nohup bash scripts/r5_demo.sh > /tmp/r5_demo.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
. scripts/window_lib.sh

while pgrep -f 'scripts/r5_agenda\.sh' > /dev/null; do
  echo "[$(stamp)] banking agenda still running; waiting 120s"
  sleep 120
done

wait_healthy_tunnel
echo "[$(stamp)] == CFG demo (tpu_demo.sh) =="
bash scripts/tpu_demo.sh && echo "[$(stamp)] demo OK" \
  || echo "[$(stamp)] demo FAILED"
echo "[$(stamp)] r5 demo leg complete — inspect docs/demo/guidance_*/"
