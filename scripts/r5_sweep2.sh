#!/usr/bin/env bash
# Round-5 second sweep wave: after the profile leg, measure the FUSED
# Pallas flash backward inside the full train step at the tuned batch
# points (the r4 sweep measured flash_pallas split-bwd only), then
# re-record the bench if anything moved the best.
#   nohup bash scripts/r5_sweep2.sh > /tmp/r5_sweep2.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
. scripts/window_lib.sh

while pgrep -f 'scripts/r5_(agenda|demo|profile)\.sh' > /dev/null; do
  echo "[$(stamp)] earlier r5 legs still running; waiting 120s"
  sleep 120
done

wait_healthy_tunnel
best_before=$(tuned_best)
echo "[$(stamp)] == fused-bwd sweep (best so far: $best_before) =="
python scripts/tune_north.py --attns flash_pallas_fused --batches 8,16 \
  --loss_chunks 256 --claim_retries 3 \
  && echo "[$(stamp)] fused leg OK" || echo "[$(stamp)] fused leg FAILED"
rebench_if_improved "$best_before" s2
echo "[$(stamp)] r5 sweep-2 leg complete"
