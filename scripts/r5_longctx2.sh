#!/usr/bin/env bash
# Round-5 final leg: extend docs/LONGCTX.json with the sparse-windowed
# stack at the same long sequence lengths — the committed record that
# the SPARSE training path (the depth-64 config's attention) also
# sustains long context where the dense xla path OOMs. Runs after every
# other r5 leg.
#   nohup bash scripts/r5_longctx2.sh > /tmp/r5_longctx2.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
. scripts/window_lib.sh

while pgrep -f 'scripts/r5_(agenda|demo|profile|sweep2)\.sh' > /dev/null; do
  echo "[$(stamp)] earlier r5 legs still running; waiting 120s"
  sleep 120
done

wait_healthy_tunnel
echo "[$(stamp)] == long-context probe: sparse_windowed =="
python scripts/longctx_probe.py --seqs 2560,5120,10240 \
  --impls sparse_windowed \
  && echo "[$(stamp)] sparse longctx OK" \
  || echo "[$(stamp)] sparse longctx FAILED"
echo "[$(stamp)] r5 longctx-2 leg complete"
