#!/usr/bin/env bash
# Round-4 closing agenda, reordered for short windows (07-31 07:16 showed
# a window can close within ~1 min of a successful claim): bank the most
# valuable artifact FIRST.
#   1. full bench at the tuned defaults -> docs/BENCH_TPU_<ts>.json
#      (the committed artifacts predate the 115.0k tuned best; the
#      closing re-record is unconditional)
#   2. kernel/sync smoke papertrail
#   3. window-4 micro-sweep (batches 4/6, 4x128@8, loss_chunk 128/512)
#   4. window-3 flash tile sweep (256x256, 128x256, 256x128, 640x128)
#   each sweep block ends with a conditional re-bench if it moved the best
# Safe to launch any time:
#   nohup bash scripts/r4_closing2.sh > /tmp/r4_closing2.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
. scripts/window_lib.sh

wait_healthy_tunnel
echo "[$(stamp)] == 1/4 closing full bench (tuned defaults) =="
run_full_bench closing2

echo "[$(stamp)] == 2/4 tpu_smoke =="
bash scripts/tpu_smoke.sh && echo "[$(stamp)] smoke OK" \
  || echo "[$(stamp)] smoke FAILED"

best_before=$(tuned_best)
echo "[$(stamp)] == 3/4 micro-sweep around batch-8 best ($best_before) =="
python scripts/tune_north.py --attns flash --batches 4,6 \
  --loss_chunks 256 --claim_retries 3 \
  && echo "[$(stamp)] small-batch leg OK" \
  || echo "[$(stamp)] small-batch leg FAILED"
python scripts/tune_north.py --attns flash,xla --batches 8 \
  --loss_chunks 256 --head_cfgs 4x128 --claim_retries 3 \
  && echo "[$(stamp)] head-split leg OK" \
  || echo "[$(stamp)] head-split leg FAILED"
python scripts/tune_north.py --attns flash --batches 8 \
  --loss_chunks 128,512 --claim_retries 3 \
  && echo "[$(stamp)] loss-chunk leg OK" \
  || echo "[$(stamp)] loss-chunk leg FAILED"
rebench_if_improved "$best_before" c2a

best_before=$(tuned_best)
echo "[$(stamp)] == 4/4 flash tile sweep ($best_before) =="
python scripts/tune_north.py --attns flash --batches 8 \
  --loss_chunks 256 --flash_blocks 256x256,128x256,256x128,640x128 \
  --claim_retries 3 \
  && echo "[$(stamp)] tile sweep OK" || echo "[$(stamp)] tile sweep FAILED"
rebench_if_improved "$best_before" c2b

echo "[$(stamp)] round-4 closing agenda (v2) complete — inspect and commit"
