#!/usr/bin/env bash
# TPU hardware smoke (VERDICT r3 item 8): re-validate the compiled Pallas
# kernels and the host-sync timing discipline on a healthy chip in <2 min,
# leaving a committed papertrail.
#
# Run on the TPU machine from the repo root:
#   bash scripts/tpu_smoke.sh
#
# Writes docs/TPU_SMOKE_<date>.json with:
#   * bench.py --config kernels   — flash + block-sparse fwd/bwd rel-diffs,
#     compiled on-chip (interpreted must be false, parity_ok true)
#   * axon_sync_repro.py          — block_until_ready vs host-fetch TFLOP/s
#     (fetch-synced number must be <= the chip's bf16 peak)
# Exit 0 only when both checks hold. Commit the JSON.
set -u
cd "$(dirname "$0")/.."
out="docs/TPU_SMOKE_$(date -u +%Y-%m-%d).json"

kernels=$(python bench.py --config kernels 2>/dev/null | tail -1)
sync=$(python scripts/axon_sync_repro.py --json 2>/dev/null | tail -1)

python - "$out" "$kernels" "$sync" <<'EOF'
import json, sys
out, kernels_raw, sync_raw = sys.argv[1], sys.argv[2], sys.argv[3]
rec = {"kernels": None, "sync": None, "ok": False}
problems = []
try:
    k = json.loads(kernels_raw)
    rec["kernels"] = k
    if k.get("interpreted") is not False:
        problems.append("kernels ran interpreted (not compiled on-chip)")
    if k.get("parity_ok") is not True:
        problems.append("kernel parity failed")
except Exception as e:
    problems.append(f"kernels config unparseable: {e}: {kernels_raw[:200]}")
try:
    s = json.loads(sync_raw)
    rec["sync"] = s
    if s.get("fetch_tflops", 1e9) > s.get("peak_tflops", 0):
        problems.append("fetch-synced TFLOP/s above physical peak")
except Exception as e:
    problems.append(f"sync repro unparseable: {e}: {sync_raw[:200]}")
rec["ok"] = not problems
rec["problems"] = problems
with open(out, "w") as f:
    json.dump(rec, f, indent=2)
print(json.dumps({"ok": rec["ok"], "problems": problems, "wrote": out}))
sys.exit(0 if rec["ok"] else 1)
EOF
