#!/usr/bin/env bash
# TPU hardware smoke (VERDICT r3 item 8): re-validate the compiled Pallas
# kernels and the host-sync timing discipline on a healthy chip in <2 min,
# leaving a committed papertrail.
#
# Run on the TPU machine from the repo root:
#   bash scripts/tpu_smoke.sh
#
# Writes docs/TPU_SMOKE_<date>.json with:
#   * bench.py --config kernels   — flash + block-sparse fwd/bwd rel-diffs,
#     compiled on-chip (interpreted must be false, parity_ok true)
#   * axon_sync_repro.py          — block_until_ready vs host-fetch TFLOP/s
#     (fetch-synced number must be <= the chip's bf16 peak)
# Exit 0 only when both checks hold. Commit the JSON.
#
# Wedged-tunnel behavior: bench fails fast via its own claim deadline (its
# stale-artifact fallback is REJECTED here — a smoke must measure, not
# recall), and the sync repro runs under timeout(1) so a pending claim
# cannot hang the probe for the tunnel's ~25-min pend.
set -u
cd "$(dirname "$0")/.."
out="docs/TPU_SMOKE_$(date -u +%Y-%m-%d).json"
# one bring-up deadline for the whole probe: bench.py's backend claim
# reads this env (resilience.retry discipline, ROADMAP launcher-wiring
# item) and the sync repro's timeout below derives from the same value
deadline=${BENCH_INIT_DEADLINE_S:-600}
export BENCH_INIT_DEADLINE_S="$deadline"

# no pipes here: $? must be the python/timeout status, not tail's
kernels=$(python bench.py --config kernels 2>/dev/null)
kernels_rc=$?
kernels=$(printf '%s\n' "$kernels" | tail -1)
sync=$(timeout "$((deadline + 120))" python scripts/axon_sync_repro.py \
       --json 2>/dev/null)
sync_rc=$?
sync=$(printf '%s\n' "$sync" | tail -1)

python - "$out" "$kernels" "$kernels_rc" "$sync" "$sync_rc" <<'EOF'
import json, sys
out, kernels_raw, kernels_rc, sync_raw, sync_rc = sys.argv[1:6]
rec = {"kernels": None, "sync": None, "ok": False}
problems = []
try:
    k = json.loads(kernels_raw)
    rec["kernels"] = k
    if k.get("stale"):
        problems.append("bench returned its stale fallback artifact "
                        "(tunnel wedged) — not a fresh kernels run")
    elif int(kernels_rc) != 0:
        problems.append(f"bench --config kernels exited {kernels_rc}")
    else:
        if k.get("interpreted") is not False:
            problems.append("kernels ran interpreted (not compiled on-chip)")
        if k.get("parity_ok") is not True:
            problems.append("kernel parity failed")
except Exception as e:
    problems.append(f"kernels config unparseable: {e}: {kernels_raw[:200]}")
try:
    if int(sync_rc) != 0:
        problems.append(f"sync repro exited {sync_rc} "
                        "(124 = timeout: tunnel claim pending?)")
    else:
        s = json.loads(sync_raw)
        rec["sync"] = s
        if s.get("fetch_tflops", 1e9) > s.get("peak_tflops", 0):
            problems.append("fetch-synced TFLOP/s above physical peak")
except Exception as e:
    problems.append(f"sync repro unparseable: {e}: {sync_raw[:200]}")
rec["ok"] = not problems
rec["problems"] = problems
with open(out, "w") as f:
    json.dump(rec, f, indent=2)
print(json.dumps({"ok": rec["ok"], "problems": problems, "wrote": out}))
sys.exit(0 if rec["ok"] else 1)
EOF
