#!/usr/bin/env bash
# Round-4 third-wave agenda: refinements on top of whatever window 2
# banked. Run after scripts/r4_window2.sh completes (or standalone in any
# healthy window):
#   nohup bash scripts/r4_window3.sh > /tmp/r4_window3.log 2>&1 &
#
#   1. flash tile-size sweep at the best-known batch points — the knob
#      landed after window 2's agenda was frozen
#   2. re-record the full bench if the sweep moved the tuned best
set -u
cd "$(dirname "$0")/.."
. scripts/window_lib.sh

wait_healthy_tunnel
echo "[$(stamp)] running the window-3 agenda"
best_before=$(tuned_best)

echo "[$(stamp)] == 1/2 flash tile sweep (best so far: $best_before) =="
python scripts/tune_north.py --attns flash --batches 8,16 \
  --loss_chunks 256 --flash_blocks 256x256,128x256,256x128,640x128 \
  --claim_retries 2 \
  && echo "[$(stamp)] tile sweep OK" || echo "[$(stamp)] tile sweep FAILED"

echo "[$(stamp)] == 2/2 conditional re-bench =="
rebench_if_improved "$best_before" w3
echo "[$(stamp)] window-3 agenda complete"
