#!/usr/bin/env bash
# Round-4 third-wave agenda: refinements on top of whatever window 2
# banked. Run after scripts/r4_window2.sh completes (or standalone in any
# healthy window):
#   nohup bash scripts/r4_window3.sh > /tmp/r4_window3.log 2>&1 &
#
#   1. flash tile-size sweep at the best-known batch points — the knob
#      landed after window 2's agenda was frozen
#   2. re-record the full bench if the sweep moved the tuned best
set -u
cd "$(dirname "$0")/.."
stamp() { date -u +"%H:%M:%S"; }

echo "[$(stamp)] waiting for a healthy tunnel (10-min probe deadline/try)"
until BENCH_INIT_DEADLINE_S=${BENCH_INIT_DEADLINE_S:-600} \
      python - <<'EOF'
import os, sys, threading
ok = {}
def probe():
    try:
        import jax
        ok["d"] = jax.devices()
    except Exception:
        pass
t = threading.Thread(target=probe, daemon=True)
t.start()
t.join(float(os.environ.get("BENCH_INIT_DEADLINE_S", "600")))
sys.stdout.flush()
os._exit(0 if "d" in ok else 1)
EOF
do
  echo "[$(stamp)] still wedged; sleeping 120s"
  sleep 120
done
echo "[$(stamp)] tunnel healthy — running the window-3 agenda"

best_before=$(python -c "
import json
try: print(json.load(open('docs/TUNE_NORTH.json'))['best']['tokens_sec_chip'])
except Exception: print(0)")

echo "[$(stamp)] == 1/2 flash tile sweep (best so far: $best_before) =="
python scripts/tune_north.py --attns flash --batches 8,16 \
  --loss_chunks 256 --flash_blocks 256x256,128x256,256x128,640x128 \
  --claim_retries 2 \
  && echo "[$(stamp)] tile sweep OK" || echo "[$(stamp)] tile sweep FAILED"

best_after=$(python -c "
import json
try: print(json.load(open('docs/TUNE_NORTH.json'))['best']['tokens_sec_chip'])
except Exception: print(0)")

if python -c "exit(0 if float('$best_after') > float('$best_before') else 1)"
then
  echo "[$(stamp)] == 2/2 full bench (best improved: $best_before -> $best_after) =="
  out="docs/BENCH_TPU_$(date -u +%Y-%m-%d_%H%M).json"
  if python bench.py > /tmp/bench_w3.json 2>/tmp/bench_w3.err; then
    python -c "
import json
d = json.load(open('/tmp/bench_w3.json'))
json.dump(d, open('$out', 'w'), indent=2)
print('wrote $out')" && echo "[$(stamp)] bench OK"
  else
    echo "[$(stamp)] bench FAILED"; tail -3 /tmp/bench_w3.err
  fi
else
  echo "[$(stamp)] tuned best unchanged ($best_after); skipping re-bench"
fi
echo "[$(stamp)] window-3 agenda complete"
