"""Build a download-free REAL-image caption dataset for the end-to-end
trained proof (VERDICT r3 item 4 — this repo's answer to the reference's
2000-landscape demo, reference README.md:9-13).

Sources (photographs shipped inside installed packages, zero egress):
  * sklearn.datasets.load_sample_images — china.jpg (temple), flower.jpg
  * matplotlib mpl-data — grace_hopper.jpg (portrait)

Each base photo is expanded into many square crops (random position/scale,
optional horizontal flip, mild brightness jitter) resized to --size px, with
a caption drawn from per-subject templates, so the DALLE can associate
caption words with visual content the way the reference demo does.

Writes: <out>/images/0/*.png (the reference's ImageFolder-style
single-class layout both train CLIs expect — reference trainDALLE.py:185),
<out>/captions.txt ("file : caption"), <out>/only.txt (captions-only vocab
corpus). Point both CLIs at --dataPath <out>/images.

Run: python scripts/make_demo_dataset.py --out data/demo --n 600 --size 128
"""

import argparse
import os

import numpy as np
from PIL import Image

TEMPLATES = {
    "temple": [
        "a photo of an ancient chinese temple",
        "ornate temple roof against the sky",
        "a traditional pagoda building with carved eaves",
        "an old asian temple with decorated rooftops",
    ],
    "flower": [
        "a photo of a purple flower",
        "a close up of a blooming flower",
        "bright petals of a tropical flower",
        "a flower blossom in the garden",
    ],
    "portrait": [
        "a portrait of a woman in uniform",
        "a photo of a woman wearing glasses",
        "a formal portrait photograph of a woman",
        "a woman in a navy uniform looking at the camera",
    ],
}


def base_images():
    from sklearn.datasets import load_sample_images
    import matplotlib
    imgs = load_sample_images()
    by_name = dict(zip([os.path.basename(f) for f in imgs.filenames],
                       imgs.images))
    hopper = os.path.join(os.path.dirname(matplotlib.__file__), "mpl-data",
                          "sample_data", "grace_hopper.jpg")
    return {
        "temple": np.asarray(by_name["china.jpg"], np.uint8),
        "flower": np.asarray(by_name["flower.jpg"], np.uint8),
        "portrait": np.asarray(Image.open(hopper).convert("RGB"), np.uint8),
    }


def augment(img: np.ndarray, rng: np.random.Generator, size: int):
    h, w, _ = img.shape
    side = int(rng.uniform(0.5, 1.0) * min(h, w))
    y = rng.integers(0, h - side + 1)
    x = rng.integers(0, w - side + 1)
    crop = img[y:y + side, x:x + side]
    if rng.random() < 0.5:
        crop = crop[:, ::-1]
    out = Image.fromarray(crop).resize((size, size), Image.LANCZOS)
    arr = np.asarray(out, np.float32) * float(rng.uniform(0.85, 1.15))
    return Image.fromarray(np.clip(arr, 0, 255).astype(np.uint8))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="data/demo")
    ap.add_argument("--n", type=int, default=600, help="total images")
    ap.add_argument("--size", type=int, default=128)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    rng = np.random.default_rng(args.seed)
    img_dir = os.path.join(args.out, "images", "0")
    os.makedirs(img_dir, exist_ok=True)
    bases = base_images()
    subjects = sorted(bases)
    pairs = []
    for i in range(args.n):
        subject = subjects[i % len(subjects)]
        fn = f"{subject}_{i:04d}.png"
        augment(bases[subject], rng, args.size).save(
            os.path.join(img_dir, fn))
        caption = TEMPLATES[subject][int(rng.integers(
            len(TEMPLATES[subject])))]
        pairs.append((fn, caption))

    with open(os.path.join(args.out, "captions.txt"), "w") as f:
        for fn, cap in pairs:
            f.write(f"{fn} : {cap}\n")
    all_caps = sorted({c for caps in TEMPLATES.values() for c in caps})
    with open(os.path.join(args.out, "only.txt"), "w") as f:
        f.write("\n".join(all_caps) + "\n")
    print(f"wrote {len(pairs)} images to {img_dir} "
          f"({len(all_caps)} distinct captions)")


if __name__ == "__main__":
    main()
