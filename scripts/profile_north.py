"""Where does the north-config step's time go? (VERDICT r4 item 2.)

Poor-man's profiler that works under this platform's broken
``block_until_ready`` (see scripts/axon_sync_repro.py): times each piece
of the depth-12 train step IN ISOLATION with host-fetch-synced chained
executions — attention fwd+bwd (flash vs xla), the GEGLU/projection
matmuls, the 12k-vocab CE head (dense vs chunked), the embedding +
position lookups, and the adam update — then compares the sum against the
measured full step so the residual (XLA fusion wins, dispatch, data
movement) is visible.

Run on the chip: python scripts/profile_north.py [--batch 8] [--steps 10]
Prints one JSON line per piece plus a summary; all times are per-step ms.
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time(fn, args, steps, fetch):
    """Wall ms/step for ``steps`` CHAINED fn calls, host-fetch synced.

    Chaining is real, not nominal: each iteration's first argument carries a
    zero-valued term data-dependent on the previous output (one fused
    elementwise add on one leaf), so the final ``fetch`` — a host round-trip
    on the last output — cannot complete until every iteration has executed.
    Same discipline as bench.time_steps (this platform's block_until_ready
    returns early; scripts/axon_sync_repro.py)."""
    import jax

    a0, rest = args[0], args[1:]

    @jax.jit
    def chained(a0, *rest):
        out = fn(a0, *rest)
        dep = jax.tree.leaves(out)[0].ravel()[0] * 0
        leaves, treedef = jax.tree.flatten(a0)
        leaves[0] = leaves[0] + dep.astype(leaves[0].dtype)
        return out, jax.tree.unflatten(treedef, leaves)

    out, a = chained(a0, *rest)
    fetch(out)                                   # compile + settle
    t0 = time.perf_counter()
    for _ in range(steps):
        out, a = chained(a, *rest)
    fetch(out)
    return (time.perf_counter() - t0) / steps * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=0,
                    help="0 = the tuned/default bench batch")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--claim_retries", type=int, default=3)
    args = ap.parse_args()

    from bench import claim_backend
    claim = claim_backend(args.claim_retries, attempt_env="PROFILE_ATTEMPT")
    if claim is not None:
        print(json.dumps({"error": claim[0], "claim_attempts": claim[1]}),
              flush=True)
        os._exit(1)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import bench
    from bench import build_cfg, setup_train, time_steps, _fetch
    from dalle_pytorch_tpu.models import dalle as D
    from dalle_pytorch_tpu.ops import attention as attn_ops
    from dalle_pytorch_tpu.ops import transformer as T
    from dalle_pytorch_tpu.parallel import make_mesh

    n_dev = len(jax.devices())
    mesh = make_mesh({"dp": n_dev})

    # mirror bench_north's tuned defaults so the full-step baseline is the
    # config bench actually records (attn impl, batch, loss_chunk)
    tuned = {}
    if not args.tiny:
        try:
            with open(os.path.join(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))), "docs",
                    "TUNE_NORTH.json")) as f:
                payload = json.load(f)
            if payload.get("backend") == jax.default_backend():
                tuned = payload.get("best", {})
        except (OSError, ValueError):
            pass
    bench_attn = tuned.get("attn") or (
        "flash" if jax.default_backend() == "tpu" else "xla")
    cfg = build_cfg(args.tiny, depth=12 if not args.tiny else 2,
                    attn_impl=bench_attn,
                    loss_chunk=tuned.get("loss_chunk") or 0,
                    heads=tuned.get("heads", 8),
                    dim_head=tuned.get("dim_head", 64),
                    remat=tuned.get("remat") or "none",
                    reversible=bool(tuned.get("reversible", False)))
    batch = args.batch or (tuned.get("batch_per_chip", 8) * n_dev
                           if not args.tiny else 4)
    key = jax.random.PRNGKey(0)
    b, n, d = batch, cfg.seq_len, cfg.dim
    h_dim = cfg.heads
    dh = cfg.dim_head
    dt = jnp.bfloat16
    results = {}

    def fetch(x):
        return _fetch(x if isinstance(x, jax.Array) else jax.tree.leaves(x)[0])

    def note(msg):
        # progress to stderr so a hang is localizable to a piece (the
        # 2026-07-31 run sat silent for 25 min before being killed);
        # every note also beats the shared stall watchdog
        bench.beat(msg)
        print(f"[profile] {msg}", file=sys.stderr, flush=True)

    # Mid-run stall protection: emit the pieces measured so far as ONE
    # partial JSON line (exit 0 — a partial profile is still a profile)
    # instead of hanging the window orchestrator forever on a wedge.
    def _on_stall(failure):
        try:
            line = json.dumps({**results, "partial": True, "stall": failure,
                               "backend": jax.default_backend()})
        except RuntimeError:     # results mutated mid-copy: main is alive,
            return               # let the watch loop re-check later
        print(line, flush=True)
        os._exit(0)

    bench.start_stall_watchdog(on_stall=_on_stall)

    # -- attention fwd+bwd, all impls, one layer x depth -------------------
    x = jax.random.normal(key, (b, h_dim, n, dh), dt)
    for impl in ("flash", "flash_pallas_bwd", "flash_pallas_fused", "xla"):
        if impl == "xla":
            # dense attention materializes (b,h,n,n) f32 weights. One
            # layer in isolation fits at the tuned batches (b=16 is
            # ~2.5G with the bwd's saved+grad copies — the full-model
            # OOMs in the 2026-07-31 sweep came from 12 STACKED layers
            # of saved weights, which this piece doesn't have); the
            # guard only protects pathological batches from wedging the
            # remote-compile helper.
            score_bytes = 3 * b * h_dim * n * n * 4
            if score_bytes > 10e9:
                note(f"skip attn_xla (est {score_bytes/1e9:.1f}G of score "
                     "tensors)")
                results[f"attn_xla_fwdbwd_ms_x{cfg.depth}"] = None
                continue
        note(f"attn impl={impl}")
        if impl.startswith("flash"):
            from dalle_pytorch_tpu.ops.flash_attention import flash_attention
            bwd = {"flash_pallas_bwd": "pallas",
                   "flash_pallas_fused": "pallas_fused"}.get(impl, "xla")
            att = functools.partial(
                flash_attention, causal=True, scale=d ** -0.5, bwd_impl=bwd)
        else:
            def att(q, k, v):
                w = attn_ops.dense_attention_weights(q, k, d ** -0.5, None,
                                                     True)
                return jnp.einsum("bhij,bhjd->bhid", w, v)

        # jaxlint: disable=JL004 — profiling harness: one jit per attention
        # impl under test, a handful of constructions total (the same
        # waived idiom as bench.py's per-kernel timing loops)
        fb = jax.jit(jax.grad(lambda q, k, v: att(q, k, v).astype(
            jnp.float32).sum(), argnums=(0, 1, 2)))
        ms = _time(fb, (x, x, x), args.steps, fetch)
        results[f"attn_{impl}_fwdbwd_ms_x{cfg.depth}"] = round(
            ms * cfg.depth, 2)

    # -- the non-attention layer matmuls (qkv/out/GEGLU), fwd+bwd ----------
    note("layer matmuls")
    lkey = jax.random.PRNGKey(1)
    tcfg = cfg.transformer
    lp = T.layer_init(lkey, tcfg, dtype=dt)
    xl = jax.random.normal(jax.random.fold_in(key, 1), (b, n, d), dt)

    def layer_no_attn(lp, x):
        p = lp["attn"]
        from dalle_pytorch_tpu.ops import core
        hh = core.layernorm(p["ln"], x)
        q, k, v = attn_ops.qkv_project(p, hh, tcfg.heads)
        o = attn_ops.output_tail(p, v)           # skip the attention mix
        x = x + o
        return x + T.ff_branch(lp, x, tcfg, None, False)

    fb = jax.jit(jax.grad(
        lambda lp, x: layer_no_attn(lp, x).astype(jnp.float32).sum()))
    ms = _time(fb, (lp, xl), args.steps, fetch)
    results[f"layer_matmuls_fwdbwd_ms_x{cfg.depth}"] = round(
        ms * cfg.depth, 2)

    # -- CE head: dense vs chunked, fwd+bwd --------------------------------
    params = D.dalle_init(jax.random.fold_in(key, 2), cfg, dtype=dt)
    hfull = jax.random.normal(jax.random.fold_in(key, 3), (b, n, d), dt)
    text = jax.random.randint(jax.random.fold_in(key, 4),
                              (b, cfg.text_seq_len), 0,
                              cfg.num_text_tokens)
    img = jax.random.randint(jax.random.fold_in(key, 5),
                             (b, cfg.image_seq_len), 0,
                             cfg.num_image_tokens)
    import dataclasses
    chunk = cfg.loss_chunk or 256
    for name, c in (("dense", dataclasses.replace(cfg, loss_chunk=0)),
                    (f"chunk{chunk}",
                     dataclasses.replace(cfg, loss_chunk=chunk))):
        note(f"ce head {name}")
        # jaxlint: disable=JL004 — profiling harness: one jit per CE-head
        # variant (dense vs chunked), two constructions total
        fb = jax.jit(jax.grad(lambda hh, c=c: D.ce_from_hidden(
            params, hh, text, img, cfg=c)))
        ms = _time(fb, (hfull,), args.steps, fetch)
        results[f"ce_head_{name}_fwdbwd_ms"] = round(ms, 2)

    # -- embeddings ---------------------------------------------------------
    note("embeddings")
    emb = jax.jit(lambda t, i: D.embed_prompt(params, cfg, t, i))
    results["embed_fwd_ms"] = round(
        _time(emb, (text, img), args.steps, fetch), 2)

    # -- adam update over the full param tree ------------------------------
    opt = optax.adam(1e-4)
    opt_state = jax.jit(opt.init)(params)
    grads = jax.tree.map(jnp.ones_like, params)

    @jax.jit
    def adam_step(params, opt_state, grads):
        upd, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, upd), opt_state

    note("adam update")
    ms = _time(lambda p, s: adam_step(p, s, grads),
               (params, opt_state), args.steps, fetch)
    results["adam_update_ms"] = round(ms, 2)

    # -- the real full step for comparison ---------------------------------
    note("full step")
    step, p2, s2, data, k2 = setup_train(cfg, batch, mesh)
    dt_s, _, _ = time_steps(step, p2, s2, data, k2, 2, args.steps)
    results["full_step_ms"] = round(dt_s / args.steps * 1e3, 2)
    # account with the attention impl and CE head the full step ACTUALLY
    # ran, so the residual is fusion/dispatch/data movement, not impl gaps
    ce_key = ("ce_head_dense_fwdbwd_ms" if not cfg.loss_chunk
              else f"ce_head_chunk{chunk}_fwdbwd_ms")
    # the tuned name 'flash_pallas' is recorded by the impl loop as
    # 'flash_pallas_bwd' (same flash-fwd + Pallas-bwd pairing build_cfg
    # resolves)
    attn_key = ("flash_pallas_bwd" if bench_attn == "flash_pallas"
                else bench_attn)
    parts = (results[f"attn_{attn_key}_fwdbwd_ms_x{cfg.depth}"],
             results[f"layer_matmuls_fwdbwd_ms_x{cfg.depth}"],
             results[ce_key],
             results["embed_fwd_ms"], results["adam_update_ms"])
    results["accounted_ms"] = (round(sum(parts), 2)
                               if None not in parts else None)
    results["full_step_attn"] = bench_attn
    results["full_step_loss_chunk"] = cfg.loss_chunk
    results["batch"] = batch
    results["backend"] = jax.default_backend()
    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
