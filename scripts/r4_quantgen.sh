#!/usr/bin/env bash
# Measure int8-quantized generation on the chip (bench --config north
# --gen_quant) after the probe chain finishes. The artifact is named
# QUANTGEN_* so bench's stale-fallback glob (BENCH_TPU_*) never mistakes
# this single-config payload for a full bench record.
#   nohup bash scripts/r4_quantgen.sh > /tmp/r4_quantgen.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
. scripts/window_lib.sh

while pgrep -f 'scripts/r4_(probe|closing2)\.sh' > /dev/null; do
  echo "[$(stamp)] probe/closing chain still running; waiting 120s"
  sleep 120
done

wait_healthy_tunnel
echo "[$(stamp)] == quantized-gen bench =="
out="docs/QUANTGEN_TPU_$(date -u +%Y-%m-%d_%H%M).json"
if python bench.py --config north --gen_quant --gen_batches 1,4 \
     > /tmp/quantgen.json 2>/tmp/quantgen.err; then
  python -c "
import json
d = json.load(open('/tmp/quantgen.json'))
json.dump(d, open('$out', 'w'), indent=2)
print('wrote $out')" && echo "[$(stamp)] quantgen OK"
else
  echo "[$(stamp)] quantgen FAILED"; tail -3 /tmp/quantgen.err
fi
echo "[$(stamp)] quantgen agenda complete — inspect and commit"
