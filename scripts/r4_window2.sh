#!/usr/bin/env bash
# Round-4 second-window agenda. The 2026-07-31 00:59-01:16 window banked
# tune + full bench + kernel smoke before the tunnel wedged again; this
# orchestrator waits for the next healthy window and runs what that one
# missed, highest-value first:
#   1. remat sweep        — remat='full' unlocks batch>=32 (every such
#                           config OOM'd un-rematerialized); also re-probes
#                           batch 8 vs 16 on the same chip/day
#   2. scripts/tpu_demo.sh — end-to-end trained proof (VERDICT r3 missing 2)
#   3. scripts/profile_north.py — step decomposition (now with progress)
#   4. python bench.py    — re-record with whatever defaults the sweep won
# Same usage as healthy_window.sh:
#   nohup bash scripts/r4_window2.sh > /tmp/r4_window2.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
. scripts/window_lib.sh

wait_healthy_tunnel
echo "[$(stamp)] running the window-2 agenda"

echo "[$(stamp)] == 1/4 remat + reversible sweep =="
# legs sized to known memory behavior (2026-07-31 sweep: un-rematerialized
# OOMs at batch>=32; 'dots' reclaims ~65% of residual bytes at near-zero
# FLOP cost, 'full' ~91% at ~1/3 more FLOPs; reversible is O(1) by
# inversion and measured FASTER than sequential at batch 8 on 2026-07-30)
python scripts/tune_north.py --attns flash --batches 8,16 \
  --loss_chunks 256 --remats none --claim_retries 2 \
  && echo "[$(stamp)] none leg OK" || echo "[$(stamp)] none leg FAILED"
python scripts/tune_north.py --attns flash --batches 16,32,64 \
  --loss_chunks 256 --remats dots --claim_retries 2 \
  && echo "[$(stamp)] dots leg OK" || echo "[$(stamp)] dots leg FAILED"
python scripts/tune_north.py --attns flash --batches 32,64 \
  --loss_chunks 256 --remats full --claim_retries 2 \
  && echo "[$(stamp)] full leg OK" || echo "[$(stamp)] full leg FAILED"
python scripts/tune_north.py --attns flash --batches 8,32,64 \
  --loss_chunks 256 --reversibles 1 --claim_retries 2 \
  && echo "[$(stamp)] reversible leg OK" \
  || echo "[$(stamp)] reversible leg FAILED"

echo "[$(stamp)] == 2/4 tpu_demo =="
bash scripts/tpu_demo.sh && echo "[$(stamp)] demo OK" \
  || echo "[$(stamp)] demo FAILED"

echo "[$(stamp)] == 3/4 profile_north =="
if python scripts/profile_north.py > /tmp/profile_north.json \
     2>/tmp/profile_north.err; then
  cp /tmp/profile_north.json docs/PROFILE_NORTH.json
  cat docs/PROFILE_NORTH.json; echo "[$(stamp)] profile OK"
else
  echo "[$(stamp)] profile FAILED"; tail -3 /tmp/profile_north.err
fi

echo "[$(stamp)] == 4/4 full bench =="
out="docs/BENCH_TPU_$(date -u +%Y-%m-%d_%H%M).json"
if python bench.py > /tmp/bench_window.json 2>/tmp/bench_window.err; then
  python -c "
import json
d = json.load(open('/tmp/bench_window.json'))
json.dump(d, open('$out', 'w'), indent=2)
print('wrote $out')" && echo "[$(stamp)] bench OK"
else
  echo "[$(stamp)] bench FAILED"; tail -3 /tmp/bench_window.err
fi
echo "[$(stamp)] window-2 agenda complete — inspect artifacts and commit"
