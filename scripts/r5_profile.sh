#!/usr/bin/env bash
# Round-5 profile leg: waits for the banking agenda and the demo leg,
# then re-runs the step-decomposition profiler — now including the
# fused single-pass Pallas flash backward (bwd_impl='pallas_fused') —
# so docs/PROFILE_NORTH.json records whether the fused kernel finally
# beats the XLA blockwise backward (VERDICT r4 item 3's flash half).
#   nohup bash scripts/r5_profile.sh > /tmp/r5_profile.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
. scripts/window_lib.sh

while pgrep -f 'scripts/r5_(agenda|demo)\.sh' > /dev/null; do
  echo "[$(stamp)] earlier r5 legs still running; waiting 120s"
  sleep 120
done

wait_healthy_tunnel
echo "[$(stamp)] == profile_north (with pallas_fused) =="
python scripts/profile_north.py && echo "[$(stamp)] profile OK" \
  || echo "[$(stamp)] profile FAILED"
echo "[$(stamp)] r5 profile leg complete — inspect docs/PROFILE_NORTH.json"
