"""Observability-layer tests (ISSUE 15 acceptance criteria).

The load-bearing ones: per-request trace spans TILE (their durations sum
back to the caller-observed latency), survive the socket transport
byte-faithfully, stay transfer-clean in the steady state, and link a
failover replay to the original trace with a visible ``replayed_from``
gap — with the victim's flight-recorder dump embedded in the fence event
(parent-side mirror, so a SIGKILL cannot destroy it). Plus the /metrics
exposition (histogram counts == distinct delivered requests), the
/debug/events surface, the typed /admin/profile 409, and the
MetricsLogger thread-safety fix (concurrent appends, zero torn lines).

All CPU, tiny model (total_len 24) so the file stays cheap inside
tier-1; the one process+socket test is the SIGKILL acceptance row.
"""

import json
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.analysis import guards
from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V
from dalle_pytorch_tpu.obs.flight import FlightRecorder, RecordingMetrics
from dalle_pytorch_tpu.obs.registry import (Histogram, LabeledHistogram,
                                            Registry)
from dalle_pytorch_tpu.obs.trace import Trace, new_trace_id
from dalle_pytorch_tpu.resilience import faults
from dalle_pytorch_tpu.resilience.retry import RetryPolicy
from dalle_pytorch_tpu.serve import (OK, Request, RequestHandle,
                                     RequestQueue, SamplingParams)
from dalle_pytorch_tpu.serve.engine import Engine, ProfileError

VCFG = V.VAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                   num_layers=2, hidden_dim=8)
CFG = D.DALLEConfig(dim=16, depth=2, vae=VCFG, num_text_tokens=50,
                    text_seq_len=8, heads=2, dim_head=8)

FAST_BRINGUP = RetryPolicy(max_attempts=1, deadline_s=None,
                           base_backoff_s=0.01, backoff_multiplier=2.0,
                           max_backoff_s=0.1, jitter=0.0)

REQS = [
    Request(codes=(3, 7, 9), seed=11),
    Request(codes=(5, 2, 8, 1, 4), seed=23,
            sampling=SamplingParams(temperature=0.7, filter_thres=0.8)),
    Request(codes=(6, 6), seed=5,
            sampling=SamplingParams(temperature=1.3, top_p=0.9)),
    Request(codes=(2, 4, 4), seed=7),
    Request(codes=(1, 5), seed=13),
    Request(codes=(4, 4, 4, 4), seed=17),
]


@pytest.fixture(scope="module")
def bundle():
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG)
    params = D.dalle_init(key, CFG, vae_params)
    return params, vae_params


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.deactivate()
    yield
    faults.deactivate()


# ---------------------------------------------------------------------------
# obs/trace.py
# ---------------------------------------------------------------------------

class TestTrace:
    def test_spans_tile_and_sum(self):
        tr = Trace(new_trace_id(7), 7, t0=100.0)
        tr.span("submit", 100.0)
        tr.span("queue_wait", 100.5)
        tr.span("prefill_admit", 100.75, bucket=4, mode="cold")
        tr.span("decode_chunk", 101.0, tokens=4)
        tr.span("decode_chunk", 101.25, tokens=4)
        s = tr.summary()
        assert s["request_id"] == 7 and s["attempts"] == 1
        names = [x["name"] for x in s["spans"]]
        assert names == ["submit", "queue_wait", "prefill_admit",
                         "decode_chunk"]
        # tiling: the sum of durations IS the wall interval
        assert s["span_total_s"] == pytest.approx(1.25)
        chunk = next(x for x in s["spans"]
                     if x["name"] == "decode_chunk")
        assert chunk["n"] == 2 and chunk["total_s"] == pytest.approx(0.5)

    def test_replay_marker_covers_the_gap_visibly(self):
        """The fence gap is a LABELED span, not fabricated decode time
        and not a hole: the replayed_from marker's duration is the gap,
        so span sums still tile while the timeline shows the fence."""
        tr = Trace("t", 1, t0=0.0)
        tr.span("queue_wait", 0.1)
        tr.span("decode_chunk", 0.4, tokens=4)
        rec = tr.replay(1.4, reason="crash: boom", replica=1)
        assert rec["span"] == "replayed_from"
        assert rec["dur_s"] == pytest.approx(1.0)       # the gap
        assert rec["from_attempt"] == 0 and rec["attempt"] == 1
        tr.span("queue_wait", 1.5)
        tr.span("decode_chunk", 2.0, tokens=8)
        s = tr.summary()
        assert s["attempts"] == 2
        assert s["replays"] == [{"from_attempt": 0,
                                 "reason": "crash: boom",
                                 "gap_s": pytest.approx(1.0)}]
        assert s["span_total_s"] == pytest.approx(2.0)

    def test_has_in_attempt_resets_per_attempt(self):
        tr = Trace("t", 1, t0=0.0)
        tr.span("queue_wait", 0.1)
        assert tr.has_in_attempt("queue_wait")
        tr.replay(0.2, reason="fence")
        assert not tr.has_in_attempt("queue_wait")

    def test_wire_spans_cross_the_frame_codec_byte_faithfully(self):
        """Float timestamps/durations survive the JSON frame protocol
        exactly (repr round-trip — the same rule Request.to_wire
        relies on), so a child's spans merge bit-identical."""
        from dalle_pytorch_tpu.serve import ipc
        tr = Trace("abc-123", 9, t0=12345.678901234567)
        tr.span("queue_wait", 12345.981234567891)
        tr.span("decode_chunk", 12346.123456789012, tokens=3)
        spans = tr.wire_spans()
        frame = ipc.encode_frame(
            ipc.HARVEST, {"results": [{"spans": spans}]}, seq=4)
        kind, payload, seq = ipc.decode_frame(frame)
        assert payload["results"][0]["spans"] == spans

    def test_merge_wire_skips_malformed_and_reanchors(self):
        tr = Trace("t", 1, t0=0.0)
        tr.span("route", 0.5, replica=0)
        n = tr.merge_wire(
            [{"span": "queue_wait", "dur_s": 0.25, "t0": 0.5,
              "attempt": 0, "event": "span"},
             "garbage", {"nope": 1}, None], now=1.0)
        assert n == 1
        tr.span("postprocess", 1.5)
        s = tr.summary()
        assert [x["name"] for x in s["spans"]] == \
            ["route", "queue_wait", "postprocess"]
        assert s["spans"][-1]["total_s"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# obs/flight.py
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_bounded_ring_and_since(self):
        fl = FlightRecorder(capacity=4)
        for i in range(10):
            fl.record({"i": i})
        assert len(fl) == 4
        assert [r["i"] for r in fl.dump()] == [6, 7, 8, 9]
        assert [r["i"] for r in fl.tail(2)] == [8, 9]
        seq, recs = fl.since(0)
        assert seq == 10 and [r["i"] for r in recs] == [6, 7, 8, 9]
        fl.record({"i": 10})
        seq2, recs2 = fl.since(seq)
        assert [r["i"] for r in recs2] == [10] and seq2 == 11

    def test_recording_metrics_tees_and_forwards(self):
        fl = FlightRecorder(capacity=8)

        class Sink:
            events: list = []

            def event(self, **f):
                self.events.append(f)

        sink = Sink()
        m = RecordingMetrics(fl, sink)
        m.event(event="resilience", kind="x", a=1)
        assert fl.dump()[0]["kind"] == "x"
        assert sink.events[0]["a"] == 1
        # no sink: the ring still records (always-on is the point)
        m2 = RecordingMetrics(FlightRecorder(4), None)
        m2.event(kind="y")
        assert m2.flight.dump()[0]["kind"] == "y"

    def test_wrap_never_chains_rings(self):
        from dalle_pytorch_tpu.obs.flight import wrap_metrics
        base = object()
        inner = RecordingMetrics(FlightRecorder(4), base)
        outer = wrap_metrics(FlightRecorder(4), inner)
        assert outer.inner is base


# ---------------------------------------------------------------------------
# obs/registry.py
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_histogram_buckets_count_sum_percentile(self):
        h = Histogram(buckets=(0.1, 1.0), window=100)
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        assert h.count == 3 and h.sum == pytest.approx(5.55)
        assert h.counts == [1, 1, 1]
        assert h.percentile(0.5) == pytest.approx(0.5)
        assert h.percentile(0.99) == pytest.approx(5.0)

    def test_labeled_histogram_renders_prometheus_text(self):
        reg = Registry()
        lh = reg.histogram("x_seconds", "help text", buckets=(0.1, 1.0))
        lh.observe(0.05, weights_version="v1")
        lh.observe(0.5, weights_version="v2")
        text = reg.render()
        assert "# TYPE x_seconds histogram" in text
        assert 'x_seconds_bucket{le="0.1",weights_version="v1"} 1' \
            in text
        assert 'x_seconds_bucket{le="+Inf",weights_version="v1"} 1' \
            in text
        assert 'x_seconds_count{weights_version="v2"} 1' in text
        assert lh.total_count() == 2
        # merged percentiles across children (the /stats surface)
        p = lh.percentiles_ms()
        assert p["p50"] == pytest.approx(50.0) \
            or p["p50"] == pytest.approx(500.0)

    def test_counters_gauges_and_escaping(self):
        reg = Registry()
        text = reg.render(
            counters=[("c_total", "a counter",
                       [({"k": 'we"ird\nvalue\\x'}, 3)])],
            gauges=[("g", "a gauge", [(None, 1.5)]),
                    ("empty", "dropped", [])])
        assert "# TYPE c_total counter" in text
        assert 'c_total{k="we\\"ird\\nvalue\\\\x"} 3' in text
        assert "g 1.5" in text
        assert "empty" not in text      # no samples -> no headers

    def test_bad_metric_name_rejected(self):
        with pytest.raises(ValueError):
            Registry().histogram("9bad-name", "x")


# ---------------------------------------------------------------------------
# utils.metrics.MetricsLogger thread-safety (satellite)
# ---------------------------------------------------------------------------

class TestMetricsLoggerConcurrency:
    def test_concurrent_events_no_torn_lines(self, tmp_path):
        from dalle_pytorch_tpu.utils.metrics import MetricsLogger
        path = tmp_path / "m.jsonl"
        m = MetricsLogger(str(path))
        n_threads, n_events = 8, 200

        def spam(tid):
            for i in range(n_events):
                m.event(event="serve", tid=tid, i=i,
                        pad="x" * 64)      # wide enough to tear

        threads = [threading.Thread(target=spam, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        m.close()
        lines = path.read_text().splitlines()
        assert len(lines) == n_threads * n_events
        seen = set()
        for line in lines:
            rec = json.loads(line)      # a torn line would fail here
            seen.add((rec["tid"], rec["i"]))
        assert len(seen) == n_threads * n_events


# ---------------------------------------------------------------------------
# engine-level tracing
# ---------------------------------------------------------------------------

class TestEngineTracing:
    def test_trace_rides_result_and_sums_to_latency(self, bundle):
        params, _ = bundle
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=4)
        handles = [queue.submit(r) for r in REQS[:3]]
        engine.run_until_idle()
        for h in handles:
            res = h.result(timeout=5)
            assert res.status == OK
            tr = res.trace
            assert tr is not None and tr["attempts"] == 1
            names = [s["name"] for s in tr["spans"]]
            assert names[:3] == ["submit", "queue_wait",
                                 "prefill_admit"]
            assert "decode_chunk" in names
            # tiling: single-process spans sum EXACTLY to the
            # caller-observed latency (same clock, no process gaps;
            # total_s rounds to 6 places)
            assert tr["span_total_s"] == pytest.approx(res.total_s,
                                                       abs=2e-5)
            chunk = next(s for s in tr["spans"]
                         if s["name"] == "decode_chunk")
            assert chunk["n"] == engine.harvests or chunk["n"] >= 1

    def test_span_stamping_is_transfer_clean(self, bundle):
        """The tracing layer adds ZERO host<->device traffic: the full
        steady-state iteration — admission, chunk dispatch, emit-ring
        harvest, span stamps, flight-ring appends — runs under
        guards.no_transfers, the same contract the pre-obs engine
        pinned."""
        params, _ = bundle
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=4)
        # warm run compiles the decode program + both buckets
        for r in REQS[:2]:
            queue.submit(r)
        engine.run_until_idle()
        with guards.no_transfers():
            handles = [queue.submit(r) for r in REQS[:2]]
            engine.run_until_idle()
        for h in handles:
            res = h.result(timeout=5)
            assert res.status == OK and res.trace is not None
            assert any(s["name"] == "decode_chunk"
                       for s in res.trace["spans"])

    def test_spans_and_events_land_in_flight_ring(self, bundle):
        params, _ = bundle
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=4)
        h = queue.submit(REQS[0])
        engine.run_until_idle()
        assert h.result(timeout=5).status == OK
        # (the zero-dur submit marker is stamped by the QUEUE, which
        # has no ring — it reaches /debug/events via the trace dumps)
        kinds = {r.get("span") for r in engine.flight.dump()
                 if r.get("event") == "span"}
        assert {"queue_wait", "prefill_admit", "decode_chunk"} <= kinds
        assert engine.stats()["flight_events"] == len(engine.flight)

    def test_profile_409_while_active_and_completes(self, bundle,
                                                    tmp_path):
        params, _ = bundle
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=4)
        rec = engine.request_profile(str(tmp_path / "prof"), chunks=2)
        assert rec["kind"] == "serve_profile_armed"
        with pytest.raises(ProfileError) as ei:
            engine.request_profile(str(tmp_path / "other"), chunks=1)
        assert ei.value.record["reason"] == "capture_active"
        queue.submit(REQS[0])
        engine.run_until_idle()
        assert not engine.profile_active()
        assert engine.profiles_taken == 1
        assert any((tmp_path / "prof").iterdir())
        # re-armable once the capture completed
        engine.request_profile(str(tmp_path / "prof2"), chunks=1)


# ---------------------------------------------------------------------------
# replica-set tracing: thread-mode failover replay link
# ---------------------------------------------------------------------------

class TestReplicaTracing:
    pytestmark = pytest.mark.faults

    def test_thread_crash_yields_linked_replay_trace(self, bundle):
        from dalle_pytorch_tpu.serve.replica import ReplicaSet
        params, _ = bundle
        queue = RequestQueue(max_depth=16)
        with faults.injected(fault_replica=1, replica_crash_at_chunk=2):
            rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                            chunk_steps=4, bringup_policy=FAST_BRINGUP)
            handles = [queue.submit(r) for r in REQS]
            rs.run_until_idle(max_steps=500_000)
        assert rs.failovers == 1
        traces = [h.result(timeout=5).trace for h in handles]
        assert all(t is not None for t in traces)
        replayed = [t for t in traces if t["replays"]]
        assert replayed, "the crash replayed nothing?"
        for t in replayed:
            assert t["attempts"] >= 2
            assert "crash" in t["replays"][0]["reason"]
            # the gap is visible AND the sums still tile
            assert any(s["name"] == "replayed_from"
                       for s in t["spans"])
            res = next(h.result(timeout=0) for h in handles
                       if h.result(timeout=0).trace is t)
            assert t["span_total_s"] == pytest.approx(res.total_s,
                                                      abs=2e-5)
        # routed requests carry the router's spans
        assert any(s["name"] == "route"
                   for t in traces for s in t["spans"])
        # the fence event embedded the victim's flight dump, and the
        # set-level /debug surface serves it
        dump = rs.debug_events()
        fences = [e for e in dump["server"]
                  if e.get("kind") == "serve_replica_fenced"]
        assert fences and fences[0].get("flight"), \
            "fence event carries no flight dump"
        assert any(e.get("event") == "span"
                   for e in fences[0]["flight"])
        assert dump["fenced"], "no fenced-replica dump retained"

    def test_scale_error_embeds_flight_tail(self, bundle):
        from dalle_pytorch_tpu.serve.replica import ReplicaSet, ScaleError
        params, _ = bundle
        queue = RequestQueue(max_depth=8)
        rs = ReplicaSet(params, CFG, queue, replicas=1, num_slots=2,
                        chunk_steps=4, bringup_policy=FAST_BRINGUP)
        with pytest.raises(ScaleError) as ei:
            rs.remove_replica(0)
        assert isinstance(ei.value.record.get("flight"), list)


# ---------------------------------------------------------------------------
# THE acceptance row: process+socket SIGKILL -> linked trace + dumps
# ---------------------------------------------------------------------------

class TestProcessObsAcceptance:
    pytestmark = pytest.mark.faults

    def test_sigkill_linked_trace_and_flight_dump_socket(self, bundle):
        """A process+socket 2-replica run with a mid-decode SIGKILL:
        the victim's flight-recorder dump (parent-side mirror — the
        corpse answers nothing), a replayed trace linked to the
        original trace_id whose span durations sum to the caller-
        observed latency within one harvest chunk of slop, and zero
        requests lost."""
        import time as _time

        from dalle_pytorch_tpu.serve.replica import RUNNING, ReplicaSet

        def wait_all_ready(rs, timeout=180.0):
            # same deflake as test_replica's helper: children come up
            # seconds apart, and the first-ready replica's admission
            # window could swallow the burst before the fault target
            # ever decodes a chunk
            deadline = _time.perf_counter() + timeout
            while _time.perf_counter() < deadline:
                rs.step_once()
                live = [r for r in rs.replicas if r.state == RUNNING
                        and r.engine is not None]
                if len(live) == rs.n_replicas and all(
                        getattr(r.engine, "ready", True) for r in live):
                    return
                _time.sleep(0.01)
            raise AssertionError("replicas never all became ready")
        params, _ = bundle
        queue = RequestQueue(max_depth=16)
        with faults.injected(fault_replica=1,
                             replica_sigkill_at_chunk=2):
            rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                            chunk_steps=4, isolation="process",
                            transport="socket",
                            bringup_policy=FAST_BRINGUP)
            try:
                wait_all_ready(rs)
                t_submit = _time.perf_counter()
                handles = [queue.submit(r) for r in REQS]
                rs.run_until_idle(max_steps=500_000)
                assert rs.failovers == 1
                wall = _time.perf_counter() - t_submit
                results = [h.result(timeout=10) for h in handles]
                assert all(r.status == OK for r in results), \
                    [(r.status, r.reason) for r in results]
                traces = [r.trace for r in results]
                assert all(t is not None for t in traces)
                # original trace ids survive the replay: attempts > 1
                # under the SAME trace_id, linked by replayed_from
                replayed = [t for t in traces if t["replays"]]
                assert replayed, "the SIGKILL replayed nothing?"
                for t in replayed:
                    assert t["attempts"] >= 2
                    assert any(s["name"] == "replayed_from"
                               for s in t["spans"])
                # span sums reconstruct caller latency: cross-process
                # tiling leaves only IPC-absorb gaps, bounded by one
                # harvest chunk of slop per attempt
                for r in results:
                    t = r.trace
                    assert 0 < t["span_total_s"] <= r.total_s + 1e-4
                    assert r.total_s - t["span_total_s"] \
                        < 0.5 * wall + 0.25, (t, r.total_s)
                # child-side spans crossed the socket and merged
                assert any(s["name"] == "decode_chunk"
                           for t in traces for s in t["spans"])
                # the victim's mirror dump: embedded in the fence
                # event AND retained under fenced[]
                dump = rs.debug_events()
                fences = [e for e in dump["server"]
                          if e.get("kind") == "serve_replica_fenced"]
                assert fences
                victim = fences[0].get("flight")
                assert victim, "SIGKILL destroyed the flight dump?"
                assert any(e.get("event") == "span" for e in victim), \
                    "no spans survived in the parent-side mirror"
                assert dump["fenced"].get("1") is not None
            finally:
                rs.close()


# ---------------------------------------------------------------------------
# server surface: /metrics, /debug/events, /admin/profile over HTTP
# ---------------------------------------------------------------------------

class TestServerObs:
    @pytest.fixture()
    def server(self, bundle, tmp_path):
        from dalle_pytorch_tpu.serve.server import (InferenceServer,
                                                    make_http_server)
        params, vae_params = bundle
        srv = InferenceServer(params, vae_params, CFG, num_slots=2,
                              chunk_steps=4, decode_images=False,
                              profile_dir=str(tmp_path / "prof"))
        srv.start()
        httpd = make_http_server(srv, port=0)
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            yield srv, port
        finally:
            httpd.shutdown()
            httpd.server_close()
            srv.close()

    @staticmethod
    def _get(port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}") as r:
            return r.status, r.read().decode()

    @staticmethod
    def _post(port, path, body, token=None):
        headers = {}
        if token:
            headers["Authorization"] = f"Bearer {token}"
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(body).encode(), method="POST",
            headers=headers)
        try:
            with urllib.request.urlopen(req) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_metrics_stats_debug_and_profile(self, server):
        srv, port = server
        for i, r in enumerate(REQS[:3]):
            res = srv.submit(r.codes, seed=r.seed).result(timeout=60)
            assert res.ok
        # /stats: operator latency percentiles off the histogram window
        stats = srv.stats()
        lat = stats["latency_ms"]
        assert lat["e2e"]["p50"] > 0
        assert set(lat["queue_wait"]) == {"p50", "p95", "p99"}
        # /metrics: required families + count == delivered requests
        st, text = self._get(port, "/metrics")
        assert st == 200
        for fam in ("dalle_serve_requests_submitted_total",
                    "dalle_serve_requests_completed_total",
                    "dalle_serve_tokens_decoded_total",
                    "dalle_serve_queue_depth",
                    "dalle_serve_e2e_latency_seconds_bucket",
                    "dalle_serve_queue_wait_seconds_count",
                    "dalle_serve_decode_ms_per_token_count",
                    "dalle_serve_info"):
            assert fam in text, f"missing family {fam}"
        count = [ln for ln in text.splitlines()
                 if ln.startswith("dalle_serve_e2e_latency_seconds_"
                                  "count")]
        assert count and count[0].split()[-1] == "3", count
        # the prefill family is fed from the trace summary, which must
        # exist BEFORE the on_fulfill hook runs (regression: it was
        # attached only later, inside handle.fulfill, leaving the
        # family headers-only forever)
        pre = [ln for ln in text.splitlines()
               if ln.startswith("dalle_serve_prefill_seconds_count")]
        assert pre and int(pre[0].split()[-1]) == 3, pre
        # /debug/events: span records served with no sink configured
        st, body = self._get(port, "/debug/events")
        events = json.loads(body)["server"]
        assert any(e.get("event") == "span" for e in events)
        # HTTP result bodies carry the trace summary
        st, gen = self._post(port, "/generate",
                             {"codes": [1, 2], "seed": 3})
        assert st == 200 and "trace" in gen \
            and gen["trace"]["span_total_s"] > 0
        # /admin/profile: 401 unauthenticated, 200 armed, 409 active
        st, _ = self._post(port, "/admin/profile", {})
        assert st == 401
        st, rec = self._post(port, "/admin/profile", {"chunks": 500},
                             token=srv.admin_token)
        assert st == 200 and rec["kind"] == "serve_profile_armed"
        st, rec = self._post(port, "/admin/profile", {"chunks": 1},
                             token=srv.admin_token)
        assert st == 409 and rec["reason"] == "capture_active"

    def test_profile_thread_set_guard_is_process_wide(self, bundle,
                                                      tmp_path):
        """jax.profiler is one trace per PROCESS: in a thread-isolation
        replica set a capture on any replica must 409 arms targeting
        its siblings — a second start_trace would crash the sibling's
        decode step mid-request."""
        import time as _time

        from dalle_pytorch_tpu.serve.server import InferenceServer
        params, vae_params = bundle
        srv = InferenceServer(params, vae_params, CFG, num_slots=2,
                              chunk_steps=4, decode_images=False,
                              replicas=2,
                              profile_dir=str(tmp_path / "prof"))
        srv.start()
        try:
            deadline = _time.perf_counter() + 120.0
            while _time.perf_counter() < deadline:
                if all(r.engine is not None
                       for r in srv.engine.replicas):
                    break
                _time.sleep(0.05)
            rec = srv.profile(replica=0)
            assert rec["kind"] == "serve_profile_armed"
            with pytest.raises(ProfileError) as ei:
                srv.profile(replica=1)
            assert ei.value.record["reason"] == "capture_active"
            assert ei.value.record["replica"] == 0
        finally:
            srv.close()

    def test_profile_without_dir_typed_reject(self, bundle):
        from dalle_pytorch_tpu.serve.server import InferenceServer
        params, vae_params = bundle
        srv = InferenceServer(params, vae_params, CFG, num_slots=2,
                              decode_images=False)
        try:
            with pytest.raises(ProfileError) as ei:
                srv.profile()
            assert ei.value.record["reason"] == "no_profile_dir"
        finally:
            srv.close()
