"""Multi-host backend test: a REAL two-process jax.distributed cluster on
localhost CPU (the standard stand-in for a multi-host pod, same shape as the
virtual-device mesh tests but with actual cross-process collectives).

Each subprocess exposes 2 virtual CPU devices -> a 4-device global mesh
over 2 processes; the test runs a global-sum over a dp-sharded array whose
shards live on DIFFERENT processes, so the psum crosses the process
boundary through the distributed runtime.
"""

import os
import socket
import subprocess
import sys

_WORKER = r"""
import sys
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from dalle_pytorch_tpu.parallel import make_mesh
from dalle_pytorch_tpu.parallel.multihost import initialize, is_primary

port, pid = sys.argv[1], int(sys.argv[2])
assert initialize(coordinator_address=f"127.0.0.1:{port}",
                  num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, jax.devices()
assert is_primary() == (pid == 0)

mesh = make_mesh({"dp": 4})
sharding = NamedSharding(mesh, P("dp"))
# each process contributes DIFFERENT local data: process p holds 2 elements
# of value p+1 -> global array [1,1,2,2], sum 6
local = np.full((2,), pid + 1, np.float32)
arr = jax.make_array_from_process_local_data(sharding, local, (4,))
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(arr)
print(f"RESULT {float(total)}", flush=True)

# the CLI data path: shard_batch assembles per-host LOCAL batches into the
# global batch, and one sharded train step crosses the process boundary
import optax
from dalle_pytorch_tpu.parallel import shard_batch
from dalle_pytorch_tpu.parallel.train import make_train_step, setup_sharded

params = {"w": jnp.full((2,), 2.0)}
opt = optax.sgd(0.1)
params, opt_state = setup_sharded(params, opt, mesh)
step = make_train_step(
    lambda p, b, r: jnp.mean(jnp.sum(b["x"] * p["w"], -1)), opt)
batch = shard_batch(mesh, {"x": np.full((2, 2), pid + 1.0, np.float32)})
# global batch rows: [1,1],[1,1],[2,2],[2,2]; row sums x w=2 -> [4,4,8,8]
params, opt_state, loss = step(params, opt_state, batch,
                               jax.random.PRNGKey(0))
print(f"RESULT2 {float(loss)}", flush=True)    # mean = 6.0

# checkpoint gate: both processes call save; only process 0 writes. The
# collective after the save is a barrier: process 0's (synchronous) write
# is complete before process 1 can pass it and check the directory.
import os
from dalle_pytorch_tpu import checkpoint as ckpt
path = os.path.join(sys.argv[3], "mh-ckpt")
ckpt.save(path, jax.device_get(params), step=1)
float(jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(arr))
print(f"RESULT3 {os.path.isdir(path)}", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


import jax
import pytest


@pytest.mark.skipif(not hasattr(jax, "shard_map"),
                    reason="two-process jax.distributed cluster needs the "
                           "jax>=0.8 runtime this code targets; the 0.4.x "
                           "fallback (parallel/_compat.py) covers "
                           "single-process paths only")
def test_two_process_cluster_global_sum(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)     # never touch the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen([sys.executable, "-c", _WORKER, str(port), str(p),
                          str(tmp_path)],
                         cwd=repo, env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for p in range(2)
    ]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"
        assert "RESULT 6.0" in out, out
        assert "RESULT2 6.0" in out, out
        assert "RESULT3 True" in out, out
    # the checkpoint was written exactly once (no .ckpt-tmp- residue from a
    # second racing writer)
    residue = [d for d in os.listdir(tmp_path) if d.startswith(".ckpt-tmp-")]
    assert not residue, residue
