"""MoE feed-forward: routing exactness, capacity semantics, ep sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.ops import core
from dalle_pytorch_tpu.ops.moe import (MoEConfig, moe_apply, moe_init,
                                       moe_param_specs)


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


def test_shapes_and_aux(key):
    cfg = MoEConfig(dim=16, num_experts=4, k=2)
    params = moe_init(key, cfg)
    x = jax.random.normal(key, (2, 12, 16))
    out, aux = jax.jit(lambda p, x: moe_apply(p, x, cfg=cfg))(params, x)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()
    assert aux.shape == () and float(aux) > 0


def test_single_expert_equals_plain_geglu(key):
    """E=1, k=1, ample capacity: routing is the identity, so the layer must
    equal the plain GEGLU FF with the same weights and unit gate."""
    cfg = MoEConfig(dim=8, num_experts=1, k=1, capacity_factor=2.0)
    params = moe_init(key, cfg)
    x = jax.random.normal(key, (2, 6, 8))
    out, _ = moe_apply(params, x, cfg=cfg)

    h = jnp.einsum("bnd,df->bnf", x, params["w1"][0])
    h, gates = jnp.split(h, 2, axis=-1)
    ref = jnp.einsum("bnf,fd->bnd", h * core.gelu(gates), params["w2"][0])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_capacity_drops_to_zero(key):
    """With capacity far below the load, overflow tokens contribute zero
    (Switch graceful-overflow: the residual path carries them)."""
    cfg = MoEConfig(dim=8, num_experts=2, k=1, capacity_factor=0.01)
    params = moe_init(key, cfg)
    x = jax.random.normal(key, (1, 16, 8))
    out, _ = moe_apply(params, x, cfg=cfg)
    # capacity floors at 1 per expert -> between 1 and 2 nonzero rows (a
    # zero-width queue that silently zeroes EVERY token is the bug class
    # this guards against)
    nonzero_rows = (np.abs(np.asarray(out[0])).sum(-1) > 1e-7).sum()
    assert 1 <= nonzero_rows <= 2


def test_k_exceeding_experts_rejected():
    with pytest.raises(ValueError, match="exceeds"):
        MoEConfig(dim=8, num_experts=1, k=2)


def test_gradients_finite(key):
    cfg = MoEConfig(dim=8, num_experts=4, k=2)
    params = moe_init(key, cfg)
    x = jax.random.normal(key, (2, 8, 8))

    def loss(p):
        out, aux = moe_apply(p, x, cfg=cfg)
        return (out ** 2).sum() + 1e-2 * aux

    g = jax.grad(loss)(params)
    for leaf in jax.tree.leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()
    # the router must receive gradient (through gates and aux)
    assert float(jnp.abs(g["router"]["w"]).sum()) > 0


def test_ep_sharded_matches_unsharded(key):
    """Experts sharded over an ep axis via GSPMD: same numbers as the
    unsharded layer."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    cfg = MoEConfig(dim=16, num_experts=8, k=2)
    params = moe_init(key, cfg)
    x = jax.random.normal(key, (2, 16, 16))
    ref, aux_ref = moe_apply(params, x, cfg=cfg)

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("ep",))
    specs = moe_param_specs("ep")
    sharded = jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), params,
        specs, is_leaf=lambda v: isinstance(v, P))
    out, aux = jax.jit(lambda p, x: moe_apply(p, x, cfg=cfg))(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


def test_bf16(key):
    cfg = MoEConfig(dim=16, num_experts=4, k=2)
    params = moe_init(key, cfg, dtype=jnp.bfloat16)
    x = jax.random.normal(key, (2, 8, 16), jnp.bfloat16)
    out, aux = moe_apply(params, x, cfg=cfg)
    assert out.dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(out, dtype=np.float32)).all()


def test_transformer_stack_with_moe(key):
    """MoE FF inside the scanned stack: aux accumulates over depth, grads
    finite, eval path (with_aux=False) returns activations only."""
    import dataclasses
    from dalle_pytorch_tpu.ops.transformer import (TransformerConfig,
                                                   transformer_apply,
                                                   transformer_init)
    cfg = TransformerConfig(dim=16, depth=3, seq_len=8, heads=2, dim_head=8,
                            moe_experts=4, moe_k=2)
    params = transformer_init(key, cfg)
    x = jax.random.normal(key, (2, 8, 16))
    out, aux = transformer_apply(params, x, cfg=cfg, with_aux=True)
    assert out.shape == x.shape and float(aux) > 0
    y = transformer_apply(params, x, cfg=cfg)           # no-aux call
    np.testing.assert_array_equal(np.asarray(y), np.asarray(out))

    g = jax.grad(lambda p: transformer_apply(
        p, x, cfg=cfg, with_aux=True)[1])(params)
    router_g = g["ff"]["moe"]["router"]["w"]
    assert float(jnp.abs(router_g).sum()) > 0

    # reversible + moe is rejected loudly
    with pytest.raises(ValueError, match="reversible"):
        transformer_apply(params, x, cfg=dataclasses.replace(
            cfg, reversible=True))


def test_dalle_moe_loss_and_generation(key):
    """MoE DALLE: training loss includes the aux term, and the KV-cache
    sampler decodes through the MoE FF (the user-facing train->generate
    journey)."""
    from dalle_pytorch_tpu.models import dalle as D
    from dalle_pytorch_tpu.models import vae as V
    vcfg = V.VAEConfig(image_size=16, num_tokens=12, codebook_dim=16,
                       num_layers=2, hidden_dim=8)
    cfg = D.DALLEConfig(dim=16, depth=2, vae=vcfg, num_text_tokens=20,
                        text_seq_len=8, heads=4, dim_head=4, moe_experts=4)
    params = D.dalle_init(key, cfg)
    vae_params = V.vae_init(jax.random.PRNGKey(9), vcfg)
    text = jax.random.randint(jax.random.fold_in(key, 1), (2, 8), 0, 20)
    image = jax.random.randint(jax.random.fold_in(key, 2), (2, 16), 0, 12)
    loss = D.dalle_apply(params, text, image, cfg=cfg, return_loss=True)
    assert np.isfinite(float(loss))

    # aux really participates: zero coef changes the loss
    import dataclasses
    cfg0 = dataclasses.replace(cfg, moe_aux_coef=0.0)
    loss0 = D.dalle_apply(params, text, image, cfg=cfg0, return_loss=True)
    assert float(loss) != float(loss0)

    images = D.generate_images(params, vae_params, text, cfg=cfg,
                               rng=jax.random.PRNGKey(1))
    assert images.shape[0] == 2
    assert np.isfinite(np.asarray(images)).all()


def test_sp_rejects_moe_pp_accepts(key):
    """sp still excludes MoE (route tokens before sharding them); pp
    composes with it since r5 (aux threaded through the tick scan) — a
    pipelined MoE stack must match the single-device one."""
    from dalle_pytorch_tpu.ops.transformer import (TransformerConfig,
                                                   transformer_apply,
                                                   transformer_init)
    from dalle_pytorch_tpu.parallel import (make_mesh, pipeline_transformer,
                                            sp_transformer_apply)
    cfg = TransformerConfig(dim=16, depth=2, seq_len=16, heads=2, dim_head=8,
                            moe_experts=4)
    params = transformer_init(key, cfg)
    x = jax.random.normal(key, (2, 16, 16))
    mesh = make_mesh({"sp": 4}, jax.devices()[:4])
    with pytest.raises(ValueError, match="MoE"):
        sp_transformer_apply(params, x, cfg=cfg, mesh=mesh)
    mesh2 = make_mesh({"pp": 2}, jax.devices()[:2])
    y_pp, aux_pp = jax.jit(lambda p, x: pipeline_transformer(
        p, x, cfg=cfg, mesh=mesh2, with_aux=True))(params, x)
    y_ref, aux_ref = transformer_apply(params, x, cfg=cfg, with_aux=True)
    np.testing.assert_allclose(np.asarray(y_pp), np.asarray(y_ref),
                               atol=2e-5)
    np.testing.assert_allclose(float(aux_pp), float(aux_ref), rtol=1e-5)


def test_torch_export_rejects_moe(key):
    from dalle_pytorch_tpu.compat.torch_export import export_transformer
    from dalle_pytorch_tpu.ops.transformer import (TransformerConfig,
                                                   transformer_init)
    cfg = TransformerConfig(dim=16, depth=2, seq_len=8, heads=2, dim_head=8,
                            moe_experts=4)
    params = transformer_init(key, cfg)
    with pytest.raises(ValueError, match="MoE"):
        export_transformer(params)
