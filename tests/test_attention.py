"""Attention semantics tests — the behavioral contracts from SURVEY.md §5.

The dense path must reproduce the reference Attention
(/root/reference/dalle_pytorch/transformer.py:51-89): dim**-0.5 scale,
pair pad-mask, strict-upper-triangle causal mask. Verified directly against a
torch re-derivation on identical weights.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.ops import attention as A
from dalle_pytorch_tpu.ops import sparse


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


def _apply(params, x, mask=None, causal=True, heads=2, dim_head=8, dim=16):
    return A.attention_apply(params, x, heads=heads, dim_head=dim_head,
                             scale=dim ** -0.5, causal=causal, mask=mask)


def test_causal_no_future_leak(key):
    """Changing a future token must not change earlier outputs."""
    dim, n = 16, 10
    params = A.attention_init(key, dim, 2, 8)
    x = jax.random.normal(key, (1, n, dim))
    y1 = _apply(params, x)
    x2 = x.at[0, -1].set(100.0)
    y2 = _apply(params, x2)
    np.testing.assert_allclose(y1[0, :-1], y2[0, :-1], atol=1e-5)
    assert not np.allclose(y1[0, -1], y2[0, -1])


def test_pad_mask_blocks_keys(key):
    """Masked keys must not influence unmasked queries."""
    dim, n = 16, 8
    params = A.attention_init(key, dim, 2, 8)
    x = jax.random.normal(key, (1, n, dim))
    mask = jnp.ones((1, n), bool).at[0, 5:].set(False)
    y1 = _apply(params, x, mask=mask, causal=False)
    x2 = x.at[0, 6].set(50.0)
    y2 = _apply(params, x2, mask=mask, causal=False)
    np.testing.assert_allclose(y1[0, :5], y2[0, :5], atol=1e-5)


def test_matches_torch_reference(key):
    """Bit-level semantics vs a torch reimplementation of the reference
    Attention.forward on the same weights.

    One documented deviation (see ops.flash_attention docstring): the causal
    mask uses -inf rather than the finite -fmax, so FULLY-PADDED rows
    average over their causal prefix instead of leaking future positions.
    The torch path below mirrors that (float('-inf') for the causal fill);
    valid rows are unaffected either way."""
    torch = pytest.importorskip("torch")
    dim, heads, dim_head, n, b = 16, 2, 8, 12, 2
    params = A.attention_init(key, dim, heads, dim_head)
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(1), (b, n, dim)),
                   dtype=np.float32)
    mask_np = np.ones((b, n), bool)
    mask_np[:, n - 3:] = False

    y = A.attention_apply(params, jnp.asarray(x), heads=heads,
                          dim_head=dim_head, scale=dim ** -0.5, causal=True,
                          mask=jnp.asarray(mask_np))

    # torch reference path (transformer.py:66-89)
    xt = torch.tensor(x)
    w_qkv = torch.tensor(np.array(params["qkv"]["w"]))
    w_out = torch.tensor(np.array(params["out"]["w"]))
    b_out = torch.tensor(np.array(params["out"]["b"]))
    qkv = xt @ w_qkv
    q, k, v = qkv.chunk(3, dim=-1)
    reshape = lambda t: t.view(b, n, heads, dim_head).transpose(1, 2)
    q, k, v = map(reshape, (q, k, v))
    dots = torch.einsum("bhid,bhjd->bhij", q, k) * (dim ** -0.5)
    mask_value = -torch.finfo(dots.dtype).max
    mt = torch.tensor(mask_np)
    pair = mt[:, None, :, None] * mt[:, None, None, :]
    dots.masked_fill_(~pair, mask_value)
    causal = torch.ones(n, n).triu_(1).bool()
    dots.masked_fill_(causal, float("-inf"))
    attn = dots.softmax(dim=-1)
    out = torch.einsum("bhij,bhjd->bhid", attn, v)
    out = out.transpose(1, 2).reshape(b, n, heads * dim_head)
    out = out @ w_out + b_out

    np.testing.assert_allclose(np.array(y), out.numpy(), atol=2e-5)


def test_sparse_layout_structure():
    """VariableSparsityConfig-equivalent layout: local windows + global block 0
    + causal (SURVEY.md §2a row 1)."""
    L = sparse.variable_sparsity_layout(8, num_local_blocks=4,
                                        global_blocks=(0,), causal=True)
    # causal: no block above diagonal
    assert not np.triu(L, 1).any()
    # global column 0 fully attended (causally)
    assert L[:, 0].all()
    # block 5 (window [4..7]) sees 4,5 and global 0, not 1..3
    assert L[5, 4] and L[5, 5] and L[5, 0]
    assert not L[5, 1] and not L[5, 2] and not L[5, 3]


def test_sparse_ref_subset_of_dense(key):
    """With layout all-True (window >= seq blocks), sparse ref == dense."""
    dim, heads, dim_head, n = 16, 2, 8, 32
    params = A.attention_init(key, dim, heads, dim_head)
    x = jax.random.normal(key, (2, n, dim))
    q, k, v = A.qkv_project(params, x, heads)
    out_sparse = sparse.sparse_attention_ref(
        q, k, v, scale=dim ** -0.5, causal=True, block=16,
        num_local_blocks=2, global_blocks=(0,))  # 2 blocks = whole seq window
    dense = A.dense_attention_weights(q, k, dim ** -0.5, None, True)
    out_dense = jnp.einsum("bhij,bhjd->bhid", dense, v)
    np.testing.assert_allclose(np.array(out_sparse), np.array(out_dense),
                               atol=1e-5)


def test_sparse_ref_causal(key):
    dim, heads, dim_head, n = 16, 2, 8, 64
    params = A.attention_init(key, dim, heads, dim_head)
    x = jax.random.normal(key, (1, n, dim))
    q, k, v = A.qkv_project(params, x, heads)
    y1 = sparse.sparse_attention_ref(q, k, v, scale=dim ** -0.5, causal=True)
    x2 = x.at[0, -1].set(99.0)
    q2, k2, v2 = A.qkv_project(params, x2, heads)
    y2 = sparse.sparse_attention_ref(q2, k2, v2, scale=dim ** -0.5, causal=True)
    np.testing.assert_allclose(np.array(y1[0, :, :-1]), np.array(y2[0, :, :-1]),
                               atol=1e-5)
