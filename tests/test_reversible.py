"""Reversible engine tests: inversion-based backward == plain autodiff.

The reference's implicit invariant (SURVEY.md §4c): the memory-saving custom
backward must produce the same gradients as ordinary autodiff through the
same two-stream forward. Plus the behavioral contracts: stream duplication on
input, mean of streams on output (reference reversible.py:150,157).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.ops import transformer as T
from dalle_pytorch_tpu.ops.transformer import (TransformerConfig,
                                               transformer_apply,
                                               transformer_init)

CFG = TransformerConfig(dim=32, depth=3, seq_len=16, heads=2, dim_head=16,
                        reversible=True)


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


def plain_reversible_forward(params, x, cfg, mask=None):
    """The same two-stream computation, written without custom_vjp, as the
    autodiff oracle."""
    x1 = x2 = x
    for i in range(cfg.depth):
        lp = jax.tree.map(lambda a: a[i], params)
        y1 = x1 + T.attn_branch(lp, x2, mask, cfg, False, None, False)
        y2 = x2 + T.ff_branch(lp, y1, cfg, None, False)
        x1, x2 = y1, y2
    return (x1 + x2) * 0.5


def test_forward_matches_plain(key):
    params = transformer_init(key, CFG)
    x = jax.random.normal(key, (2, 16, 32))
    mask = jnp.ones((2, 16), bool).at[:, 12:].set(False)
    y = transformer_apply(params, x, cfg=CFG, mask=mask)
    y_ref = plain_reversible_forward(params, x, CFG, mask)
    np.testing.assert_allclose(np.array(y), np.array(y_ref), atol=1e-5)


def test_gradients_match_plain_autodiff(key):
    params = transformer_init(key, CFG)
    x = jax.random.normal(key, (2, 16, 32))
    mask = jnp.ones((2, 16), bool).at[:, 10:].set(False)

    def loss_rev(p, x):
        return jnp.sum(transformer_apply(p, x, cfg=CFG, mask=mask) ** 2)

    def loss_plain(p, x):
        return jnp.sum(plain_reversible_forward(p, x, CFG, mask) ** 2)

    (l1, (gp1, gx1)) = jax.value_and_grad(loss_rev, argnums=(0, 1))(params, x)
    (l2, (gp2, gx2)) = jax.value_and_grad(loss_plain, argnums=(0, 1))(params,
                                                                      x)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    np.testing.assert_allclose(np.array(gx1), np.array(gx2), atol=1e-4)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.array(a), np.array(b), atol=1e-4), gp1, gp2)


def test_gradients_under_jit(key):
    params = transformer_init(key, CFG)
    x = jax.random.normal(key, (1, 16, 32))

    def loss(p):
        return jnp.sum(transformer_apply(p, x, cfg=CFG) ** 2)

    g_eager = jax.grad(loss)(params)
    g_jit = jax.jit(jax.grad(loss))(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.array(a), np.array(b), atol=1e-5), g_eager, g_jit)


def test_dropout_replays_identically(key):
    """Dropout gradients through the inversion-based backward must match
    plain autodiff of the same two-stream forward with the SAME per-layer
    keys — i.e. the recompute pass replays the forward's dropout masks (the
    property the reference needs CUDA RNG snapshots for, reference
    reversible.py:20-50; free with stateless keys, but only if the backward
    routes the keys correctly)."""
    cfg = TransformerConfig(dim=32, depth=2, seq_len=16, heads=2, dim_head=16,
                            reversible=True, attn_dropout=0.3, ff_dropout=0.3)
    params = transformer_init(key, cfg)
    x = jax.random.normal(key, (1, 16, 32))
    r = jax.random.PRNGKey(3)
    keys = T._layer_keys(r, cfg.depth)

    def plain_loss(p):
        x1 = x2 = x
        for i in range(cfg.depth):
            lp = jax.tree.map(lambda a: a[i], p)
            y1 = x1 + T.attn_branch(lp, x2, None, cfg, False, keys[i, 0],
                                    True)
            y2 = x2 + T.ff_branch(lp, y1, cfg, keys[i, 1], True)
            x1, x2 = y1, y2
        return jnp.sum(((x1 + x2) * 0.5) ** 2)

    def rev_loss(p):
        return jnp.sum(
            transformer_apply(p, x, cfg=cfg, rng=r, train=True) ** 2)

    g_rev = jax.grad(rev_loss)(params)
    g_plain = jax.grad(plain_loss)(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.array(a), np.array(b), atol=1e-4), g_rev, g_plain)


def test_reversible_with_sparse_pattern(key):
    cfg = TransformerConfig(dim=32, depth=4, seq_len=32, heads=2, dim_head=16,
                            reversible=True,
                            sparse_attn=(True, False, True, False))
    params = transformer_init(key, cfg)
    x = jax.random.normal(key, (1, 32, 32))

    def loss(p):
        return jnp.sum(transformer_apply(p, x, cfg=cfg) ** 2)

    l, g = jax.value_and_grad(loss)(params)
    assert np.isfinite(float(l))
    finite = jax.tree.map(lambda a: np.isfinite(np.array(a)).all(), g)
    assert all(jax.tree.leaves(finite))


def test_memory_contract_no_per_layer_residuals(key):
    """Structural check: the vjp of the reversible stack should not stash a
    per-depth stack of (b, n, dim) activations. We verify the saved residuals
    contain no array with a leading depth*batch*seq*dim footprint beyond the
    stacked params + final streams + keys."""
    params = transformer_init(key, CFG)
    x = jax.random.normal(key, (2, 16, 32))
    _, vjp_fn = jax.vjp(
        lambda p, x: transformer_apply(p, x, cfg=CFG), params, x)
    leaves = [a for a in jax.tree.leaves(vjp_fn) if hasattr(a, "size")]
    b, n = x.shape[0], x.shape[1]
    # any leaf as big as a depth-stacked activation (regardless of layout)
    # that is not one of the stacked parameter tensors is a stash
    param_sizes = {a.size for a in jax.tree.leaves(params)}
    act_size = CFG.depth * b * n * CFG.dim
    act_like = [a for a in leaves
                if a.size >= act_size and a.size not in param_sizes]
    assert not act_like, f"found per-layer activation stash: " \
                         f"{[a.shape for a in act_like]}"


def test_unrolled_and_cond_paths_agree(key, monkeypatch):
    """The static-unroll and traced lax.cond paths of the reversible engine
    compute the same loss and gradients for the same periodic pattern."""
    from dalle_pytorch_tpu.ops import transformer as T

    cfg = TransformerConfig(dim=32, depth=4, seq_len=32, heads=2, dim_head=16,
                            reversible=True,
                            sparse_attn=(True, False, True, False))
    params = transformer_init(key, cfg)
    x = jax.random.normal(key, (1, 32, 32))

    def loss(p):
        return jnp.sum(transformer_apply(p, x, cfg=cfg) ** 2)

    l_unroll, g_unroll = jax.value_and_grad(loss)(params)
    monkeypatch.setattr(T, "_MAX_UNROLL_PERIOD", 0)   # force cond fallback
    l_cond, g_cond = jax.value_and_grad(loss)(params)

    np.testing.assert_allclose(float(l_unroll), float(l_cond), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_unroll), jax.tree.leaves(g_cond)):
        np.testing.assert_allclose(np.array(a), np.array(b), atol=1e-4)
