"""Serving subsystem tests (ISSUE 2 + ISSUE 4 acceptance criteria).

The load-bearing one is equivalence: for the same params/prompt/seed/
sampling knobs, the slot-batched engine's emitted image tokens are
IDENTICAL to ``models.dalle.generate_images`` at batch 1 — including
requests that join mid-stream while other slots are mid-decode, different
prompt lengths, per-request temperature/top-k/top-p, and EVERY fused
chunk size K (the device-resident loop only changes where the host reads
the stream, never what the device computes). Plus the structured-
backpressure contract (queue-full and deadline-exceeded are typed results,
no hangs, no silent drops) and the compile/transfer contracts: the fused
decode program traces exactly once across a multi-request run, each
prefill BUCKET traces exactly once for the engine's life, and the whole
steady-state iteration — chunk dispatch, double-buffered emit-ring
harvest, and a mid-stream join — holds under
``analysis.guards.no_transfers()``.

All CPU, tiny model (total_len 24) so the whole file stays cheap inside
tier-1.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.analysis import guards
from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V
from dalle_pytorch_tpu.serve import (DEADLINE_EXCEEDED, ERROR, OK,
                                     InvalidRequest, PageAllocator,
                                     PagePoolExhausted, QueueClosed,
                                     QueueFull, Request, RequestQueue,
                                     SamplingParams, bucket_for,
                                     prefill_buckets)
from dalle_pytorch_tpu.serve.engine import Engine

VCFG = V.VAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                   num_layers=2, hidden_dim=8)
CFG = D.DALLEConfig(dim=16, depth=2, vae=VCFG, num_text_tokens=50,
                    text_seq_len=8, heads=2, dim_head=8)


@pytest.fixture(scope="module")
def bundle():
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG)
    params = D.dalle_init(key, CFG, vae_params)
    return params, vae_params


_REF_CACHE: dict = {}


def reference_tokens(params, vae_params, req: Request) -> np.ndarray:
    """generate_images at batch 1 — the one-shot path the engine must
    reproduce token-for-token. Memoized on the request's sampling
    identity (params are the module-scoped ``bundle`` everywhere): many
    tests check the same three REQS, and each uncached call costs a
    generate_images run, which is most of this file's tier-1 time."""
    key = (req.codes, req.seed, req.sampling.temperature,
           req.sampling.filter_thres, req.sampling.top_p)
    if key not in _REF_CACHE:
        text = jnp.asarray([req.codes], jnp.int32)
        _, img_seq = D.generate_images(
            params, vae_params, text, cfg=CFG,
            rng=jax.random.PRNGKey(req.seed),
            filter_thres=req.sampling.filter_thres,
            top_p=req.sampling.top_p,
            temperature=req.sampling.temperature, return_img_seq=True)
        _REF_CACHE[key] = np.asarray(img_seq)[0]
    return _REF_CACHE[key]


def reference_tokens_int8(params, vae_params, req: Request) -> np.ndarray:
    """Memoized generate_images(quantize_cache=True) reference — shared
    by the dense and paged int8-KV equivalence tests (identical
    one-shot side, ~one generate_images run saved per extra caller)."""
    key = ("int8", req.codes, req.seed)
    if key not in _REF_CACHE:
        text = jnp.asarray([req.codes], jnp.int32)
        _, img_seq = D.generate_images(
            params, vae_params, text, cfg=CFG,
            rng=jax.random.PRNGKey(req.seed), return_img_seq=True,
            quantize_cache=True)
        _REF_CACHE[key] = np.asarray(img_seq)[0]
    return _REF_CACHE[key]


REQS = [
    Request(codes=(3, 7, 9), seed=11),
    Request(codes=(5, 2, 8, 1, 4), seed=23,
            sampling=SamplingParams(temperature=0.7, filter_thres=0.8)),
    Request(codes=(6, 6), seed=5,
            sampling=SamplingParams(temperature=1.3, top_p=0.9)),
]


class TestEquivalence:
    def test_tokens_identical_to_generate_images(self, bundle):
        """3 requests (different prompt lengths / temperatures / top-k /
        top-p) through a 2-slot pool: more requests than slots, so slots
        are reused (leave + join) — every emitted image-token sequence
        must equal the one-shot sampler's, and the decode program must
        have compiled exactly once."""
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r) for r in REQS]

        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2)
        handles = [queue.submit(r) for r in REQS]
        # the shared guard (analysis.guards — same one bench_serve runs
        # under): a recompiling decode step fails tier-1, not just bench
        with guards.compile_count(lambda: engine.decode_traces, expect=1,
                                  label="serve decode program"):
            engine.run_until_idle()

        for h, ref in zip(handles, refs):
            res = h.result(timeout=5)
            assert res.status == OK
            np.testing.assert_array_equal(np.asarray(res.tokens), ref)
            assert res.total_s > 0 and res.decode_s > 0
        # prefill compiles once per BUCKET admission padded into, never
        # per request or per distinct prompt length
        used = {bucket_for(len(r.codes), engine.buckets) for r in REQS}
        assert engine.prefill_traces == len(used)
        for b in used:
            assert engine.prefill_trace_count(b) == 1

    def test_steady_state_decode_is_transfer_clean(self, bundle):
        """Full K-step chunks — dispatch, double-buffered emit-ring
        harvest, AND a mid-chunk slot join (admission prefill + the
        device-side state merge) — run under ``guards.no_transfers()``:
        per-slot decode state never leaves the device, every crossing is
        an explicit device_put/device_get at its site (there is no
        per-step allowance left to waive), and the guard must not
        perturb the token stream. Each prefill bucket compiles exactly
        once for the engine's LIFE (the guards.compile_count contract),
        even though both buckets admit twice."""
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r)
                for r in REQS[:2]]
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=4)
        b0 = bucket_for(len(REQS[0].codes), engine.buckets)
        b1 = bucket_for(len(REQS[1].codes), engine.buckets)
        assert b0 != b1             # the join exercises a SECOND bucket
        with guards.compile_count(
                lambda: engine.prefill_trace_count(b0), expect=1,
                label=f"prefill bucket {b0}"), \
            guards.compile_count(
                lambda: engine.prefill_trace_count(b1), expect=1,
                label=f"prefill bucket {b1}"):
            # warm run: compiles the fused decode program + both buckets
            for r in REQS[:2]:
                queue.submit(r)
            engine.run_until_idle()
            # steady state, transfer-guarded: a runs, b joins mid-stream
            h_a = queue.submit(REQS[0])
            engine.step_once()      # a admitted, chunk 1 in flight
            with guards.no_transfers():
                h_b = queue.submit(REQS[1])
                engine.step_once()  # join + chunk 2 + harvest of chunk 1
                engine.step_once()  # pure steady-state chunk
            engine.run_until_idle()
        np.testing.assert_array_equal(
            np.asarray(h_a.result(timeout=5).tokens), refs[0])
        np.testing.assert_array_equal(
            np.asarray(h_b.result(timeout=5).tokens), refs[1])
        assert engine.decode_traces == 1

    @pytest.mark.parametrize("k", [1, 32])
    def test_tokens_identical_across_chunk_sizes(self, bundle, k):
        """The fused chunk size K must not change a single emitted token
        — K only moves the host read boundary. K=1 degenerates to the
        old per-step engine, K=32 covers a whole request in one chunk
        (every slot finishes into the dead mask mid-chunk); the default
        K=8 mid-chunk-boundary case is every other test in the file."""
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r) for r in REQS]
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=k)
        handles = [queue.submit(r) for r in REQS]
        engine.run_until_idle()
        for h, ref in zip(handles, refs):
            np.testing.assert_array_equal(
                np.asarray(h.result(timeout=5).tokens), ref)
        assert engine.decode_traces == 1

    def test_fulfillment_timestamped_at_harvest(self, bundle):
        """A request that emits its last token mid-chunk becomes
        observable only when the emit ring lands on the host (one chunk
        later, double-buffered) — its recorded latency must be the
        harvest-time, caller-observed number, not the in-chunk finish
        (docs/SERVING.md 'Choosing K')."""
        params, _ = bundle

        class Clock:
            t = 0.0

            def __call__(self):
                return self.t

        clock = Clock()
        queue = RequestQueue(max_depth=4, clock=clock)
        engine = Engine(params, CFG, queue, num_slots=1, chunk_steps=64,
                        clock=clock)
        h = queue.submit(REQS[0])       # submit_t = 0.0
        engine.step_once()              # one 64-step chunk covers the
        #                                 whole sequence: finished ON
        #                                 DEVICE, but not yet harvested
        assert not h.done()
        clock.t = 5.0
        engine.step_once()              # harvest lands the ring NOW
        res = h.result(timeout=5)
        assert res.status == OK
        assert res.total_s == 5.0       # caller-observed harvest time
        assert res.decode_s == 5.0

    def test_join_midstream_does_not_perturb_running_slot(self, bundle):
        """A request admitted while another slot is mid-decode (the
        continuous-batching join) must not change either slot's tokens."""
        params, vae_params = bundle
        r_a, r_b = REQS[0], REQS[1]
        ref_a = reference_tokens(params, vae_params, r_a)
        ref_b = reference_tokens(params, vae_params, r_b)

        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=2)
        h_a = queue.submit(r_a)
        for _ in range(3):                  # a is ~6 tokens into decode
            engine.step_once()
        assert engine.active_slots() == 1
        h_b = queue.submit(r_b)             # b joins mid-stream
        engine.run_until_idle()

        np.testing.assert_array_equal(
            np.asarray(h_a.result(timeout=5).tokens), ref_a)
        np.testing.assert_array_equal(
            np.asarray(h_b.result(timeout=5).tokens), ref_b)
        assert engine.decode_traces == 1

    def test_int8_kv_slot_cache_runs(self, bundle):
        """quantize_cache composes with the slot pool: the engine matches
        generate_images(quantize_cache=True) token-for-token (both sides
        quantize rows the same way, ops.decode._store_rows)."""
        params, vae_params = bundle
        req = REQS[0]
        ref = reference_tokens_int8(params, vae_params, req)
        queue = RequestQueue(max_depth=4)
        engine = Engine(params, CFG, queue, num_slots=2,
                        quantize_cache=True)
        h = queue.submit(req)
        engine.run_until_idle()
        np.testing.assert_array_equal(np.asarray(h.result(5).tokens),
                                      ref)


class TestPagedKV:
    """The paged KV-cache subsystem (serve/kv_pool.py +
    ops.decode.decode_loop_paged): block-pool memory manager, paged
    decode path, and the PagePoolExhausted eviction/requeue
    backpressure. The load-bearing contract is the same as dense —
    token-for-token equality with ``generate_images`` at batch 1 — plus
    page accounting (allocate on admission, grow across page boundaries,
    free on completion) and the compile/transfer discipline unchanged:
    ONE decode trace for the engine's life and a transfer-clean steady
    state (block-table growth is an explicit device_put)."""

    @pytest.mark.parametrize("k", [1, 8, 32])
    def test_paged_tokens_identical_across_chunk_sizes(self, bundle, k):
        """Paged-vs-dense token-exact equivalence for K in {1, 8, 32}:
        more requests than slots (slot reuse), mixed prompt lengths /
        temperatures / top-k / top-p, page_size 4 so every request
        crosses several page boundaries mid-stream — and the fused
        paged decode program compiles exactly once."""
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r) for r in REQS]
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=k,
                        kv="paged", page_size=4)
        handles = [queue.submit(r) for r in REQS]
        with guards.compile_count(lambda: engine.decode_traces, expect=1,
                                  label="paged decode program"):
            engine.run_until_idle()
        for h, ref in zip(handles, refs):
            res = h.result(timeout=5)
            assert res.status == OK
            np.testing.assert_array_equal(np.asarray(res.tokens), ref)
        # every page returned to the pool once the engine drained
        assert engine.alloc.in_use == 0
        assert engine.alloc.peak_in_use > 0

    def test_paged_int8_kv_tokens_identical(self, bundle):
        """int8-KV composes with paging: the paged int8 pool matches
        generate_images(quantize_cache=True) token-for-token (same
        _quantize_rows, same scale discipline, per page)."""
        params, vae_params = bundle
        req = REQS[0]
        ref = reference_tokens_int8(params, vae_params, req)
        queue = RequestQueue(max_depth=4)
        engine = Engine(params, CFG, queue, num_slots=2, kv="paged",
                        page_size=4, quantize_cache=True)
        h = queue.submit(req)
        engine.run_until_idle()
        np.testing.assert_array_equal(np.asarray(h.result(5).tokens),
                                      ref)

    def test_paged_steady_state_transfer_clean_midstream_join(self,
                                                              bundle):
        """The dense engine's transfer-discipline test, on the paged
        path: full chunks, double-buffered harvest, AND a mid-stream
        join (paged prefill + block-table update + page growth across a
        boundary) under ``guards.no_transfers()`` — the only paged-
        specific crossing is the explicit block-table device_put."""
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r)
                for r in REQS[:2]]
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=4,
                        kv="paged", page_size=4)
        for r in REQS[:2]:              # warm: compile decode + buckets
            queue.submit(r)
        engine.run_until_idle()
        h_a = queue.submit(REQS[0])
        engine.step_once()              # a admitted, chunk 1 in flight
        with guards.no_transfers():
            h_b = queue.submit(REQS[1])
            engine.step_once()          # join + chunk 2 + harvest 1
            engine.step_once()          # pure steady-state chunk
        engine.run_until_idle()
        np.testing.assert_array_equal(
            np.asarray(h_a.result(timeout=5).tokens), refs[0])
        np.testing.assert_array_equal(
            np.asarray(h_b.result(timeout=5).tokens), refs[1])
        assert engine.decode_traces == 1

    def test_eviction_victim_completes_after_readmission(self, bundle):
        """The PagePoolExhausted backpressure path end-to-end: a pool
        too small for the offered concurrency must EVICT the lowest-
        priority active request back to the queue (pages freed, handle
        re-queued, never dropped) — and the victim must still complete
        with the exact one-shot token stream after re-admission
        (deterministic sampling replays it). The higher-priority
        requests' streams must be untouched by the churn."""
        params, vae_params = bundle
        # REQS[1] made lowest priority (highest value) -> the victim
        reqs = [REQS[0],
                Request(codes=REQS[1].codes, seed=REQS[1].seed,
                        sampling=REQS[1].sampling, priority=7),
                REQS[2]]
        refs = [reference_tokens(params, vae_params, r) for r in reqs]
        queue = RequestQueue(max_depth=8)
        # seq 24 at page_size 4 = 6 pages/request; 8 usable pages with
        # 2 slots is a genuine overcommit: two mid-sequence requests
        # need up to 12
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=4,
                        kv="paged", page_size=4, num_pages=9)
        handles = [queue.submit(r) for r in reqs]
        with guards.compile_count(lambda: engine.decode_traces, expect=1,
                                  label="paged decode under eviction"):
            engine.run_until_idle()
        assert engine.evicted >= 1, "pool was sized to force eviction"
        assert queue.requeued >= 1
        for h, ref in zip(handles, refs):
            res = h.result(timeout=5)
            assert res.status == OK
            np.testing.assert_array_equal(np.asarray(res.tokens), ref)
        assert engine.alloc.in_use == 0
        # tokens_decoded counts DISTINCT delivered tokens: a victim's
        # harvested prefix is un-credited at eviction (its replay
        # re-credits every token), so the counter equals the per-request
        # decode spans exactly — no eviction inflation
        assert engine.tokens_decoded == sum(
            engine.total_len - len(r.codes) for r in reqs)

    def test_admission_gated_on_free_pages_not_slots(self, bundle):
        """With free slots but no free pages, admission is gated: the
        request WAITS in the queue (no per-chunk pop/defer/requeue churn
        — a dry pool means the engine doesn't pop at all) until
        completions free pages, then runs to the exact reference
        stream."""
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r)
                for r in REQS[:2]]
        queue = RequestQueue(max_depth=8)
        # exactly one full sequence of pages: the second request CANNOT
        # be admitted while the first holds the pool
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=24,
                        kv="paged", page_size=4, num_pages=7)
        h_a = queue.submit(REQS[0])
        engine.step_once()      # a admitted and mapped ahead (all pages)
        assert engine.alloc.free == 0
        h_b = queue.submit(REQS[1])
        engine.step_once()      # pool dry: b stays queued, un-popped
        assert queue.depth() == 1
        assert queue.requeued == 0              # no churn while waiting
        assert not h_b.done()                   # gated, not dropped
        engine.run_until_idle()
        np.testing.assert_array_equal(
            np.asarray(h_a.result(timeout=5).tokens), refs[0])
        np.testing.assert_array_equal(
            np.asarray(h_b.result(timeout=5).tokens), refs[1])

    def test_head_of_line_request_not_starved_by_smaller(self, bundle):
        """No-starvation: a page-deferred request at the head of the
        line RESERVES its page need — a later, smaller request must not
        be admitted past it on the pages freed for it (requeue preserves
        arrival order; the admission floor becomes the head's need)."""
        params, vae_params = bundle
        # b needs bucket 8 = 2 pages at admission; c (submitted AFTER b)
        # needs bucket 2 = 1 page
        reqs = [REQS[0],
                Request(codes=(4, 1, 2, 3, 5, 6, 7, 8), seed=31),
                REQS[2]]
        refs = [reference_tokens(params, vae_params, r) for r in reqs]
        queue = RequestQueue(max_depth=8)
        # capacity 7 pages at page_size 4 (6/full sequence): once a is
        # admitted and mapped ahead, exactly ONE page stays free
        engine = Engine(params, CFG, queue, num_slots=3, chunk_steps=24,
                        kv="paged", page_size=4, num_pages=8)
        h_a = queue.submit(reqs[0])
        engine.step_once()              # a admitted, mapped to the end
        assert engine.alloc.free == 1
        h_b = queue.submit(reqs[1])
        h_c = queue.submit(reqs[2])
        engine.step_once()
        # b cannot be mapped (needs 2) -> it AND c wait; the one free
        # page must NOT go to c even though c alone would fit (a may
        # have completed inside this same step — harvest runs after
        # admission — so only the head-of-line state is deterministic)
        assert not h_b.done() and not h_c.done()
        assert queue.depth() == 2
        assert engine._hol_rid == h_b.request.request_id
        assert engine._hol_need == 2
        engine.run_until_idle()
        for h, ref in zip([h_a, h_b, h_c], refs):
            res = h.result(timeout=5)
            assert res.status == OK
            np.testing.assert_array_equal(np.asarray(res.tokens), ref)
        assert engine.alloc.in_use == 0

    def test_pool_must_hold_one_full_sequence(self, bundle):
        params, _ = bundle
        with pytest.raises(ValueError, match="full sequence"):
            Engine(params, CFG, RequestQueue(max_depth=2), num_slots=1,
                   kv="paged", page_size=4, num_pages=4)

    def test_allocator_typed_exhaustion_and_reuse(self):
        alloc = PageAllocator(4)            # 3 usable + trash
        a = alloc.alloc(2)
        assert 0 not in a                   # trash page never handed out
        with pytest.raises(PagePoolExhausted) as ei:
            alloc.alloc(2)
        rec = ei.value.record
        assert rec["kind"] == "serve_page_exhausted"
        assert rec["pages_needed"] == 2 and rec["pages_free"] == 1
        alloc.release(a)
        assert alloc.free == 3
        assert alloc.peak_in_use == 2

    def test_allocator_double_release_is_hard_error(self):
        """A page freed twice would eventually be handed to TWO live
        slots (silent KV corruption) — the allocator fails at the bug's
        site instead."""
        alloc = PageAllocator(4)
        a = alloc.alloc(2)
        alloc.release(a)
        with pytest.raises(ValueError, match="double release"):
            alloc.release([a[0]])
        with pytest.raises(ValueError, match="never allocatable"):
            alloc.release([0])              # the trash page

    def test_paged_stats_surface(self, bundle):
        params, _ = bundle
        queue = RequestQueue(max_depth=4)
        engine = Engine(params, CFG, queue, num_slots=2, kv="paged",
                        page_size=4)
        queue.submit(REQS[0])
        engine.run_until_idle()
        stats = engine.stats()
        assert stats["kv"] == "paged"
        assert stats["page_size"] == 4
        assert stats["pages_in_use"] == 0           # drained
        assert stats["pages_peak"] >= 1
        assert stats["pages_in_use_p95"] >= 1
        assert stats["kv_hbm_bytes"] > 0
        # dense engines report layout + bytes too (bench compares them)
        dense = Engine(params, CFG, RequestQueue(max_depth=2),
                       num_slots=2)
        assert dense.stats()["kv"] == "dense"
        assert dense.stats()["kv_hbm_bytes"] > stats["kv_hbm_bytes"] / 2


class TestBucketedPrefill:
    """Prompt-length bucketing: admission pads prompts up to a small
    fixed set of lengths so prefill compiles once per bucket, ever —
    and padding must be invisible in the tokens (causality: rows and
    first-token logits depend only on positions < the true length)."""

    def test_default_buckets_are_powers_of_two_to_text_seq_len(self):
        assert prefill_buckets(8) == (1, 2, 4, 8)
        assert prefill_buckets(5) == (1, 2, 4, 5)
        assert prefill_buckets(1) == (1,)
        assert prefill_buckets(256)[-1] == 256

    def test_bucket_for_picks_smallest_holding_bucket(self):
        assert bucket_for(3, (1, 2, 4, 8)) == 4
        assert bucket_for(4, (1, 2, 4, 8)) == 4
        assert bucket_for(8, (1, 2, 4, 8)) == 8
        with pytest.raises(ValueError, match="largest bucket"):
            bucket_for(9, (1, 2, 4, 8))

    def test_engine_rejects_buckets_not_ending_at_text_seq_len(self,
                                                              bundle):
        params, _ = bundle
        with pytest.raises(ValueError, match="prefill_buckets"):
            Engine(params, CFG, RequestQueue(max_depth=2), num_slots=1,
                   prefill_buckets=(1, 2, 4))  # can't hold a full prompt

    def test_custom_buckets_share_one_prefill_program(self, bundle):
        """With a single bucket = text_seq_len, EVERY prompt length
        admits through ONE prefill program — and stays token-identical
        to the unpadded one-shot path."""
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r) for r in REQS[:2]]
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2,
                        prefill_buckets=(CFG.text_seq_len,))
        handles = [queue.submit(r) for r in REQS[:2]]
        with guards.compile_count(
                lambda: engine.prefill_traces, expect=1,
                label="single-bucket prefill"):
            engine.run_until_idle()
        for h, ref in zip(handles, refs):
            np.testing.assert_array_equal(
                np.asarray(h.result(timeout=5).tokens), ref)


class TestBackpressure:
    def test_queue_full_is_typed_and_structured(self, bundle):
        params, _ = bundle
        events = []
        queue = RequestQueue(max_depth=2, on_event=events.append)
        for i in range(2):
            queue.submit(Request(codes=(1, 2), seed=i))
        with pytest.raises(QueueFull) as ei:
            queue.submit(Request(codes=(1, 2), seed=9))
        rec = ei.value.record
        assert rec["kind"] == "serve_reject"
        assert rec["reason"] == "queue_full"
        assert rec["queue_depth"] == 2
        assert events and events[0]["kind"] == "serve_reject"
        assert queue.rejected == 1

    def test_deadline_expired_in_queue(self, bundle):
        """A request whose deadline passes while queued completes as a
        typed deadline_exceeded result without ever taking a slot."""
        params, _ = bundle
        queue = RequestQueue(max_depth=4)
        engine = Engine(params, CFG, queue, num_slots=1)
        h = queue.submit(Request(codes=(1, 2), seed=0, deadline_s=0.0))
        time.sleep(0.01)
        engine.run_until_idle()
        res = h.result(timeout=5)
        assert res.status == DEADLINE_EXCEEDED
        assert "queued" in res.reason
        assert engine.decode_steps == 0     # never spent a slot on it

    def test_deadline_expired_mid_decode(self, bundle):
        """A deadline that passes while the request is decoding cancels
        the slot with a typed result; other slots keep their exact token
        streams."""
        params, vae_params = bundle
        ref = reference_tokens(params, vae_params, REQS[0])
        queue = RequestQueue(max_depth=4)
        engine = Engine(params, CFG, queue, num_slots=2)
        h_ok = queue.submit(REQS[0])
        h_dead = queue.submit(Request(codes=(2, 2), seed=1,
                                      deadline_s=0.005))
        engine.step_once()                  # both admitted, one step in
        time.sleep(0.02)                    # deadline passes mid-decode
        engine.run_until_idle()
        res = h_dead.result(timeout=5)
        assert res.status == DEADLINE_EXCEEDED
        assert "decoding" in res.reason
        np.testing.assert_array_equal(
            np.asarray(h_ok.result(timeout=5).tokens), ref)

    def test_expired_reaped_even_with_full_pool(self, bundle):
        """A dead queued entry must get its typed result (and stop
        holding queue capacity) even while every slot is busy — reaping
        is not gated on free slots."""
        params, _ = bundle
        queue = RequestQueue(max_depth=2)
        engine = Engine(params, CFG, queue, num_slots=1)
        queue.submit(Request(codes=(1, 1), seed=0))
        engine.step_once()                  # pool now full
        h_dead = queue.submit(Request(codes=(2, 2), seed=1,
                                      deadline_s=0.0))
        time.sleep(0.01)
        engine.step_once()                  # free == 0, still reaps
        res = h_dead.result(timeout=1)
        assert res.status == DEADLINE_EXCEEDED
        assert queue.depth() == 0           # capacity released

    def test_cancel_active_fulfills_inflight_slots(self, bundle):
        """Shutdown covers requests already admitted to slots, not just
        queued ones (the no-hangs contract through close())."""
        from dalle_pytorch_tpu.serve import CANCELLED
        params, _ = bundle
        queue = RequestQueue(max_depth=4)
        engine = Engine(params, CFG, queue, num_slots=2)
        h = queue.submit(Request(codes=(1, 2), seed=0))
        engine.step_once()                  # admitted, mid-decode
        assert engine.active_slots() == 1
        assert engine.cancel_active() == 1
        assert h.result(timeout=1).status == CANCELLED
        assert engine.active_slots() == 0

    def test_priority_orders_admission(self, bundle):
        """With one slot busy, a later high-priority (lower value) submit
        is admitted before an earlier low-priority one."""
        params, _ = bundle
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=1)
        running = queue.submit(Request(codes=(1, 1), seed=0))
        engine.step_once()                  # occupies the only slot
        low = queue.submit(Request(codes=(2, 2), seed=1, priority=5))
        high = queue.submit(Request(codes=(3, 3), seed=2, priority=0))
        order = []
        done = set()
        while len(done) < 3:
            engine.step_once()
            for name, h in (("running", running), ("low", low),
                            ("high", high)):
                if name not in done and h.done():
                    done.add(name)
                    order.append(name)
        assert order == ["running", "high", "low"]

    def test_requeue_preserves_arrival_order(self):
        """An evicted/page-deferred request re-enters at its ORIGINAL
        position in its priority class — later-arriving requests never
        leapfrog it (the scheduler half of the no-starvation
        guarantee)."""
        queue = RequestQueue(max_depth=8)
        a = queue.submit(Request(codes=(1,), seed=0))
        popped, _ = queue.pop_ready(1)
        assert popped == [a]
        b = queue.submit(Request(codes=(2,), seed=0))
        queue.requeue(a)
        popped, _ = queue.pop_ready(2)
        assert popped == [a, b]

    def test_requeue_after_drain_is_cancelled_not_stranded(self):
        """A requeue landing after the shutdown drain (engine thread
        outliving close()'s join timeout) must fulfil the handle as
        cancelled — the heap is dead, so enqueueing would strand the
        caller in result() forever."""
        queue = RequestQueue(max_depth=8)
        h = queue.submit(Request(codes=(1,), seed=0))
        queue.pop_ready(1)
        queue.close()
        assert queue.drain() == []
        queue.requeue(h)
        res = h.result(timeout=1)
        assert res.status == "cancelled"
        assert queue.depth() == 0


class TestFaultHardening:
    """A malformed or unlucky request must produce a typed reject/error —
    never a dead serving loop (the no-hangs contract under faults)."""

    def test_invalid_prompt_typed_reject_at_submit(self, bundle):
        params, vae_params = bundle
        from dalle_pytorch_tpu.serve.server import InferenceServer
        server = InferenceServer(params, vae_params, CFG, num_slots=1,
                                 queue_depth=4, decode_images=False)
        too_long = tuple(range(CFG.text_seq_len + 1))
        with pytest.raises(InvalidRequest) as ei:
            server.submit(too_long)
        rec = ei.value.record
        assert rec["reason"] == "invalid_prompt"
        assert rec["prompt_len"] == CFG.text_seq_len + 1
        assert rec["max_prompt_len"] == CFG.text_seq_len
        with pytest.raises(InvalidRequest):
            server.submit(())
        server.close()

    def test_malformed_admission_errors_not_crashes(self, bundle):
        """A raw queue has no prompt validation; the engine must turn an
        impossible prompt into a typed error result at admission and keep
        serving the well-formed request behind it."""
        params, vae_params = bundle
        ref = reference_tokens(params, vae_params, REQS[0])
        queue = RequestQueue(max_depth=8)       # no max_prompt_len
        engine = Engine(params, CFG, queue, num_slots=2)
        h_bad = queue.submit(Request(
            codes=tuple(range(CFG.text_seq_len + 3)), seed=0))
        h_ok = queue.submit(REQS[0])
        engine.run_until_idle()
        res = h_bad.result(timeout=5)
        assert res.status == ERROR
        assert "invalid prompt length" in res.reason
        np.testing.assert_array_equal(
            np.asarray(h_ok.result(timeout=5).tokens), ref)

    def test_run_loop_survives_step_exception(self, bundle):
        """An exception out of a decode step must fail the in-slot
        requests with typed error results and leave the serving thread
        alive and correct for the next request."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2)
        good_fn = engine._decode_fn

        def boom(*a, **k):
            raise RuntimeError("injected decode fault")

        h_bad = queue.submit(REQS[0])
        engine._decode_fn = boom
        stop = threading.Event()
        t = threading.Thread(target=engine.run, args=(stop,), daemon=True)
        t.start()
        try:
            res = h_bad.result(timeout=30)
            assert res.status == ERROR
            assert "injected decode fault" in res.reason
            assert t.is_alive(), "serving loop died on a step exception"
            # recovered: the same engine serves the next request with
            # token-exact results (admission rewrites the slot state)
            engine._decode_fn = good_fn
            ref = reference_tokens(params, vae_params, REQS[1])
            h_ok = queue.submit(REQS[1])
            np.testing.assert_array_equal(
                np.asarray(h_ok.result(timeout=60).tokens), ref)
        finally:
            stop.set()
            t.join(10)

    def test_submit_racing_close_is_typed_reject(self, bundle):
        params, vae_params = bundle
        from dalle_pytorch_tpu.serve.server import InferenceServer
        server = InferenceServer(params, vae_params, CFG, num_slots=1,
                                 queue_depth=4, decode_images=False)
        server.close()
        with pytest.raises(QueueClosed) as ei:
            server.submit((1, 2))
        assert ei.value.record["reason"] == "queue_closed"


class TestBurstOccupancy:
    def test_burst_fills_slots_and_decodes_concurrently(self, bundle):
        """A burst larger than the pool keeps every slot busy — the
        continuous-batching win over one-at-a-time gen_dalle."""
        params, _ = bundle
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=3)
        handles = [queue.submit(Request(codes=(1 + i, 2), seed=i))
                   for i in range(6)]
        engine.step_once()
        assert engine.active_slots() == 3   # full pool from the burst
        engine.run_until_idle()
        assert all(h.result(5).status == OK for h in handles)
        stats = engine.stats()
        assert stats["mean_occupancy"] > 1.5
        assert stats["decode_compiles"] == 1
        assert stats["completed"] == 6


class TestServerPipeline:
    def test_server_decodes_images_and_matches_one_shot(self, bundle):
        """The full pipeline (queue -> engine thread -> postprocess
        thread): the returned image equals generate_images' decoded
        pixels for the same request."""
        params, vae_params = bundle
        from dalle_pytorch_tpu.serve.server import InferenceServer
        req = REQS[0]
        text = jnp.asarray([req.codes], jnp.int32)
        ref_img = np.asarray(D.generate_images(
            params, vae_params, text, cfg=CFG,
            rng=jax.random.PRNGKey(req.seed)))[0]

        server = InferenceServer(params, vae_params, CFG, num_slots=2,
                                 queue_depth=8).start()
        try:
            res = server.generate(req.codes, seed=req.seed, timeout=60)
            assert res.status == OK
            np.testing.assert_allclose(res.image, ref_img, rtol=1e-5,
                                       atol=1e-5)
            stats = server.stats()
            assert stats["completed"] == 1
            # latency is recorded at fulfillment, AFTER postprocess time
            # lands in total_s — the percentile must equal what the
            # caller saw, not the decode-only number
            assert stats["p50_latency_s"] == round(res.total_s, 4)
        finally:
            server.close()

    def test_clip_scores_completed_text_span_like_one_shot(self, bundle):
        """CLIP rerank through the pipeline scores the COMPLETED text
        span — for a prompt shorter than text_seq_len the score must
        match generate_images' rerank (which scores full[:, :text_seq_len]
        including the model-sampled text tokens), not a zero-padded
        prompt."""
        params, vae_params = bundle
        from dalle_pytorch_tpu.models import clip as C
        from dalle_pytorch_tpu.serve.server import InferenceServer
        clip_cfg = C.CLIPConfig(
            dim_text=16, dim_image=16, dim_latent=16,
            num_text_tokens=CFG.num_text_tokens,
            text_enc_depth=1, text_seq_len=CFG.text_seq_len, text_heads=2,
            visual_enc_depth=1, visual_heads=2,
            visual_image_size=VCFG.image_size, visual_patch_size=8,
            sparse_attn=False)
        clip_params = C.clip_init(jax.random.PRNGKey(7), clip_cfg)
        req = REQS[0]                       # len 3 < text_seq_len 8
        text = jnp.asarray([req.codes], jnp.int32)
        _, ref_scores = D.generate_images(
            params, vae_params, text, cfg=CFG,
            rng=jax.random.PRNGKey(req.seed),
            clip_params=clip_params, clip_cfg=clip_cfg)

        server = InferenceServer(params, vae_params, CFG, num_slots=2,
                                 queue_depth=8, clip_params=clip_params,
                                 clip_cfg=clip_cfg).start()
        try:
            res = server.generate(req.codes, seed=req.seed, timeout=60)
            assert res.status == OK
            assert len(res.text_tokens) == CFG.text_seq_len
            np.testing.assert_array_equal(res.text_tokens[:len(req.codes)],
                                          req.codes)
            np.testing.assert_allclose(
                res.clip_score, float(np.asarray(ref_scores)[0]),
                rtol=1e-4, atol=1e-5)
        finally:
            server.close()

    def test_server_close_cancels_queued(self, bundle):
        params, vae_params = bundle
        from dalle_pytorch_tpu.serve import CANCELLED
        from dalle_pytorch_tpu.serve.server import InferenceServer
        server = InferenceServer(params, vae_params, CFG, num_slots=1,
                                 queue_depth=8, decode_images=False)
        # never started: everything queued is cancelled with a typed
        # result at close
        h = server.submit((1, 2), seed=0)
        server.close()
        assert h.result(timeout=5).status == CANCELLED

    def test_http_generate_and_stats(self, bundle):
        """The stdlib HTTP facade end-to-end on a loopback port."""
        import json
        import urllib.request
        params, vae_params = bundle
        from dalle_pytorch_tpu.serve.server import (InferenceServer,
                                                    make_http_server)
        server = InferenceServer(params, vae_params, CFG, num_slots=2,
                                 queue_depth=8,
                                 decode_images=False).start()
        httpd = make_http_server(server, "127.0.0.1", 0)   # ephemeral port
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            body = json.dumps({"codes": [3, 7, 9], "seed": 11}).encode()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/generate", data=body,
                    timeout=60) as resp:
                out = json.loads(resp.read())
            assert out["status"] == "ok"
            ref = reference_tokens(params, vae_params, REQS[0])
            assert out["tokens"] == [int(t) for t in ref]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/stats", timeout=10) as resp:
                stats = json.loads(resp.read())
            assert stats["completed"] == 1
            assert stats["decode_compiles"] == 1
            # a malformed request is a 400 at the edge — it must never
            # reach (and kill) the engine thread
            import urllib.error
            bad = json.dumps(
                {"codes": list(range(CFG.text_seq_len + 1))}).encode()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/generate", data=bad,
                    timeout=10)
            assert ei.value.code == 400
            assert json.loads(ei.value.read())["reason"] == "invalid_prompt"
            # the serving loop is still alive and healthy afterwards
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10) as resp:
                assert json.loads(resp.read())["ok"] is True
            body2 = json.dumps({"codes": [6, 6], "seed": 5,
                                "temperature": 1.3, "top_p": 0.9}).encode()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/generate", data=body2,
                    timeout=60) as resp:
                out2 = json.loads(resp.read())
            assert out2["status"] == "ok"
            ref2 = reference_tokens(params, vae_params, REQS[2])
            assert out2["tokens"] == [int(t) for t in ref2]
        finally:
            httpd.shutdown()
            httpd.server_close()
            server.close()


class TestSamplingValidation:
    def test_bad_sampling_params_raise_at_construction(self):
        with pytest.raises(ValueError, match="temperature"):
            SamplingParams(temperature=0.0)
        with pytest.raises(ValueError, match="top_p"):
            SamplingParams(top_p=1.5)
