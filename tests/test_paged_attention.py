"""Ragged paged-attention kernel tests (ISSUE 9 acceptance criteria).

The load-bearing contract is the oracle relation: the Pallas kernel
(``ops/paged_attention.py``, ``attn_impl='kernel'``) must agree with
``_decode_step_math`` over ``paged_view``'s dense gather — allclose on
the step outputs under the same masking (rows >= pos dead, trash-page
rows never attended), and BYTE-IDENTICAL emitted tokens end-to-end
against ``generate_images`` through the serve engine, for K ∈ {1, 8},
fp32 and int8-KV, page_size ∈ {8, 16}, ragged per-slot positions
(including pos=0 parked dead slots and a slot on its last row), under
``guards.no_transfers`` with the decode program compiled exactly once.
Plus the typed page-size gate (``kv_pool.PageSizeError`` at pool init,
naming the kernel tile constraint) and the ``paged_view`` trim: the
gather never drags K/V or scale pages for wholly-unmapped logical pages
beyond ``total_len``.

All CPU (the kernel runs under the Pallas interpreter — the same code
path CI's serve-perf kernel leg smokes), tiny model, inside tier-1.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.analysis import guards
from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V
from dalle_pytorch_tpu.ops import decode as decode_ops
from dalle_pytorch_tpu.ops import paged_attention as PA
from dalle_pytorch_tpu.serve import (Request, RequestQueue,
                                     SamplingParams)
from dalle_pytorch_tpu.serve import kv_pool as KV
from dalle_pytorch_tpu.serve.engine import Engine

VCFG = V.VAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                   num_layers=2, hidden_dim=8)
CFG = D.DALLEConfig(dim=16, depth=2, vae=VCFG, num_text_tokens=50,
                    text_seq_len=8, heads=2, dim_head=8)


@pytest.fixture(scope="module")
def bundle():
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG)
    params = D.dalle_init(key, CFG, vae_params)
    return params, vae_params


_REF_CACHE: dict = {}


def reference_tokens(params, vae_params, req: Request,
                     quantize_cache: bool = False) -> np.ndarray:
    """Memoized generate_images at batch 1 — the one-shot stream every
    engine path must reproduce token-for-token (test_serve's idiom)."""
    key = (quantize_cache, req.codes, req.seed, req.sampling.temperature,
           req.sampling.filter_thres, req.sampling.top_p)
    if key not in _REF_CACHE:
        text = jnp.asarray([req.codes], jnp.int32)
        _, img_seq = D.generate_images(
            params, vae_params, text, cfg=CFG,
            rng=jax.random.PRNGKey(req.seed),
            filter_thres=req.sampling.filter_thres,
            top_p=req.sampling.top_p,
            temperature=req.sampling.temperature,
            quantize_cache=quantize_cache, return_img_seq=True)
        _REF_CACHE[key] = np.asarray(img_seq)[0]
    return _REF_CACHE[key]


REQS = [
    Request(codes=(3, 7, 9), seed=11),
    Request(codes=(5, 2, 8, 1, 4), seed=23,
            sampling=SamplingParams(temperature=0.7, filter_thres=0.8)),
    Request(codes=(6, 6), seed=5,
            sampling=SamplingParams(temperature=1.3, top_p=0.9)),
]


def _random_pool(key, page_size, num_pages, quantized):
    """A pool with fully-random page content — including the trash page
    and unallocated pages, so an out-of-bounds read cannot hide behind
    zeros."""
    tcfg = CFG.transformer
    shape = (tcfg.depth, num_pages, tcfg.heads, page_size, tcfg.dim_head)
    if quantized:
        return {
            "k": jax.random.randint(jax.random.fold_in(key, 0), shape,
                                    -127, 128, jnp.int8),
            "v": jax.random.randint(jax.random.fold_in(key, 1), shape,
                                    -127, 128, jnp.int8),
            "k_scale": jax.random.uniform(jax.random.fold_in(key, 2),
                                          shape[:-1], minval=0.01,
                                          maxval=0.1),
            "v_scale": jax.random.uniform(jax.random.fold_in(key, 3),
                                          shape[:-1], minval=0.01,
                                          maxval=0.1),
        }
    return {"k": jax.random.normal(jax.random.fold_in(key, 0), shape),
            "v": jax.random.normal(jax.random.fold_in(key, 1), shape)}


class TestKernelVsGatherOracle:
    """Direct math parity: the kernel against ``_decode_step_math`` over
    the gathered view — the oracle relation ISSUE 9 names."""

    @pytest.mark.parametrize("page_size", [8, 16])
    @pytest.mark.parametrize("quantized", [False, True])
    def test_step_math_matches_gather_view(self, bundle, page_size,
                                           quantized):
        """Ragged per-slot positions — a slot on its LAST row
        (pos = seq_len - 1), one mid-sequence, one parked dead at
        pos 0 whose unmapped table rows all point at the trash page —
        with random content in every physical page (a read through an
        unmapped entry would show up, not read zeros)."""
        params, _ = bundle
        tcfg = CFG.transformer
        L = CFG.seq_len
        mp = KV.pages_for(L, page_size)
        pool = _random_pool(jax.random.PRNGKey(7), page_size,
                            2 * mp + 1, quantized)
        bt = np.zeros((3, mp), np.int32)
        bt[0] = np.arange(1, mp + 1)             # slot at the last row
        bt[1] = np.arange(mp + 1, 2 * mp + 1)    # ragged mid-sequence
        #                                          (trailing cols trash)
        bt[1, KV.pages_for(6, page_size):] = 0
        bt = jnp.asarray(bt)                     # slot 2: all trash
        pos = jnp.asarray([L - 1, 5, 0], jnp.int32)
        # one slot carries a padded-off prompt row: the kernel must
        # honor the pad mask exactly like the gather's key_mask
        key_mask = jnp.ones((3, L), bool).at[1, 1].set(False)
        x_tok = jax.random.normal(jax.random.PRNGKey(9), (3, CFG.dim))

        view = decode_ops.paged_view(pool, bt, L)
        h_g, ks_g, vs_g = decode_ops._decode_step_math(
            params["transformer"], x_tok, pos, view, cfg=tcfg,
            key_mask=key_mask)
        h_k, ks_k, vs_k = decode_ops._decode_step_math(
            params["transformer"], x_tok, pos, pool, cfg=tcfg,
            key_mask=key_mask, attn_impl="kernel", block_tables=bt)
        np.testing.assert_allclose(np.asarray(h_k), np.asarray(h_g),
                                   rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(np.asarray(ks_k), np.asarray(ks_g),
                                   rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(np.asarray(vs_k), np.asarray(vs_g),
                                   rtol=2e-5, atol=2e-6)

    def test_kernel_requires_per_slot_pos_and_tables(self, bundle):
        params, _ = bundle
        pool = _random_pool(jax.random.PRNGKey(0), 8, 7, False)
        key_mask = jnp.ones((2, CFG.seq_len), bool)
        x_tok = jnp.zeros((2, CFG.dim))
        with pytest.raises(ValueError, match="per-slot"):
            decode_ops._decode_step_math(
                params["transformer"], x_tok, 3, pool,
                cfg=CFG.transformer, key_mask=key_mask,
                attn_impl="kernel",
                block_tables=jnp.zeros((2, 3), jnp.int32))
        with pytest.raises(ValueError, match="block_tables"):
            decode_ops._decode_step_math(
                params["transformer"], x_tok,
                jnp.zeros((2,), jnp.int32), pool,
                cfg=CFG.transformer, key_mask=key_mask,
                attn_impl="kernel")


class TestKernelEngineTokens:
    """End-to-end through the serve engine: ``paged_attn='kernel'`` must
    emit byte-identical tokens to ``generate_images`` inside the same
    one-compile fused-K emit-ring regime as the gather path."""

    @pytest.mark.parametrize("k", [1, 8])
    def test_tokens_identical_across_chunk_sizes(self, bundle, k):
        """3 requests over 2 slots (slot reuse; mixed prompt lengths /
        temperature / top-k / top-p; slots die mid-chunk into the dead
        mask at K=8) — byte-identical streams, ONE decode trace, every
        page back in the pool."""
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r) for r in REQS]
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=k,
                        kv="paged", page_size=8, paged_attn="kernel")
        handles = [queue.submit(r) for r in REQS]
        with guards.compile_count(lambda: engine.decode_traces, expect=1,
                                  label="paged-attention kernel decode"):
            engine.run_until_idle()
        for h, ref in zip(handles, refs):
            res = h.result(timeout=5)
            assert res.status == "ok"
            np.testing.assert_array_equal(np.asarray(res.tokens), ref)
        assert engine.alloc.in_use == 0
        assert engine.stats()["paged_attn"] == "kernel"

    def test_tokens_identical_at_page_size_16(self, bundle):
        """page_size 16 leaves the last logical page PARTIAL (seq 24 =
        one full page + 8 rows) — the kernel's whole-page mask padding
        must keep the tail rows dead."""
        params, vae_params = bundle
        ref = reference_tokens(params, vae_params, REQS[0])
        queue = RequestQueue(max_depth=4)
        engine = Engine(params, CFG, queue, num_slots=2, kv="paged",
                        page_size=16, paged_attn="kernel")
        h = queue.submit(REQS[0])
        engine.run_until_idle()
        np.testing.assert_array_equal(np.asarray(h.result(5).tokens),
                                      ref)

    def test_int8_kv_tokens_identical(self, bundle):
        """int8-KV composes: per-page dequantization inside the kernel
        (scales outside the contractions) matches
        generate_images(quantize_cache=True) token-for-token."""
        params, vae_params = bundle
        req = REQS[0]
        ref = reference_tokens(params, vae_params, req,
                               quantize_cache=True)
        queue = RequestQueue(max_depth=4)
        engine = Engine(params, CFG, queue, num_slots=2, kv="paged",
                        page_size=8, paged_attn="kernel",
                        quantize_cache=True)
        h = queue.submit(req)
        engine.run_until_idle()
        np.testing.assert_array_equal(np.asarray(h.result(5).tokens),
                                      ref)

    def test_steady_state_transfer_clean_midstream_join(self, bundle):
        """The transfer-discipline contract survives the kernel path:
        full chunks, double-buffered harvest, AND a mid-stream join
        (paged prefill + block-table growth) under
        ``guards.no_transfers()`` — the interpreted Pallas call is
        traced device code, not a host round-trip."""
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r)
                for r in REQS[:2]]
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=4,
                        kv="paged", page_size=8, paged_attn="kernel")
        for r in REQS[:2]:              # warm: compile decode + buckets
            queue.submit(r)
        engine.run_until_idle()
        h_a = queue.submit(REQS[0])
        engine.step_once()              # a admitted, chunk 1 in flight
        with guards.no_transfers():
            h_b = queue.submit(REQS[1])
            engine.step_once()          # join + chunk 2 + harvest 1
            engine.step_once()          # pure steady-state chunk
        engine.run_until_idle()
        np.testing.assert_array_equal(
            np.asarray(h_a.result(timeout=5).tokens), refs[0])
        np.testing.assert_array_equal(
            np.asarray(h_b.result(timeout=5).tokens), refs[1])
        assert engine.decode_traces == 1


class TestPageSizeValidation:
    """The typed pool-init gate: a page size the kernel cannot tile is
    rejected with the constraint NAMED, not an opaque Mosaic failure
    inside pallas_call."""

    def test_kernel_engine_rejects_untileable_page_size(self, bundle):
        params, _ = bundle
        for bad in (4, 12):
            with pytest.raises(KV.PageSizeError,
                               match="paged_attention"):
                Engine(params, CFG, RequestQueue(max_depth=2),
                       num_slots=1, kv="paged", page_size=bad,
                       paged_attn="kernel")

    def test_gather_engine_keeps_arbitrary_page_sizes(self, bundle):
        """The gather path has no tile floor — page_size 4 (the
        pre-kernel test suite's size) must keep constructing."""
        params, _ = bundle
        Engine(params, CFG, RequestQueue(max_depth=2), num_slots=1,
               kv="paged", page_size=4)       # no raise

    def test_kernel_requires_paged_kv(self, bundle):
        params, _ = bundle
        with pytest.raises(ValueError, match="kv='paged'"):
            Engine(params, CFG, RequestQueue(max_depth=2), num_slots=1,
                   kv="dense", paged_attn="kernel")

    def test_validate_page_size_typed_record(self):
        KV.validate_page_size(8)
        KV.validate_page_size(16)
        with pytest.raises(KV.PageSizeError) as ei:
            KV.validate_page_size(4)
        rec = ei.value.record
        assert rec["kind"] == "serve_page_size_invalid"
        assert rec["page_size"] == 4
        assert rec["min_page_size"] == KV.KERNEL_MIN_PAGE_SIZE

    def test_kernel_entry_validates_directly(self):
        """A direct caller (no Engine in front) hits the same typed
        error at the kernel entry."""
        pool = _random_pool(jax.random.PRNGKey(0), 4, 7, False)
        with pytest.raises(KV.PageSizeError):
            PA.paged_decode_attention(
                jnp.zeros((1, CFG.heads, CFG.dim_head)),
                pool["k"][0], pool["v"][0],
                jnp.zeros((1, 6), jnp.int32),
                jnp.zeros((1,), jnp.int32),
                jnp.ones((1, 24), bool), scale=1.0)


class TestPagedViewTrim:
    """The scale-gather trim (ISSUE 9 fix): ``paged_view`` must trim the
    block tables to ``ceil(total_len / page_size)`` columns BEFORE the
    gather, so K/V — and the int8 pool's k_scale/v_scale — never move
    pages that are wholly unmapped beyond ``total_len``."""

    def _pool_and_tables(self):
        L = CFG.seq_len                          # 24 -> 3 pages of 8
        pool = _random_pool(jax.random.PRNGKey(3), 8, 9, True)
        need = KV.pages_for(L, 8)
        bt = jnp.asarray(np.arange(1, 2 * need + 1, dtype=np.int32)
                         .reshape(2, need))
        # a WIDER table (the pool-max shape a caller actually holds):
        # tail columns point at other live pages — if they leaked into
        # the gather's output window the values would differ
        bt_wide = jnp.concatenate(
            [bt, jnp.full((2, 4), 8, jnp.int32)], axis=1)
        return L, pool, bt, bt_wide

    def test_shapes_and_values_independent_of_tail_columns(self):
        L, pool, bt, bt_wide = self._pool_and_tables()
        view = decode_ops.paged_view(pool, bt, L)
        wide = decode_ops.paged_view(pool, bt_wide, L)
        tcfg = CFG.transformer
        for k in ("k", "v"):
            assert wide[k].shape == (tcfg.depth, 2, tcfg.heads, L,
                                     tcfg.dim_head)
        for k in ("k_scale", "v_scale"):
            # the shape contract the fix pins: scales slice to the SAME
            # total_len window as the rows
            assert wide[k].shape == (tcfg.depth, 2, tcfg.heads, L)
        for k in view:
            np.testing.assert_array_equal(np.asarray(wide[k]),
                                          np.asarray(view[k]))

    def test_gather_consumes_only_trimmed_tables(self):
        """Shape regression at the jaxpr level: the ONLY consumer of
        the over-wide table is the trim slice — every downstream eqn
        (the K/V takes AND the scale takes) sees the
        ``pages_for(total_len)``-column table, so unmapped tail pages
        are never gathered at all."""
        L, pool, _, bt_wide = self._pool_and_tables()
        need = KV.pages_for(L, 8)
        wide_shape = tuple(bt_wide.shape)
        jaxpr = jax.make_jaxpr(
            lambda bt: decode_ops.paged_view(pool, bt, L))(bt_wide)
        consumers = [eqn for eqn in jaxpr.jaxpr.eqns
                     if any(getattr(v, "aval", None) is not None
                            and v.aval.shape == wide_shape
                            and v.aval.dtype == jnp.int32
                            for v in eqn.invars)]
        assert consumers, "expected the trim slice to consume the table"
        assert all(e.primitive.name == "slice" for e in consumers), \
            [e.primitive.name for e in consumers]
        assert all(tuple(e.outvars[0].aval.shape) == (2, need)
                   for e in consumers)


class TestVisibilityOracle:
    """ISSUE 12 oracle: the precomputed per-(layer, position) visible-
    page set must agree EXACTLY with the dense ``_sparse_layout`` row
    under the any-token-in-page reduction — for every position, across
    page sizes and both sparse layout shapes the repo serves (the
    reference block-16 VariableSparsity and the tighter block-4 layout
    the sparse-reads tests/bench use)."""

    @pytest.mark.parametrize("page_size", [8, 16])
    @pytest.mark.parametrize("block,num_local_blocks",
                             [(16, 4), (4, 4), (4, 2)])
    def test_visible_pages_matches_layout_row_reduction(
            self, page_size, block, num_local_blocks):
        from dalle_pytorch_tpu.ops import sparse as sparse_ops
        L = 108
        vis, cnt = sparse_ops.visible_pages(
            L, page_size, block, num_local_blocks=num_local_blocks)
        padded = ((L + block - 1) // block) * block
        layout = sparse_ops.token_layout_mask(
            padded, block, num_local_blocks=num_local_blocks)[:L, :L]
        for p in range(L):
            want = sorted({t // page_size for t in range(L)
                           if layout[p, t]})
            got = list(vis[p, :cnt[p]])
            assert got == want, (p, got, want)
            # padding entries are zeros, never visibility grants
            assert (vis[p, cnt[p]:] == 0).all()
        # ascending order is load-bearing: the kernel's online-softmax
        # walk and the causal prefix trim both assume it
        assert all(list(vis[p, :cnt[p]])
                   == sorted(vis[p, :cnt[p]]) for p in range(L))

    @pytest.mark.parametrize("page_size", [8, 16])
    def test_causal_trip_counts(self, page_size):
        """``_sparse_page_visibility``'s decode trip count: the prefix
        of visible pages starting strictly before p — page g readable
        iff g*ps < p (its first row is cached), matching the prefix
        walk's ceil(pos/ps) raggedness page-for-page."""
        from dalle_pytorch_tpu.ops import decode as dec
        L = CFG.seq_len
        cfg = D.DALLEConfig(dim=16, depth=2, vae=VCFG,
                            num_text_tokens=50, text_seq_len=8, heads=2,
                            dim_head=8, sparse_attn=(True, False),
                            sparse_block=4).transformer
        vis, cnt, ccnt = dec._sparse_page_visibility(cfg, L, page_size)
        for p in range(L):
            want = sum(1 for g in vis[p, :cnt[p]] if g * page_size < p)
            assert ccnt[p] == want
        assert ccnt[0] == 0      # a parked dead slot walks zero pages


class TestReadBytesModel:
    def test_kernel_model_reads_fewer_bytes_than_gather(self):
        """The analytic model bench_serve records: the kernel's
        ragged-page reads must undercut the gather's full-view reads
        for any prompt shorter than the sequence."""
        common = dict(depth=2, heads=8, dim_head=64, total_len=1088,
                      page_size=16, prompt_len=64, itemsize=2)
        g = PA.modeled_kv_read_bytes_per_token(impl="gather", **common)
        k = PA.modeled_kv_read_bytes_per_token(impl="kernel", **common)
        assert k < g
        # at prompt ~= total_len the two converge (every page live)
        late = dict(common, prompt_len=1087)
        g2 = PA.modeled_kv_read_bytes_per_token(impl="gather", **late)
        k2 = PA.modeled_kv_read_bytes_per_token(impl="kernel", **late)
        assert k2 == pytest.approx(g2, rel=0.02)
        with pytest.raises(ValueError, match="impl"):
            PA.modeled_kv_read_bytes_per_token(impl="x", **common)

    def test_sparse_reads_model_undercuts_dense_reads(self):
        """The sparse-reads model: sparse layers read only visible
        pages, dense layers unchanged — so bytes drop for both impls,
        by more when more layers are sparse, and the sparse pattern is
        required (silently modeling a dense stack as sparse would fake
        the win)."""
        common = dict(depth=2, heads=2, dim_head=16, total_len=108,
                      page_size=8, prompt_len=4, itemsize=2,
                      sparse_block=4)
        for impl in ("gather", "kernel"):
            dense = PA.modeled_kv_read_bytes_per_token(impl=impl,
                                                       **common)
            half = PA.modeled_kv_read_bytes_per_token(
                impl=impl, sparse_reads=True,
                sparse_pattern=(True, False), **common)
            full = PA.modeled_kv_read_bytes_per_token(
                impl=impl, sparse_reads=True,
                sparse_pattern=(True, True), **common)
            assert full < half < dense, (impl, full, half, dense)
            # the all-sparse block-4 layout sees <= 3 of 14 pages: the
            # acceptance-criterion ratio holds with margin
            assert full <= 0.5 * dense, (impl, full, dense)
        with pytest.raises(ValueError, match="sparse_pattern"):
            PA.modeled_kv_read_bytes_per_token(impl="kernel",
                                               sparse_reads=True,
                                               **common)
