"""DALLE tests: vocab/mask contracts, loss construction, KV-cache parity.

Behavioral contracts from SURVEY.md §5: logit space [text | image | EOS],
mask row i governs the token predicted there (token i+1), tied codebook,
labels = [text, image+offset] shifted with EOS appended, top-k keeps the top
(1-thres) fraction. The cache tests prove the jit decode engine reproduces
the full re-forward logits exactly (teacher-forced replay) for sequential,
reversible, and sparse stacks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V
from dalle_pytorch_tpu.ops import decode as decode_ops

VCFG = V.VAEConfig(image_size=32, num_tokens=48, codebook_dim=32,
                   num_layers=2, hidden_dim=16)
CFG = D.DALLEConfig(dim=32, depth=2, vae=VCFG, num_text_tokens=100,
                    text_seq_len=16, heads=2, dim_head=16)


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


@pytest.fixture
def vae_params(key):
    return V.vae_init(jax.random.fold_in(key, 1), VCFG)


@pytest.fixture
def params(key, vae_params):
    return D.dalle_init(key, CFG, vae_params)


class TestTopP:
    """Filtered entries are the codebase's neg_inf fill = -finfo.max
    (reference parity, a FINITE float) — test keep/drop via a threshold,
    not isfinite."""

    @staticmethod
    def _kept(out):
        from dalle_pytorch_tpu.ops import core
        return (np.asarray(out) > float(core.neg_inf(jnp.float32)) / 2)[0]

    def test_tiny_p_keeps_only_argmax(self):
        logits = jnp.asarray([[1.0, 3.0, 2.0, -jnp.inf]])
        out = D.top_p_filter(logits, 1e-6)
        assert float(out[0, 1]) == 3.0
        assert self._kept(out).tolist() == [False, True, False, False]

    def test_p_one_keeps_all_unmasked(self):
        logits = jnp.asarray([[1.0, 3.0, 2.0, -jnp.inf]])
        out = D.top_p_filter(logits, 1.0)
        # masked stays dropped
        assert self._kept(out).tolist() == [True, True, True, False]

    def test_nucleus_cut(self):
        """p=0.6 over probs [.655,.242,.089,...]: the first token holds
        .655 >= .6, second starts at cum .655 >= p -> only argmax kept;
        p=0.7 keeps the first two."""
        logits = jnp.log(jnp.asarray([[0.655, 0.242, 0.089, 0.014]]))
        assert self._kept(D.top_p_filter(logits, 0.6)).tolist() == \
            [True, False, False, False]
        assert self._kept(D.top_p_filter(logits, 0.7)).tolist() == \
            [True, True, False, False]

    def test_generation_with_top_p(self, key, vae_params, params):
        imgs = D.generate_images(params, vae_params,
                                 jax.random.randint(key, (1, 5), 3, 100),
                                 cfg=CFG, rng=jax.random.fold_in(key, 4),
                                 top_p=0.9)
        assert imgs.shape == (1, 32, 32, 3)
        assert bool(jnp.all(jnp.isfinite(imgs)))


class TestGuidance:
    def test_guidance_one_matches_unguided(self, key, vae_params, params):
        """s=1.0 reduces the mix to the conditional logits, and the rng
        key schedule is identical — the guided program must reproduce the
        unguided samples exactly."""
        text = jax.random.randint(jax.random.fold_in(key, 2), (2, 5),
                                  3, 100)
        plain = D.generate_images(params, vae_params, text, cfg=CFG,
                                  rng=jax.random.fold_in(key, 4),
                                  return_img_seq=True)[1]
        guided = D.generate_images(params, vae_params, text, cfg=CFG,
                                   rng=jax.random.fold_in(key, 4),
                                   guidance=1.0, return_img_seq=True)[1]
        np.testing.assert_array_equal(np.asarray(plain),
                                      np.asarray(guided))

    def test_guided_generation_runs(self, key, vae_params, params):
        text = jax.random.randint(jax.random.fold_in(key, 2), (2, 5),
                                  3, 100)
        imgs, seq = D.generate_images(params, vae_params, text, cfg=CFG,
                                      rng=jax.random.fold_in(key, 4),
                                      guidance=3.0, return_img_seq=True)
        assert imgs.shape == (2, 32, 32, 3)        # cond stream only
        assert bool(jnp.all(jnp.isfinite(imgs)))
        assert int(seq.min()) >= 0
        assert int(seq.max()) < CFG.num_image_tokens


def test_rerank_rejects_undersized_clip_vocab(key, vae_params, params):
    """A CLIP vocab smaller than the DALLE's would NaN the rerank scores
    via an out-of-range gather (XLA fills instead of erroring); the
    library raises at trace time instead."""
    from dalle_pytorch_tpu.models import clip as C
    clip_cfg = C.CLIPConfig(
        dim_text=16, dim_image=16, dim_latent=16,
        num_text_tokens=CFG.num_text_tokens // 2,     # undersized
        text_enc_depth=1, text_seq_len=CFG.text_seq_len, text_heads=2,
        visual_enc_depth=1, visual_image_size=CFG.vae.image_size,
        visual_patch_size=8, visual_heads=2)
    clip_params = C.clip_init(jax.random.fold_in(key, 9), clip_cfg)
    text = jax.random.randint(jax.random.fold_in(key, 2), (1, 5), 3, 100)
    with pytest.raises(ValueError, match="num_text_tokens"):
        D.generate_images(params, vae_params, text, cfg=CFG,
                          rng=jax.random.fold_in(key, 4),
                          clip_params=clip_params, clip_cfg=clip_cfg)


def _toy_batch(key, b=2):
    kt, ki = jax.random.split(key)
    text = jax.random.randint(kt, (b, CFG.text_seq_len), 0,
                              CFG.num_text_tokens)
    image_ids = jax.random.randint(ki, (b, CFG.image_seq_len), 0,
                                   CFG.num_image_tokens)
    return text, image_ids


def test_derived_dims():
    assert CFG.image_seq_len == 64          # (32 / 2**2)**2
    assert CFG.seq_len == 16 + 64
    assert CFG.total_tokens == 100 + 48 + 1
    assert CFG.eos_token_id == 148


def test_tied_codebook_seed(params, vae_params):
    np.testing.assert_array_equal(np.array(params["image_emb"]["w"]),
                                  np.array(vae_params["codebook"]["w"]))


def test_tied_codebook_dim_mismatch_raises(key):
    bad = D.DALLEConfig(dim=64, depth=1, vae=VCFG, text_seq_len=8)
    with pytest.raises(ValueError):
        D.dalle_init(key, bad, V.vae_init(key, VCFG))


def test_logits_mask_layout():
    m = np.array(D.logits_mask(CFG))        # True = forbidden
    t, nt = CFG.text_seq_len, CFG.num_text_tokens
    # rows < t-1 predict text: image+EOS forbidden, text allowed
    assert not m[0, :nt].any() and m[0, nt:].all()
    # rows >= t-1 predict image ids: text forbidden
    assert m[t - 1, :nt].all() and not m[t - 1, nt:-1].any()
    # EOS only at the very last row
    assert m[:-1, -1].all() and not m[-1, -1]
    # last row also allows image ids only
    assert m[-1, :nt].all() and not m[-1, nt:-1].any()


def test_forward_logits_shape_and_mask_applied(key, params, vae_params):
    text, image_ids = _toy_batch(key)
    logits = D.dalle_apply(params, text, image_ids, cfg=CFG,
                           vae_params=vae_params)
    assert logits.shape == (2, CFG.seq_len, CFG.total_tokens)
    m = np.array(D.logits_mask(CFG))
    lg = np.array(logits)
    fill = -np.finfo(lg.dtype).max
    assert (lg[:, m] == fill).all()


def test_loss_matches_manual_ce(key, params, vae_params):
    text, image_ids = _toy_batch(key)
    loss = D.dalle_apply(params, text, image_ids, cfg=CFG,
                         vae_params=vae_params, return_loss=True)
    logits = D.dalle_apply(params, text, image_ids, cfg=CFG,
                           vae_params=vae_params)
    labels = np.concatenate(
        [np.array(text), np.array(image_ids) + CFG.num_text_tokens,
         np.full((2, 1), CFG.eos_token_id)], axis=1)[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    manual = -np.mean(np.take_along_axis(np.array(logp), labels[..., None],
                                         axis=-1))
    np.testing.assert_allclose(float(loss), manual, rtol=1e-5)


def test_raw_image_tokenization_no_vae_grad(key, params, vae_params):
    text, _ = _toy_batch(key)
    imgs = jax.random.uniform(key, (2, 32, 32, 3), minval=-1, maxval=1)

    def loss_fn(p, vp):
        return D.dalle_apply(p, text, imgs, cfg=CFG, vae_params=vp,
                             return_loss=True)

    loss, gvae = jax.value_and_grad(loss_fn, argnums=1)(params, vae_params)
    assert np.isfinite(float(loss))
    # token ids come through stop_gradient: VAE encoder gets NO gradient
    # (reference @torch.no_grad get_codebook_indices, dalle_pytorch.py:120)
    total = sum(float(jnp.abs(g).sum()) for g in jax.tree.leaves(gvae))
    assert total == 0.0


def test_text_mask_padded_over_image_span(key, params, vae_params):
    text, image_ids = _toy_batch(key)
    mask = jnp.ones((2, CFG.text_seq_len), bool).at[:, 10:].set(False)
    loss = D.dalle_apply(params, text, image_ids, cfg=CFG, mask=mask,
                         vae_params=vae_params, return_loss=True)
    assert np.isfinite(float(loss))


def test_top_k_filter_keeps_top_half():
    logits = jnp.asarray(np.random.default_rng(0)
                         .standard_normal((2, 100), dtype=np.float32))
    out = np.array(D.top_k_filter(logits, 0.5))
    kept = np.isfinite(np.maximum(out, -1e30)) & (out > -1e30)
    assert (kept.sum(axis=-1) == 50).all()
    # kept entries are exactly the top-50 of each row
    for i in range(2):
        top = set(np.argsort(np.array(logits[i]))[-50:])
        assert set(np.where(kept[i])[0]) == top


@pytest.mark.parametrize("variant", ["sequential", "reversible", "sparse"])
def test_cache_replay_matches_full_forward(key, vae_params, variant):
    """Teacher-forced replay: stepping the KV-cache decoder over a known
    sequence must reproduce the full forward's logits at every position."""
    kw = {}
    if variant == "reversible":
        kw["reversible"] = True
    if variant == "sparse":
        kw["sparse_attn"] = (True, False)
    cfg = D.DALLEConfig(dim=32, depth=2, vae=VCFG, num_text_tokens=100,
                        text_seq_len=16, heads=2, dim_head=16, **kw)
    params = D.dalle_init(key, cfg, vae_params)
    text, image_ids = _toy_batch(key)

    full_logits = D.dalle_apply(params, text, image_ids, cfg=cfg,
                                vae_params=vae_params)

    tokens = D.embed_prompt(params, cfg, text, image_ids)
    t0 = cfg.text_seq_len
    h, cache = decode_ops.prefill(params["transformer"], tokens[:, :t0],
                                  cfg=cfg.transformer, total_len=cfg.seq_len)
    key_mask = jnp.ones((2, cfg.seq_len), bool)

    # prefill last row == full forward row t0-1 (pre-mask comparison)
    pre = D.to_logits(params, h[:, -1])
    forb = D.logits_mask(cfg)
    pre = jnp.where(forb[t0 - 1][None], -jnp.finfo(pre.dtype).max, pre)
    np.testing.assert_allclose(np.array(pre), np.array(full_logits[:, t0 - 1]),
                               atol=1e-4)

    for p in range(t0, cfg.seq_len):
        h_tok, cache = decode_ops.decode_step(
            params["transformer"], tokens[:, p], jnp.asarray(p), cache,
            cfg=cfg.transformer, key_mask=key_mask)
        lg = D.to_logits(params, h_tok)
        lg = jnp.where(forb[p][None], -jnp.finfo(lg.dtype).max, lg)
        np.testing.assert_allclose(
            np.array(lg), np.array(full_logits[:, p]), atol=1e-4,
            err_msg=f"{variant} mismatch at position {p}")


def test_generate_images_shapes_and_token_ranges(key, params, vae_params):
    text = jax.random.randint(key, (2, CFG.text_seq_len), 3,
                              CFG.num_text_tokens)
    images, img_seq = D.generate_images(params, vae_params, text, cfg=CFG,
                                        rng=key, return_img_seq=True)
    assert images.shape == (2, 32, 32, 3)
    ids = np.array(img_seq)
    assert ids.shape == (2, CFG.image_seq_len)
    assert (ids >= 0).all() and (ids < CFG.num_image_tokens).all()


def test_generate_text_completion_mode(key, params, vae_params):
    """Short unpadded prompt (genDALLE.py:106): the sampler must complete
    the text span with TEXT ids before generating image tokens."""
    t0 = 5
    text = jax.random.randint(key, (1, t0), 3, CFG.num_text_tokens)
    images, img_seq = D.generate_images(params, vae_params, text, cfg=CFG,
                                        rng=key, return_img_seq=True)
    assert images.shape == (1, 32, 32, 3)
    ids = np.array(img_seq)
    assert (ids >= 0).all() and (ids < CFG.num_image_tokens).all()


def test_generate_is_jittable_and_deterministic(key, params, vae_params):
    text = jax.random.randint(key, (1, CFG.text_seq_len), 3,
                              CFG.num_text_tokens)
    f = jax.jit(lambda p, vp, t, r: D.generate_images(
        p, vp, t, cfg=CFG, rng=r, return_img_seq=True)[1])
    a = f(params, vae_params, text, key)
    b = f(params, vae_params, text, key)
    # jaxlint: disable=JL001 — terminal fetch for the equality assertion
    np.testing.assert_array_equal(np.array(a), np.array(b))


def test_oo_wrapper(key):
    vae = V.DiscreteVAE(key, image_size=32, num_tokens=48, codebook_dim=32,
                        num_layers=2, hidden_dim=16)
    model = D.DALLE(dim=32, vae=vae, depth=2, key=key, num_text_tokens=100,
                    text_seq_len=16, heads=2, dim_head=16)
    text = jax.random.randint(jax.random.fold_in(key, 1), (1, 16), 0, 100)
    imgs = jax.random.uniform(jax.random.fold_in(key, 2), (1, 32, 32, 3))
    loss = model(text, imgs, return_loss=True)
    assert np.isfinite(float(loss))
    with pytest.raises(TypeError):
        D.DALLE(dim=32, vae="not a vae", depth=1)


class TestChunkedCE:
    """loss_chunk streams the 12k-vocab head over sequence chunks; the loss
    and gradients must match the dense path (models/dalle._chunked_ce)."""

    def _setup(self, loss_chunk):
        import dataclasses
        from dalle_pytorch_tpu.models import dalle as D
        from dalle_pytorch_tpu.models import vae as V
        vcfg = V.VAEConfig(image_size=16, num_tokens=12, codebook_dim=16,
                           num_layers=2, hidden_dim=8)
        cfg = D.DALLEConfig(dim=16, depth=2, vae=vcfg, num_text_tokens=20,
                            text_seq_len=6, heads=2, dim_head=8,
                            loss_chunk=loss_chunk)
        params = D.dalle_init(jax.random.PRNGKey(0), cfg)
        text = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, 20)
        ids = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 12)
        return D, cfg, params, text, ids

    @pytest.mark.parametrize("chunk", [4, 7, 64])
    def test_loss_and_grads_match_dense(self, chunk):
        import dataclasses
        D, cfg, params, text, ids = self._setup(chunk)
        dense_cfg = dataclasses.replace(cfg, loss_chunk=0)

        def loss(p, c):
            return D.dalle_apply(p, text, ids, cfg=c, return_loss=True)

        l_dense, g_dense = jax.value_and_grad(loss)(params, dense_cfg)
        l_chunk, g_chunk = jax.value_and_grad(loss)(params, cfg)
        np.testing.assert_allclose(float(l_chunk), float(l_dense),
                                   rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
            g_chunk, g_dense)

    def test_logits_path_unaffected(self):
        D, cfg, params, text, ids = self._setup(4)
        logits = D.dalle_apply(params, text, ids, cfg=cfg)
        assert logits.shape == (2, 22, cfg.total_tokens)


def test_north_composition_remat_flash_chunk_matches_plain(key, params):
    """The tuned bench config composes remat='full' + attn_impl='flash' +
    chunked CE in one train step (bench.py build_cfg); loss and grads must
    match the plain dense/xla/un-rematerialized path, since remat and the
    CE streaming are pure memory strategies and flash is an exact
    attention algorithm (not an approximation)."""
    import dataclasses

    north = dataclasses.replace(CFG, remat="full", attn_impl="flash",
                                loss_chunk=16)
    plain = CFG
    text = jax.random.randint(jax.random.fold_in(key, 2), (2, 16), 0, 100)
    ids = jax.random.randint(jax.random.fold_in(key, 3), (2, 64), 0, 48)

    def loss(p, c):
        return D.dalle_apply(p, text, ids, cfg=c, return_loss=True)

    l_p, g_p = jax.value_and_grad(loss)(params, plain)
    l_n, g_n = jax.value_and_grad(loss)(params, north)
    np.testing.assert_allclose(float(l_n), float(l_p), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.array(a), np.array(b), atol=5e-4), g_p, g_n)
