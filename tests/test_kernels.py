"""Pallas kernel tests (interpret mode on CPU): flash + block-sparse vs the
XLA oracles, forward and backward, with and without pad masks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.ops import attention as A
from dalle_pytorch_tpu.ops import sparse
from dalle_pytorch_tpu.ops.block_sparse import block_sparse_attention
from dalle_pytorch_tpu.ops.flash_attention import flash_attention


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


def _qkv(key, b=2, h=2, n=256, d=32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, h, n, d)) for k in ks)


def dense_oracle(q, k, v, scale, causal, mask):
    attn = A.dense_attention_weights(q, k, scale, mask, causal)
    return jnp.einsum("bhij,bhjd->bhid", attn, v)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dense(key, causal):
    q, k, v = _qkv(key)
    scale = 0.17
    out = flash_attention(q, k, v, scale=scale, causal=causal, block_q=64,
                          block_k=64)
    ref = dense_oracle(q, k, v, scale, causal, None)
    np.testing.assert_allclose(np.array(out), np.array(ref), atol=2e-5)


def test_flash_with_pad_mask_matches_dense_everywhere(key):
    """Exact agreement INCLUDING fully-padded rows (shared two-fill
    semantics)."""
    q, k, v = _qkv(key)
    mask = jnp.ones((2, 256), bool).at[:, 200:].set(False)
    out = flash_attention(q, k, v, scale=0.2, causal=True, mask=mask,
                          block_q=64, block_k=64)
    ref = dense_oracle(q, k, v, 0.2, True, mask)
    np.testing.assert_allclose(np.array(out), np.array(ref), atol=2e-5)


def test_flash_ragged_seq_blocks(key):
    """Sequence not a multiple of the q/k blocks still works (forward)."""
    q, k, v = _qkv(key, n=80)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = dense_oracle(q, k, v, q.shape[-1] ** -0.5, True, None)
    np.testing.assert_allclose(np.array(out), np.array(ref), atol=2e-5)


def test_flash_gradients_match_dense(key):
    q, k, v = _qkv(key, n=128)
    mask = jnp.ones((2, 128), bool).at[:, 100:].set(False)
    tgt = jax.random.normal(key, q.shape)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, scale=0.2, causal=True, mask=mask,
                            block_q=64, block_k=64)
        return jnp.sum((o - tgt) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum((dense_oracle(q, k, v, 0.2, True, mask) - tgt) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.array(a), np.array(b), atol=5e-4)


def test_flash_bf16_runs(key):
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(key, n=128))
    out = flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    assert np.isfinite(np.array(out, dtype=np.float32)).all()


@pytest.mark.parametrize("causal", [True, False])
def test_block_sparse_matches_oracle(key, causal):
    q, k, v = _qkv(key, n=256)
    scale = 0.2
    out = block_sparse_attention(q, k, v, scale=scale, causal=causal,
                                 block=16, block_q=64, block_k=64)
    ref = sparse.sparse_attention_ref(q, k, v, scale=scale, causal=causal,
                                     block=16)
    np.testing.assert_allclose(np.array(out), np.array(ref), atol=2e-5)


def test_block_sparse_key_mask_matches_oracle(key):
    q, k, v = _qkv(key, n=128)
    mask = jnp.ones((2, 128), bool).at[:, 112:].set(False)
    out = block_sparse_attention(q, k, v, scale=0.2, causal=True, mask=mask,
                                 block=16, block_q=64, block_k=64)
    ref = sparse.sparse_attention_ref(q, k, v, scale=0.2, causal=True,
                                     mask=mask, block=16)
    np.testing.assert_allclose(np.array(out), np.array(ref), atol=2e-5)


def test_block_sparse_gradients_match_oracle(key):
    q, k, v = _qkv(key, n=128)
    tgt = jax.random.normal(key, q.shape)

    def loss_pallas(q, k, v):
        o = block_sparse_attention(q, k, v, scale=0.2, causal=True,
                                   block=16, block_q=64, block_k=64)
        return jnp.sum((o - tgt) ** 2)

    def loss_ref(q, k, v):
        o = sparse.sparse_attention_ref(q, k, v, scale=0.2, causal=True,
                                        block=16)
        return jnp.sum((o - tgt) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.array(a), np.array(b), atol=5e-4)


def test_transformer_attn_impl_flash_matches_xla(key):
    from dalle_pytorch_tpu.ops.transformer import (TransformerConfig,
                                                   transformer_apply,
                                                   transformer_init)
    base = dict(dim=32, depth=2, seq_len=128, heads=2, dim_head=16)
    cfg_x = TransformerConfig(**base)
    cfg_f = TransformerConfig(**base, attn_impl="flash")
    params = transformer_init(key, cfg_x)
    x = jax.random.normal(key, (2, 128, 32))
    mask = jnp.ones((2, 128), bool).at[:, 100:].set(False)
    yx = transformer_apply(params, x, cfg=cfg_x, mask=mask)
    yf = transformer_apply(params, x, cfg=cfg_f, mask=mask)
    np.testing.assert_allclose(np.array(yx), np.array(yf), atol=1e-4)


def test_transformer_sparse_impl_pallas_matches_ref(key):
    from dalle_pytorch_tpu.ops.transformer import (TransformerConfig,
                                                   transformer_apply,
                                                   transformer_init)
    base = dict(dim=32, depth=2, seq_len=128, heads=2, dim_head=16,
                sparse_attn=True, sparse_block=16)
    cfg_r = TransformerConfig(**base)
    cfg_p = TransformerConfig(**base, sparse_impl="pallas")
    params = transformer_init(key, cfg_r)
    x = jax.random.normal(key, (2, 128, 32))
    yr = transformer_apply(params, x, cfg=cfg_r)
    yp = transformer_apply(params, x, cfg=cfg_p)
    np.testing.assert_allclose(np.array(yr), np.array(yp), atol=1e-4)


def test_flash_gradients_ragged_seq(key):
    """Backward at a sequence length NOT a multiple of the block (ADVICE r1:
    the bwd asserted n % block_k == 0 while the forward padded — e.g. DALLE
    text_seq_len=300 -> seq 1324). Grads must match dense exactly."""
    n = 200                                      # 200 % 128 != 0
    q, k, v = _qkv(key, n=n)
    mask = jnp.ones((2, n), bool).at[:, 180:].set(False)
    tgt = jax.random.normal(key, q.shape)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, scale=0.2, causal=True, mask=mask,
                            block_q=128, block_k=128)
        return jnp.sum((o - tgt) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum((dense_oracle(q, k, v, 0.2, True, mask) - tgt) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.array(a), np.array(b), atol=5e-4)


def test_static_tile_schedule_selection():
    """The schedule factorization itself (r5): exactly which layouts
    admit the python-unrolled tile list, and which fall back."""
    from dalle_pytorch_tpu.ops.block_sparse import _static_tile_schedule
    # the default VariableSparsity layout: diagonal + global tile 0
    assert _static_tile_schedule(128, 128, 16, 64, (0,), True) == [0]
    # multiple global blocks in distinct tiles
    assert _static_tile_schedule(128, 128, 16, 64, (0, 8), True) == [0, 1]
    # non-causal, mismatched tiles, window not dividing: all fall back
    assert _static_tile_schedule(128, 128, 16, 64, (0,), False) is None
    assert _static_tile_schedule(64, 128, 16, 64, (0,), True) is None
    assert _static_tile_schedule(96, 96, 16, 64, (0,), True) is None
    # a global block straddling a tile boundary falls back (window 16
    # divides the 64 tile, so this reaches the straddle check itself:
    # block 48, g=1 spans tokens 48..95 = tiles 0 and 1)
    assert _static_tile_schedule(64, 64, 48, 16, (1,), True) is None


def test_block_sparse_gradients_masked_static_schedule(key):
    """Grads through the STATIC-schedule backward (r5: diagonal piece +
    global strip instead of the key-tile scan) with a pad-key mask —
    n=256 with 128-tiles factors the layout, so this exercises
    _bs_bwd_static; parity vs the dense-masked oracle."""
    n = 256
    q, k, v = _qkv(key, n=n)
    mask = jnp.ones((2, n), bool).at[:, 230:].set(False)
    tgt = jax.random.normal(key, q.shape)

    def loss_pallas(q, k, v):
        o = block_sparse_attention(q, k, v, scale=0.2, causal=True,
                                   mask=mask, block=16, block_q=128,
                                   block_k=128)
        return jnp.sum((o - tgt) ** 2)

    def loss_ref(q, k, v):
        o = sparse.sparse_attention_ref(q, k, v, scale=0.2, causal=True,
                                        mask=mask, block=16)
        return jnp.sum((o - tgt) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.array(a), np.array(b), atol=5e-4)


def test_block_sparse_gradients_ragged_seq(key):
    """Same ragged-length regression for the block-sparse backward."""
    n = 160                                      # multiple of block=16 only
    q, k, v = _qkv(key, n=n)
    tgt = jax.random.normal(key, q.shape)

    def loss_pallas(q, k, v):
        o = block_sparse_attention(q, k, v, scale=0.2, causal=True,
                                   block=16, block_q=128, block_k=128)
        return jnp.sum((o - tgt) ** 2)

    def loss_ref(q, k, v):
        o = sparse.sparse_attention_ref(q, k, v, scale=0.2, causal=True,
                                        block=16)
        return jnp.sum((o - tgt) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.array(a), np.array(b), atol=5e-4)


def test_flash_gradients_ragged_no_mask_non_causal(key):
    """Ragged + no pad mask + non-causal: padded key columns must still be
    excluded from dq (structural bound added by the bwd itself)."""
    n = 72
    q, k, v = _qkv(key, n=n)
    tgt = jax.random.normal(key, q.shape)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, scale=0.3, causal=False,
                            block_q=64, block_k=64)
        return jnp.sum((o - tgt) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum((dense_oracle(q, k, v, 0.3, False, None) - tgt) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.array(a), np.array(b), atol=5e-4)


class TestBf16Operands:
    """The kernels keep MXU operands in the input dtype (bf16 at full
    systolic rate) with f32 accumulation; parity vs the f32 oracle must
    stay at bf16 rounding scale (~0.5%), not blow up."""

    def _qkv(self, b=2, h=2, n=256, d=64):
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (b, h, n, d), jnp.bfloat16)
                   for kk in ks)
        mask = jnp.ones((b, n), bool).at[1, 200:].set(False)
        return q, k, v, mask, d ** -0.5

    def test_flash_bf16_fwd_and_grad(self):
        from dalle_pytorch_tpu.ops.attention import dense_attention_weights
        from dalle_pytorch_tpu.ops.flash_attention import flash_attention
        q, k, v, mask, scale = self._qkv()
        o = flash_attention(q, k, v, scale=scale, causal=True, mask=mask)
        w = dense_attention_weights(q.astype(jnp.float32),
                                    k.astype(jnp.float32), scale, mask, True)
        ref = jnp.einsum("bhij,bhjd->bhid", w, v.astype(jnp.float32))
        rel = float(jnp.max(jnp.abs(o.astype(jnp.float32) - ref))
                    / jnp.max(jnp.abs(ref)))
        assert rel < 2e-2, rel

        def loss(fn):
            return lambda *a: (fn(*a).astype(jnp.float32) ** 2).sum()

        g = jax.grad(loss(lambda q, k, v: flash_attention(
            q, k, v, scale=scale, causal=True, mask=mask)),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss(lambda q, k, v: jnp.einsum(
            "bhij,bhjd->bhid",
            dense_attention_weights(q, k, scale, mask, True), v)),
            argnums=(0, 1, 2))(q.astype(jnp.float32), k.astype(jnp.float32),
                               v.astype(jnp.float32))
        grel = max(float(jnp.max(jnp.abs(a.astype(jnp.float32) - b_))
                         / (float(jnp.max(jnp.abs(b_))) + 1e-9))
                   for a, b_ in zip(g, gr))
        assert grel < 3e-2, grel

    def test_block_sparse_bf16_fwd(self):
        from dalle_pytorch_tpu.ops.block_sparse import block_sparse_attention
        from dalle_pytorch_tpu.ops.sparse import sparse_attention_ref
        q, k, v, mask, scale = self._qkv()
        o = block_sparse_attention(q, k, v, scale=scale, causal=True,
                                   mask=mask)
        r = sparse_attention_ref(q.astype(jnp.float32),
                                 k.astype(jnp.float32),
                                 v.astype(jnp.float32), scale=scale,
                                 causal=True, mask=mask)
        rel = float(jnp.max(jnp.abs(o.astype(jnp.float32) - r))
                    / jnp.max(jnp.abs(r)))
        assert rel < 2e-2, rel


@pytest.mark.parametrize("causal", [True, False])
def test_windowed_sparse_matches_oracle(key, causal):
    q, k, v = _qkv(key, n=256)
    out = sparse.sparse_attention_windowed(q, k, v, scale=0.2, causal=causal,
                                           block=16)
    ref = sparse.sparse_attention_ref(q, k, v, scale=0.2, causal=causal,
                                      block=16)
    np.testing.assert_allclose(np.array(out), np.array(ref), atol=2e-5)


def test_windowed_sparse_ragged_and_mask_matches_oracle(key):
    """n not a multiple of the 64-token window (but a block multiple, as
    the transformer guarantees) + ragged pad-key mask."""
    q, k, v = _qkv(key, n=176)                       # 11 blocks, 2.75 windows
    mask = jnp.ones((2, 176), bool).at[0, 150:].set(False) \
                                   .at[1, 16:].set(False)
    out = sparse.sparse_attention_windowed(q, k, v, scale=0.2, causal=True,
                                           mask=mask, block=16)
    ref = sparse.sparse_attention_ref(q, k, v, scale=0.2, causal=True,
                                      mask=mask, block=16)
    np.testing.assert_allclose(np.array(out), np.array(ref), atol=2e-5)


def test_windowed_sparse_gradients_match_oracle(key):
    q, k, v = _qkv(key, n=128)
    tgt = jax.random.normal(key, q.shape)

    def loss_win(q, k, v):
        o = sparse.sparse_attention_windowed(q, k, v, scale=0.2, causal=True,
                                             block=16)
        return jnp.sum((o - tgt) ** 2)

    def loss_ref(q, k, v):
        o = sparse.sparse_attention_ref(q, k, v, scale=0.2, causal=True,
                                        block=16)
        return jnp.sum((o - tgt) ** 2)

    gw = jax.grad(loss_win, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gw, gr):
        np.testing.assert_allclose(np.array(a), np.array(b), atol=5e-4)


def test_windowed_sparse_multiple_global_blocks(key):
    q, k, v = _qkv(key, n=256)
    out = sparse.sparse_attention_windowed(q, k, v, scale=0.2, causal=True,
                                           block=16, global_blocks=(0, 5))
    ref = sparse.sparse_attention_ref(q, k, v, scale=0.2, causal=True,
                                      block=16, global_blocks=(0, 5))
    np.testing.assert_allclose(np.array(out), np.array(ref), atol=2e-5)


class TestPallasBackward:
    """flash_attention(bwd_impl='pallas') — the kernelized backward must
    match the XLA blockwise backward (itself oracle-verified above) on
    every masking combination, interpret mode."""

    def _grads(self, key, bwd_impl, *, causal=True, mask=None, n=256,
               dtype=jnp.float32):
        q, k, v = (x.astype(dtype) for x in _qkv(key, n=n))
        tgt = jax.random.normal(key, q.shape).astype(dtype)

        def loss(q, k, v):
            o = flash_attention(q, k, v, scale=0.2, causal=causal,
                                mask=mask, bwd_impl=bwd_impl)
            return jnp.sum((o.astype(jnp.float32) - tgt.astype(
                jnp.float32)) ** 2)

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    @pytest.mark.parametrize("impl", ["pallas", "pallas_fused"])
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_xla_bwd(self, key, causal, impl):
        gp = self._grads(key, impl, causal=causal)
        gx = self._grads(key, "xla", causal=causal)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(np.array(a), np.array(b), atol=2e-4)

    @pytest.mark.parametrize("impl", ["pallas", "pallas_fused"])
    def test_with_pad_mask(self, key, impl):
        mask = jnp.ones((2, 256), bool).at[0, 200:].set(False) \
                                       .at[1, 10:].set(False)
        gp = self._grads(key, impl, mask=mask)
        gx = self._grads(key, "xla", mask=mask)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(np.array(a), np.array(b), atol=2e-4)

    @pytest.mark.parametrize("impl", ["pallas", "pallas_fused"])
    def test_ragged_seq(self, key, impl):
        gp = self._grads(key, impl, n=192)   # pads to 256-tile inside
        gx = self._grads(key, "xla", n=192)
        for a, b in zip(gp, gx):
            np.testing.assert_allclose(np.array(a), np.array(b), atol=2e-4)

    @pytest.mark.parametrize("impl", ["pallas", "pallas_fused"])
    def test_bf16_finite(self, key, impl):
        gp = self._grads(key, impl, dtype=jnp.bfloat16)
        for g in gp:
            assert g.dtype == jnp.bfloat16
            assert np.isfinite(np.array(g, dtype=np.float32)).all()

    def test_rejects_unknown_impl(self, key):
        q, k, v = _qkv(key, n=64)
        with pytest.raises(ValueError):
            flash_attention(q, k, v, bwd_impl="cuda")
