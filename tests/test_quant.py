"""Int8 weight quantization (ops/quant.py): rounding bound, linear
equivalence, tree hygiene, and the quantized DALLE decode path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V
from dalle_pytorch_tpu.ops import core, quant

VCFG = V.VAEConfig(image_size=32, num_tokens=48, codebook_dim=32,
                   num_layers=2, hidden_dim=16)
CFG = D.DALLEConfig(dim=32, depth=2, vae=VCFG, num_text_tokens=100,
                    text_seq_len=16, heads=2, dim_head=16)


def test_quantize_rounding_bound():
    """Dequantized weights sit within half a scale step of the originals
    (symmetric round-to-nearest)."""
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 48), jnp.float32)
    q = quant.quantize_linear_int8({"w": w})
    w_hat = q["w_q"].astype(jnp.float32) * q["scale"][None, :]
    err = jnp.abs(w_hat - w)
    assert float(jnp.max(err - q["scale"][None, :] / 2)) <= 1e-6
    assert q["w_q"].dtype == jnp.int8
    assert int(jnp.max(jnp.abs(q["w_q"]))) <= 127


def test_quantized_linear_close_and_bias_kept():
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 48), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(2), (48,), jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(3), (8, 64), jnp.float32)
    dense = core.linear({"w": w, "b": b}, x)
    quantized = core.linear(quant.quantize_linear_int8({"w": w, "b": b}), x)
    rel = float(jnp.max(jnp.abs(quantized - dense))
                / jnp.max(jnp.abs(dense)))
    assert rel < 0.02


def test_quantize_stacked_weights():
    """Depth-stacked (D, in, out) weights quantize with a (D, out) scale,
    so the scan's per-layer slices stay consistent."""
    w = jax.random.normal(jax.random.PRNGKey(4), (3, 16, 8), jnp.float32)
    q = quant.quantize_linear_int8({"w": w})
    assert q["w_q"].shape == (3, 16, 8)
    assert q["scale"].shape == (3, 8)
    # slicing layer 1 equals quantizing layer 1 alone
    alone = quant.quantize_linear_int8({"w": w[1]})
    np.testing.assert_array_equal(np.asarray(q["w_q"][1]),
                                  np.asarray(alone["w_q"]))


def test_tree_quantizes_linears_only():
    tree = {"ln": {"g": jnp.ones((4,)), "b": jnp.zeros((4,))},
            "proj": {"w": jnp.ones((4, 4))},
            "moe_stack": jnp.ones((2, 4, 4))}       # raw array: untouched
    out = quant.quantize_tree_int8(tree)
    assert "w_q" in out["proj"] and "w" not in out["proj"]
    assert "g" in out["ln"]
    assert out["moe_stack"].dtype == jnp.float32


def test_quantize_for_decode_keeps_embeddings():
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG)
    params = D.dalle_init(key, CFG, vae_params)
    qp = D.quantize_for_decode(params)
    # embeddings still gatherable; transformer linears quantized
    assert "w" in qp["text_emb"] and "w" in qp["image_emb"]
    flat = jax.tree.leaves(
        jax.tree.map(lambda x: x.dtype == jnp.int8, qp["transformer"]))
    assert any(flat), "no transformer weight was quantized"
    assert qp["to_logits"]["proj"]["w_q"].dtype == jnp.int8


def test_quantized_forward_close():
    """Teacher-forced logits with quantized weights track the dense ones
    (small model: generous-but-meaningful tolerance on the argmax rate)."""
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG)
    params = D.dalle_init(key, CFG, vae_params)
    text = jax.random.randint(jax.random.fold_in(key, 2), (2, 16), 3, 100)
    image = jax.random.uniform(jax.random.fold_in(key, 3), (2, 32, 32, 3),
                               minval=-1, maxval=1)
    dense = D.dalle_apply(params, text, image, cfg=CFG,
                          vae_params=vae_params)
    q = D.dalle_apply(D.quantize_for_decode(params), text, image, cfg=CFG,
                      vae_params=vae_params)
    assert q.shape == dense.shape
    denom = float(jnp.max(jnp.abs(dense)))
    assert float(jnp.max(jnp.abs(q - dense))) / denom < 0.05


def test_quantized_tree_rejected_by_torch_export():
    """Quantization is lossy and inference-only; exporting a
    quantize_for_decode tree to .pth must fail loudly (the guard sits in
    the shared _linear walker, which every quantized linear passes
    through), not KeyError deep in the walk."""
    from dalle_pytorch_tpu.compat.torch_export import export_transformer
    from dalle_pytorch_tpu.ops import transformer as T
    cfg = T.TransformerConfig(dim=16, depth=2, seq_len=8, heads=2,
                              dim_head=8)
    p = T.transformer_init(jax.random.PRNGKey(0), cfg)
    export_transformer(p)                       # dense export works
    with pytest.raises(ValueError, match="quantized"):
        export_transformer(quant.quantize_tree_int8(p))


def test_quantized_generation_runs():
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG)
    params = D.quantize_for_decode(D.dalle_init(key, CFG, vae_params))
    text = jax.random.randint(jax.random.fold_in(key, 2), (1, 5), 3, 100)
    imgs = D.generate_images(params, vae_params, text, cfg=CFG,
                             rng=jax.random.fold_in(key, 4))
    assert imgs.shape == (1, 32, 32, 3)
    assert bool(jnp.all(jnp.isfinite(imgs)))


def test_quantized_kv_cache_decode_close():
    """int8 KV cache (ops/decode.py): decode_step attention outputs track
    the fp-cache path within quantization tolerance, the cache really
    stores int8 rows, and a full generate runs finite end-to-end."""
    from dalle_pytorch_tpu.ops import decode as decode_ops
    key = jax.random.PRNGKey(0)
    tcfg = CFG.transformer
    params = D.dalle_init(key, CFG)["transformer"]
    x = jax.random.normal(jax.random.fold_in(key, 1), (2, 8, CFG.dim))
    total = CFG.seq_len

    h_f, cache_f = decode_ops.prefill(params, x, cfg=tcfg, total_len=total)
    h_q, cache_q = decode_ops.prefill(params, x, cfg=tcfg, total_len=total,
                                      quantize_cache=True)
    assert cache_q["k"].dtype == jnp.int8
    assert cache_q["k_scale"].shape == cache_q["k"].shape[:-1]
    # prefill output is cache-independent (queries attend pre-cache keys)
    np.testing.assert_allclose(np.asarray(h_q), np.asarray(h_f), atol=1e-5)

    key_mask = decode_ops._full_key_mask(None, 2, 8, total)
    tok = jax.random.normal(jax.random.fold_in(key, 2), (2, CFG.dim))
    out_f, cache_f = decode_ops.decode_step(params, tok, 8, cache_f,
                                            cfg=tcfg, key_mask=key_mask)
    out_q, cache_q = decode_ops.decode_step(params, tok, 8, cache_q,
                                            cfg=tcfg, key_mask=key_mask)
    assert cache_q["k"].dtype == jnp.int8       # written row stays int8
    err = np.max(np.abs(np.asarray(out_q) - np.asarray(out_f)))
    ref = np.max(np.abs(np.asarray(out_f)))
    assert err / ref < 0.02, (err, ref)          # ~0.4% int8 step, headroom

    # a second step reads the quantized row written by the first
    tok2 = jax.random.normal(jax.random.fold_in(key, 3), (2, CFG.dim))
    out_f2, _ = decode_ops.decode_step(params, tok2, 9, cache_f,
                                       cfg=tcfg, key_mask=key_mask)
    out_q2, _ = decode_ops.decode_step(params, tok2, 9, cache_q,
                                       cfg=tcfg, key_mask=key_mask)
    err2 = np.max(np.abs(np.asarray(out_q2) - np.asarray(out_f2)))
    assert err2 / np.max(np.abs(np.asarray(out_f2))) < 0.02

    # end-to-end: weights AND cache int8 in one jit program
    vae_params = V.vae_init(jax.random.fold_in(key, 4), VCFG)
    dparams = D.quantize_for_decode(D.dalle_init(key, CFG, vae_params))
    text = jax.random.randint(jax.random.fold_in(key, 5), (1, 5), 3, 100)
    imgs = D.generate_images(dparams, vae_params, text, cfg=CFG,
                             rng=jax.random.fold_in(key, 6),
                             quantize_cache=True)
    assert imgs.shape == (1, 32, 32, 3)
    assert bool(jnp.all(jnp.isfinite(imgs)))


def test_quantized_moe_generation_runs():
    """Quantization composes with MoE decode: the router (a core.linear
    dict) quantizes, the expert einsum stacks stay raw — one program."""
    import dataclasses
    cfg = dataclasses.replace(CFG, moe_experts=2)
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG)
    params = D.quantize_for_decode(D.dalle_init(key, cfg, vae_params))
    moe_ff = params["transformer"]["ff"]["moe"]
    assert moe_ff["router"]["w_q"].dtype == jnp.int8
    assert moe_ff["w1"].dtype != jnp.int8          # expert stacks raw
    text = jax.random.randint(jax.random.fold_in(key, 2), (1, 5), 3, 100)
    imgs = D.generate_images(params, vae_params, text, cfg=cfg,
                             rng=jax.random.fold_in(key, 4))
    assert imgs.shape == (1, 32, 32, 3)
    assert bool(jnp.all(jnp.isfinite(imgs)))
