"""End-to-end CLI tests on tiny synthetic data (SURVEY.md §4f): one real
train_vae run (loss decreases, checkpoint restorable), kill/resume, the
VAE->DALLE->gen_dalle pipeline text-in -> PNG-out, and the mix_vae demo."""

import json
import os

import numpy as np
import pytest

from dalle_pytorch_tpu import checkpoint as ckpt

IMG = 16          # tiny images: 2 conv layers -> 4x4 = 16 image tokens


@pytest.fixture(scope="module")
def workdir(tmp_path_factory):
    """Synthetic dataset: 8 images + captions, shared dirs for all tests."""
    from PIL import Image
    root = tmp_path_factory.mktemp("cli")
    img_dir = root / "imagedata" / "0"
    img_dir.mkdir(parents=True)
    rng = np.random.default_rng(0)
    names = []
    for i in range(8):
        arr = np.zeros((IMG, IMG, 3), np.uint8)
        # structured content so the VAE has something to learn
        arr[:, :, i % 3] = 255
        arr[i:i + 6, i:i + 6] = rng.integers(0, 255, (6, 6, 3))
        name = f"img{i}.png"
        Image.fromarray(arr).save(img_dir / name)
        names.append(name)
    colors = ["red", "blue", "green", "gray"]
    (root / "only.txt").write_text(
        "".join(f"a {colors[i % 4]} square\n" for i in range(8)))
    (root / "pairs.txt").write_text(
        "".join(f"{n} : a {colors[i % 4]} square\n"
                for i, n in enumerate(names)))
    (root / "models").mkdir()
    (root / "results").mkdir()
    return root


def vae_args(root, extra=()):
    return [
        "--dataPath", str(root / "imagedata"),
        "--imageSize", str(IMG), "--batchSize", "4",
        "--num_layers", "2", "--num_tokens", "24", "--codebook_dim", "16",
        "--hidden_dim", "8", "--lr", "3e-3",
        "--models_dir", str(root / "models"),
        "--results_dir", str(root / "results"),
        "--metrics", str(root / "metrics.jsonl"),
        "--log_interval", "1", "--dp", "1",
    ] + list(extra)


@pytest.mark.slow
class TestTrainVAE:
    def test_two_epochs_decreasing_loss_and_artifacts(self, workdir):
        from dalle_pytorch_tpu.cli.train_vae import main
        # --guard_transfers: the CI train smoke runs the real step body
        # under analysis.guards.no_transfers — an implicit host<->device
        # transfer creeping into the hot path fails the test, naming the
        # offending call (ROADMAP's no_transfers-around-train-step item)
        main(vae_args(workdir, ["--n_epochs", "2", "--tempsched",
                                "--guard_transfers"]))

        # loss decreased epoch 0 -> 1
        losses = {}
        with open(workdir / "metrics.jsonl") as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("event") == "checkpoint":
                    losses[rec["epoch"]] = rec["avg_loss"]
        assert losses[1] < losses[0]

        # recon grid written per epoch
        assert (workdir / "results" / "vae_epoch_0.png").exists()
        assert (workdir / "results" / "vae_epoch_1.png").exists()

        # checkpoint restorable with config + schedule state
        path, epoch = ckpt.latest(str(workdir / "models"), "vae")
        assert epoch == 1
        params, manifest = ckpt.restore_params(path)
        assert manifest["kind"] == "vae"
        assert manifest["meta"]["temperature"] < 0.9   # tempsched ran
        cfg = ckpt.vae_config_from_manifest(manifest)
        assert cfg.image_size == IMG and cfg.num_tokens == 24

    def test_resume_from_checkpoint(self, workdir):
        """Kill/resume: epoch numbering continues, opt state restores
        (reference --loadVAE/--start_epoch, trainVAE.py:20-21,52-54)."""
        from dalle_pytorch_tpu.cli.train_vae import main
        main(vae_args(workdir, ["--n_epochs", "1", "--loadVAE", "vae",
                                "--start_epoch", "2"]))
        path, epoch = ckpt.latest(str(workdir / "models"), "vae")
        assert epoch == 2
        assert ckpt.load_manifest(path)["meta"]["epoch"] == 2


def require_ckpt(workdir, name, epoch):
    """The CLI tests build on each other's checkpoints through the
    module-scoped workdir (train_vae -> train_dalle -> gen/mix/clip).
    Running a later class alone skips with a pointer instead of a
    confusing FileNotFoundError."""
    if ckpt.latest(str(workdir / "models"), name) is None:
        pytest.skip(f"needs the {name!r} checkpoint from the earlier CLI "
                    "tests in this module — run the whole file")


@pytest.mark.slow
class TestTrainDALLE:
    def test_train_and_sample(self, workdir):
        require_ckpt(workdir, "vae", 2)
        from dalle_pytorch_tpu.cli.train_dalle import main
        main([
            "--dataPath", str(workdir / "imagedata"),
            "--imageSize", str(IMG), "--batchSize", "4",
            "--captions_only", str(workdir / "only.txt"),
            "--captions", str(workdir / "pairs.txt"),
            "--vaename", "vae", "--vae_epoch", "2",
            "--name", "toy", "--n_epochs", "1",
            "--dim", "16", "--depth", "2", "--heads", "2",
            "--dim_head", "8", "--num_text_tokens", "50",
            "--text_seq_len", "8", "--attn_dropout", "0",
            "--ff_dropout", "0", "--lr", "1e-3",
            "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
            "--log_interval", "1", "--dp", "1", "--sample_every", "1",
            "--guard_transfers",
        ])
        # checkpoint + vocab + sample grid exist
        path, epoch = ckpt.latest(str(workdir / "models"), "toy_dalle")
        assert epoch == 0
        manifest = ckpt.load_manifest(path)
        assert manifest["kind"] == "dalle"
        assert manifest["meta"]["vae_checkpoint"].endswith("vae-2")
        assert (workdir / "models" / "toy-vocab.json").exists()
        assert (workdir / "results" / "toy_dalle_epoch_0.png").exists()

        # codebook tie: image_emb was seeded from the VAE codebook and
        # trained; config round-trips
        cfg = ckpt.dalle_config_from_manifest(manifest)
        assert cfg.dim == 16 and cfg.vae.num_tokens == 24

    def test_gen_dalle_text_to_png(self, workdir):
        require_ckpt(workdir, "toy_dalle", 0)
        from dalle_pytorch_tpu.cli.gen_dalle import main
        main([
            "a red square",
            "--name", "toy", "--dalle_epoch", "0",
            "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
            "--num_images", "2",
        ])
        outs = [f for f in os.listdir(workdir / "results")
                if f.startswith("gendalletoy_epoch_0-")]
        assert outs, "gen_dalle wrote no PNG"

    def test_ema_train_and_sample(self, workdir):
        """--ema_decay writes EMA weights with the checkpoint and
        gen_dalle --use_ema samples from them (beyond-reference)."""
        require_ckpt(workdir, "vae", 2)
        from dalle_pytorch_tpu.cli.gen_dalle import main as gen_main
        from dalle_pytorch_tpu.cli.train_dalle import main as train_main
        train_main([
            "--dataPath", str(workdir / "imagedata"),
            "--imageSize", str(IMG), "--batchSize", "4",
            "--captions_only", str(workdir / "only.txt"),
            "--captions", str(workdir / "pairs.txt"),
            "--vaename", "vae", "--vae_epoch", "2",
            "--name", "toy_ema", "--n_epochs", "1",
            "--dim", "16", "--depth", "2", "--heads", "2",
            "--dim_head", "8", "--num_text_tokens", "50",
            "--text_seq_len", "8", "--attn_dropout", "0",
            "--ff_dropout", "0", "--lr", "1e-3",
            "--ema_decay", "0.99",
            "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
            "--log_interval", "1", "--dp", "1", "--sample_every", "0",
        ])
        path, _ = ckpt.latest(str(workdir / "models"), "toy_ema_dalle")
        ema = ckpt.restore_ema(path)
        assert ema is not None
        import jax.numpy as jnp
        assert all(leaf.dtype == jnp.float32
                   for leaf in __import__("jax").tree.leaves(ema))
        before = set(os.listdir(workdir / "results"))
        gen_main([
            "a red square",
            "--name", "toy_ema", "--dalle_epoch", "0", "--use_ema",
            "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
        ])
        new = set(os.listdir(workdir / "results")) - before
        assert any(f.startswith("gendalletoy_ema_epoch_0-") for f in new)

    def test_caption_drop_and_guided_gen(self, workdir):
        """--caption_drop trains through null captions; gen_dalle
        --guidance samples with classifier-free guidance."""
        require_ckpt(workdir, "vae", 2)
        from dalle_pytorch_tpu.cli.gen_dalle import main as gen_main
        from dalle_pytorch_tpu.cli.train_dalle import main as train_main
        train_main([
            "--dataPath", str(workdir / "imagedata"),
            "--imageSize", str(IMG), "--batchSize", "4",
            "--captions_only", str(workdir / "only.txt"),
            "--captions", str(workdir / "pairs.txt"),
            "--vaename", "vae", "--vae_epoch", "2",
            "--name", "toy_cfg", "--n_epochs", "1",
            "--dim", "16", "--depth", "2", "--heads", "2",
            "--dim_head", "8", "--num_text_tokens", "50",
            "--text_seq_len", "8", "--attn_dropout", "0",
            "--ff_dropout", "0", "--lr", "1e-3",
            "--caption_drop", "0.5",
            "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
            "--log_interval", "1", "--dp", "1", "--sample_every", "0",
        ])
        before = set(os.listdir(workdir / "results"))
        gen_main([
            "a red square",
            "--name", "toy_cfg", "--dalle_epoch", "0",
            "--guidance", "3.0",
            "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
        ])
        new = set(os.listdir(workdir / "results")) - before
        assert any(f.startswith("gendalletoy_cfg_epoch_0-") for f in new)

    def test_caption_drop_rejected_under_sp(self, workdir):
        from dalle_pytorch_tpu.cli.train_dalle import main as train_main
        with pytest.raises(SystemExit, match="dense path"):
            train_main([
                "--dataPath", str(workdir / "imagedata"),
                "--captions_only", str(workdir / "only.txt"),
                "--captions", str(workdir / "pairs.txt"),
                "--vaename", "vae", "--vae_epoch", "2",
                "--caption_drop", "0.1", "--sp", "2", "--dp", "1",
                "--models_dir", str(workdir / "models"),
                "--results_dir", str(workdir / "results"),
            ])

    @pytest.mark.parametrize("mode", ["int8", "int8_kv"])
    def test_gen_dalle_quantized(self, workdir, mode):
        """--quantize int8 runs the same sampler on int8 linears
        (ops/quant.py); int8_kv additionally stores the KV cache int8
        (ops/decode.py). Both still write a grid."""
        require_ckpt(workdir, "toy_dalle", 0)
        from dalle_pytorch_tpu.cli.gen_dalle import main
        before = set(os.listdir(workdir / "results"))
        main([
            "a red square",
            "--name", "toy", "--dalle_epoch", "0",
            "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
            "--quantize", mode,
        ])
        new = set(os.listdir(workdir / "results")) - before
        assert any(f.startswith("gendalletoy_epoch_0-") for f in new), \
            "quantized gen_dalle wrote no PNG"

    def test_gen_dalle_clip_rerank(self, workdir):
        require_ckpt(workdir, "toy_dalle", 0)
        """--clip_name reranks the jitted sampler's output (reference
        dalle_pytorch.py:354-356); scores print best-first and a grid is
        still written."""
        import jax
        import jax.numpy as jnp
        from dalle_pytorch_tpu.models import clip as C
        ccfg = C.CLIPConfig(dim_text=16, dim_image=16, dim_latent=8,
                            num_text_tokens=50, text_seq_len=8,
                            text_enc_depth=1, visual_enc_depth=1,
                            text_heads=2, visual_heads=2,
                            visual_image_size=IMG, visual_patch_size=8,
                            sparse_attn=False)
        cparams = C.clip_init(jax.random.PRNGKey(3), ccfg)
        ckpt.save(ckpt.ckpt_path(str(workdir / "models"), "clip", 0),
                  cparams, step=0, config=ccfg, kind="clip")

        from dalle_pytorch_tpu.cli.gen_dalle import main
        scores_path = workdir / "scores.jsonl"
        main([
            "a red square",
            "--name", "toy", "--dalle_epoch", "0",
            "--clip_name", "clip", "--clip_epoch", "0",
            "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
            "--num_images", "2", "--guidance", "0",
            "--scores_json", str(scores_path),
        ])
        outs = [f for f in os.listdir(workdir / "results")
                if f.startswith("gendalletoy_epoch_0-")]
        assert outs
        # --scores_json appended a machine-readable adherence record
        import json
        rec = json.loads(scores_path.read_text().splitlines()[-1])
        assert rec["caption"] == "a red square"
        assert rec["guidance"] == 0.0
        assert len(rec["scores"]) == 2
        assert rec["scores"] == sorted(rec["scores"], reverse=True)

    def test_gen_dalle_oov_raises(self, workdir):
        from dalle_pytorch_tpu.cli.gen_dalle import main
        with pytest.raises(KeyError):
            main(["a purple hexagon", "--name", "toy", "--dalle_epoch", "0",
                  "--models_dir", str(workdir / "models"),
                  "--results_dir", str(workdir / "results")])


@pytest.mark.slow
class TestMixVAE:
    def test_mix_grids(self, workdir):
        from dalle_pytorch_tpu.cli.mix_vae import main
        out_dir = workdir / "mixed"
        main([
            "--vaename", "vae", "--load_epoch", "2",
            "--models_dir", str(workdir / "models"),
            "--dataPath", str(workdir / "imagedata"),
            "--imageSize", str(IMG), "--batchSize", "4",
            "--out_dir", str(out_dir), "--max_batches", "1",
        ])
        assert (out_dir / "mixed_epoch_2_0.png").exists()


class TestResolveResume:
    def test_bare_name_uses_latest(self, tmp_path):
        from dalle_pytorch_tpu.cli.common import resolve_resume
        params = {"w": np.zeros((2,))}
        for e in (0, 4):
            ckpt.save(ckpt.ckpt_path(str(tmp_path), "vae", e), params,
                      step=e)
        path, start = resolve_resume("vae", str(tmp_path), 0)
        assert path.endswith("vae-4") and start == 5

    def test_explicit_epoch(self, tmp_path):
        from dalle_pytorch_tpu.cli.common import resolve_resume
        path, start = resolve_resume("vae", str(tmp_path), 3)
        assert path.endswith("vae-2") and start == 3

    def test_missing_name_raises(self, tmp_path):
        from dalle_pytorch_tpu.cli.common import resolve_resume
        with pytest.raises(FileNotFoundError):
            resolve_resume("ghost", str(tmp_path), 0)


@pytest.mark.slow
class TestParamDtype:
    def test_bf16_vae_trains_and_checkpoints(self, workdir, tmp_path):
        import jax
        import jax.numpy as jnp
        from dalle_pytorch_tpu.cli.train_vae import main
        main(vae_args(workdir, ["--n_epochs", "1", "--param_dtype",
                                "bfloat16", "--name", "vae16",
                                "--models_dir", str(tmp_path)]))
        path, _ = ckpt.latest(str(tmp_path), "vae16")
        params, _ = ckpt.restore_params(path)
        leaves = jax.tree.leaves(params)
        assert all(leaf.dtype == jnp.bfloat16 for leaf in leaves)


class TestLRScheduleMath:
    """make_optimizer's schedule values, independent of any CLI run."""

    @staticmethod
    def _args(**kw):
        import argparse
        base = dict(lr=1e-3, lr_schedule="cosine", warmup_steps=10,
                    decay_steps=0, lr_end_ratio=0.1, n_epochs=4)
        base.update(kw)
        return argparse.Namespace(**base)

    @staticmethod
    def _lr_at(opt, step):
        """Effective LR at ``step`` read off a single-param update."""
        import jax.numpy as jnp
        params = {"w": jnp.zeros(())}
        state = opt.init(params)
        # advance the optimizer count to `step`
        for _ in range(step):
            _, state = opt.update({"w": jnp.ones(())}, state, params)
        upd, _ = opt.update({"w": jnp.ones(())}, state, params)
        # adam update of a constant unit gradient = -lr (bias-corrected
        # m/sqrt(v) == 1 for every step with a constant gradient)
        return float(-upd["w"])

    def test_warmup_reaches_peak_and_decays_to_floor(self):
        from dalle_pytorch_tpu.cli.common import make_optimizer
        args = self._args()
        opt = make_optimizer(args, steps_per_epoch=10, start_epoch=0)
        lr_peak = self._lr_at(opt, 10)        # end of warmup
        lr_mid = self._lr_at(opt, 25)
        lr_end = self._lr_at(opt, 40)         # horizon = 4 * 10
        assert lr_peak == pytest.approx(1e-3, rel=0.05)
        assert 1e-4 < lr_mid < 1e-3
        assert lr_end == pytest.approx(1e-4, rel=0.1)   # lr * end_ratio

    def test_resume_extends_horizon(self):
        """start_epoch shifts the cosine horizon so a resumed run keeps
        decaying instead of sitting at the floor from step 0."""
        from dalle_pytorch_tpu.cli.common import make_optimizer
        args = self._args(warmup_steps=0)
        fresh = make_optimizer(args, steps_per_epoch=10, start_epoch=0)
        resumed = make_optimizer(args, steps_per_epoch=10, start_epoch=4)
        # at optimizer step 40: the fresh horizon (40) is exhausted, the
        # resumed horizon (80) is mid-decay
        assert self._lr_at(fresh, 40) == pytest.approx(1e-4, rel=0.1)
        assert self._lr_at(resumed, 40) > 2e-4

    def test_constant_with_warmup_holds_peak(self):
        from dalle_pytorch_tpu.cli.common import make_optimizer
        args = self._args(lr_schedule="constant", warmup_steps=5)
        opt = make_optimizer(args, steps_per_epoch=10, start_epoch=0)
        assert self._lr_at(opt, 2) < 1e-3
        assert self._lr_at(opt, 50) == pytest.approx(1e-3, rel=0.02)

    def test_clip_grad_norm_chains_and_clips(self):
        """--clip_grad_norm caps the gradient BEFORE adam's moments.
        Adam's first step is sign-normalized (update ~ g/|g| for any
        magnitude), so a one-step comparison cannot see the clip; the
        second moment CAN — an unclipped 5e6-norm gradient poisons v and
        collapses the next update toward zero, a clipped one does not."""
        import jax.numpy as jnp

        from dalle_pytorch_tpu.cli.common import make_optimizer

        def two_step_second_update(opt):
            params = {"w": jnp.zeros((2,))}
            state = opt.init(params)
            u1, state = opt.update({"w": jnp.array([3e6, 4e6])}, state,
                                   params)
            u2, _ = opt.update({"w": jnp.array([0.6, 0.8])}, state, params)
            return u2["w"]

        clipped = make_optimizer(self._args(lr_schedule="constant",
                                            warmup_steps=0,
                                            clip_grad_norm=1.0))
        plain = make_optimizer(self._args(lr_schedule="constant",
                                          warmup_steps=0,
                                          clip_grad_norm=0.0))
        u2_clip = two_step_second_update(clipped)
        u2_plain = two_step_second_update(plain)
        # with the clip, step 2 sees two same-scale gradients -> full
        # lr-sized update; without it, the 5e6-norm outlier dominates both
        # moments and drags the next update to ~0.67*lr (adam's bias
        # correction cancels most but not all of the poisoning). The gap
        # exists ONLY when the clip is chained.
        assert float(jnp.abs(u2_clip).min()) > 0.98e-3
        assert float(jnp.abs(u2_plain).max()) < 0.75e-3

    def test_resolve_schedule_snapshot_wins_on_resume(self):
        """--auto_resume reconstructs the ORIGINAL cosine horizon from the
        checkpoint's persisted lr_schedule meta: a restart with the
        remaining epoch count (n_epochs=1) must NOT shrink the decay to
        the remaining run (ROADMAP open item)."""
        from dalle_pytorch_tpu.cli.common import resolve_schedule
        # original run: 4 epochs x 10 steps -> horizon 30 after warmup
        orig = resolve_schedule(self._args(), steps_per_epoch=10,
                                start_epoch=0)
        assert orig["decay_steps"] == 30
        assert orig["epochs_total"] == 4
        # restart passes only the REMAINING epochs; the snapshot rides the
        # checkpoint meta and keeps the original horizon + total
        resumed = resolve_schedule(self._args(n_epochs=1),
                                   steps_per_epoch=10, start_epoch=3,
                                   resume_meta={"lr_schedule": orig})
        assert resumed["decay_steps"] == 30
        assert resumed["epochs_total"] == 4
        # an explicit --decay_steps still wins over the snapshot
        forced = resolve_schedule(self._args(n_epochs=1, decay_steps=77),
                                  steps_per_epoch=10, start_epoch=3,
                                  resume_meta={"lr_schedule": orig})
        assert forced["decay_steps"] == 77

    def test_make_optimizer_uses_schedule_snapshot(self):
        """An original run pinned --decay_steps 120; the restart does NOT
        re-pass it. With the checkpoint's snapshot the optimizer keeps
        decaying over the original 120-step horizon; without it, the
        recomputed default horizon (40) has already bottomed out."""
        from dalle_pytorch_tpu.cli.common import (make_optimizer,
                                                  resolve_schedule)
        orig = resolve_schedule(self._args(warmup_steps=0,
                                           decay_steps=120),
                                steps_per_epoch=10, start_epoch=0)
        assert orig["decay_steps"] == 120
        restart_args = self._args(warmup_steps=0, n_epochs=1)   # no flag
        snap = resolve_schedule(restart_args, steps_per_epoch=10,
                                start_epoch=3,
                                resume_meta={"lr_schedule": orig})
        with_snap = make_optimizer(restart_args, schedule=snap)
        without = make_optimizer(restart_args, steps_per_epoch=10,
                                 start_epoch=3)
        assert self._lr_at(without, 50) == pytest.approx(1e-4, rel=0.1)
        assert self._lr_at(with_snap, 50) > 2e-4

    def test_resume_with_toggled_clip_fails_clearly(self):
        """Toggling --clip_grad_norm on resume changes the opt-state tree;
        restore must say which flags to check, not raise a raw flax
        KeyError (checkpoint.restore_train guard)."""
        import jax.numpy as jnp

        from dalle_pytorch_tpu import checkpoint as ckpt_mod
        from dalle_pytorch_tpu.cli.common import make_optimizer
        params = {"w": jnp.zeros((2,))}
        plain = make_optimizer(self._args(lr_schedule="constant",
                                          warmup_steps=0,
                                          clip_grad_norm=0.0))
        import tempfile
        with tempfile.TemporaryDirectory() as d:
            path = ckpt_mod.save(f"{d}/ck-0", params,
                                 opt_state=plain.init(params),
                                 config={}, meta={})
            clipped = make_optimizer(self._args(lr_schedule="constant",
                                                warmup_steps=0,
                                                clip_grad_norm=1.0))
            with pytest.raises(ValueError, match="clip_grad_norm"):
                ckpt_mod.restore_train(path, clipped)


@pytest.mark.slow
class TestLRSchedule:
    def test_cosine_warmup_trains(self, workdir, tmp_path):
        """--lr_schedule cosine --warmup_steps: beyond-reference schedule
        (fixed-LR Adam only, reference trainVAE.py:69) trains and
        checkpoints; the horizon defaults to the requested run length."""
        from dalle_pytorch_tpu.cli.train_vae import main
        main(vae_args(workdir, [
            "--n_epochs", "1", "--name", "cosvae",
            "--lr_schedule", "cosine", "--warmup_steps", "2",
            "--models_dir", str(tmp_path),
        ]))
        assert ckpt.latest(str(tmp_path), "cosvae")[1] == 0

    def test_schedule_resumes_from_opt_count(self, workdir, tmp_path):
        """Resume continues the schedule: the restored opt state carries
        the step count the schedule rides."""
        from dalle_pytorch_tpu.cli.train_vae import main
        sched = ["--lr_schedule", "cosine", "--warmup_steps", "2",
                 "--models_dir", str(tmp_path)]
        main(vae_args(workdir, ["--n_epochs", "1", "--name", "cosres"]
                      + sched))
        main(vae_args(workdir, ["--n_epochs", "1", "--name", "cosres",
                                "--loadVAE", "cosres"] + sched))
        assert ckpt.latest(str(tmp_path), "cosres")[1] == 1


@pytest.mark.slow
class TestTrainDALLESequenceParallel:
    def test_sp_train_runs_and_checkpoints(self, workdir, tmp_path):
        require_ckpt(workdir, "vae", 2)
        """--sp 4 on the 8-device CPU mesh: dp=2 x sp=4, ring attention in
        the stack, one epoch trains and checkpoints."""
        from dalle_pytorch_tpu.cli.train_dalle import main
        main([
            "--dataPath", str(workdir / "imagedata"),
            "--imageSize", str(IMG), "--batchSize", "4",
            "--captions_only", str(workdir / "only.txt"),
            "--captions", str(workdir / "pairs.txt"),
            "--vaename", "vae", "--vae_epoch", "2",
            "--name", "sptoy", "--n_epochs", "1",
            "--dim", "16", "--depth", "2", "--heads", "4",
            "--dim_head", "4", "--num_text_tokens", "50",
            "--text_seq_len", "8", "--attn_dropout", "0",
            "--ff_dropout", "0", "--lr", "1e-3", "--sp", "4",
            "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
            "--log_interval", "1", "--sample_every", "100",
        ])
        path, epoch = ckpt.latest(str(workdir / "models"), "sptoy_dalle")
        assert epoch == 0

    def test_sp_trains_with_dropout(self, workdir):
        """--sp with the flagship nonzero dropout (r3 item 7): accepted and
        trains — positional dropout keys make it SPMD-safe."""
        require_ckpt(workdir, "vae", 2)
        from dalle_pytorch_tpu.cli.train_dalle import main
        main([
            "--dataPath", str(workdir / "imagedata"),
            "--imageSize", str(IMG), "--batchSize", "4",
            "--captions_only", str(workdir / "only.txt"),
            "--captions", str(workdir / "pairs.txt"),
            "--vaename", "vae", "--vae_epoch", "2",
            "--name", "spdrop", "--n_epochs", "1",
            "--dim", "16", "--depth", "2", "--heads", "4",
            "--dim_head", "4", "--num_text_tokens", "50",
            "--text_seq_len", "8", "--attn_dropout", "0.1",
            "--ff_dropout", "0.1", "--lr", "1e-3", "--sp", "4",
            "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
            "--log_interval", "1", "--sample_every", "100",
        ])
        path, epoch = ckpt.latest(str(workdir / "models"), "spdrop_dalle")
        assert epoch == 0

    def test_sp_trains_with_remat_full(self, workdir):
        """--sp 4 --remat full (VERDICT r4 item 7): sequence sharding and
        activation thrift compose in one program — the long-context
        training recipe trains and checkpoints through the CLI."""
        require_ckpt(workdir, "vae", 2)
        from dalle_pytorch_tpu.cli.train_dalle import main
        main([
            "--dataPath", str(workdir / "imagedata"),
            "--imageSize", str(IMG), "--batchSize", "4",
            "--captions_only", str(workdir / "only.txt"),
            "--captions", str(workdir / "pairs.txt"),
            "--vaename", "vae", "--vae_epoch", "2",
            "--name", "spremat", "--n_epochs", "1",
            "--dim", "16", "--depth", "2", "--heads", "4",
            "--dim_head", "4", "--num_text_tokens", "50",
            "--text_seq_len", "8", "--attn_dropout", "0",
            "--ff_dropout", "0", "--lr", "1e-3", "--sp", "4",
            "--remat", "full",
            "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
            "--log_interval", "1", "--sample_every", "100",
        ])
        path, epoch = ckpt.latest(str(workdir / "models"), "spremat_dalle")
        assert epoch == 0


class TestTrainDALLEMoE:
    def test_moe_train_runs_and_checkpoints(self, workdir):
        """--moe_experts 4: the MoE FF trains end-to-end through the CLI
        (aux loss in the objective) and checkpoints."""
        require_ckpt(workdir, "vae", 2)
        from dalle_pytorch_tpu.cli.train_dalle import main
        main([
            "--dataPath", str(workdir / "imagedata"),
            "--imageSize", str(IMG), "--batchSize", "8",
            "--captions_only", str(workdir / "only.txt"),
            "--captions", str(workdir / "pairs.txt"),
            "--vaename", "vae", "--vae_epoch", "2",
            "--name", "moetoy", "--n_epochs", "1",
            "--dim", "16", "--depth", "2", "--heads", "4",
            "--dim_head", "4", "--num_text_tokens", "50",
            "--text_seq_len", "8", "--moe_experts", "4",
            "--lr", "1e-3", "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
            "--log_interval", "1", "--sample_every", "100",
        ])
        path, epoch = ckpt.latest(str(workdir / "models"), "moetoy_dalle")
        assert epoch == 0


class TestTrainDALLERemat:
    def test_remat_full_trains_and_checkpoints(self, workdir):
        """--remat full: the rematerialized layer body trains end-to-end
        through the CLI (the batch-unlocking lever, ANALYSIS_NORTH.md)."""
        require_ckpt(workdir, "vae", 2)
        from dalle_pytorch_tpu.cli.train_dalle import main
        main([
            "--dataPath", str(workdir / "imagedata"),
            "--imageSize", str(IMG), "--batchSize", "8",
            "--captions_only", str(workdir / "only.txt"),
            "--captions", str(workdir / "pairs.txt"),
            "--vaename", "vae", "--vae_epoch", "2",
            "--name", "remattoy", "--n_epochs", "1",
            "--dim", "16", "--depth", "2", "--heads", "4",
            "--dim_head", "4", "--num_text_tokens", "50",
            "--text_seq_len", "8", "--remat", "full",
            "--lr", "1e-3", "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
            "--log_interval", "1", "--sample_every", "100",
        ])
        path, epoch = ckpt.latest(str(workdir / "models"), "remattoy_dalle")
        assert epoch == 0


class TestTrainDALLEPipelineParallel:
    def test_pp_train_runs_and_checkpoints(self, workdir):
        """--pp 4 on the 8-device CPU mesh: dp=2 x pp=4, one layer per
        stage with the stack stage-sharded, one epoch trains and
        checkpoints (r3 item 6: pp is trainable, mirroring --sp)."""
        require_ckpt(workdir, "vae", 2)
        from dalle_pytorch_tpu.cli.train_dalle import main
        main([
            "--dataPath", str(workdir / "imagedata"),
            "--imageSize", str(IMG), "--batchSize", "8",
            "--captions_only", str(workdir / "only.txt"),
            "--captions", str(workdir / "pairs.txt"),
            "--vaename", "vae", "--vae_epoch", "2",
            "--name", "pptoy", "--n_epochs", "1",
            "--dim", "16", "--depth", "4", "--heads", "4",
            "--dim_head", "4", "--num_text_tokens", "50",
            "--text_seq_len", "8", "--attn_dropout", "0.1",
            "--ff_dropout", "0.1", "--lr", "1e-3", "--pp", "4",
            "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
            "--log_interval", "1", "--sample_every", "100",
        ])
        path, epoch = ckpt.latest(str(workdir / "models"), "pptoy_dalle")
        assert epoch == 0


@pytest.mark.slow
class TestTrainCLIP:
    def test_train_and_rerank_pipeline(self, workdir):
        require_ckpt(workdir, "toy_dalle", 0)
        """train_clip one epoch on the synthetic pairs, then gen_dalle
        reranks with the TRAINED checkpoint — the full reranker pipeline
        (reference README.md:119-126) as CLIs."""
        from dalle_pytorch_tpu.cli.train_clip import main
        main([
            "--dataPath", str(workdir / "imagedata"),
            "--imageSize", str(IMG), "--batchSize", "4",
            "--captions_only", str(workdir / "only.txt"),
            "--captions", str(workdir / "pairs.txt"),
            "--name", "clipcli", "--n_epochs", "1",
            "--dim_text", "16", "--dim_image", "16", "--dim_latent", "8",
            "--num_text_tokens", "50", "--text_seq_len", "8",
            "--text_enc_depth", "1", "--visual_enc_depth", "1",
            "--text_heads", "2", "--visual_heads", "2",
            "--visual_patch_size", "8", "--dense", "--lr", "1e-3",
            "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
            "--log_interval", "1", "--dp", "1", "--guard_transfers",
        ])
        path, epoch = ckpt.latest(str(workdir / "models"), "clipcli")
        assert epoch == 0
        manifest = ckpt.load_manifest(path)
        assert manifest["kind"] == "clip"

        from dalle_pytorch_tpu.cli.gen_dalle import main as gen_main
        gen_main([
            "a red square",
            "--name", "toy", "--dalle_epoch", "0",
            "--clip_name", "clipcli", "--clip_epoch", "0",
            "--models_dir", str(workdir / "models"),
            "--results_dir", str(workdir / "results"),
            "--num_images", "2",
        ])
