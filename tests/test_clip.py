"""CLIP tests: pooling, normalization, InfoNCE, rerank integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import clip as C
from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V

CFG = C.CLIPConfig(dim_text=32, dim_image=32, dim_latent=24,
                   num_text_tokens=100, text_enc_depth=2, text_seq_len=16,
                   text_heads=2, visual_enc_depth=2, visual_heads=2,
                   visual_image_size=32, visual_patch_size=8,
                   sparse_attn=False)


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


@pytest.fixture
def params(key):
    return C.clip_init(key, CFG)


def _batch(key, b=3):
    kt, ki = jax.random.split(key)
    text = jax.random.randint(kt, (b, CFG.text_seq_len), 0, 100)
    imgs = jax.random.uniform(ki, (b, 32, 32, 3), minval=-1, maxval=1)
    return text, imgs


def test_config_patch_divisibility():
    with pytest.raises(ValueError):
        C.CLIPConfig(visual_image_size=30, visual_patch_size=8)


def test_scores_shape_and_latent_norm(key, params):
    text, imgs = _batch(key)
    scores = C.clip_apply(params, text, imgs, cfg=CFG)
    assert scores.shape == (3,)
    tl = C.encode_text(params, text, CFG)
    il = C.encode_image(params, imgs, CFG)
    np.testing.assert_allclose(np.linalg.norm(np.array(tl), axis=-1), 1.0,
                               rtol=1e-5)
    np.testing.assert_allclose(np.linalg.norm(np.array(il), axis=-1), 1.0,
                               rtol=1e-5)
    # paired scores = diagonal of the sim matrix * exp(temperature)
    sim = np.array(tl) @ np.array(il).T * np.exp(
        float(params["temperature"]))
    np.testing.assert_allclose(np.array(scores), np.diag(sim), atol=1e-5)


def test_infonce_loss_one_directional(key, params):
    text, imgs = _batch(key)
    loss = C.clip_apply(params, text, imgs, cfg=CFG, return_loss=True)
    tl = np.array(C.encode_text(params, text, CFG))
    il = np.array(C.encode_image(params, imgs, CFG))
    sim = tl @ il.T * np.exp(float(params["temperature"]))
    logp = sim - np.log(np.exp(sim).sum(-1, keepdims=True))
    manual = -np.mean(np.diag(logp))       # text->image CE vs arange labels
    np.testing.assert_allclose(float(loss), manual, rtol=1e-4)


def test_masked_mean_pooling(key, params):
    text, imgs = _batch(key)
    mask = jnp.ones((3, CFG.text_seq_len), bool).at[:, 8:].set(False)
    a = C.clip_apply(params, text, imgs, cfg=CFG, text_mask=mask)
    b = C.clip_apply(params, text, imgs, cfg=CFG)
    assert not np.allclose(np.array(a), np.array(b))
    # masked_mean ignores padded rows entirely
    t = jax.random.normal(key, (2, 4, 8))
    m = jnp.asarray([[True, True, False, False], [True, False, False, False]])
    got = C.masked_mean(t, m)
    np.testing.assert_allclose(np.array(got[0]),
                               np.array(t[0, :2].mean(0)), rtol=1e-5)
    np.testing.assert_allclose(np.array(got[1]), np.array(t[1, 0]), rtol=1e-5)


def test_patchify_feature_order():
    """(p1, p2, c) ordering — row within patch is the slowest feature axis
    (weight-layout parity with the reference rearrange)."""
    img = jnp.arange(2 * 4 * 4 * 3, dtype=jnp.float32).reshape(2, 4, 4, 3)
    patches = C.patchify(img, 2)
    assert patches.shape == (2, 4, 12)
    first = np.array(patches[0, 0]).reshape(2, 2, 3)
    np.testing.assert_array_equal(first, np.array(img[0, :2, :2, :]))


def test_gradients_flow(key, params):
    text, imgs = _batch(key)
    g = jax.grad(lambda p: C.clip_apply(p, text, imgs, cfg=CFG,
                                        return_loss=True))(params)
    assert float(jnp.abs(g["temperature"]).sum()) > 0
    for leaf in jax.tree.leaves(g):
        assert np.isfinite(np.array(leaf)).all()


def test_sparse_default_runs(key):
    cfg = C.CLIPConfig(dim_text=32, dim_image=32, dim_latent=24,
                       num_text_tokens=50, text_enc_depth=1, text_seq_len=32,
                       text_heads=2, visual_enc_depth=1, visual_heads=2,
                       visual_image_size=32, visual_patch_size=4)
    assert cfg.sparse_attn is True          # the reference default
    params = C.clip_init(key, cfg)
    text = jax.random.randint(jax.random.fold_in(key, 1), (2, 32), 0, 50)
    imgs = jax.random.uniform(jax.random.fold_in(key, 2), (2, 32, 32, 3))
    scores = C.clip_apply(params, text, imgs, cfg=cfg)
    assert np.isfinite(np.array(scores)).all()


def test_rerank_integration(key):
    vae = V.DiscreteVAE(key, image_size=32, num_tokens=48, codebook_dim=32,
                        num_layers=2, hidden_dim=16)
    dalle = D.DALLE(dim=32, vae=vae, depth=1, key=key, num_text_tokens=100,
                    text_seq_len=16, heads=2, dim_head=16)
    clip = C.CLIP(key, **{**CFG.__dict__})
    text = jax.random.randint(key, (2, 16), 0, 100)
    images, scores = dalle.generate_images(text, rng=key, clip=clip)
    assert images.shape == (2, 32, 32, 3)
    assert scores.shape == (2,)
    assert np.isfinite(np.array(scores)).all()
