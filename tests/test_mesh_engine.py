"""Mesh-sharded serving engine tests (ISSUE 11 acceptance criteria).

The load-bearing one is BYTE-IDENTITY: for the same params / prompts /
seeds / sampling knobs, a ``MeshEngine`` pjit-sharded over a multi-device
mesh emits tokens identical to the single-device ``Engine`` (itself
pinned token-identical to ``generate_images`` by tests/test_serve.py) —
across fused-chunk sizes K, dense AND paged KV, int8-KV, and a
mid-stream join under ``guards.no_transfers`` with ``decode_traces ==
1``. The serve partition rules (parallel/serve_specs.py) make this hold
BY CONSTRUCTION — no contracted dimension is ever sharded, so every
collective is data movement, never a float reassociation — and these
tests are the tripwire for anything (a GSPMD propagation change, a new
spec rule) that would break it.

Plus the composition contract: a ``ReplicaSet`` whose replicas are mesh
SLICES fails over with zero loss and byte-identical replay through the
unchanged supervision logic, and the checkpoint-path attach spec loads/
validates locally with typed failure.

Runs on the forced multi-device CPU platform tests/conftest.py sets up
(``--xla_force_host_platform_device_count=8`` — the standard JAX
substitute for a pod). Tiny model (total_len 24): depth 2 and heads 2
both divide the 2-device mesh, so params AND the KV store genuinely
shard.
"""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.analysis import guards
from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V
from dalle_pytorch_tpu.parallel import serve_specs as SS
from dalle_pytorch_tpu.resilience import faults
from dalle_pytorch_tpu.resilience.retry import RetryPolicy
from dalle_pytorch_tpu.serve import (OK, Request, RequestQueue,
                                     SamplingParams)
from dalle_pytorch_tpu.serve.engine import Engine
from dalle_pytorch_tpu.serve.mesh_engine import (MeshEngine,
                                                 MeshPagedAttnError,
                                                 hbm_report)
from dalle_pytorch_tpu.serve.replica import ReplicaSet

VCFG = V.VAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                   num_layers=2, hidden_dim=8)
CFG = D.DALLEConfig(dim=16, depth=2, vae=VCFG, num_text_tokens=50,
                    text_seq_len=8, heads=2, dim_head=8)

FAST_BRINGUP = RetryPolicy(max_attempts=1, deadline_s=None,
                           base_backoff_s=0.01, backoff_multiplier=2.0,
                           max_backoff_s=0.1, jitter=0.0)

REQS = [
    Request(codes=(3, 7, 9), seed=11),
    Request(codes=(5, 2, 8, 1, 4), seed=23,
            sampling=SamplingParams(temperature=0.7, filter_thres=0.8)),
    Request(codes=(6, 6), seed=5,
            sampling=SamplingParams(temperature=1.3, top_p=0.9)),
]


@pytest.fixture(scope="module")
def bundle():
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG)
    params = D.dalle_init(key, CFG, vae_params)
    return params, vae_params


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.deactivate()
    yield
    faults.deactivate()


def mesh_devices(n=2):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices (conftest forces 8 on CPU)")
    return tuple(devs[:n])


# single-device reference tokens, memoized per engine config: the mesh
# engine's contract is equality with the single-device ENGINE (itself
# pinned to generate_images by test_serve), so the reference is the
# cheap one-chip run, not a generate_images resample per test
_REF: dict = {}


def engine_tokens(params, engine_cls, *, K=8, reqs=REQS, **kw):
    queue = RequestQueue(max_depth=16)
    engine = engine_cls(params, CFG, queue, num_slots=2, chunk_steps=K,
                        **kw)
    handles = [queue.submit(r) for r in reqs]
    engine.run_until_idle()
    toks = []
    for h in handles:
        res = h.result(timeout=60)
        assert res.status == OK, (res.status, res.reason)
        toks.append(np.asarray(res.tokens))
    return engine, toks


def single_device_tokens(params, *, K=8, reqs=REQS, **kw):
    key = (K, len(reqs), tuple(sorted(kw.items())))
    if key not in _REF:
        _, toks = engine_tokens(params, Engine, K=K, reqs=reqs, **kw)
        _REF[key] = toks
    return _REF[key]


class TestMeshByteIdentity:
    @pytest.mark.parametrize("K", [1, 8])
    def test_dense_tokens_byte_identical(self, bundle, K):
        """THE acceptance criterion: same requests, same seeds — the
        2-device mesh engine's tokens equal the single-device engine's
        byte for byte, with the fused decode program compiled exactly
        once for the engine's life."""
        params, _ = bundle
        ref = single_device_tokens(params, K=K)
        engine, toks = engine_tokens(params, MeshEngine, K=K,
                                     devices=mesh_devices())
        assert engine.decode_traces == 1
        assert engine.params_sharded and engine.kv_sharded
        for a, b in zip(ref, toks):
            np.testing.assert_array_equal(a, b)

    @pytest.mark.parametrize("K", [1, 8])
    def test_paged_tokens_byte_identical(self, bundle, K):
        """Paged KV on the mesh: the page pool shards along heads, the
        block tables stay host-authoritative and replicated, and the
        gather oracle rides the per-shard slices — tokens unchanged."""
        params, _ = bundle
        kw = dict(kv="paged", page_size=8)
        ref = single_device_tokens(params, K=K, **kw)
        engine, toks = engine_tokens(params, MeshEngine, K=K,
                                     devices=mesh_devices(), **kw)
        assert engine.decode_traces == 1
        assert engine.kv_sharded
        for a, b in zip(ref, toks):
            np.testing.assert_array_equal(a, b)

    @pytest.mark.parametrize("kw", [dict(quantize_cache=True),
                                    dict(kv="paged", page_size=8,
                                         quantize_cache=True)])
    def test_int8_kv_tokens_byte_identical(self, bundle, kw):
        """int8-KV composes: the quantized rows and their f32 scale
        pages shard along heads together, and quantize/dequantize are
        per-row elementwise — still byte-identical."""
        params, _ = bundle
        ref = single_device_tokens(params, K=8, **kw)
        engine, toks = engine_tokens(params, MeshEngine, K=8,
                                     devices=mesh_devices(), **kw)
        assert engine.decode_traces == 1
        for a, b in zip(ref, toks):
            np.testing.assert_array_equal(a, b)

    def test_mid_stream_join_transfer_clean(self, bundle):
        """The steady-state transfer discipline survives sharding: full
        chunks, a mid-stream join (admission while another slot is
        mid-decode), and the emit-ring harvest all run under
        ``guards.no_transfers`` — GSPMD's collectives are device-side,
        and the only host traffic is the engine's explicit puts/gets.
        Tokens stay byte-identical through the join."""
        params, _ = bundle
        ref = single_device_tokens(params, K=8, kv="paged", page_size=8)
        queue = RequestQueue(max_depth=16)
        engine = MeshEngine(params, CFG, queue, num_slots=2,
                            chunk_steps=8, devices=mesh_devices(),
                            kv="paged", page_size=8)
        h0 = queue.submit(REQS[0])
        engine.step_once()              # admit + first chunk (compiles)
        engine.step_once()
        with guards.no_transfers():
            h2 = queue.submit(REQS[2])  # joins while slot 0 is mid-decode
            for _ in range(4):
                engine.step_once()
        engine.run_until_idle()
        assert engine.decode_traces == 1
        np.testing.assert_array_equal(
            np.asarray(h0.result(timeout=60).tokens), ref[0])
        np.testing.assert_array_equal(
            np.asarray(h2.result(timeout=60).tokens), ref[2])

    def test_prefix_cache_warm_hit_byte_identical(self, bundle):
        """The prefix cache composes with the head-sharded pool: a warm
        hit on the mesh — shared pages mapped into the replicated block
        tables, the COW boundary fork through the sharding-pinned pool
        update, first token from the cached (replicated) h_last row —
        emits tokens byte-identical to the single-device prefix-blind
        engine, with one decode trace and a guided pair riding along."""
        params, _ = bundle
        p8 = (4, 1, 2, 3, 5, 6, 7, 2)
        reqs = [Request(codes=p8, seed=31), Request(codes=p8, seed=37),
                Request(codes=p8, seed=41, cfg_scale=1.5)]
        kw = dict(kv="paged", page_size=8)
        _, ref = engine_tokens(params, Engine, reqs=reqs, **kw)
        engine, toks = engine_tokens(params, MeshEngine,
                                     devices=mesh_devices(),
                                     prefix_cache=True, reqs=reqs, **kw)
        assert engine.decode_traces == 1
        assert engine.kv_sharded
        assert engine.prefix_hits >= 1    # the same-prompt fan-out hit
        assert engine.cfg_pairs == 1
        for a, b in zip(ref, toks):
            np.testing.assert_array_equal(a, b)


class TestMeshSurfaceAndSpecs:
    def test_kernel_attn_gated_typed(self, bundle):
        """paged_attn='kernel' on a mesh is a typed init-time rejection
        (the Pallas custom call cannot be GSPMD-partitioned), never an
        opaque partitioner failure inside the first chunk."""
        params, _ = bundle
        queue = RequestQueue(max_depth=4)
        with pytest.raises(MeshPagedAttnError):
            MeshEngine(params, CFG, queue, devices=mesh_devices(),
                       kv="paged", page_size=8, paged_attn="kernel")
        with pytest.raises(MeshPagedAttnError):
            ReplicaSet(params, CFG, RequestQueue(max_depth=4),
                       replicas=2, devices_per_replica=2,
                       kv="paged", page_size=8, paged_attn="kernel")

    def test_stats_and_hbm_surface(self, bundle):
        """/stats mesh satellite: mesh_shape, devices_per_replica, and
        the per-shard residency — a 2-way heads-sharded pool's per-shard
        bytes are exactly half the global pool."""
        params, _ = bundle
        queue = RequestQueue(max_depth=4)
        engine = MeshEngine(params, CFG, queue, num_slots=2,
                            devices=mesh_devices(), kv="paged",
                            page_size=8)
        st = engine.stats()
        assert st["mesh_shape"] == {"mp": 2}
        assert st["devices_per_replica"] == 2
        assert st["kv_hbm_bytes_per_shard"] * 2 == st["kv_hbm_bytes"]
        rep = hbm_report(engine)
        assert rep["kv_hbm_bytes_per_shard"] * 2 == rep["kv_hbm_bytes"]
        # depth-sharded stacks + vocab-sharded tables: strictly under a
        # full replica, strictly over the impossible total/2 (some
        # leaves — layernorms, positional tables — stay replicated)
        assert rep["param_bytes"] / 2 < rep["param_bytes_per_shard"] \
            < rep["param_bytes"]
        # the baseline engine reports the degenerate surface
        st1 = Engine(params, CFG, RequestQueue(max_depth=4),
                     num_slots=2).stats()
        assert st1["devices_per_replica"] == 1
        assert st1["mesh_shape"] is None
        assert st1["kv_hbm_bytes_per_shard"] == st1["kv_hbm_bytes"]

    @pytest.mark.parametrize("kw", [
        dict(kv="dense"),
        dict(kv="paged", page_size=8),
        dict(kv="paged", page_size=8, quantize_cache=True)])
    def test_modeled_kv_bytes_matches_live_pool(self, bundle, kw):
        """The config-only model (replica-set /stats for child engines,
        bench HBM math) must equal what the live engine's arrays
        actually occupy — a drift here silently mis-budgets HBM."""
        from dalle_pytorch_tpu.serve import kv_pool as KV
        params, _ = bundle
        engine = Engine(params, CFG, RequestQueue(max_depth=4),
                        num_slots=2, **kw)
        assert KV.modeled_kv_bytes(
            CFG.transformer, kv=kw["kv"], num_slots=2,
            total_len=CFG.seq_len, page_size=kw.get("page_size", 0),
            quantized=kw.get("quantize_cache", False),
            dtype_bytes=4) == engine.kv_hbm_bytes()

    def test_remote_attach_mesh_needs_no_local_devices(self, bundle):
        """A mesh fleet whose engines live on WORKER hosts (socket
        remote attach) must construct on a parent that cannot hold even
        one slice locally — the workers slice their own jax clients'
        devices, and the head node may have zero accelerators."""
        params, _ = bundle
        rs = ReplicaSet(params, CFG, RequestQueue(max_depth=4),
                        replicas=2, isolation="process",
                        transport="socket", worker_cmd="",
                        devices_per_replica=16)   # > the 8 forced devs
        try:
            # no local SLICE was computed (the worker resolves its own);
            # the single-device bookkeeping placement may remain
            assert all(not isinstance(r.device, tuple)
                       for r in rs.replicas)
        finally:
            rs.close(timeout=2.0)

    def test_slice_devices_composition_rule(self):
        """replica=slice: non-overlapping slices, wrapping like the
        single-chip i %% n placement when replicas outnumber slices."""
        devs = list(range(8))
        assert SS.slice_devices(devs, 0, 2) == (0, 1)
        assert SS.slice_devices(devs, 3, 2) == (6, 7)
        assert SS.slice_devices(devs, 4, 2) == (0, 1)   # wraps
        assert SS.slice_devices(devs, 5, 1) == (5,)     # m=1 == i % n
        with pytest.raises(ValueError):
            SS.slice_devices(devs[:1], 0, 2)

    def test_param_specs_shard_only_uncontracted_dims(self, bundle):
        """The no-reassociation rule, structurally: transformer stacks
        shard dim 0 (depth), the logits head shards its OUTPUT dim,
        embedding tables their row dim — and nothing else shards."""
        params, _ = bundle
        mesh = SS.serve_mesh(mesh_devices())
        specs = SS.serve_param_specs(params, CFG, mesh)
        from jax.sharding import PartitionSpec as P
        qkv = specs["transformer"]["attn"]["qkv"]["w"]
        assert qkv.spec == P("mp")                      # depth axis
        assert specs["transformer"]["attn"]["ln"]["g"].spec == P("mp")
        # total_tokens is 83 here — odd, so the logits head exercises
        # the divisibility FALLBACK (replicated, never wrongly split);
        # the 50-row text table shards its vocab rows
        assert specs["to_logits"]["proj"]["w"].spec == P()
        assert specs["text_emb"]["w"].spec == P("mp")
        assert specs["image_emb"]["w"].spec == P("mp")
        assert specs["text_pos_emb"]["w"].spec == P()   # replicated
        kv_specs = SS.serve_kv_specs(
            {"k": jnp.zeros((2, 3, 2, 8, 8))}, mesh)
        assert kv_specs["k"].spec == P(None, None, "mp")
        # heads=3 does not divide 2: falls back replicated, not wrong
        kv_specs = SS.serve_kv_specs(
            {"k": jnp.zeros((2, 3, 3, 8, 8))}, mesh)
        assert kv_specs["k"].spec == P()


class TestMeshServer:
    def test_server_serves_mesh_engine_with_mesh_health(self, bundle):
        """InferenceServer(mesh_devices=2): the single-engine thread
        loop drives the mesh engine unchanged, and /healthz + /stats
        carry the mesh observability block."""
        params, vae_params = bundle
        from dalle_pytorch_tpu.serve.server import InferenceServer
        srv = InferenceServer(params, vae_params, CFG, num_slots=2,
                              chunk_steps=8, mesh_devices=2,
                              decode_images=False).start()
        try:
            res = srv.generate(REQS[0].codes, seed=REQS[0].seed,
                               timeout=120)
            assert res.status == OK
            np.testing.assert_array_equal(
                np.asarray(res.tokens),
                single_device_tokens(params, K=8)[0])
            health = srv.health()
            assert health["ok"]
            assert health["devices_per_replica"] == 2
            assert health["mesh_shape"] == {"mp": 2}
            st = srv.stats()
            assert st["mesh_shape"] == {"mp": 2}
            assert st["kv_hbm_bytes_per_shard"] * 2 == st["kv_hbm_bytes"]
        finally:
            srv.close()


class TestMeshReplicaSet:
    pytestmark = pytest.mark.faults

    def test_mesh_slice_failover_replay_byte_identical(self, bundle):
        """ReplicaSet-of-mesh-slices: replica 1 (devices 2-3) crashes
        mid-decode; its in-flight requests replay on replica 0 (devices
        0-1) with byte-identical tokens — the unchanged supervision
        logic, now over 2-device engines."""
        params, _ = bundle
        ref = single_device_tokens(params, K=4, reqs=REQS)
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, devices_per_replica=2,
                        bringup_policy=FAST_BRINGUP)
        assert [tuple(d.id for d in r.device) for r in rs.replicas] \
            == [(0, 1), (2, 3)]
        handles = [queue.submit(r) for r in REQS]
        with faults.injected(fault_replica=1, replica_crash_at_chunk=2):
            rs.run_until_idle()
        assert rs.failovers == 1
        assert rs.reclaimed >= 1, "the kill must have stranded work"
        for h, want in zip(handles, ref):
            res = h.result(timeout=10)
            assert res.status == OK, (res.status, res.reason)
            np.testing.assert_array_equal(np.asarray(res.tokens), want)
        stats = rs.stats()
        assert stats["completed"] == len(REQS)
        assert stats["devices_per_replica"] == 2
        assert stats["mesh_shape"] == {"mp": 2}
        assert all(c == 1 for c in rs.decode_compiles_per_replica())
        assert stats["tokens_decoded"] == sum(
            CFG.seq_len - len(r.codes) for r in REQS)


class TestWorkerCheckpointSpec:
    def test_load_ckpt_params_validates_and_restores(self, bundle):
        """The checkpoint-path attach loader: a valid checkpoint
        restores the exact params; the latest: form resolves through
        latest_valid; a torn checkpoint is a typed rejection naming the
        reason."""
        from dalle_pytorch_tpu import checkpoint as ckpt
        from dalle_pytorch_tpu.serve.worker import (WorkerCheckpointError,
                                                    load_ckpt_params)
        params, _ = bundle
        host = jax.tree.map(np.asarray, params)
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "w-3")
            ckpt.save(path, host)
            got = load_ckpt_params({"ckpt_path": path})
            np.testing.assert_array_equal(got["text_emb"]["w"],
                                          host["text_emb"]["w"])
            got = load_ckpt_params({"ckpt_path": f"latest:{d}:w"})
            np.testing.assert_array_equal(got["text_emb"]["w"],
                                          host["text_emb"]["w"])
            # torn payload: validate must refuse it, typed
            with open(os.path.join(path, "params.msgpack"), "r+b") as f:
                f.truncate(10)
            with pytest.raises(WorkerCheckpointError) as ei:
                load_ckpt_params({"ckpt_path": path})
            assert ei.value.record["kind"] == "serve_worker_ckpt_invalid"
            with pytest.raises(WorkerCheckpointError):
                load_ckpt_params({"ckpt_path": f"latest:{d}:w"})
        with pytest.raises(WorkerCheckpointError):
            load_ckpt_params({"ckpt_path": "/nonexistent/ckpt"})
        with pytest.raises(WorkerCheckpointError):
            load_ckpt_params({"ckpt_path": "latest:only-one-colon"})

    def test_worker_ckpt_requires_socket_transport(self, bundle):
        params, _ = bundle
        with pytest.raises(ValueError, match="socket"):
            ReplicaSet(params, CFG, RequestQueue(max_depth=4),
                       replicas=2, worker_ckpt="/tmp/x")

    def test_worker_transforms_require_worker_ckpt(self, bundle):
        """EMA/int8 transforms describe the worker's LOCAL load path;
        without a ckpt-path spec they would silently do nothing."""
        params, _ = bundle
        with pytest.raises(ValueError, match="worker_ckpt"):
            ReplicaSet(params, CFG, RequestQueue(max_depth=4),
                       replicas=2, isolation="process",
                       transport="socket", worker_use_ema=True)
        with pytest.raises(ValueError, match="worker_quantize"):
            ReplicaSet(params, CFG, RequestQueue(max_depth=4),
                       replicas=2, isolation="process",
                       transport="socket", worker_ckpt="/tmp/x",
                       worker_quantize="fp4")

    def test_load_ckpt_params_applies_worker_transforms(self, bundle):
        """The PR-11 follow-up: a checkpoint-path spec carries
        use_ema/quantize, and the worker applies them AFTER its local
        load in the in-process CLI's order — weight trees identical to
        ``ema_as``/``quantize_for_decode`` on the parent. A spec asking
        for EMA from an EMA-less checkpoint is the typed rejection
        (exit 5 downstream), not a KeyError."""
        from dalle_pytorch_tpu import checkpoint as ckpt
        from dalle_pytorch_tpu.cli.common import ema_as
        from dalle_pytorch_tpu.serve.worker import (WorkerCheckpointError,
                                                    load_ckpt_params)
        params, _ = bundle
        host = jax.tree.map(np.asarray, params)
        ema = jax.tree.map(
            lambda p: np.asarray(p, np.float32) * 1.25 + 0.01, host)
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "w-1")
            ckpt.save(path, host, ema=ema)
            got = load_ckpt_params({"ckpt_path": path,
                                    "ckpt_use_ema": True})
            want = ema_as(ema, host)
            jax.tree.map(np.testing.assert_array_equal, got, want)
            got_q = load_ckpt_params({"ckpt_path": path,
                                      "ckpt_quantize": "int8"})
            want_q = D.quantize_for_decode(host)
            jax.tree.map(
                lambda a, b: np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b)), got_q, want_q)
            with pytest.raises(WorkerCheckpointError, match="quantize"):
                load_ckpt_params({"ckpt_path": path,
                                  "ckpt_quantize": "fp4"})
            # EMA-less checkpoint + EMA spec: typed, names the cause
            path2 = os.path.join(d, "x-1")
            ckpt.save(path2, host)
            with pytest.raises(WorkerCheckpointError) as ei:
                load_ckpt_params({"ckpt_path": path2,
                                  "ckpt_use_ema": True})
            assert ei.value.record["kind"] == "serve_worker_ckpt_invalid"
            assert "EMA" in ei.value.record["reason"]

    @pytest.mark.slow
    def test_ckpt_attach_with_ema_serves_token_exact(self, bundle):
        """End-to-end (spawned children, socket transport): workers
        load the checkpoint locally AND apply the spec's EMA swap —
        tokens byte-identical to an in-process engine serving
        ``ema_as(ema, params)``."""
        from dalle_pytorch_tpu import checkpoint as ckpt
        from dalle_pytorch_tpu.cli.common import ema_as
        params, _ = bundle
        host = jax.tree.map(np.asarray, params)
        ema = jax.tree.map(
            lambda p: np.asarray(p, np.float32) * 1.25 + 0.01, host)
        ema_params = ema_as(ema, host)
        _, ref = engine_tokens(ema_params, Engine, K=8, reqs=REQS[:2])
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "w-0")
            ckpt.save(path, host, ema=ema)
            queue = RequestQueue(max_depth=16)
            rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                            chunk_steps=8, isolation="process",
                            transport="socket", worker_ckpt=path,
                            worker_use_ema=True,
                            heartbeat_s=60.0, spawn_timeout_s=240.0,
                            bringup_policy=FAST_BRINGUP)
            try:
                handles = [queue.submit(r) for r in REQS[:2]]
                rs.run_until_idle(max_steps=2_000_000)
                for h, want in zip(handles, ref):
                    res = h.result(timeout=10)
                    assert res.status == OK, (res.status, res.reason)
                    np.testing.assert_array_equal(
                        np.asarray(res.tokens), want)
            finally:
                rs.close()

    @pytest.mark.slow
    def test_ckpt_attach_serves_token_exact_and_bad_ckpt_is_typed(
            self, bundle):
        """End-to-end (spawned children, socket transport): workers
        load weights from the LOCAL checkpoint path — no params in the
        attach spec — and serve token-exact; a worker pointed at a
        missing checkpoint dies with the typed exit the parent decodes
        (exit 5: invalid checkpoint)."""
        from dalle_pytorch_tpu import checkpoint as ckpt
        params, _ = bundle
        ref = single_device_tokens(params, K=8, reqs=REQS[:2])
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "w-0")
            ckpt.save(path, jax.tree.map(np.asarray, params))
            queue = RequestQueue(max_depth=16)
            rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                            chunk_steps=8, isolation="process",
                            transport="socket", worker_ckpt=path,
                            heartbeat_s=60.0, spawn_timeout_s=240.0,
                            bringup_policy=FAST_BRINGUP)
            try:
                handles = [queue.submit(r) for r in REQS[:2]]
                rs.run_until_idle(max_steps=2_000_000)
                for h, want in zip(handles, ref):
                    res = h.result(timeout=10)
                    assert res.status == OK, (res.status, res.reason)
                    np.testing.assert_array_equal(
                        np.asarray(res.tokens), want)
            finally:
                rs.close()
