"""Torch-checkpoint import tests: reference-layout state dicts (built with
plain torch modules arranged per the documented reference structure) are
imported and checked for FORWARD parity against torch on the same weights.

Covers the cross-framework contracts: conv/linear layout transposition,
ConvTranspose semantics, Sequential index mapping with/without resblocks,
sequential vs reversible transformer key schemes, config inference, and the
DALLE tied-codebook round trip (SURVEY.md §5 contracts)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402
import torch.nn.functional as F  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from dalle_pytorch_tpu.compat import (import_clip, import_dalle,  # noqa: E402
                                      import_transformer, import_vae)
from dalle_pytorch_tpu.models import vae as V  # noqa: E402
from dalle_pytorch_tpu.ops import transformer as T  # noqa: E402


def _np(t):
    return t.detach().cpu().numpy()


# ---------------------------------------------------------------------------
# torch model builders, Sequential layout per reference dalle_pytorch.py:88-119
# ---------------------------------------------------------------------------

def build_torch_vae(num_tokens=24, codebook_dim=16, num_layers=2,
                    num_resnet_blocks=0, hidden_dim=8, channels=3):
    def resblock(ch):
        m = nn.Module()
        m.net = nn.Sequential(nn.Conv2d(ch, ch, 3, padding=1), nn.ReLU(),
                              nn.Conv2d(ch, ch, 3, padding=1), nn.ReLU(),
                              nn.Conv2d(ch, ch, 1))
        m.forward = lambda x, _m=m: _m.net(x) + x
        return m

    has_res = num_resnet_blocks > 0
    enc_ch = [channels] + [hidden_dim] * num_layers
    dec_ch = [hidden_dim] * num_layers

    enc_layers = [nn.Sequential(nn.Conv2d(i, o, 4, stride=2, padding=1),
                                nn.ReLU())
                  for i, o in zip(enc_ch[:-1], enc_ch[1:])]
    dec_in = dec_ch[0] if has_res else codebook_dim
    dec_io = list(zip([dec_in] + dec_ch[:-1], dec_ch))
    dec_layers = [nn.Sequential(nn.ConvTranspose2d(i, o, 4, stride=2,
                                                   padding=1), nn.ReLU())
                  for i, o in dec_io]
    for _ in range(num_resnet_blocks):
        enc_layers.append(resblock(enc_ch[-1]))
        dec_layers.insert(0, resblock(dec_ch[0]))
    if has_res:
        dec_layers.insert(0, nn.Conv2d(codebook_dim, dec_ch[0], 1))
    enc_layers.append(nn.Conv2d(enc_ch[-1], num_tokens, 1))
    dec_layers.append(nn.Conv2d(dec_ch[-1], channels, 1))

    m = nn.Module()
    m.codebook = nn.Embedding(num_tokens, codebook_dim)
    m.encoder = nn.Sequential(*enc_layers)
    m.decoder = nn.Sequential(*dec_layers)
    return m


class TorchPreNormAttn(nn.Module):
    """Reference Attention under PreNorm (reference transformer.py:24-89)."""

    def __init__(self, dim, heads, dim_head):
        super().__init__()
        self.norm = nn.LayerNorm(dim)
        self.fn = nn.Module()
        inner = heads * dim_head
        self.fn.to_qkv = nn.Linear(dim, inner * 3, bias=False)
        self.fn.to_out = nn.Sequential(nn.Linear(inner, dim), nn.Dropout(0.0))
        self.heads, self.dim_head, self.scale = heads, dim_head, dim ** -0.5

    def forward(self, x):
        h = self.norm(x)
        b, n, _ = h.shape
        q, k, v = self.fn.to_qkv(h).chunk(3, dim=-1)
        shape = lambda t: t.view(b, n, self.heads, self.dim_head).transpose(1, 2)
        q, k, v = map(shape, (q, k, v))
        dots = q @ k.transpose(-1, -2) * self.scale
        causal = torch.ones(n, n).triu_(1).bool()
        dots = dots.masked_fill(causal, float("-inf"))
        out = dots.softmax(-1) @ v
        out = out.transpose(1, 2).reshape(b, n, -1)
        return self.fn.to_out(out)


class TorchPreNormFF(nn.Module):
    """Reference GEGLU FeedForward under PreNorm (transformer.py:33-49)."""

    def __init__(self, dim, mult=4):
        super().__init__()
        self.norm = nn.LayerNorm(dim)
        self.fn = nn.Module()
        self.fn.net = nn.Sequential(
            nn.Linear(dim, dim * mult * 2), nn.Identity(), nn.Dropout(0.0),
            nn.Linear(dim * mult, dim))

    def forward(self, x):
        h = self.fn.net[0](self.norm(x))
        h, gates = h.chunk(2, dim=-1)
        return self.fn.net[3](h * F.gelu(gates))


def build_torch_transformer(dim=16, depth=3, heads=2, dim_head=8):
    m = nn.Module()
    m.layers = nn.Module()
    m.layers.layers = nn.ModuleList([
        nn.ModuleList([TorchPreNormAttn(dim, heads, dim_head),
                       TorchPreNormFF(dim)])
        for _ in range(depth)])

    def fwd(x):
        for f, g in m.layers.layers:
            x = x + f(x)
            x = x + g(x)
        return x

    m.forward = fwd
    return m


# ---------------------------------------------------------------------------
# VAE parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("resblocks", [0, 2])
def test_vae_forward_parity(resblocks):
    torch.manual_seed(0)
    tm = build_torch_vae(num_resnet_blocks=resblocks)
    params, cfg_kw = import_vae({k: _np(v) for k, v in
                                 tm.state_dict().items()}, image_size=16)
    assert cfg_kw["num_layers"] == 2
    assert cfg_kw["num_resnet_blocks"] == resblocks
    assert cfg_kw["hidden_dim"] == 8

    img = np.random.default_rng(0).uniform(-1, 1, (2, 16, 16, 3)) \
        .astype(np.float32)
    # encoder logits: ours NHWC vs torch NCHW
    cfg = V.VAEConfig(**cfg_kw)
    ours = V.vae_apply(params, jnp.asarray(img), cfg=cfg, return_logits=True)
    with torch.no_grad():
        theirs = tm.encoder(torch.tensor(img).permute(0, 3, 1, 2))
    np.testing.assert_allclose(np.asarray(ours),
                               _np(theirs.permute(0, 2, 3, 1)),
                               atol=2e-5)

    # decoder: token ids -> image (reference decode, dalle_pytorch.py:126-136)
    ids = np.random.default_rng(1).integers(0, 24, (2, 16))
    ours_img = V.decode(params, jnp.asarray(ids))
    with torch.no_grad():
        emb = tm.codebook(torch.tensor(ids))           # (b, n, d)
        emb = emb.view(2, 4, 4, 16).permute(0, 3, 1, 2)
        theirs_img = tm.decoder(emb)
    np.testing.assert_allclose(np.asarray(ours_img),
                               _np(theirs_img.permute(0, 2, 3, 1)),
                               atol=2e-5)


# ---------------------------------------------------------------------------
# transformer stack parity
# ---------------------------------------------------------------------------

def test_transformer_stack_parity():
    torch.manual_seed(1)
    dim, depth = 16, 3
    tm = build_torch_transformer(dim=dim, depth=depth)
    stacked = import_transformer({k: _np(v)
                                  for k, v in tm.state_dict().items()})
    assert stacked["attn"]["qkv"]["w"].shape == (depth, dim, 48)

    x = np.random.default_rng(2).normal(size=(2, 10, dim)).astype(np.float32)
    cfg = T.TransformerConfig(dim=dim, depth=depth, seq_len=10, heads=2,
                              dim_head=8, causal=True)
    ours = T.transformer_apply(jax.tree.map(jnp.asarray, stacked),
                               jnp.asarray(x), cfg=cfg)
    with torch.no_grad():
        theirs = tm.forward(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(ours), _np(theirs), atol=3e-5)


def test_reversible_key_scheme_maps_to_same_layout():
    """A reversible-save (layers.blocks.{i}.{f,g}.net..., reference
    reversible.py:143-157) must import identically to a sequential save of
    the same weights."""
    torch.manual_seed(2)
    tm = build_torch_transformer(dim=16, depth=2)
    sd = {k: _np(v) for k, v in tm.state_dict().items()}
    rev_sd = {}
    for k, v in sd.items():
        m = k.split(".")
        # layers.layers.{i}.{0|1}.rest -> layers.blocks.{i}.{f|g}.net.rest
        branch = "f" if m[3] == "0" else "g"
        rev_sd[".".join(["layers", "blocks", m[2], branch, "net"] + m[4:])] = v
    a = import_transformer(sd)
    b = import_transformer(rev_sd)
    jax.tree.map(np.testing.assert_array_equal, a, b)


# ---------------------------------------------------------------------------
# DALLE / CLIP assembly
# ---------------------------------------------------------------------------

def _dalle_state_dict(dim=16, depth=2, num_text=32, text_seq=8,
                      image_size=16):
    torch.manual_seed(3)
    vae = build_torch_vae(num_tokens=24, codebook_dim=dim)
    tr = build_torch_transformer(dim=dim, depth=depth)
    sd = {}
    for k, v in vae.state_dict().items():
        sd[f"vae.{k}"] = _np(v)
    for k, v in tr.state_dict().items():
        sd[f"transformer.{k}"] = _np(v)
    sd["text_emb.weight"] = np.random.randn(num_text, dim).astype(np.float32)
    sd["image_emb.weight"] = sd["vae.codebook.weight"]       # tied (ref :283)
    sd["text_pos_emb.weight"] = np.random.randn(text_seq, dim) \
        .astype(np.float32)
    # summed-mode axial ParameterList over (image_size, image_size)
    # (reference dalle_pytorch.py:268)
    sd["image_pos_emb.weights.0"] = np.random.randn(
        1, image_size, 1, dim).astype(np.float32)
    sd["image_pos_emb.weights.1"] = np.random.randn(
        1, 1, image_size, dim).astype(np.float32)
    total = num_text + 24 + 1
    sd["to_logits.0.weight"] = np.ones(dim, np.float32)
    sd["to_logits.0.bias"] = np.zeros(dim, np.float32)
    sd["to_logits.1.weight"] = np.random.randn(total, dim).astype(np.float32)
    sd["to_logits.1.bias"] = np.zeros(total, np.float32)
    return sd


def test_dalle_import_and_forward():
    from dalle_pytorch_tpu.models import dalle as D
    sd = _dalle_state_dict()
    params, vae_params, cfg_kw, vae_cfg_kw = import_dalle(sd, image_size=16)

    assert cfg_kw == {"dim": 16, "depth": 2, "num_text_tokens": 32,
                      "text_seq_len": 8, "dim_head": 2,
                      "axial_compat": "full_image"}
    np.testing.assert_array_equal(params["image_emb"]["w"],
                                  vae_params["codebook"]["w"])
    assert params["image_pos_emb"]["rows"].shape == (16, 16)

    cfg = D.DALLEConfig(vae=V.VAEConfig(**vae_cfg_kw), heads=2,
                        **{k: v for k, v in cfg_kw.items()
                           if k != "dim_head"}, dim_head=8)
    params = jax.tree.map(jnp.asarray, params)
    text = jnp.zeros((1, 8), jnp.int32)
    ids = jnp.zeros((1, cfg.image_seq_len), jnp.int32)
    loss = D.dalle_apply(params, text, ids, cfg=cfg, return_loss=True)
    assert np.isfinite(float(loss))


def test_clip_import_shapes_and_config():
    torch.manual_seed(4)
    dim = 16
    sd = {}
    for k, v in build_torch_transformer(dim=dim, depth=2).state_dict().items():
        sd[f"text_transformer.{k}"] = _np(v)
        sd[f"visual_transformer.{k}"] = _np(v)
    sd["text_emb.weight"] = np.random.randn(32, dim).astype(np.float32)
    sd["text_pos_emb.weight"] = np.random.randn(8, dim).astype(np.float32)
    sd["to_text_latent.weight"] = np.random.randn(12, dim).astype(np.float32)
    patch, side = 8, 2
    sd["to_visual_embedding.weight"] = np.random.randn(
        dim, 3 * patch * patch).astype(np.float32)
    sd["to_visual_embedding.bias"] = np.zeros(dim, np.float32)
    sd["visual_pos_emb.weight"] = np.random.randn(side * side, dim) \
        .astype(np.float32)
    sd["to_visual_latent.weight"] = np.random.randn(12, dim) \
        .astype(np.float32)
    sd["temperature"] = np.asarray(1.0, np.float32)

    params, cfg_kw = import_clip(sd)
    assert cfg_kw["visual_patch_size"] == patch
    assert cfg_kw["visual_image_size"] == side * patch
    assert cfg_kw["dim_latent"] == 12
    assert params["temperature"].shape == ()

    from dalle_pytorch_tpu.models import clip as C
    cfg = C.CLIPConfig(text_heads=2, visual_heads=2, sparse_attn=False,
                       **cfg_kw)
    params = jax.tree.map(jnp.asarray, params)
    text = jnp.zeros((2, 8), jnp.int32)
    imgs = jnp.zeros((2, 16, 16, 3), jnp.float32)
    loss = C.clip_apply(params, text, imgs, cfg=cfg, return_loss=True)
    assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# end-to-end: .pth -> import CLI -> framework checkpoint -> restore
# ---------------------------------------------------------------------------

def test_import_cli_vae_roundtrip(tmp_path):
    from dalle_pytorch_tpu import checkpoint as ckpt
    from dalle_pytorch_tpu.cli.import_torch import main

    torch.manual_seed(5)
    tm = build_torch_vae(num_resnet_blocks=1)
    pth = tmp_path / "vae.pth"
    torch.save(tm.state_dict(), pth)

    out = tmp_path / "vae-7"
    main(["vae", str(pth), "--out", str(out), "--image_size", "16",
          "--epoch", "7"])

    params, manifest = ckpt.restore_params(str(out))
    assert manifest["kind"] == "vae"
    assert manifest["config"]["num_resnet_blocks"] == 1
    cfg = ckpt.vae_config_from_manifest(manifest)
    img = jnp.zeros((1, 16, 16, 3), jnp.float32)
    ids = V.get_codebook_indices(params, img)
    assert ids.shape == (1, cfg.image_seq_len)


def test_import_cli_dalle_roundtrip(tmp_path):
    from dalle_pytorch_tpu import checkpoint as ckpt
    from dalle_pytorch_tpu.cli.import_torch import main
    from dalle_pytorch_tpu.models import dalle as D

    sd = _dalle_state_dict()
    pth = tmp_path / "dalle.pth"
    torch.save({k: torch.tensor(v) for k, v in sd.items()}, pth)

    out = tmp_path / "dalle-0"
    vout = tmp_path / "vae-0"
    main(["dalle", str(pth), "--out", str(out), "--vae_out", str(vout),
          "--image_size", "16", "--heads", "2"])

    params, manifest = ckpt.restore_params(str(out))
    cfg = ckpt.dalle_config_from_manifest(manifest)
    assert cfg.heads == 2 and cfg.axial_compat == "full_image"
    vparams, vmanifest = ckpt.restore_params(str(vout))
    assert vmanifest["kind"] == "vae"

    text = jnp.zeros((1, cfg.text_seq_len), jnp.int32)
    ids = jnp.zeros((1, cfg.image_seq_len), jnp.int32)
    loss = D.dalle_apply(params, text, ids, cfg=cfg, return_loss=True)
    assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# full-model golden parity: imported DALLE forward + loss vs a torch oracle
# ---------------------------------------------------------------------------

def _torch_dalle_forward(sd, text, ids, cfg):
    """Torch re-derivation of the reference DALLE.forward on a state dict
    (reference dalle_pytorch.py:360-407): embeddings + summed-axial image
    pos-emb, causal transformer, LN+Linear head, per-position logits mask,
    shifted-label CE. Returns (masked logits, loss)."""
    tt = {k: torch.tensor(v) for k, v in sd.items()}
    b, t = text.shape
    n_img = ids.shape[1]

    emb = tt["text_emb.weight"][text] + tt["text_pos_emb.weight"][:t]
    ax = (tt["image_pos_emb.weights.0"] + tt["image_pos_emb.weights.1"]) \
        .reshape(-1, emb.shape[-1])[:n_img]
    img = tt["image_emb.weight"][ids] + ax
    x = torch.cat([emb, img], dim=1)

    depth = max(int(k.split(".")[3]) for k in sd
                if k.startswith("transformer.layers.layers.")) + 1
    n = x.shape[1]
    causal = torch.ones(n, n).triu_(1).bool()
    for i in range(depth):
        p = f"transformer.layers.layers.{i}."
        h = F.layer_norm(x, x.shape[-1:], tt[p + "0.norm.weight"],
                         tt[p + "0.norm.bias"])
        q, k, v = (h @ tt[p + "0.fn.to_qkv.weight"].T).chunk(3, dim=-1)
        heads, dim = 2, x.shape[-1]
        shape = lambda z: z.view(b, n, heads, -1).transpose(1, 2)
        q, k, v = map(shape, (q, k, v))
        dots = q @ k.transpose(-1, -2) * dim ** -0.5
        dots = dots.masked_fill(causal, float("-inf"))
        o = (dots.softmax(-1) @ v).transpose(1, 2).reshape(b, n, -1)
        x = x + o @ tt[p + "0.fn.to_out.0.weight"].T \
            + tt[p + "0.fn.to_out.0.bias"]
        h = F.layer_norm(x, x.shape[-1:], tt[p + "1.norm.weight"],
                         tt[p + "1.norm.bias"])
        h = h @ tt[p + "1.fn.net.0.weight"].T + tt[p + "1.fn.net.0.bias"]
        h, gates = h.chunk(2, dim=-1)
        x = x + (h * F.gelu(gates)) @ tt[p + "1.fn.net.3.weight"].T \
            + tt[p + "1.fn.net.3.bias"]

    h = F.layer_norm(x, x.shape[-1:], tt["to_logits.0.weight"],
                     tt["to_logits.0.bias"])
    logits = h @ tt["to_logits.1.weight"].T + tt["to_logits.1.bias"]

    # logits mask (reference dalle_pytorch.py:303-315) and loss (:398-406)
    n_text, total = cfg.num_text_tokens, cfg.total_tokens
    seq = torch.arange(n)[:, None]
    lr = torch.arange(total)[None, :]
    tb = cfg.text_seq_len - 1
    forbidden = (((seq >= tb) & (lr < n_text))
                 | ((seq < tb) & (lr >= n_text))
                 | ((seq != n - 1) & (lr >= total - 1)))
    logits = logits.masked_fill(forbidden[None],
                                -torch.finfo(logits.dtype).max)
    labels = torch.cat([text, ids + n_text,
                        torch.full((b, 1), total - 1, dtype=text.dtype)], 1)
    loss = F.cross_entropy(logits.permute(0, 2, 1), labels[:, 1:])
    return logits, loss


def test_dalle_full_forward_and_loss_parity():
    """End-to-end golden numerics: the imported checkpoint must produce the
    torch pipeline's logits and CE loss bit-close, axial quirk included."""
    from dalle_pytorch_tpu.models import dalle as D

    sd = _dalle_state_dict()
    params, vae_params, cfg_kw, vae_cfg_kw = import_dalle(sd, image_size=16)
    cfg = D.DALLEConfig(vae=V.VAEConfig(**vae_cfg_kw), heads=2,
                        **{k: v for k, v in cfg_kw.items()
                           if k != "dim_head"}, dim_head=8)
    params = jax.tree.map(jnp.asarray, params)

    rng = np.random.default_rng(7)
    text_np = rng.integers(0, cfg.num_text_tokens, (2, cfg.text_seq_len))
    ids_np = rng.integers(0, cfg.num_image_tokens, (2, cfg.image_seq_len))
    text, ids = jnp.asarray(text_np), jnp.asarray(ids_np)

    ours_logits = D.dalle_apply(params, text, ids, cfg=cfg)
    ours_loss = D.dalle_apply(params, text, ids, cfg=cfg, return_loss=True)

    with torch.no_grad():
        t_logits, t_loss = _torch_dalle_forward(
            sd, torch.tensor(text_np), torch.tensor(ids_np), cfg)

    keep = ~np.asarray(D.logits_mask(cfg))        # compare allowed positions
    a = np.asarray(ours_logits)[:, keep]
    b = _np(t_logits)[:, keep]
    np.testing.assert_allclose(a, b, atol=5e-4)
    np.testing.assert_allclose(float(ours_loss), float(t_loss), rtol=1e-5)


# ---------------------------------------------------------------------------
# export: round trips and torch-loadability
# ---------------------------------------------------------------------------

class TestExport:
    def test_vae_roundtrip_bit_exact(self):
        from dalle_pytorch_tpu.compat import export_vae
        torch.manual_seed(8)
        tm = build_torch_vae(num_resnet_blocks=1)
        sd = {k: _np(v) for k, v in tm.state_dict().items()}
        params, _ = import_vae(sd, image_size=16)
        back = export_vae(params)
        assert set(back) == set(sd)
        for k in sd:
            np.testing.assert_array_equal(back[k], sd[k]), k

    def test_dalle_roundtrip_bit_exact(self):
        from dalle_pytorch_tpu.compat import export_dalle
        sd = _dalle_state_dict()
        params, vae_params, _, _ = import_dalle(sd, image_size=16)
        back = export_dalle(params, vae_params, image_size=16)
        assert set(back) == set(sd)
        for k in sd:
            np.testing.assert_array_equal(back[k], sd[k]), k

    def test_exported_pth_loads_in_torch_vae(self, tmp_path):
        """A freshly-initialized framework VAE exports to a .pth that a
        torch reference-layout module load_state_dict()s strictly."""
        from dalle_pytorch_tpu.compat import (export_vae,
                                              save_torch_state_dict)
        cfg = V.VAEConfig(image_size=16, num_tokens=24, codebook_dim=16,
                          num_layers=2, hidden_dim=8)
        params = V.vae_init(jax.random.PRNGKey(0), cfg)
        path = tmp_path / "exported.pth"
        save_torch_state_dict(export_vae(params), str(path))

        tm = build_torch_vae()          # same hyperparams as cfg
        loaded = torch.load(path, weights_only=True)
        tm.load_state_dict(loaded, strict=True)

        # and the torch module now computes the same encoder logits
        img = np.random.default_rng(3).uniform(-1, 1, (1, 16, 16, 3)) \
            .astype(np.float32)
        ours = V.vae_apply(params, jnp.asarray(img), cfg=cfg,
                           return_logits=True)
        with torch.no_grad():
            theirs = tm.encoder(torch.tensor(img).permute(0, 3, 1, 2))
        np.testing.assert_allclose(np.asarray(ours),
                                   _np(theirs.permute(0, 2, 3, 1)),
                                   atol=2e-5)

    def test_clip_roundtrip(self):
        from dalle_pytorch_tpu.compat import export_clip
        from dalle_pytorch_tpu.models import clip as C
        cfg = C.CLIPConfig(dim_text=16, dim_image=16, dim_latent=8,
                           num_text_tokens=32, text_seq_len=8,
                           text_enc_depth=2, visual_enc_depth=2,
                           text_heads=2, visual_heads=2,
                           visual_image_size=16, visual_patch_size=8,
                           sparse_attn=False)
        params = C.clip_init(jax.random.PRNGKey(4), cfg)
        sd = export_clip(params)
        params2, cfg_kw = import_clip(sd)
        assert cfg_kw["visual_patch_size"] == 8
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a, np.float32),
                                                    np.asarray(b, np.float32),
                                                    atol=0),
            params, params2)

    def test_export_cli_roundtrip(self, tmp_path):
        """vae .pth -> import CLI -> checkpoint -> export CLI -> .pth with
        identical tensors."""
        from dalle_pytorch_tpu.cli.import_torch import main
        torch.manual_seed(9)
        tm = build_torch_vae()
        pth = tmp_path / "in.pth"
        torch.save(tm.state_dict(), pth)
        out = tmp_path / "vae-0"
        main(["vae", str(pth), "--out", str(out), "--image_size", "16"])
        back = tmp_path / "back.pth"
        main(["export-vae", str(back), "--out", str(out)])
        a = torch.load(pth, weights_only=True)
        b = torch.load(back, weights_only=True)
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(_np(a[k]), _np(b[k])), k
