"""Gateway-tier tests (ISSUE 17): auth helper, tenancy, weighted-fair
queueing, prefix-affinity routing, hedging, cell-down replay, and
/metrics federation.

The jax-free half (auth / tenancy / WFQ) runs on hand-built queues with
a fake clock — no device, microseconds each. The engine-backed half
builds tiny two-cell gateways (the test_serve.py tiny config, total_len
24) and pins the tentpole contracts: repeated prompts land warm via the
content-addressed rendezvous key, a dead cell's flights replay on the
survivor with byte-identical tokens and zero loss, the hedge race is
first-fulfill-wins, and the gateway's federated /metrics samples sum to
exactly what the cells' own /stats report.
"""

import json
import time

import pytest

from dalle_pytorch_tpu.resilience import faults
from dalle_pytorch_tpu.serve import auth
from dalle_pytorch_tpu.serve import prefix_cache as PC
from dalle_pytorch_tpu.serve import scheduler as S
from dalle_pytorch_tpu.serve import tenancy as T


# ---------------------------------------------------------------------------
# auth helper (satellite: the one constant-time token check)
# ---------------------------------------------------------------------------

class TestAuth:
    def test_check_token(self):
        assert auth.check_token("secret", "secret")
        assert not auth.check_token("secret", "other")
        assert not auth.check_token("", "secret")

    def test_empty_expected_always_refuses(self):
        # an unconfigured secret is a refusal, never a wildcard
        assert not auth.check_token("", "")
        assert not auth.check_token("anything", "")

    def test_non_strings_refused(self):
        assert not auth.check_token(None, "secret")
        assert not auth.check_token(["secret"], "secret")
        assert not auth.check_token("secret", None)

    def test_http_token_bearer_wins(self):
        headers = {"Authorization": "Bearer abc", "X-Admin-Token": "z"}
        assert auth.http_token(headers) == "abc"
        assert auth.http_token({"X-Admin-Token": "z"}) == "z"
        assert auth.http_token({}) == ""
        assert auth.http_token({"X-API-Key": "k"}, "X-API-Key") == "k"

    def test_check_http(self):
        assert auth.check_http({"Authorization": "Bearer t"}, "t")
        assert not auth.check_http({}, "t")


# ---------------------------------------------------------------------------
# tenancy: specs, buckets, table, quotas
# ---------------------------------------------------------------------------

class TestTenancy:
    def test_spec_validation(self):
        with pytest.raises(ValueError):
            T.TenantSpec(name="")
        with pytest.raises(ValueError):
            T.TenantSpec(name="a", weight=0)
        with pytest.raises(ValueError):
            T.TenantSpec(name="a", tier="platinum")

    def test_tier_hedge_defaults(self):
        assert T.TenantSpec(name="a", tier="gold").hedge_after_s \
            == T.TIERS["gold"]
        assert T.TenantSpec(name="a", tier="bronze").hedge_after_s \
            is None
        assert T.TenantSpec(name="a", tier="bronze",
                            hedge_s=0.5).hedge_after_s == 0.5

    def test_token_bucket_refill(self):
        clock = [0.0]
        tb = T.TokenBucket(2.0, clock=lambda: clock[0])
        assert tb.take() == 0.0 and tb.take() == 0.0
        retry = tb.take()
        assert retry > 0.0
        clock[0] += retry
        assert tb.take() == 0.0

    def test_token_bucket_zero_rate_unlimited(self):
        tb = T.TokenBucket(0.0, clock=lambda: 0.0)
        assert all(tb.take() == 0.0 for _ in range(100))

    def test_table_from_json_and_authenticate(self):
        tbl = T.TenantTable.from_json({"tenants": [
            {"name": "a", "key": "ka"}, {"name": "b", "key": "kb"}]})
        assert tbl.names() == ["a", "b"]
        assert tbl.authenticate("kb").name == "b"
        with pytest.raises(T.AuthError) as ei:
            tbl.authenticate("wrong")
        assert ei.value.record["kind"] == "gateway_auth_failed"

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError):
            T.TenantTable.from_json([{"name": "a"}, {"name": "a"}])

    def test_open_tenant_matches_empty_key_only(self):
        tbl = T.TenantTable.from_json([{"name": "dev"}])
        assert tbl.authenticate("").name == "dev"
        with pytest.raises(T.AuthError):
            tbl.authenticate("guess")

    def test_rps_throttle_typed_with_retry_after(self):
        clock = [0.0]
        tbl = T.TenantTable.from_json(
            [{"name": "a", "key": "k", "rps": 1.0}],
            clock=lambda: clock[0])
        tbl.admit("a", image_tokens=0, pages=0)
        with pytest.raises(T.TenantThrottled) as ei:
            tbl.admit("a", image_tokens=0, pages=0)
        rec = ei.value.record
        assert rec["kind"] == "tenant_throttled"
        assert rec["quota"] == "rps"
        assert ei.value.retry_after_s > 0.0
        clock[0] += ei.value.retry_after_s
        tbl.admit("a", image_tokens=0, pages=0)    # refilled

    def test_page_budget_all_or_nothing(self):
        tbl = T.TenantTable.from_json(
            [{"name": "a", "key": "k", "max_pages": 4}],
            clock=lambda: 0.0)
        tbl.admit("a", image_tokens=0, pages=4)
        with pytest.raises(T.TenantThrottled) as ei:
            tbl.admit("a", image_tokens=0, pages=1)
        assert ei.value.record["quota"] == "pages"
        tbl.release("a", pages=4)
        tbl.admit("a", image_tokens=0, pages=4)    # budget returned
        assert tbl.stats()["a"]["pages_in_flight"] == 4

    def test_reload_keeps_ledger_for_persisting_tenants(self):
        clock = [0.0]
        tbl = T.TenantTable.from_json(
            [{"name": "a", "key": "k", "rps": 1.0, "max_pages": 8}],
            clock=lambda: clock[0])
        tbl.admit("a", image_tokens=0, pages=3)
        with pytest.raises(T.TenantThrottled):
            tbl.admit("a", image_tokens=0, pages=1)   # rps spent
        rec = tbl.reload([{"name": "a", "key": "k2", "rps": 1.0,
                           "max_pages": 8},
                          {"name": "b", "key": "kb"}])
        assert rec["added"] == ["b"] and rec["removed"] == []
        # the spent bucket did NOT reset with the reload
        with pytest.raises(T.TenantThrottled):
            tbl.admit("a", image_tokens=0, pages=1)
        # pages reserved before the reload still count
        assert tbl.stats()["a"]["pages_in_flight"] == 3
        # the new key authenticates, the old one no longer does
        assert tbl.authenticate("k2").name == "a"
        with pytest.raises(T.AuthError):
            tbl.authenticate("k")


# ---------------------------------------------------------------------------
# weighted-fair queueing (satellite: 2:1 share, no permanent debt)
# ---------------------------------------------------------------------------

def _wfq(weights, **kw):
    return S.WeightedFairQueue(
        max_depth=kw.pop("max_depth", 512),
        clock=kw.pop("clock", lambda: 0.0),
        weight_of=lambda t: weights.get(t, 1.0), **kw)


_TOKEN_COST = {"a": 256.0, "b": 64.0, "big": 256.0, "small": 256.0}


def _token_cost(request):
    """Per-request decode cost in image tokens — the gateway's fairness
    unit. Tenants here carry DIFFERENT per-request costs (a
    variable-resolution fleet), which is exactly the case where
    request-count shares and token shares diverge."""
    return _TOKEN_COST[request.tenant]


class TestWeightedFairQueue:
    def test_two_to_one_share_under_saturation(self):
        # two tenants at weights 2:1, both with deep backlogs — but
        # tenant a's requests cost 4x the tokens of tenant b's
        # (256 vs 64): the drain order must give the weight-2 tenant
        # 2/3 of the service IN TOKENS within 10% — which means only
        # ~1/3 of the popped REQUESTS. Asserting request counts here
        # would reward exactly the fan-out gaming the token cost_fn
        # exists to close
        for n in (15, 30, 60):     # every prefix of the drain is fair
            qq = _wfq({"a": 2.0, "b": 1.0}, cost_fn=_token_cost)
            for _ in range(120):
                qq.submit(S.Request(codes=(1,), tenant="a"))
                qq.submit(S.Request(codes=(1,), tenant="b"))
            ready, _ = qq.pop_ready(n)
            tok = {"a": 0.0, "b": 0.0}
            for h in ready:
                tok[h.request.tenant] += _token_cost(h.request)
            share = tok["a"] / (tok["a"] + tok["b"])
            # one 256-token pop is a big quantum at small n: allow one
            # request's worth of slack on top of the 10% bar
            assert abs(share - 2 / 3) <= 0.1 * (2 / 3) \
                + 256.0 / (tok["a"] + tok["b"]), (n, share)
            # and the request-count share is NOT 2/3 — a's requests are
            # 4x heavier, so it gets 2/3 of the tokens via ~1/3 of the
            # pops (the satellite's point, pinned)
            req_share = sum(1 for h in ready
                            if h.request.tenant == "a") / n
            assert req_share < 0.5, (n, req_share)

    def test_weighted_share_is_work_proportional(self):
        # equal per-request cost: token shares and the 3:1 weights
        # agree — 75% of the serviced tokens go to the weight-3 tenant
        q = _wfq({"big": 3.0, "small": 1.0}, cost_fn=_token_cost)
        for _ in range(80):
            q.submit(S.Request(codes=(1,), tenant="big"))
            q.submit(S.Request(codes=(1,), tenant="small"))
        ready, _ = q.pop_ready(40)
        tok = {"big": 0.0, "small": 0.0}
        for h in ready:
            tok[h.request.tenant] += _token_cost(h.request)
        assert abs(tok["big"] / (tok["big"] + tok["small"]) - 0.75) \
            <= 0.1

    def test_no_permanent_debt_after_idle(self):
        # a tenant whose backlog pushed its finish tag far ahead goes
        # idle; after the OTHER tenant advances virtual time past it,
        # a fresh submit must start at V (caught up), not pay old debt
        q = _wfq({"a": 1.0, "b": 1.0}, cost_fn=_token_cost)
        for _ in range(20):
            q.submit(S.Request(codes=(1,), tenant="a"))
        q.pop_ready(20)                       # drain a's backlog
        tag_a = q.finish_tag("a")
        assert tag_a > q.virtual_time()       # tag raced ahead of V
        for _ in range(40):
            q.submit(S.Request(codes=(1,), tenant="b"))
        q.pop_ready(40)                       # V advances past tag_a
        assert q.virtual_time() > tag_a
        h = q.submit(S.Request(codes=(1,), tenant="a"))
        # caught up: the new start tag is V, not the stale finish tag;
        # the finish tag sits one request's TOKEN cost (over weight)
        # ahead — virtual time is token-denominated now
        assert h.vstart == pytest.approx(q.virtual_time())
        assert h.vfinish == pytest.approx(
            h.vstart + _token_cost(h.request))

    def test_gateway_charges_image_tokens(self, bundle):
        # the gateway's WFQ must charge cfg.image_seq_len per request
        # (fairness in decoded work), not 1.0: a submitted handle's
        # finish tag advances by image tokens over weight
        _, _, cfg = bundle
        gw = _gateway(bundle, n_cells=1)
        try:
            h = gw.submit((1, 2), seed=0)
            assert h.vfinish - h.vstart == pytest.approx(
                float(cfg.image_seq_len))
            assert h.result(90).ok
        finally:
            gw.close()

    def test_no_banked_credit_from_idle(self):
        # an idle tenant must NOT accumulate credit while others run:
        # its first submit starts at V, so it cannot monopolize the
        # queue to "catch up" on service it never asked for
        q = _wfq({"a": 1.0, "b": 1.0})
        for _ in range(30):
            q.submit(S.Request(codes=(1,), tenant="b"))
        q.pop_ready(30)
        v = q.virtual_time()
        h = q.submit(S.Request(codes=(1,), tenant="a"))
        assert h.vstart == pytest.approx(v)

    def test_priority_dominates_fairness(self):
        q = _wfq({"a": 1.0, "b": 100.0})
        q.submit(S.Request(codes=(1,), tenant="b", priority=1))
        h = q.submit(S.Request(codes=(1,), tenant="a", priority=0))
        ready, _ = q.pop_ready(1)
        assert ready[0] is h

    def test_requeue_preserves_virtual_position(self):
        # eviction/failover requeue must re-enter at the ORIGINAL
        # virtual finish tag (cached on the handle) — replay
        # determinism and no-starvation both hang on this
        q = _wfq({"a": 1.0, "b": 1.0})
        h1 = q.submit(S.Request(codes=(1,), tenant="a"))
        tag = h1.vfinish
        for _ in range(10):
            q.submit(S.Request(codes=(1,), tenant="b"))
        popped, _ = q.pop_ready(1)
        assert popped[0] is h1
        q.requeue(h1)
        assert h1.vfinish == tag              # tag survived the trip
        ready, _ = q.pop_ready(1)
        assert ready[0] is h1                 # still first in line

    def test_base_queue_ordering_unchanged(self):
        # the refactor hook must leave the plain queue byte-identical:
        # (priority, arrival) order, tenants ignored
        q = S.RequestQueue(max_depth=16, clock=lambda: 0.0)
        h1 = q.submit(S.Request(codes=(1,), tenant="z", priority=1))
        h2 = q.submit(S.Request(codes=(1,), tenant="a", priority=0))
        h3 = q.submit(S.Request(codes=(1,), tenant="m", priority=0))
        ready, _ = q.pop_ready(3)
        assert ready == [h2, h3, h1]

    def test_tenant_rides_the_wire(self):
        r = S.Request(codes=(1, 2), tenant="acme")
        d = r.to_wire(now=0.0)
        assert d["tenant"] == "acme"
        back = S.Request.from_wire(d, now=1.0)
        assert back.tenant == "acme"
        # pre-tenancy frames decode as the anonymous tenant
        del d["tenant"]
        assert S.Request.from_wire(d, now=1.0).tenant == ""


# ---------------------------------------------------------------------------
# routing key + fault rows (jax-free)
# ---------------------------------------------------------------------------

class TestRoutingPlumbing:
    def test_content_key_matches_engine_key(self):
        from dalle_pytorch_tpu.models import dalle as D
        from dalle_pytorch_tpu.models import vae as V
        vcfg = V.VAEConfig(image_size=16, num_tokens=32,
                           codebook_dim=16, num_layers=2, hidden_dim=8)
        cfg = D.DALLEConfig(dim=16, depth=2, vae=vcfg,
                            num_text_tokens=50, text_seq_len=8,
                            heads=2, dim_head=8)
        codes = (3, 4, 5)
        want = PC.prefix_key(
            codes, model_version="v0",
            layer_sig=PC.layer_signature(cfg.transformer),
            quantized=False)
        assert PC.content_key(codes, cfg=cfg,
                              model_version="v0") == want
        # and the transformer config works directly too
        assert PC.content_key(codes, cfg=cfg.transformer,
                              model_version="v0") == want
        # different version -> different cell affinity
        assert PC.content_key(codes, cfg=cfg,
                              model_version="v1") != want

    def test_gateway_fault_rows_fire_once(self):
        with faults.injected(gateway_cell_down_at_request=2):
            assert not faults.on_gateway_dispatch(1)
            assert faults.on_gateway_dispatch(2)
            assert not faults.on_gateway_dispatch(3)   # fire-once
        assert not faults.on_gateway_dispatch(99)      # no plan
        with faults.injected(tenant_flood="abuser",
                             tenant_flood_requests=7):
            spec = faults.gateway_flood()
            assert spec == {"tenant": "abuser", "requests": 7}
            assert faults.gateway_flood() is None      # fire-once
        assert faults.gateway_flood() is None

    def test_fault_plan_env_round_trip(self):
        plan = faults.FaultPlan(gateway_cell_down_at_request=3,
                                tenant_flood="t", tenant_flood_requests=5)
        blob = json.dumps({"gateway_cell_down_at_request": 3,
                           "tenant_flood": "t",
                           "tenant_flood_requests": 5})
        assert faults.FaultPlan(**json.loads(blob)) == plan


# ---------------------------------------------------------------------------
# engine-backed gateway tests (tiny model, CPU)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def bundle():
    import jax
    from dalle_pytorch_tpu.models import dalle as D
    from dalle_pytorch_tpu.models import vae as V
    vcfg = V.VAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                       num_layers=2, hidden_dim=8)
    cfg = D.DALLEConfig(dim=16, depth=2, vae=vcfg, num_text_tokens=50,
                        text_seq_len=8, heads=2, dim_head=8)
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), vcfg)
    params = D.dalle_init(key, cfg, vae_params)
    return params, vae_params, cfg


def _cell(bundle, **kw):
    from dalle_pytorch_tpu.serve.server import InferenceServer
    params, vae_params, cfg = bundle
    kw.setdefault("num_slots", 2)
    kw.setdefault("queue_depth", 16)
    kw.setdefault("kv", "paged")
    kw.setdefault("page_size", 4)
    kw.setdefault("prefix_cache", True)
    kw.setdefault("decode_images", False)
    kw.setdefault("weights_version", "v0")
    return InferenceServer(params, vae_params, cfg, **kw).start()


def _gateway(bundle, n_cells=2, **kw):
    from dalle_pytorch_tpu.serve.gateway import Gateway
    _, _, cfg = bundle
    cells = [_cell(bundle) for _ in range(n_cells)]
    kw.setdefault("cfg", cfg)
    kw.setdefault("model_version", "v0")
    kw.setdefault("queue_depth", 64)
    kw.setdefault("pages_per_request", 6)
    return Gateway(cells, **kw).start()


class TestGateway:
    def test_affinity_routes_repeats_warm(self, bundle):
        gw = _gateway(bundle)
        try:
            prompt = (3, 4, 5)
            # waves of <= capacity so the affine cell is never
            # saturated: every wave after the first admits warm on
            # the SAME cell
            for wave in range(3):
                hs = [gw.submit(prompt, seed=7) for _ in range(2)]
                for h in hs:
                    assert h.result(90).ok
            routes = gw.events("gateway_route")
            assert len(routes) == 6
            assert len({e["cell"] for e in routes}) == 1
            assert all(e["affine"] for e in routes)
            st = gw.stats()
            assert st["fleet"]["prefix_hits"] >= 4
            assert st["spills"] == 0
        finally:
            gw.close()

    def test_spill_when_affine_cell_saturated(self, bundle):
        gw = _gateway(bundle)
        try:
            prompt = (6, 7)
            hs = [gw.submit(prompt, seed=1) for _ in range(4)]
            for h in hs:
                assert h.result(90).ok
            # 4 same-key requests against capacity-2 cells: the two
            # that couldn't fit on the affine cell spilled, typed
            assert gw.spills >= 1
            spills = gw.events("gateway_spill")
            assert spills and spills[0]["affine"] != spills[0]["cell"]
            routes = gw.events("gateway_route")
            assert len({e["cell"] for e in routes}) == 2
        finally:
            gw.close()

    def test_replay_identical_same_seed(self, bundle):
        gw = _gateway(bundle)
        try:
            rs = [gw.generate((9, 2, 4), seed=3, timeout=90)
                  for _ in range(3)]
            assert all(r.ok for r in rs)
            toks = {tuple(int(t) for t in r.tokens) for r in rs}
            assert len(toks) == 1
        finally:
            gw.close()

    def test_cell_down_replays_zero_loss(self, bundle):
        # the gateway_cell_down_at_request fault row: the cell that
        # received the first dispatch dies whole mid-stream; every
        # request it held must complete OK on the survivor via requeue
        # + replay — zero loss, and the fence is a typed event
        gw = _gateway(bundle)
        try:
            with faults.injected(gateway_cell_down_at_request=1):
                hs = [gw.submit((5, 5, 5), seed=11) for _ in range(3)]
                rs = [h.result(120) for h in hs]
            assert [r.status for r in rs] == ["ok"] * 3
            toks = {tuple(int(t) for t in r.tokens) for r in rs}
            assert len(toks) == 1          # replay byte-identical
            assert gw.cell_downs == 1
            assert gw.replays >= 1
            assert gw.events("gateway_cell_down")
            assert gw.events("gateway_replay")
            assert sum(1 for c in gw.cells if c.alive()) == 1
        finally:
            gw.close()

    def test_hedged_send_first_fulfill_wins(self, bundle):
        # hedge_s=0: every dispatch hedges on the next sweep; the
        # first arm to finish fulfils the caller (first-write-wins),
        # the loser is cooperatively cancelled — result still OK and
        # byte-identical to the unhedged run
        tbl = T.TenantTable.from_json(
            [{"name": "gold", "key": "kg", "tier": "gold",
              "hedge_s": 0.0}])
        gw = _gateway(bundle, tenants=tbl, hedge_check_s=0.0)
        try:
            baseline = gw.generate((1, 2, 3), api_key="kg", seed=5,
                                   timeout=90)
            assert baseline.ok
            r = gw.generate((8, 1, 2), api_key="kg", seed=5,
                            timeout=90)
            assert r.ok
            assert gw.hedges >= 1
            assert gw.events("gateway_hedge")
        finally:
            gw.close()

    def test_tenant_flood_isolation(self, bundle):
        # the degradation contract, unit-sized: the abusive tenant
        # exhausts its own rps quota (typed 429 + retry-after), the
        # victim's requests all complete
        tbl = T.TenantTable.from_json([
            {"name": "victim", "key": "kv", "weight": 2},
            {"name": "abuser", "key": "ka", "weight": 1, "rps": 2.0}])
        gw = _gateway(bundle, tenants=tbl)
        try:
            throttled = 0
            with faults.injected(tenant_flood="abuser",
                                 tenant_flood_requests=12):
                flood = faults.gateway_flood()
                assert flood["tenant"] == "abuser"
                flood_handles = []
                for i in range(flood["requests"]):
                    try:
                        flood_handles.append(
                            gw.submit((7, 7), api_key="ka", seed=i))
                    except T.TenantThrottled as e:
                        assert e.record["kind"] == "tenant_throttled"
                        assert e.retry_after_s > 0.0
                        throttled += 1
                victims = [gw.submit((2, 2, 2), api_key="kv", seed=0)
                           for _ in range(2)]
                assert all(h.result(120).ok for h in victims)
            assert throttled > 0
            assert gw.tenants.stats()["abuser"]["throttled"] \
                == throttled
            for h in flood_handles:    # admitted flood still completes
                assert h.result(120).status == S.OK
        finally:
            gw.close()

    def test_metrics_federation_pins_cell_sums(self, bundle):
        # satellite 6: sum of the per-cell samples the gateway
        # federates == the unlabeled fleet sample == what the cells'
        # own /stats report; tenant labels present on the gateway
        # counters and the latency histogram
        tbl = T.TenantTable.from_json(
            [{"name": "acme", "key": "k1"}])
        gw = _gateway(bundle, tenants=tbl)
        try:
            for i in range(4):
                assert gw.generate((1, 1, i + 1), api_key="k1",
                                   timeout=90).ok
            text = gw.metrics_text()
            want_sum = sum(c.server.stats()["completed"]
                           for c in gw.cells)
            per_cell, fleet = {}, None
            for line in text.splitlines():
                if not line.startswith(
                        "dalle_serve_requests_completed_total"):
                    continue
                name, value = line.rsplit(" ", 1)
                if "cell=" in name:
                    per_cell[name] = float(value)
                else:
                    fleet = float(value)
            assert per_cell and fleet is not None
            assert sum(per_cell.values()) == fleet == want_sum == 4
            assert 'dalle_gateway_tenant_admitted_total' \
                   '{tenant="acme"} 4' in text
            assert 'dalle_gateway_e2e_latency_seconds' in text
            assert 'tenant="acme"' in text
        finally:
            gw.close()

    def test_gateway_http_surface(self, bundle):
        # POST /generate with an API key, 401 on a bad key, 429 with
        # Retry-After on throttle, authenticated /admin/tenants hot
        # reload, /metrics and /tenants exposition
        import urllib.error
        import urllib.request
        from dalle_pytorch_tpu.serve.gateway import (
            make_gateway_http_server)
        tbl = T.TenantTable.from_json(
            [{"name": "acme", "key": "k1", "rps": 2.0}])
        gw = _gateway(bundle, tenants=tbl,
                      admin_token="admintok")
        httpd = make_gateway_http_server(gw, port=0)
        host, port = httpd.server_address[:2]
        import threading
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()

        def post(path, body, headers=None, timeout=90):
            req = urllib.request.Request(
                f"http://{host}:{port}{path}",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json",
                         **(headers or {})})
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read()), dict(r.headers)

        try:
            code, body, _ = post("/generate", {"codes": [1, 2]},
                                 {"X-API-Key": "k1"})
            assert code == 200 and body["status"] == "ok"
            with pytest.raises(urllib.error.HTTPError) as ei:
                post("/generate", {"codes": [1, 2]},
                     {"X-API-Key": "bad"})
            assert ei.value.code == 401
            # burn the rps bucket -> typed 429 with Retry-After
            got_429 = None
            for _ in range(4):
                try:
                    post("/generate", {"codes": [3, 3]},
                         {"X-API-Key": "k1"})
                except urllib.error.HTTPError as e:
                    if e.code == 429:
                        got_429 = e
                        break
            assert got_429 is not None
            assert got_429.headers.get("Retry-After") is not None
            assert json.loads(got_429.read())["kind"] \
                == "tenant_throttled"
            # hot reload: 401 without the admin token, 200 with
            with pytest.raises(urllib.error.HTTPError) as ei:
                post("/admin/tenants", [{"name": "acme", "key": "k2"}])
            assert ei.value.code == 401
            # rps: 0.0 lifts the limit — and because the ledger
            # persists across reloads, anything else would leave the
            # spent bucket spent (the anti-washing contract)
            code, body, _ = post(
                "/admin/tenants",
                [{"name": "acme", "key": "k2", "rps": 0.0}],
                {"Authorization": "Bearer admintok"})
            assert code == 200 and body["tenants"] == ["acme"]
            code, body, _ = post("/generate", {"codes": [1, 2]},
                                 {"X-API-Key": "k2"})
            assert code == 200 and body["status"] == "ok"
            with urllib.request.urlopen(
                    f"http://{host}:{port}/tenants", timeout=10) as r:
                assert "acme" in json.loads(r.read())["tenants"]
            with urllib.request.urlopen(
                    f"http://{host}:{port}/metrics", timeout=10) as r:
                assert b"dalle_gateway_routed_total" in r.read()
        finally:
            httpd.shutdown()
            httpd.server_close()
            gw.close()


class TestStreamingFanoutGateway:
    """Fan-out and streams through the fleet door (ISSUE 20): the WFQ
    charges decoded work (n_samples x the per-sample span), tenant page
    budgets charge the COW footprint (one prompt span + N generation
    spans, not N cold prefills), hedging is a typed reject for live
    streams, and a streamed best-of-N round trip feeds the
    gateway-owned sinks end to end."""

    def test_wfq_charges_n_samples_times_span(self, bundle):
        # fairness stays decoded-work-denominated under fan-out: a
        # best-of-3 advances the finish tag by 3x the span, and a
        # short-grid override charges its shorter span — neither
        # splitting nor shrinking work can game the share
        _, _, cfg = bundle
        gw = _gateway(bundle, n_cells=1)
        try:
            h = gw.submit((1, 2), seed=0, n_samples=3)
            assert h.vfinish - h.vstart == pytest.approx(
                3.0 * cfg.image_seq_len)
            h2 = gw.submit((1, 3), seed=0, n_samples=2,
                           image_seq_len_override=8)
            assert h2.vfinish - h2.vstart == pytest.approx(2.0 * 8)
            assert h.result(120).ok and h2.result(120).ok
        finally:
            gw.close()

    def test_tenant_pages_charge_cow_footprint(self, bundle):
        # the page reservation models the COW group: tiny cfg has
        # text=8 + image=16 = 24 positions, base 6 pages per request.
        # best-of-4 shares ONE prompt span: (8 + 4*16)/24 * 6 = 18
        # pages — strictly under the 24 four cold prefills would cost
        gw = _gateway(bundle, n_cells=1)
        try:
            base = gw.pages_per_request
            assert gw._flight_pages(1, 0) == base == 6
            assert gw._flight_pages(4, 0) == 18 < 4 * base
            # a short-grid override shrinks the generation share
            assert gw._flight_pages(4, 8) == 10
            assert gw._flight_pages(1, 8) == 4 < base
            # without a cfg the geometry is unknown: conservative N x
            saved = gw.cfg
            gw.cfg = None
            try:
                assert gw._flight_pages(4, 0) == 4 * base
            finally:
                gw.cfg = saved
        finally:
            gw.close()

    def test_hedge_is_typed_reject_for_streams(self, bundle):
        # hedge_s=0 would hedge every dispatch — but two live arms
        # would both feed the client's sinks. The stream keeps its
        # single arm; the refusal is a typed event + counter, and the
        # request still completes OK
        tbl = T.TenantTable.from_json(
            [{"name": "gold", "key": "kg", "tier": "gold",
              "hedge_s": 0.0}])
        gw = _gateway(bundle, tenants=tbl, hedge_check_s=0.0)
        try:
            h = gw.submit((4, 2, 1), api_key="kg", seed=3,
                          stream=True)
            assert h.result(120).ok
            assert gw.hedge_stream_rejects >= 1
            evs = gw.events("gateway_hedge_reject")
            assert evs and evs[0]["reason"] == "stream"
            assert not gw.events("gateway_hedge")
            assert gw.stats()["hedge_stream_rejects"] >= 1
            assert "dalle_gateway_hedge_stream_rejects_total" \
                in gw.metrics_text()
        finally:
            gw.close()

    def test_streamed_best_of_n_end_to_end(self, bundle):
        # gateway-owned sinks (replay-safe) deliver both samples'
        # token events and group-atomic sample_done frames; the
        # flight's terminal returns the COW page reservation and the
        # streams_active gauge drains back to zero
        _, _, cfg = bundle
        tbl = T.TenantTable.from_json(
            [{"name": "acme", "key": "k", "max_pages": 64}])
        gw = _gateway(bundle, n_cells=1, tenants=tbl)
        try:
            h = gw.submit((2, 3, 4), api_key="k", seed=9,
                          stream=True, n_samples=2)
            sink = gw._flights[h.request.request_id].sinks[0]
            assert sink.replayable
            seen, done_samples = {}, []
            for ev in sink.events():
                if ev["event"] == "tokens":
                    seen.setdefault(ev["sample"], {})[ev["pos"]] \
                        = ev["tokens"]
                elif ev["event"] == "sample_done":
                    done_samples.append(ev["sample"])
            res = h.result(120)
            assert res.ok and len(res.tokens) == cfg.image_seq_len
            assert sorted(done_samples) == [0, 1]
            for s in (0, 1):
                toks = []
                for pos in sorted(seen[s]):
                    toks.extend(seen[s][pos])
                assert len(toks) >= cfg.image_seq_len
            assert tbl.stats()["acme"]["pages_in_flight"] == 0
            st = gw.stats()
            assert st["streams_active"] == 0 and st["completed"] >= 1
            assert "dalle_gateway_streams_active" \
                in gw.metrics_text()
        finally:
            gw.close()


class TestCellStatsSurface:
    def test_replica_set_aggregates_prefix_stats(self, bundle):
        # the cell-stats satellite: a ReplicaSet-backed cell exposes
        # fleet-aggregated prefix_hits/prefix_entries, what the
        # gateway's affinity bench reads per cell
        server = _cell(bundle, replicas=2)
        try:
            for _ in range(3):
                assert server.generate((4, 4, 4), seed=2,
                                       timeout=90).ok
            st = server.stats()
            assert "prefix_hits" in st and "prefix_entries" in st
            assert st["prefix_entries"] >= 1
            assert st["prefix_hits"] >= 1
        finally:
            server.close()
