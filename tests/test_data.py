"""Data-layer tests: the Vocabulary contract, caption parsing/batching,
image IO round-trips, and the prefetcher (SURVEY.md §5 data contract)."""

import os

import numpy as np
import pytest

from dalle_pytorch_tpu.data import (CaptionDataset, ImageFolderDataset,
                                    PAD_TOKEN, Prefetcher, Vocabulary,
                                    load_caption_data, load_image,
                                    load_image_batch, prefetch,
                                    read_caption_pairs, read_captions_only,
                                    save_image_grid, shard_for_host,
                                    text_mask, to_uint8)


# ---------------------------------------------------------------------------
# Vocabulary — reference Vocabulary.py:3-43 contract
# ---------------------------------------------------------------------------

class TestVocabulary:
    def test_reserved_ids(self):
        v = Vocabulary()
        assert v.to_word(0) == "PAD"
        assert v.to_word(1) == "SOS"
        assert v.to_word(2) == "EOS"
        assert v.num_words == 3

    def test_words_number_from_three_in_first_seen_order(self):
        v = Vocabulary()
        v.add_sentence("a dog runs")
        v.add_sentence("a cat runs fast")
        assert v.to_index("a") == 3
        assert v.to_index("dog") == 4
        assert v.to_index("runs") == 5
        assert v.to_index("cat") == 6
        assert v.to_index("fast") == 7
        assert v.word2count["a"] == 2
        assert v.word2count["dog"] == 1

    def test_oov_raises_keyerror(self):
        # the reference's hard failure mode (Vocabulary.py:43)
        v = Vocabulary()
        v.add_sentence("hello world")
        with pytest.raises(KeyError):
            v.to_index("unseen")

    def test_sentence_stats(self):
        v = Vocabulary()
        v.add_sentence("one two three")
        v.add_sentence("one")
        assert v.num_sentences == 2
        assert v.longest_sentence == 3

    def test_encode_pads_and_skips_empty(self):
        v = Vocabulary()
        v.add_sentence("a dog")
        ids = v.encode("a  dog", pad_to=6)   # double space -> '' skipped
        assert ids == [3, 4, 0, 0, 0, 0]
        assert v.decode(ids) == "a dog"

    def test_encode_overflow_raises(self):
        v = Vocabulary()
        v.add_sentence("a b c")
        with pytest.raises(ValueError):
            v.encode("a b c", pad_to=2)

    def test_save_load_roundtrip(self, tmp_path):
        v = Vocabulary("caps")
        v.add_sentence("the quick brown fox")
        v.add_sentence("the lazy dog")
        p = str(tmp_path / "vocab.json")
        v.save(p)
        w = Vocabulary.load(p)
        assert w.word2index == v.word2index
        assert w.index2word == v.index2word
        assert w.num_words == v.num_words
        assert w.longest_sentence == v.longest_sentence


# ---------------------------------------------------------------------------
# caption files — reference trainDALLE.py:92-163
# ---------------------------------------------------------------------------

@pytest.fixture
def caption_files(tmp_path):
    (tmp_path / "only.txt").write_text(
        "a red square\na blue circle\na green square\n")
    (tmp_path / "pairs.txt").write_text(
        "img0.png : a red square\n"
        "img1.png : a blue circle\n"
        "img2.png : a green square\n")
    return str(tmp_path / "only.txt"), str(tmp_path / "pairs.txt")


class TestCaptions:
    def test_load_caption_data(self, caption_files):
        only, pairs = caption_files
        vocab, data = load_caption_data(only, pairs, text_seq_len=8)
        assert len(data) == 3
        fn, ids = data[0]
        assert fn == "img0.png"
        assert len(ids) == 8
        assert ids[:3] == [vocab.to_index("a"), vocab.to_index("red"),
                           vocab.to_index("square")]
        assert ids[3:] == [PAD_TOKEN] * 5

    def test_pairs_split_on_first_colon(self, tmp_path):
        p = tmp_path / "pairs.txt"
        p.write_text("a.png : caption with : colon\n")
        [(fn, txt)] = read_caption_pairs(str(p))
        assert fn == "a.png"
        assert "colon" in txt

    def test_dataset_fixed_batch_shape(self, caption_files):
        only, pairs = caption_files
        vocab, data = load_caption_data(only, pairs, text_seq_len=8)
        ds = CaptionDataset(data, batch_size=2)
        batches = list(ds.epoch(0))
        assert len(batches) == 2
        for paths, toks in batches:
            assert len(paths) == 2          # tail batch wraps, not ragged
            assert toks.shape == (2, 8)
            assert toks.dtype == np.int32

    def test_dataset_shuffle_deterministic(self, caption_files):
        only, pairs = caption_files
        _, data = load_caption_data(only, pairs, text_seq_len=8)
        ds = CaptionDataset(data, batch_size=3, shuffle=True, seed=7)
        a = [p for p, _ in ds.epoch(0)][0]
        b = [p for p, _ in ds.epoch(0)][0]
        c = [p for p, _ in ds.epoch(1)][0]
        assert a == b                       # same epoch -> same order
        assert set(a) == set(c)

    def test_text_mask(self):
        toks = np.array([[3, 4, 0, 0]])
        assert (text_mask(toks) == [[True, True, False, False]]).all()


# ---------------------------------------------------------------------------
# image IO
# ---------------------------------------------------------------------------

@pytest.fixture
def image_dir(tmp_path):
    from PIL import Image
    d = tmp_path / "imgs" / "0"
    d.mkdir(parents=True)
    rng = np.random.default_rng(0)
    for i in range(3):
        arr = rng.integers(0, 256, (16, 16, 3), dtype=np.uint8)
        Image.fromarray(arr).save(d / f"img{i}.png")
    return tmp_path / "imgs"


class TestImages:
    def test_load_image_range_and_shape(self, image_dir):
        img = load_image(str(image_dir / "0" / "img0.png"), image_size=8)
        assert img.shape == (8, 8, 3)
        assert img.dtype == np.float32
        assert img.min() >= -1.0 and img.max() <= 1.0

    def test_load_image_batch_resolves_subdir(self, image_dir):
        batch = load_image_batch(["img0.png", "img1.png"],
                                 data_path=str(image_dir), image_size=16)
        assert batch.shape == (2, 16, 16, 3)

    def test_folder_dataset(self, image_dir):
        ds = ImageFolderDataset(str(image_dir), image_size=16, batch_size=2,
                                drop_last=False)
        batches = list(ds)
        assert len(batches) == 2
        assert all(b.shape == (2, 16, 16, 3) for b in batches)

    def test_to_uint8_normalize(self):
        x = np.linspace(-1, 1, 12, dtype=np.float32).reshape(1, 2, 2, 3)
        u = to_uint8(x, normalize=True)
        assert u.min() == 0 and u.max() == 255

    def test_save_image_grid(self, tmp_path):
        from PIL import Image
        imgs = np.random.default_rng(0).uniform(-1, 1, (6, 8, 8, 3))
        out = str(tmp_path / "grid.png")
        save_image_grid(imgs, out, nrow=3, padding=1)
        w, h = Image.open(out).size
        assert w == 3 * 9 + 1 and h == 2 * 9 + 1


# ---------------------------------------------------------------------------
# prefetch + host sharding
# ---------------------------------------------------------------------------

class TestPrefetch:
    def test_prefetch_preserves_order_and_values(self):
        batches = [np.full((2, 3), i, np.float32) for i in range(5)]
        out = list(prefetch(iter(batches), depth=2))
        assert len(out) == 5
        for i, b in enumerate(out):
            assert np.asarray(b).flatten()[0] == i

    def test_transform_runs_in_worker(self):
        out = list(Prefetcher(iter([1, 2, 3]), depth=1,
                              transform=lambda x: np.full((2,), x * 10)))
        assert [int(np.asarray(o)[0]) for o in out] == [10, 20, 30]

    def test_error_propagates(self):
        def gen():
            yield np.zeros((1,))
            raise RuntimeError("boom")
        it = prefetch(gen())
        next(it)
        with pytest.raises(RuntimeError, match="boom"):
            next(it)
            next(it)

    def test_shard_for_host(self):
        items = list(range(10))
        assert shard_for_host(items, 0, 3) == [0, 1, 2]
        assert shard_for_host(items, 2, 3) == [6, 7, 8]
        with pytest.raises(ValueError):
            shard_for_host([1], 0, 2)
