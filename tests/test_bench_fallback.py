"""Outage-proofing of the bench artifact chain (VERDICT r3 item 5): a
wedged TPU tunnel at bench time must degrade the perf record to the last
committed on-TPU artifact (marked stale), not delete it."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _has_artifact():
    import bench
    return bench._latest_committed_artifact() is not None


def test_latest_committed_artifact_shape():
    import bench
    found = bench._latest_committed_artifact()
    if found is None:
        pytest.skip("no committed on-TPU artifact in this checkout")
    payload, path = found
    assert payload["backend"] == "tpu"
    assert payload["value"] and payload["value"] > 0
    assert os.path.basename(path).startswith("BENCH_TPU_")


def test_midrun_stall_emits_partial():
    """A tunnel that wedges MID-RUN (2026-07-31 04:19 pattern) must emit
    the configs measured so far as a ``partial: true`` payload, exit 0."""
    script = (
        "import json, os, time\n"
        "import bench\n"
        "bench._partial.update({'metric': 'm', 'value': 123.4,\n"
        "                       'unit': 'tokens/sec/chip',\n"
        "                       'configs': {'vae': {'value': 1.0}}})\n"
        "bench._start_stall_watchdog()\n"
        "bench._beat('config kernels ...')\n"
        "time.sleep(30)\n"                    # watchdog must fire first
        "raise SystemExit('watchdog never fired')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", script], cwd=REPO, capture_output=True,
        text=True, timeout=60,
        env={**os.environ, "BENCH_STALL_DEADLINE_S": "0.2",
             "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["partial"] is True
    assert d["value"] == 123.4
    assert d["configs"]["vae"]["value"] == 1.0
    assert d["stall"]["stalled_in"] == "config kernels ..."


def test_midrun_stall_without_north_falls_back_stale():
    """If the stall hits before the north number exists, degrade to the
    newest committed artifact (stale) — same contract as an init wedge."""
    script = (
        "import time\n"
        "import bench\n"
        "bench._start_stall_watchdog()\n"
        "time.sleep(30)\n"
        "raise SystemExit('watchdog never fired')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", script], cwd=REPO, capture_output=True,
        text=True, timeout=60,
        env={**os.environ, "BENCH_STALL_DEADLINE_S": "0.2",
             "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 1, proc.stderr
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    if _has_artifact():
        assert d["stale"] is True
        assert d["stale_reason"]["stalled_in"] == "watchdog start"
    else:
        assert d["value"] is None
        assert "stalled_in" in d


def test_stale_fallback_surfaces_tuned_best():
    """When the committed tune sweep's best (docs/TUNE_NORTH.json, same
    setup_train + time_steps methodology) beats the newest artifact's
    north number, the stale fallback headlines the sweep's number with
    provenance instead of underreporting the metric."""
    import bench
    best = bench._tuned_best_record()
    found = bench._latest_committed_artifact()
    if not (best and found) or \
            best["tokens_sec_chip"] <= (found[0]["value"] or 0):
        pytest.skip("no committed tuned best beating the newest artifact")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--retries", "0"],
        env={**os.environ, "BENCH_INIT_DEADLINE_S": "0.01"},
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert d["stale"] is True
    assert d["value"] == best["tokens_sec_chip"]
    assert d["value_source"] == "docs/TUNE_NORTH.json best"
    assert d["stale_bench_value"] == found[0]["value"]
    assert d["vs_baseline"] == round(
        best["tokens_sec_chip"] / bench.A100_TOKENS_PER_SEC_EST, 3)
    # the headline must carry the sweep point's identity, not the
    # artifact's (different batch/config) run
    assert d["batch"] == best["batch"]
    assert d["loss"] == best["loss"]
    assert best.get("attn", "?") in d["metric"]
    # the artifact's OWN measured perf fields must not sit at top level
    # where they'd read as the tuned config's numbers (advisor r4):
    # they move under stale_artifact_fields
    for k in ("gen_p50_ms", "gen_ms_per_token", "step_ms"):
        assert k not in d, k
    assert any(k in d.get("stale_artifact_fields", {})
               for k in ("gen_p50_ms", "gen_ms_per_token"))


def test_wedged_tunnel_emits_stale_fallback():
    """Simulated wedge (zero init deadline): stdout is ONE JSON line
    carrying the last real numbers + stale=true + the honest failure."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--retries", "0"],
        env={**os.environ, "BENCH_INIT_DEADLINE_S": "0.01"},
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1          # still an honest failure
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    if _has_artifact():
        assert d["stale"] is True
        assert d["value"] and d["value"] > 0
        assert d["stale_reason"]["error"]
        assert d["stale_artifact"].startswith("docs/")
    else:                                # no artifact: diagnostic JSON
        assert d["value"] is None
        assert "error" in d
