"""Checkpoint subsystem tests: round-trips (params/opt state/config/meta),
atomicity guarantees, the name-and-epoch template, and the VAE->DALLE
cross-CLI contract (SURVEY.md §5.4, reference trainVAE.py:119 ->
trainDALLE.py:64-67)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dalle_pytorch_tpu import checkpoint as ckpt
from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V


@pytest.fixture(scope="module")
def vae_setup():
    cfg = V.VAEConfig(image_size=16, num_tokens=24, codebook_dim=32,
                      num_layers=2, hidden_dim=8)
    params = V.vae_init(jax.random.PRNGKey(0), cfg)
    return cfg, params


def tree_equal(a, b):
    return bool(jax.tree.all(jax.tree.map(
        lambda x, y: bool((np.asarray(x) == np.asarray(y)).all()), a, b)))


class TestRoundTrip:
    def test_params_and_manifest(self, tmp_path, vae_setup):
        cfg, params = vae_setup
        path = ckpt.save(str(tmp_path / "c"), params, step=7, config=cfg,
                         kind="vae", meta={"temperature": 0.8})
        params2, manifest = ckpt.restore_params(path)
        assert tree_equal(params, params2)
        assert manifest["kind"] == "vae"
        assert manifest["step"] == 7
        assert manifest["meta"]["temperature"] == 0.8
        cfg2 = ckpt.vae_config_from_manifest(manifest)
        assert cfg2 == cfg

    def test_opt_state_roundtrip(self, tmp_path, vae_setup):
        cfg, params = vae_setup
        opt = optax.adam(1e-3)
        state = opt.init(params)
        # one real update so moments are non-trivial
        grads = jax.tree.map(jnp.ones_like, params)
        _, state = opt.update(grads, state, params)
        path = ckpt.save(str(tmp_path / "c"), params, opt_state=state,
                         config=cfg)
        _, state2, _ = ckpt.restore(path, opt_target=opt.init(params))
        assert tree_equal(state, state2)

    def test_missing_opt_state_raises(self, tmp_path, vae_setup):
        cfg, params = vae_setup
        opt = optax.adam(1e-3)
        path = ckpt.save(str(tmp_path / "c"), params)
        with pytest.raises(FileNotFoundError):
            ckpt.restore(path, opt_target=opt.init(params))

    def test_bfloat16_leaves_survive(self, tmp_path):
        tree = {"w": jnp.ones((4, 4), jnp.bfloat16) * 1.5}
        path = ckpt.save(str(tmp_path / "c"), tree)
        back, _ = ckpt.restore_params(path)
        assert back["w"].dtype == jnp.bfloat16
        assert tree_equal(tree, back)

    def test_overwrite_existing(self, tmp_path, vae_setup):
        cfg, params = vae_setup
        p = str(tmp_path / "c")
        ckpt.save(p, params, step=1)
        ckpt.save(p, params, step=2)
        assert ckpt.load_manifest(p)["step"] == 2

    def test_dalle_config_roundtrip(self, tmp_path, vae_setup):
        vcfg, _ = vae_setup
        cfg = D.DALLEConfig(dim=32, depth=2, vae=vcfg, num_text_tokens=50,
                            text_seq_len=8, heads=2, dim_head=16,
                            sparse_attn=(True, False))
        params = {"x": np.zeros((2,))}
        path = ckpt.save(str(tmp_path / "c"), params, config=cfg,
                         kind="dalle")
        manifest = ckpt.load_manifest(path)
        cfg2 = ckpt.dalle_config_from_manifest(manifest)
        assert cfg2 == cfg


class TestNaming:
    def test_ckpt_path_template(self):
        assert ckpt.ckpt_path("./models", "vae", 12).endswith("vae-12")

    def test_latest(self, tmp_path, vae_setup):
        cfg, params = vae_setup
        for e in (0, 3, 11):
            ckpt.save(ckpt.ckpt_path(str(tmp_path), "vae", e), params,
                      step=e)
        ckpt.save(ckpt.ckpt_path(str(tmp_path), "other", 99), params)
        path, epoch = ckpt.latest(str(tmp_path), "vae")
        assert epoch == 11 and path.endswith("vae-11")
        assert ckpt.latest(str(tmp_path), "missing") is None

    def test_no_tmp_dirs_left_behind(self, tmp_path, vae_setup):
        cfg, params = vae_setup
        ckpt.save(str(tmp_path / "c"), params)
        leftovers = [d for d in os.listdir(tmp_path)
                     if d.startswith(".ckpt-tmp-")]
        assert leftovers == []


@pytest.mark.faults
class TestCorruptionRecovery:
    """Partial-write/corruption fallback (ISSUE 1 satellite): a truncated
    params.msgpack, a missing manifest, and a save killed between the tmp
    write and the atomic rename must each be DETECTED (validate) and
    auto-resume must fall back to the previous valid checkpoint."""

    def _two_epochs(self, tmp_path, params):
        for e in (0, 1):
            ckpt.save(ckpt.ckpt_path(str(tmp_path), "vae", e), params,
                      step=e, meta={"epoch": e, "global_step": 2 * (e + 1)})

    def test_truncated_params_detected_and_skipped(self, tmp_path,
                                                   vae_setup):
        from dalle_pytorch_tpu.resilience import faults
        _, params = vae_setup
        self._two_epochs(tmp_path, params)
        newest = ckpt.ckpt_path(str(tmp_path), "vae", 1)
        faults.truncate_params(newest)
        ok, reason = ckpt.validate(newest)
        assert not ok and "params.msgpack" in reason
        path, epoch = ckpt.latest_valid(str(tmp_path), "vae")
        assert epoch == 0
        # the naive `latest` would still hand back the corrupt one
        assert ckpt.latest(str(tmp_path), "vae")[1] == 1

    def test_missing_manifest_detected_and_skipped(self, tmp_path,
                                                   vae_setup):
        from dalle_pytorch_tpu.resilience import faults
        _, params = vae_setup
        self._two_epochs(tmp_path, params)
        faults.remove_manifest(ckpt.ckpt_path(str(tmp_path), "vae", 1))
        ok, reason = ckpt.validate(ckpt.ckpt_path(str(tmp_path), "vae", 1))
        assert not ok and "manifest" in reason
        path, epoch = ckpt.latest_valid(str(tmp_path), "vae")
        assert epoch == 0

    def test_interrupted_save_leaves_previous_valid(self, tmp_path,
                                                    vae_setup):
        """Kill between tmp write and rename: the staging dir never
        matches the name template, the committed checkpoint stays the
        resume target, and a later save still succeeds."""
        from dalle_pytorch_tpu.resilience import faults
        _, params = vae_setup
        self._two_epochs(tmp_path, params)
        faults.simulate_interrupted_save(str(tmp_path))
        path, epoch = ckpt.latest_valid(str(tmp_path), "vae")
        assert epoch == 1
        ckpt.save(ckpt.ckpt_path(str(tmp_path), "vae", 2), params, step=2)
        assert ckpt.latest_valid(str(tmp_path), "vae")[1] == 2

    def test_corrupt_opt_state_detected(self, tmp_path, vae_setup):
        cfg, params = vae_setup
        opt = optax.adam(1e-3)
        path = ckpt.save(str(tmp_path / "c"), params,
                         opt_state=opt.init(params))
        with open(os.path.join(path, ckpt.OPT_STATE), "r+b") as f:
            f.truncate(8)
        ok, reason = ckpt.validate(path)
        assert not ok and "opt_state" in reason

    def test_restore_falls_back_through_validate(self, tmp_path, vae_setup):
        """The full loop: corrupt the newest, restore from what
        latest_valid picks — bytes round-trip from the older epoch."""
        from dalle_pytorch_tpu.resilience import faults
        _, params = vae_setup
        self._two_epochs(tmp_path, params)
        faults.truncate_params(ckpt.ckpt_path(str(tmp_path), "vae", 1))
        path, _ = ckpt.latest_valid(str(tmp_path), "vae")
        restored, manifest = ckpt.restore_params(path)
        assert tree_equal(params, restored)
        assert manifest["meta"]["epoch"] == 0


class TestStepCheckpoints:
    def test_step_template_invisible_to_epoch_latest(self, tmp_path,
                                                     vae_setup):
        _, params = vae_setup
        ckpt.save(ckpt.ckpt_path(str(tmp_path), "vae", 0), params)
        ckpt.save(ckpt.step_ckpt_path(str(tmp_path), "vae", 7), params)
        assert ckpt.latest(str(tmp_path), "vae")[1] == 0       # epoch only
        assert [s for s, _ in ckpt.step_checkpoints(
            str(tmp_path), "vae")] == [7]

    def test_gc_keeps_newest_steps_never_epochs(self, tmp_path, vae_setup):
        _, params = vae_setup
        ckpt.save(ckpt.ckpt_path(str(tmp_path), "vae", 0), params)
        for s in (1, 2, 3, 4, 5):
            ckpt.save(ckpt.step_ckpt_path(str(tmp_path), "vae", s), params)
        removed = ckpt.gc_steps(str(tmp_path), "vae", keep=2)
        assert len(removed) == 3
        assert [s for s, _ in ckpt.step_checkpoints(
            str(tmp_path), "vae")] == [4, 5]
        assert ckpt.latest(str(tmp_path), "vae")[1] == 0       # untouched


class TestCrossCLIContract:
    def test_vae_to_dalle_codebook_tie(self, tmp_path, vae_setup):
        """train_vae writes; train_dalle restores and ties image_emb to the
        codebook (reference trainVAE.py:119 -> trainDALLE.py:64-67 +
        dalle_pytorch.py:283)."""
        cfg, params = vae_setup
        path = ckpt.save(ckpt.ckpt_path(str(tmp_path), "vae", 0), params,
                         config=cfg, kind="vae")
        vae_params, manifest = ckpt.restore_params(path)
        vae_cfg = ckpt.vae_config_from_manifest(manifest)
        dcfg = D.DALLEConfig(dim=vae_cfg.codebook_dim, depth=2, vae=vae_cfg,
                             num_text_tokens=50, text_seq_len=8, heads=2,
                             dim_head=16)
        dalle_params = D.dalle_init(jax.random.PRNGKey(1), dcfg,
                                    vae_params=vae_params)
        assert tree_equal(dalle_params["image_emb"]["w"],
                          vae_params["codebook"]["w"])
