"""Direct tests for the observability utils (SURVEY.md §5.1/5.2/5.5):
metrics JSONL content and rate scaling, profiler trace windows, NaN/finite
guards. The CLIs exercise these implicitly; these pin the contracts."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.utils import (MetricsLogger, StepProfiler,
                                     enable_nan_checks)
from dalle_pytorch_tpu.utils.debug import check_finite_tree, guard_loss


class TestMetricsLogger:
    def test_jsonl_records_and_rates(self, tmp_path):
        path = tmp_path / "m.jsonl"
        m = MetricsLogger(str(path), log_interval=2, n_devices=2)
        for step in range(4):
            m.step(step, loss=1.5, epoch=0, units=100, unit_name="tokens")
        m.event(event="checkpoint", epoch=0, avg_loss=1.5)

        recs = [json.loads(line) for line in path.read_text().splitlines()]
        steps = [r for r in recs if "step" in r]
        assert [r["step"] for r in steps] == [0, 2]
        # single process: global rate = 2x the per-chip rate (2 chips)
        r = steps[1]
        assert r["tokens_per_sec"] == pytest.approx(
            2 * r["tokens_per_sec_per_chip"], rel=1e-6)
        assert recs[-1]["event"] == "checkpoint"

    def test_no_path_no_file(self, tmp_path):
        m = MetricsLogger(None, log_interval=1)
        m.step(0, loss=1.0, units=1)          # must not raise
        m.event(event="x")
        assert list(tmp_path.iterdir()) == []


class TestStepProfiler:
    def test_trace_window_writes_profile(self, tmp_path):
        prof = StepProfiler(str(tmp_path), start=1, steps=2)
        x = jnp.ones((8, 8))
        for i in range(4):
            prof.maybe_start(i)
            x = (x @ x).block_until_ready()
            prof.maybe_stop(i)
        prof.close()
        found = [f for _, _, fs in os.walk(tmp_path) for f in fs]
        assert found, "profiler wrote no trace files"

    def test_disabled_is_noop(self):
        prof = StepProfiler(None)
        prof.maybe_start(10)
        prof.maybe_stop(12)
        prof.close()


class TestDebugGuards:
    def test_check_finite_tree_names_bad_leaves(self):
        tree = {"ok": jnp.ones(3), "bad": jnp.array([1.0, np.nan])}
        with pytest.raises(FloatingPointError, match="bad"):
            check_finite_tree(tree, "params")
        check_finite_tree({"ok": jnp.ones(3)})   # clean tree passes

    def test_guard_loss(self):
        assert guard_loss(jnp.float32(1.25), 3) == 1.25
        with pytest.raises(FloatingPointError, match="step 7"):
            guard_loss(jnp.float32(np.inf), 7)

    def test_nan_check_toggle_traps_and_restores(self):
        enable_nan_checks(True)
        try:
            with pytest.raises(FloatingPointError):
                jax.jit(lambda x: x / 0.0)(jnp.float32(1.0)).block_until_ready()
        finally:
            enable_nan_checks(False)
        # trap off again: division produces inf silently
        assert not np.isfinite(float(jax.jit(lambda x: x / 0.0)(
            jnp.float32(1.0))))
