"""EMA weights (--ema_decay): f32 accumulation, checkpoint round-trip,
resume continuity, and the gen-side cast."""

import argparse
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dalle_pytorch_tpu import checkpoint as ckpt  # noqa: E402
from dalle_pytorch_tpu.cli.common import ema_as, make_ema  # noqa: E402


def _args(decay):
    return argparse.Namespace(ema_decay=decay)


def test_ema_moves_despite_bf16_params():
    """The accumulator must be f32: a bf16 EMA at decay 0.999 cannot move
    (machine eps swallows the step). Params ARE bf16 here; the EMA still
    converges toward them."""
    params = {"w": jnp.full((4,), 2.0, jnp.bfloat16)}
    ema, update = make_ema(_args(0.999), {"w": jnp.zeros((4,),
                                                        jnp.bfloat16)})
    assert ema["w"].dtype == jnp.float32
    for _ in range(100):
        ema = update(ema, params)
    # 1 - 0.999^100 ~ 0.0952 of the way from 0 to 2
    assert float(ema["w"][0]) == pytest.approx(2 * 0.0952, rel=0.01)


def test_ema_off_is_none():
    ema, update = make_ema(_args(0.0), {"w": jnp.zeros((2,))})
    assert ema is None and update is None


def test_checkpoint_roundtrip_and_resume(tmp_path):
    params = {"w": jnp.ones((3,), jnp.float32)}
    ema, update = make_ema(_args(0.9), params)
    ema = update(ema, {"w": jnp.full((3,), 5.0)})
    path = ckpt.save(str(tmp_path / "m-0"), params, config={}, ema=ema)
    # pre-EMA checkpoints return None
    path2 = ckpt.save(str(tmp_path / "n-0"), params, config={})
    assert ckpt.restore_ema(path2) is None
    restored = ckpt.restore_ema(path)
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.asarray(ema["w"]))
    # resume continues from the restored EMA, not from params
    ema2, _ = make_ema(_args(0.9), params, resume_path=path)
    np.testing.assert_allclose(np.asarray(ema2["w"]), np.asarray(ema["w"]))


def test_resume_with_ema_but_no_decay_refuses(tmp_path):
    """Resuming a checkpoint that carries an EMA without --ema_decay must
    refuse, not silently drop the accumulated average (advisor r4): the
    next save would write no ema.msgpack and the average is gone."""
    params = {"w": jnp.ones((3,), jnp.float32)}
    ema, _ = make_ema(_args(0.9), params)
    path = ckpt.save(str(tmp_path / "m-0"), params, config={}, ema=ema)
    with pytest.raises(SystemExit, match="carries an EMA"):
        make_ema(_args(0.0), params, resume_path=path)
    # explicit negative decay = discard on purpose, allowed
    ema2, upd2 = make_ema(_args(-1.0), params, resume_path=path)
    assert ema2 is None and upd2 is None
    # a pre-EMA checkpoint never triggers the guard
    plain = ckpt.save(str(tmp_path / "p-0"), params, config={})
    ema3, upd3 = make_ema(_args(0.0), params, resume_path=plain)
    assert ema3 is None and upd3 is None


def test_resume_with_changed_decay_warns(tmp_path, capsys):
    """The manifest records the decay the EMA was written with; resuming
    with a different value is legal but surfaced."""
    params = {"w": jnp.ones((3,), jnp.float32)}
    ema, _ = make_ema(_args(0.9), params)
    path = ckpt.save(str(tmp_path / "m-0"), params, config={}, ema=ema,
                     meta={"ema_decay": 0.9})
    make_ema(_args(0.99), params, resume_path=path)
    assert "ema_decay 0.9" in capsys.readouterr().out
    # same decay: silent
    make_ema(_args(0.9), params, resume_path=path)
    assert "ema_decay" not in capsys.readouterr().out


def test_corrupt_opt_state_diagnosed(tmp_path):
    """A truncated opt_state.msgpack must be reported as corruption, not
    as an optimizer-shaping-flags mismatch (advisor r4)."""
    import optax
    params = {"w": jnp.ones((3,), jnp.float32)}
    opt = optax.adam(1e-3)
    path = ckpt.save(str(tmp_path / "m-0"), params, config={},
                     opt_state=opt.init(params))
    opt_file = os.path.join(path, ckpt.OPT_STATE)
    with open(opt_file, "rb") as f:
        data = f.read()
    with open(opt_file, "wb") as f:
        f.write(data[:7])  # truncate mid-header
    with pytest.raises(ValueError, match="corrupt or truncated"):
        ckpt.restore_train(path, opt)


def test_ema_as_casts_to_param_dtypes():
    params = {"a": jnp.zeros((2,), jnp.bfloat16),
              "b": jnp.zeros((2,), jnp.int8)}
    ema = {"a": jnp.ones((2,), jnp.float32),
           "b": jnp.ones((2,), jnp.float32)}
    out = ema_as(ema, params)
    assert out["a"].dtype == jnp.bfloat16
    assert out["b"].dtype == jnp.int8
