"""EMA weights (--ema_decay): f32 accumulation, checkpoint round-trip,
resume continuity, and the gen-side cast."""

import argparse
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from dalle_pytorch_tpu import checkpoint as ckpt  # noqa: E402
from dalle_pytorch_tpu.cli.common import ema_as, make_ema  # noqa: E402


def _args(decay):
    return argparse.Namespace(ema_decay=decay)


def test_ema_moves_despite_bf16_params():
    """The accumulator must be f32: a bf16 EMA at decay 0.999 cannot move
    (machine eps swallows the step). Params ARE bf16 here; the EMA still
    converges toward them."""
    params = {"w": jnp.full((4,), 2.0, jnp.bfloat16)}
    ema, update = make_ema(_args(0.999), {"w": jnp.zeros((4,),
                                                        jnp.bfloat16)})
    assert ema["w"].dtype == jnp.float32
    for _ in range(100):
        ema = update(ema, params)
    # 1 - 0.999^100 ~ 0.0952 of the way from 0 to 2
    assert float(ema["w"][0]) == pytest.approx(2 * 0.0952, rel=0.01)


def test_ema_off_is_none():
    ema, update = make_ema(_args(0.0), {"w": jnp.zeros((2,))})
    assert ema is None and update is None


def test_checkpoint_roundtrip_and_resume(tmp_path):
    params = {"w": jnp.ones((3,), jnp.float32)}
    ema, update = make_ema(_args(0.9), params)
    ema = update(ema, {"w": jnp.full((3,), 5.0)})
    path = ckpt.save(str(tmp_path / "m-0"), params, config={}, ema=ema)
    # pre-EMA checkpoints return None
    path2 = ckpt.save(str(tmp_path / "n-0"), params, config={})
    assert ckpt.restore_ema(path2) is None
    restored = ckpt.restore_ema(path)
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.asarray(ema["w"]))
    # resume continues from the restored EMA, not from params
    ema2, _ = make_ema(_args(0.9), params, resume_path=path)
    np.testing.assert_allclose(np.asarray(ema2["w"]), np.asarray(ema["w"]))


def test_ema_as_casts_to_param_dtypes():
    params = {"a": jnp.zeros((2,), jnp.bfloat16),
              "b": jnp.zeros((2,), jnp.int8)}
    ema = {"a": jnp.ones((2,), jnp.float32),
           "b": jnp.ones((2,), jnp.float32)}
    out = ema_as(ema, params)
    assert out["a"].dtype == jnp.bfloat16
    assert out["b"].dtype == jnp.int8
