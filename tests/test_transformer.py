"""Transformer stack tests: scan engine, routing, mixed sparse patterns."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.ops.transformer import (TransformerConfig, layer_init,
                                               transformer_apply,
                                               transformer_init)
from dalle_pytorch_tpu.ops import core
from dalle_pytorch_tpu.ops import attention as A


CFG = TransformerConfig(dim=32, depth=3, seq_len=16, heads=2, dim_head=16)


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


def test_shapes_and_jit(key):
    params = transformer_init(key, CFG)
    x = jax.random.normal(key, (2, 16, 32))
    f = jax.jit(lambda p, x: transformer_apply(p, x, cfg=CFG))
    y = f(params, x)
    assert y.shape == x.shape
    # jaxlint: disable=JL001 — terminal fetch for the finiteness assert
    assert np.isfinite(np.array(y)).all()


def test_scan_matches_python_loop(key):
    """The lax.scan engine must equal an explicit per-layer residual loop
    (reference SequentialSequence, reversible.py:134-141)."""
    params = transformer_init(key, CFG)
    x = jax.random.normal(key, (2, 16, 32))
    mask = jnp.ones((2, 16), bool).at[:, 12:].set(False)
    y = transformer_apply(params, x, cfg=CFG, mask=mask)

    h = x
    for i in range(CFG.depth):
        lp = jax.tree.map(lambda a: a[i], params)
        ln = core.layernorm(lp["attn"]["ln"], h)
        h = h + A.attention_apply(ln_params_attn(lp), ln, heads=CFG.heads,
                                  dim_head=CFG.dim_head, scale=CFG.scale,
                                  causal=True, mask=mask)
        ln2 = core.layernorm(lp["ff"]["ln"], h)
        z = core.linear(lp["ff"]["w1"], ln2)
        a, g = jnp.split(z, 2, axis=-1)
        h = h + core.linear(lp["ff"]["w2"], a * core.gelu(g))
    np.testing.assert_allclose(np.array(y), np.array(h), atol=1e-5)


def ln_params_attn(lp):
    return {"qkv": lp["attn"]["qkv"], "out": lp["attn"]["out"]}


def test_mask_routed_only_to_attention(key):
    """Masked-out positions still pass through FF (mask only routes to attn,
    reference transformer.py:166-167)."""
    params = transformer_init(key, CFG)
    x = jax.random.normal(key, (1, 16, 32))
    mask = jnp.zeros((1, 16), bool).at[:, :8].set(True)
    y = transformer_apply(params, x, cfg=CFG, mask=mask)
    # masked positions are NOT zeroed — they get uniform attention + FF
    assert not np.allclose(np.array(y[0, 12]), np.array(x[0, 12]))


def test_mixed_sparse_pattern_runs(key):
    cfg = TransformerConfig(dim=32, depth=4, seq_len=32, heads=2, dim_head=16,
                            sparse_attn=(True, False, True, False),
                            sparse_block=16)
    params = transformer_init(key, cfg)
    x = jax.random.normal(key, (2, 32, 32))
    y = jax.jit(lambda p, x: transformer_apply(p, x, cfg=cfg))(params, x)
    assert y.shape == x.shape
    assert np.isfinite(np.array(y)).all()


def test_all_sparse_with_wide_window_equals_dense(key):
    """When the sparse window covers the whole sequence, sparse==dense up to
    pad-query masking (no pad -> identical)."""
    base = dict(dim=32, depth=2, seq_len=32, heads=2, dim_head=16)
    cfg_d = TransformerConfig(**base)
    cfg_s = TransformerConfig(**base, sparse_attn=True, sparse_block=16)
    # window of 4 blocks at 16-block => covers 64 tokens > 32 seq
    params = transformer_init(key, cfg_d)
    x = jax.random.normal(key, (1, 32, 32))
    y_d = transformer_apply(params, x, cfg=cfg_d)
    y_s = transformer_apply(params, x, cfg=cfg_s)
    np.testing.assert_allclose(np.array(y_d), np.array(y_s), atol=1e-5)


def test_windowed_impl_matches_ref_in_stack(key):
    """sparse_impl='windowed' (the fast exact decomposition) agrees with
    the dense-masked oracle inside the full stack, ragged mask included
    (seq 96 = 1.5 windows of 64)."""
    base = dict(dim=32, depth=2, seq_len=96, heads=2, dim_head=16,
                sparse_attn=True, sparse_block=16)
    cfg_r = TransformerConfig(**base, sparse_impl="ref")
    cfg_w = TransformerConfig(**base, sparse_impl="windowed")
    params = transformer_init(key, cfg_r)
    x = jax.random.normal(key, (2, 96, 32))
    mask = jnp.ones((2, 96), bool).at[0, 70:].set(False)
    y_r = transformer_apply(params, x, cfg=cfg_r, mask=mask)
    y_w = transformer_apply(params, x, cfg=cfg_w, mask=mask)
    np.testing.assert_allclose(np.array(y_w), np.array(y_r), atol=1e-5)


@pytest.mark.parametrize("mode", ["save_ln", "dots", "full"])
def test_remat_matches_plain(key, mode):
    """'full' recomputes the whole layer body; 'dots' keeps matmul outputs
    and recomputes only vector work (measured ~65% residual-byte cut on
    the flash north stack). In f32 the recompute is deterministic, so
    loss AND grads match the un-rematerialized path tightly."""
    cfg_r = TransformerConfig(dim=32, depth=3, seq_len=16, heads=2,
                              dim_head=16, remat=mode)
    params = transformer_init(key, CFG)
    x = jax.random.normal(key, (2, 16, 32))

    def loss(p, c):
        return jnp.sum(transformer_apply(p, x, cfg=c) ** 2)

    l1, g1 = jax.value_and_grad(loss)(params, CFG)
    l2, g2 = jax.value_and_grad(loss)(params, cfg_r)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.array(a), np.array(b), atol=1e-5), g1, g2)


def test_dropout_deterministic_given_key(key):
    cfg = TransformerConfig(dim=32, depth=2, seq_len=16, heads=2, dim_head=16,
                            attn_dropout=0.3, ff_dropout=0.3)
    params = transformer_init(key, cfg)
    x = jax.random.normal(key, (1, 16, 32))
    r = jax.random.PRNGKey(7)
    y1 = transformer_apply(params, x, cfg=cfg, rng=r, train=True)
    y2 = transformer_apply(params, x, cfg=cfg, rng=r, train=True)
    y3 = transformer_apply(params, x, cfg=cfg, rng=jax.random.PRNGKey(8),
                           train=True)
    np.testing.assert_array_equal(np.array(y1), np.array(y2))
    assert not np.allclose(np.array(y1), np.array(y3))


def test_aperiodic_pattern_matches_periodic_path(key):
    """The traced lax.cond fallback (pattern period > _MAX_UNROLL_PERIOD)
    computes the same outputs and grads as the static-unroll path for an
    equivalent layer ordering."""
    import dataclasses

    from dalle_pytorch_tpu.ops.transformer import (_MAX_UNROLL_PERIOD,
                                                   _pattern_period)

    # depth 6, aperiodic: period == 6 > 4 -> exercises the cond fallback
    pattern = (True, True, False, False, False, True)
    assert _pattern_period(pattern) > _MAX_UNROLL_PERIOD
    cfg = TransformerConfig(dim=32, depth=6, seq_len=32, heads=2, dim_head=16,
                            sparse_attn=pattern, sparse_block=16)
    params = transformer_init(key, cfg)
    x = jax.random.normal(key, (2, 32, 32))

    def loss(p, cfg):
        return jnp.sum(transformer_apply(p, x, cfg=cfg) ** 2)

    y_cond = jax.jit(lambda p: transformer_apply(p, x, cfg=cfg))(params)
    g_cond = jax.grad(lambda p: loss(p, cfg))(params)

    # same layers, forced through the static path: period-1 patterns per
    # block would change layer order, so instead force the unrolled path by
    # comparing against a per-layer python loop oracle
    from dalle_pytorch_tpu.ops.transformer import attn_branch, ff_branch
    def oracle(p):
        h = x
        for l in range(cfg.depth):
            lp = jax.tree.map(lambda a: a[l], p)
            h = h + attn_branch(lp, h, None, cfg, bool(pattern[l]), None,
                                False)
            h = h + ff_branch(lp, h, cfg, None, False)
        return h

    y_ref = oracle(params)
    g_ref = jax.grad(lambda p: jnp.sum(oracle(p) ** 2))(params)
    np.testing.assert_allclose(np.array(y_cond), np.array(y_ref), atol=1e-5)
    for a, b in zip(jax.tree.leaves(g_cond), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.array(a), np.array(b), atol=1e-4)
