"""Unit tests for ops.core primitives."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.ops import core


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


def test_linear_shapes_and_bias(key):
    p = core.linear_init(key, 8, 16)
    x = jnp.ones((2, 3, 8))
    y = core.linear(p, x)
    assert y.shape == (2, 3, 16)
    p2 = core.linear_init(key, 8, 16, bias=False)
    assert "b" not in p2


def test_layernorm_normalises(key):
    p = core.layernorm_init(6)
    x = jax.random.normal(key, (4, 6)) * 5 + 3
    y = core.layernorm(p, x)
    np.testing.assert_allclose(np.mean(y, -1), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.std(y, -1), 1.0, atol=1e-2)


def test_embedding_lookup(key):
    p = core.embedding_init(key, 10, 4)
    ids = jnp.array([[1, 2], [3, 4]])
    y = core.embedding(p, ids)
    assert y.shape == (2, 2, 4)
    np.testing.assert_array_equal(y[0, 0], p["w"][1])


def test_conv2d_stride2_downsamples(key):
    p = core.conv2d_init(key, 3, 8, 4)
    x = jnp.ones((2, 16, 16, 3))
    y = core.conv2d(p, x, stride=2, padding=1)
    assert y.shape == (2, 8, 8, 8)


def test_conv2d_transpose_doubles(key):
    p = core.conv2d_init(key, 8, 3, 4)
    x = jnp.ones((2, 8, 8, 8))
    y = core.conv2d_transpose(p, x, stride=2, padding=1)
    assert y.shape == (2, 16, 16, 3)


def test_conv_transpose_matches_torch_semantics(key):
    """conv_transpose must be the adjoint of stride-2 conv — verified against
    torch.nn.functional.conv_transpose2d on identical weights."""
    torch = pytest.importorskip("torch")
    p = core.conv2d_init(key, 4, 5, 4)
    x = np.array(jax.random.normal(jax.random.PRNGKey(1), (2, 6, 6, 4)),
                 dtype=np.float32)
    y = core.conv2d_transpose(p, jnp.asarray(x), stride=2, padding=1)

    # torch: NCHW input, (in, out, kh, kw) kernel
    xt = torch.tensor(x.transpose(0, 3, 1, 2))
    wt = torch.tensor(np.array(p["w"]).transpose(2, 3, 0, 1))
    bt = torch.tensor(np.array(p["b"]))
    yt = torch.nn.functional.conv_transpose2d(xt, wt, bt, stride=2, padding=1)
    np.testing.assert_allclose(np.array(y).transpose(0, 3, 1, 2),
                               yt.numpy(), atol=1e-4)


def test_dropout_train_eval(key):
    x = jnp.ones((100, 100))
    assert np.array_equal(core.dropout(key, x, 0.5, train=False), x)
    y = core.dropout(key, x, 0.5, train=True)
    frac = float(jnp.mean(y == 0))
    assert 0.4 < frac < 0.6
    kept = np.array(y[y != 0])
    np.testing.assert_allclose(kept, 2.0, atol=1e-6)


def test_positional_dropout_shard_invariant(key):
    """Concatenating per-shard results (each shard passing its global start
    offset) must reproduce the unsharded mask bit-for-bit — the property
    sequence-parallel dropout is built on."""
    x = jnp.arange(2 * 16 * 4, dtype=jnp.float32).reshape(2, 16, 4) + 1.0
    full = core.positional_dropout(key, x, 0.3, train=True)
    shards = [core.positional_dropout(key, x[:, s:s + 4], 0.3, train=True,
                                      offset=s)
              for s in range(0, 16, 4)]
    np.testing.assert_array_equal(np.asarray(full),
                                  np.concatenate([np.asarray(s) for s in
                                                  shards], axis=1))
    # eval / rate-0 passthrough and scaling, like plain dropout
    assert np.array_equal(core.positional_dropout(key, x, 0.3, train=False),
                          x)
    kept = np.asarray(full)[np.asarray(full) != 0]
    np.testing.assert_allclose(kept,
                               (np.asarray(x)[np.asarray(full) != 0]) / 0.7,
                               rtol=1e-6)
