"""Unit tests for the tune-sweep record merge (scripts/tune_north.py).

docs/TUNE_NORTH.json decides bench_north's recorded defaults, so the
merge semantics are load-bearing: the committed best must only ever
improve, re-measured configs must dedupe with the newest value winning,
old records written before a sweep dimension existed must collapse onto
the value those runs actually used, and off-backend payloads must be
discarded.
"""

import importlib.util
import os

spec = importlib.util.spec_from_file_location(
    "tune_north",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "scripts", "tune_north.py"))
tune = importlib.util.module_from_spec(spec)
spec.loader.exec_module(tune)


def rec(tps, **kw):
    r = {"attn": "flash", "batch": 16, "loss_chunk": 256,
         "heads": 8, "dim_head": 64, "remat": "none", "reversible": False,
         "flash_block_q": 128, "flash_block_k": 128,
         "tokens_sec_chip": tps}
    r.update(kw)
    return r


def test_first_run_writes_run_best():
    out = tune.merge_tune_payload(None, [rec(100.0)])
    assert out["best"]["tokens_sec_chip"] == 100.0
    assert len(out["results"]) == 1
    assert out["backend"] == "tpu"


def test_prior_best_survives_a_worse_run():
    prev = {"backend": "tpu", "best": rec(110.0, batch=8),
            "results": [rec(110.0, batch=8)]}
    out = tune.merge_tune_payload(prev, [rec(90.0)])
    assert out["best"]["tokens_sec_chip"] == 110.0
    assert out["best"]["batch"] == 8
    assert len(out["results"]) == 2


def test_better_run_replaces_best():
    prev = {"backend": "tpu", "best": rec(110.0, batch=8),
            "results": [rec(110.0, batch=8)]}
    out = tune.merge_tune_payload(prev, [rec(120.0, remat="full")])
    assert out["best"]["tokens_sec_chip"] == 120.0
    assert out["best"]["remat"] == "full"


def test_remeasured_config_dedupes_latest_wins():
    prev = {"backend": "tpu", "best": rec(95.0),
            "results": [rec(95.0)]}
    out = tune.merge_tune_payload(prev, [rec(97.0)])
    assert len(out["results"]) == 1
    assert out["results"][0]["tokens_sec_chip"] == 97.0


def test_pre_dimension_records_collapse_onto_defaults():
    # a record written before remat/reversible/flash blocks existed is the
    # same config as an explicit all-defaults record
    old = {"attn": "flash", "batch": 16, "loss_chunk": 256, "heads": 8,
           "dim_head": 64, "tokens_sec_chip": 95.0}
    prev = {"backend": "tpu", "best": old, "results": [old]}
    out = tune.merge_tune_payload(prev, [rec(96.0)])
    assert len(out["results"]) == 1
    assert out["results"][0]["tokens_sec_chip"] == 96.0


def test_off_backend_payload_is_discarded():
    prev = {"backend": "cpu", "best": rec(9e9),
            "results": [rec(9e9)]}
    out = tune.merge_tune_payload(prev, [rec(90.0)])
    assert out["best"]["tokens_sec_chip"] == 90.0
    assert len(out["results"]) == 1


def test_remeasured_best_corrects_downward():
    # a noisy prior best is retired when the SAME config re-measures lower
    prev = {"backend": "tpu", "best": rec(95.0), "results": [rec(95.0)]}
    out = tune.merge_tune_payload(prev, [rec(90.0)])
    assert out["best"]["tokens_sec_chip"] == 90.0


def test_non_dict_prev_payload_is_discarded():
    out = tune.merge_tune_payload([], [rec(90.0)])
    assert out["best"]["tokens_sec_chip"] == 90.0


def test_write_merged_incremental(tmp_path):
    """_write_merged is called after EVERY measured point (a mid-sweep
    wedge must not cost the points already banked): successive calls
    accumulate records and keep the best monotone."""
    import json
    out = str(tmp_path / "TUNE_NORTH.json")
    tune._write_merged([rec(100.0)], out=out)
    tune._write_merged([rec(100.0), rec(90.0, batch=32)], out=out)
    d = json.load(open(out))
    assert d["best"]["tokens_sec_chip"] == 100.0
    assert len(d["results"]) == 2
    # a later, better run replaces the best; earlier records survive
    tune._write_merged([rec(120.0, batch=4)], out=out)
    d = json.load(open(out))
    assert d["best"]["tokens_sec_chip"] == 120.0
    assert len(d["results"]) == 3
