"""Long-context probe (scripts/longctx_probe.py): merge discipline and a
CPU smoke of the measured point."""

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

spec = importlib.util.spec_from_file_location(
    "longctx_probe", os.path.join(REPO, "scripts", "longctx_probe.py"))
probe = importlib.util.module_from_spec(spec)
spec.loader.exec_module(probe)


def test_merge_latest_wins_and_sorts():
    prev = {"backend": "tpu", "results": [
        {"impl": "xla", "seq": 2560, "depth": 2, "batch": 1,
         "tokens_sec": 100.0},
        {"impl": "flash", "seq": 2560, "depth": 2, "batch": 1,
         "kind": "error", "error": "x"},
    ]}
    new = [
        {"impl": "flash", "seq": 2560, "depth": 2, "batch": 1,
         "tokens_sec": 200.0},                       # replaces the error
        {"impl": "flash", "seq": 5120, "depth": 2, "batch": 1,
         "kind": "oom", "error": "RESOURCE_EXHAUSTED"},
    ]
    out = probe.merge_longctx_payload(prev, new)
    assert out["backend"] == "tpu"
    assert len(out["results"]) == 3
    flash_2560 = [r for r in out["results"]
                  if r["impl"] == "flash" and r["seq"] == 2560][0]
    assert flash_2560["tokens_sec"] == 200.0 and "kind" not in flash_2560
    # sorted by (impl, seq) for a stable committed diff
    assert [r["seq"] for r in out["results"]] == [2560, 5120, 2560]


def test_merge_discards_foreign_backend():
    prev = {"backend": "cpu", "results": [
        {"impl": "xla", "seq": 2560, "depth": 2, "batch": 1,
         "tokens_sec": 1.0}]}
    out = probe.merge_longctx_payload(prev, [
        {"impl": "xla", "seq": 5120, "depth": 2, "batch": 1,
         "tokens_sec": 2.0}])
    assert len(out["results"]) == 1
    assert out["results"][0]["seq"] == 5120


def test_run_point_cpu_smoke():
    tps = probe.run_point("xla", 128, depth=1, batch=1, steps=2, warmup=1)
    assert tps > 0
