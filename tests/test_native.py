"""Native (C++) loader tests: decode parity vs the PIL path, PIL-style
triangle resize, error contract, and the data-layer integration/fallback.

The loader replaces the native IO the reference reaches through torchvision
(reference trainDALLE.py:185-187, trainVAE.py:59-67); parity here is
against this repo's PIL implementation of the same normalize contract.
"""

import os

import numpy as np
import pytest

pytest.importorskip("PIL")
from PIL import Image  # noqa: E402

from dalle_pytorch_tpu import native  # noqa: E402
from dalle_pytorch_tpu.data import load_image, load_image_batch  # noqa: E402

if not native.available():  # pragma: no cover - toolchain is in the image
    pytest.skip("native loader could not build", allow_module_level=True)


@pytest.fixture(scope="module")
def images(tmp_path_factory):
    d = tmp_path_factory.mktemp("native")
    rng = np.random.default_rng(0)
    arr = rng.integers(0, 256, (32, 32, 3), np.uint8)
    paths = {}
    Image.fromarray(arr).save(d / "rgb.png")
    Image.fromarray(np.dstack([arr, np.full((32, 32), 200, np.uint8)]),
                    "RGBA").save(d / "rgba.png")
    Image.fromarray(arr[:, :, 0], "L").save(d / "gray.png")
    Image.fromarray(arr).convert("P").save(d / "palette.png")
    Image.fromarray(arr).save(d / "photo.jpg", quality=95)
    Image.fromarray(rng.integers(0, 256, (48, 64, 3), np.uint8)).save(
        d / "wide.png")
    for p in os.listdir(d):
        paths[os.path.splitext(p)[0]] = str(d / p)
    return paths


class TestDecode:
    def test_png_variants_and_jpeg_match_pil_exactly(self, images):
        # decode (no resize) goes through the same libjpeg/libpng the PIL
        # path uses -> bit-identical pixels, float32 rounding only
        for name in ("rgb", "rgba", "gray", "palette", "photo"):
            out = native.load_image_batch_native([images[name]])
            ref = load_image(images[name])
            assert out.shape == (1,) + ref.shape
            np.testing.assert_allclose(out[0], ref, atol=1e-6), name

    def test_batch_is_stacked_in_order(self, images):
        paths = [images["rgb"], images["photo"], images["gray"]]
        out = native.load_image_batch_native(paths, image_size=32)
        for i, p in enumerate(paths):
            np.testing.assert_allclose(out[i], load_image(p, 32), atol=1e-6)

    def test_range_and_dtype(self, images):
        out = native.load_image_batch_native([images["rgb"]])
        assert out.dtype == np.float32
        assert out.min() >= -1.0 and out.max() <= 1.0


class TestResize:
    @pytest.mark.parametrize("size", [16, 27, 64])
    def test_triangle_resize_tracks_pil_bilinear(self, images, size):
        # PIL quantizes to uint8 between the two filter passes; the native
        # loader stays in float, so parity is within ~2 LSB of 8-bit
        out = native.load_image_batch_native([images["wide"]], size)[0]
        ref = np.asarray(
            Image.open(images["wide"]).convert("RGB").resize(
                (size, size), Image.BILINEAR), np.float32) / 255 * 2 - 1
        assert np.abs(out - ref).max() < 0.02

    def test_identity_resize_is_exact(self, images):
        out = native.load_image_batch_native([images["rgb"]], 32)[0]
        np.testing.assert_allclose(out, load_image(images["rgb"], 32),
                                   atol=1e-6)


class TestErrors:
    def test_missing_file_raises(self, images, tmp_path):
        with pytest.raises(RuntimeError, match="failed to decode"):
            native.load_image_batch_native([str(tmp_path / "missing.png")],
                                           16)

    def test_non_image_raises(self, tmp_path):
        bad = tmp_path / "junk.png"
        bad.write_bytes(b"this is not a png")
        with pytest.raises(RuntimeError, match="failed to decode"):
            native.load_image_batch_native([str(bad)], 16)

    def test_mixed_sizes_without_resize_raise(self, images):
        with pytest.raises(RuntimeError):
            native.load_image_batch_native(
                [images["rgb"], images["wide"]], 0)

    def test_empty_batch(self):
        out = native.load_image_batch_native([], 16)
        assert out.shape == (0, 16, 16, 3)


class TestDataLayerIntegration:
    def test_load_image_batch_uses_native_and_matches_pil(self, images,
                                                          monkeypatch):
        paths = [images["rgb"], images["photo"]]
        fast = load_image_batch(paths, image_size=16)
        monkeypatch.setenv("DALLE_TPU_NATIVE_LOADER", "0")
        slow = load_image_batch(paths, image_size=16)
        assert fast.shape == slow.shape == (2, 16, 16, 3)
        assert np.abs(fast - slow).max() < 0.02

    def test_unsupported_extension_falls_back_to_pil(self, tmp_path):
        arr = np.random.default_rng(1).integers(0, 256, (8, 8, 3), np.uint8)
        p = tmp_path / "img.bmp"          # not in the native fast set
        Image.fromarray(arr).save(p)
        out = load_image_batch([str(p)], image_size=8)
        np.testing.assert_allclose(out[0], load_image(str(p), 8), atol=1e-6)


class TestThreadSafety:
    def test_concurrent_batch_loads_are_stable(self, images):
        """The prefetcher decodes on worker threads while other threads may
        decode too; the C ABI must be reentrant (it keeps no global state
        besides the dlopen handle)."""
        import concurrent.futures as cf
        paths = [images["rgb"], images["photo"], images["gray"]] * 3
        ref = native.load_image_batch_native(paths, 16)

        def work(_):
            return native.load_image_batch_native(paths, 16)

        with cf.ThreadPoolExecutor(max_workers=4) as ex:
            for out in ex.map(work, range(8)):
                np.testing.assert_array_equal(out, ref)
