"""Resilience runtime unit tests: deadline/backoff/jitter bring-up,
supervisor rollback/re-warm/preemption mechanics, checkpoint-validation
driven auto-resume discovery, data-path fault handling, and the
pp_param_specs ep guard (docs/RESILIENCE.md)."""

import os
import random
import signal
import threading
import time

import numpy as np
import pytest

from dalle_pytorch_tpu import checkpoint as ckpt
from dalle_pytorch_tpu.data import Prefetcher, prefetch
from dalle_pytorch_tpu.resilience import (BringupError, DeadlineExceeded,
                                          Preempted, RetryPolicy,
                                          TrainingDiverged, TrainSupervisor,
                                          call_with_deadline, faults,
                                          find_auto_resume,
                                          retry_with_backoff)

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.deactivate()
    yield
    faults.deactivate()


# ---------------------------------------------------------------------------
# retry: deadline + exponential backoff + jitter
# ---------------------------------------------------------------------------

class TestRetry:
    def test_deadline_returns_result_and_reraises(self):
        assert call_with_deadline(lambda: 42, 5.0, "t") == 42
        with pytest.raises(ValueError, match="boom"):
            call_with_deadline(lambda: (_ for _ in ()).throw(
                ValueError("boom")), 5.0, "t")

    def test_deadline_fires_instead_of_hanging(self):
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded):
            call_with_deadline(lambda: time.sleep(30), 0.15, "wedged")
        assert time.monotonic() - t0 < 5.0     # nowhere near the 30 s hang

    def test_backoff_is_exponential_then_capped(self):
        p = RetryPolicy(base_backoff_s=1.0, backoff_multiplier=2.0,
                        max_backoff_s=5.0, jitter=0.0)
        assert [p.backoff(a) for a in range(4)] == [1.0, 2.0, 4.0, 5.0]

    def test_backoff_jitter_bounded_and_seeded(self):
        p = RetryPolicy(base_backoff_s=10.0, jitter=0.25)
        rng = random.Random(0)
        draws = [p.backoff(0, rng) for _ in range(50)]
        assert all(7.5 <= d <= 12.5 for d in draws)
        assert len(set(draws)) > 1             # actually jittered
        assert draws == [RetryPolicy(base_backoff_s=10.0, jitter=0.25)
                         .backoff(0, random.Random(0))
                         for _ in range(1)] + draws[1:]  # deterministic rng

    def test_retries_then_recovers_with_events(self):
        calls, events = [], []

        def flaky(attempt):
            calls.append(attempt)
            if attempt < 2:
                raise RuntimeError(f"fail {attempt}")
            return "ok"

        out = retry_with_backoff(
            flaky, RetryPolicy(max_attempts=3, deadline_s=5.0,
                               base_backoff_s=0.01, jitter=0.0),
            label="t", on_event=events.append)
        assert out == "ok" and calls == [0, 1, 2]
        assert [e["kind"] for e in events] == ["bringup_retry"] * 2
        assert events[0]["attempt"] == 1 and "fail 0" in events[0]["error"]

    def test_exhaustion_raises_structured_record(self):
        events = []
        with pytest.raises(BringupError) as ei:
            retry_with_backoff(
                lambda a: (_ for _ in ()).throw(RuntimeError(f"e{a}")),
                RetryPolicy(max_attempts=2, deadline_s=5.0,
                            base_backoff_s=0.01, jitter=0.0),
                label="claim", on_event=events.append)
        rec = ei.value.record
        assert rec["event"] == "resilience"
        assert rec["kind"] == "bringup_failure"
        assert rec["label"] == "claim" and rec["attempts"] == 2
        assert len(rec["errors"]) == 2 and "e1" in rec["errors"][-1]
        assert events[-1] == rec               # terminal record emitted too


# ---------------------------------------------------------------------------
# wedged backend init: injected timeout -> retries -> structured failure,
# never a hang (acceptance criterion; bench consumes the same helper)
# ---------------------------------------------------------------------------

class TestBackendBringup:
    def test_multihost_init_wedged_surfaces_record(self, monkeypatch):
        from dalle_pytorch_tpu.parallel import multihost
        monkeypatch.setattr(multihost, "_initialized", False)
        events = []
        t0 = time.monotonic()
        with faults.injected(backend_init_hang_s=30):
            with pytest.raises(BringupError) as ei:
                multihost.initialize(coordinator_address="127.0.0.1:1",
                                     num_processes=1, process_id=0,
                                     deadline_s=0.15, max_attempts=2,
                                     on_event=events.append)
        assert time.monotonic() - t0 < 15.0    # both attempts deadline-cut
        rec = ei.value.record
        assert rec["kind"] == "bringup_failure"
        assert rec["label"] == "multihost_init" and rec["attempts"] == 2
        assert any(e["kind"] == "bringup_retry" for e in events)
        assert not multihost._initialized      # failure must not mark joined

    def test_multihost_init_injected_failure_no_hang_path(self, monkeypatch):
        from dalle_pytorch_tpu.parallel import multihost
        monkeypatch.setattr(multihost, "_initialized", False)
        with faults.injected(backend_init_fail_attempts=99):
            with pytest.raises(BringupError) as ei:
                multihost.initialize(coordinator_address="127.0.0.1:1",
                                     num_processes=1, process_id=0,
                                     deadline_s=5.0, max_attempts=2)
        assert "injected backend init failure" in ei.value.record[
            "errors"][-1]

    def test_bench_claim_backend_reports_injected_failure(self, monkeypatch):
        import bench
        monkeypatch.delenv(bench.RETRY_ENV, raising=False)
        monkeypatch.setenv("BENCH_INIT_DEADLINE_S", "5")
        with faults.injected(backend_init_fail_attempts=99):
            out = bench.claim_backend(0)
        assert out is not None
        err, attempts = out
        assert "injected backend init failure" in err and attempts == 1

    def test_bench_claim_backend_deadline_cuts_injected_hang(self,
                                                            monkeypatch):
        import bench
        monkeypatch.delenv(bench.RETRY_ENV, raising=False)
        monkeypatch.setenv("BENCH_INIT_DEADLINE_S", "0.15")
        t0 = time.monotonic()
        with faults.injected(backend_init_hang_s=30):
            out = bench.claim_backend(3)       # timeout: no retry/re-exec
        assert time.monotonic() - t0 < 10.0
        err, attempts = out
        assert "deadline" in err


# ---------------------------------------------------------------------------
# data path: propagate / skip-with-cap / restart
# ---------------------------------------------------------------------------

class TestPrefetchFaults:
    def test_crashing_iterator_propagates_after_good_batches(self):
        items = [np.full((2,), i, np.float32) for i in range(4)]
        it = prefetch(faults.crashing_iterator(items, 2), depth=1)
        assert int(np.asarray(next(it))[0]) == 0
        assert int(np.asarray(next(it))[0]) == 1
        with pytest.raises(faults.FaultInjected):
            next(it)

    def test_skip_bad_records_counted_with_events(self):
        events = []

        def transform(x):
            if x % 2:
                raise ValueError(f"bad record {x}")
            return np.full((2,), x, np.float32)

        p = Prefetcher(iter(range(6)), transform=transform,
                       max_bad_records=3, on_event=events.append)
        out = [int(np.asarray(b)[0]) for b in p]
        assert out == [0, 2, 4]
        assert p.bad_records == 3
        assert [e["kind"] for e in events] == ["prefetch_bad_record"] * 3
        assert events[0]["cap"] == 3

    def test_source_pos_counts_skipped_records(self):
        """The resume contract: ``source_pos`` after receiving a batch is
        the number of SOURCE records consumed up to and including it —
        bad skipped records included, worker read-ahead excluded — so a
        mid-epoch checkpoint skips exactly the right prefix on resume
        even when --max_bad_records dropped records before the kill."""
        def transform(x):
            if x == 2:
                raise ValueError("bad")
            return np.full((1,), x, np.float32)

        p = Prefetcher(iter(range(5)), transform=transform,
                       max_bad_records=1, depth=1)
        seen, positions = [], []
        for b in p:
            seen.append(int(np.asarray(b)[0]))
            positions.append(p.source_pos)
        assert seen == [0, 1, 3, 4]
        # batch "3" carries position 4: records 0,1,bad-2,3 consumed
        assert positions == [1, 2, 4, 5]

    def test_bad_record_cap_exceeded_propagates(self):
        def transform(x):
            raise ValueError(f"bad {x}")

        p = Prefetcher(iter(range(5)), transform=transform,
                       max_bad_records=2)
        with pytest.raises(ValueError, match="bad 2"):
            list(p)
        assert p.bad_records == 2

    def test_default_still_propagates_without_skipping(self):
        # the pre-existing contract (test_data.py::test_error_propagates):
        # no opt-in, no swallowing
        def gen():
            yield np.zeros((1,))
            raise RuntimeError("boom")

        it = prefetch(gen())
        next(it)
        with pytest.raises(RuntimeError, match="boom"):
            next(it)

    def test_iterator_retry_opt_in(self):
        events = []

        class FlakySource:
            def __init__(self):
                self.i = 0
                self.failed = False

            def __iter__(self):
                return self

            def __next__(self):
                if self.i == 2 and not self.failed:
                    self.failed = True
                    raise OSError("transient read error")
                if self.i >= 4:
                    raise StopIteration
                self.i += 1
                return np.full((2,), self.i, np.float32)

        p = Prefetcher(FlakySource(), iterator_retries=1,
                       on_event=events.append)
        assert [int(np.asarray(b)[0]) for b in p] == [1, 2, 3, 4]
        assert p.iterator_retries == 1
        assert events[0]["kind"] == "prefetch_iterator_retry"

    def test_dead_worker_restarted_once(self):
        events = []

        class DiesOnce(Prefetcher):
            deaths = 0

            def _worker(self):
                if type(self).deaths == 0:
                    type(self).deaths += 1
                    return                     # hard death: NO sentinel
                super()._worker()

        p = DiesOnce(iter([np.full((2,), 7, np.float32)]),
                     on_event=events.append)
        assert int(np.asarray(next(p))[0]) == 7
        assert any(e["kind"] == "prefetch_restart" for e in events)
        with pytest.raises(StopIteration):
            next(p)

    def test_dead_worker_second_death_fails_loudly(self):
        class AlwaysDies(Prefetcher):
            def _worker(self):
                return                         # never a sentinel

        p = AlwaysDies(iter([1]))
        with pytest.raises(RuntimeError, match="died without reporting"):
            next(p)


# ---------------------------------------------------------------------------
# supervisor mechanics
# ---------------------------------------------------------------------------

def _dummy_params():
    return {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}


def _mk_sup(tmp_path, **kw):
    params = _dummy_params()
    saves = []

    def save_state(path):
        saves.append(path)
        return ckpt.save(path, params, step=len(saves))

    sup = TrainSupervisor(name="toy", models_dir=str(tmp_path),
                          save_state=save_state, **kw)
    sup._saves = saves
    return sup


class TestSupervisor:
    def test_nan_without_anchor_diverges(self, tmp_path):
        sup = _mk_sup(tmp_path)
        with pytest.raises(TrainingDiverged, match="no valid checkpoint"):
            sup.check_step(0, float("nan"))

    def test_nan_rolls_back_to_anchor_then_budget_exhausts(self, tmp_path):
        sup = _mk_sup(tmp_path, max_rollbacks=2)
        anchor = ckpt.save(str(tmp_path / "toy-step1"), _dummy_params())
        sup.register_checkpoint(anchor)
        assert sup.check_step(0, 1.0) == sup.OK
        assert sup.check_step(1, float("inf")) == sup.ROLLBACK
        assert sup.rollback_target() == anchor
        assert sup.check_step(2, float("nan")) == sup.ROLLBACK
        with pytest.raises(TrainingDiverged, match="rollback"):
            sup.check_step(3, float("nan"))

    def test_spike_detection_against_median(self, tmp_path):
        sup = _mk_sup(tmp_path, spike_factor=3.0, spike_window=8)
        anchor = ckpt.save(str(tmp_path / "toy-step1"), _dummy_params())
        sup.register_checkpoint(anchor)
        for s in range(6):
            assert sup.check_step(s, 1.0 + 0.01 * s) == sup.OK
        assert sup.check_step(6, 2.5) == sup.OK      # below 3x median
        assert sup.check_step(7, 10.0) == sup.ROLLBACK

    def test_rollback_skips_corrupt_anchor(self, tmp_path):
        sup = _mk_sup(tmp_path)
        good = ckpt.save(str(tmp_path / "toy-step1"), _dummy_params())
        newer = ckpt.save(str(tmp_path / "toy-step2"), _dummy_params())
        sup.register_checkpoint(good)
        sup.register_checkpoint(newer)
        faults.truncate_params(newer)
        assert sup.rollback_target() == good

    def test_rewarm_ramp(self, tmp_path):
        sup = _mk_sup(tmp_path, rewarm_steps=4)
        anchor = ckpt.save(str(tmp_path / "toy-step1"), _dummy_params())
        sup.register_checkpoint(anchor)
        assert sup.lr_scale(5) == 1.0
        assert sup.check_step(10, float("nan")) == sup.ROLLBACK
        assert sup.lr_scale(11) == pytest.approx(1 / 5)
        assert sup.lr_scale(13) == pytest.approx(3 / 5)
        assert sup.lr_scale(15) == 1.0
        assert sup.lr_scale(16) == 1.0           # ramp over, back to normal

    def test_cadence_save_and_retention_gc(self, tmp_path):
        sup = _mk_sup(tmp_path, save_every=1, keep=2)
        for step in range(1, 5):
            sup.end_step(step)
        steps = [s for s, _ in ckpt.step_checkpoints(str(tmp_path), "toy")]
        assert steps == [3, 4]                   # 1, 2 GC'd
        assert sup.rollback_target().endswith("toy-step4")

    def test_preemption_signal_checkpoints_and_unwinds(self, tmp_path):
        sup = _mk_sup(tmp_path).install_signal_handlers()
        try:
            os.kill(os.getpid(), signal.SIGTERM)
            assert sup.preempted
            with pytest.raises(Preempted) as ei:
                sup.end_step(7)
            assert ei.value.path.endswith("toy-step7")
            ok, _ = ckpt.validate(ei.value.path)
            assert ok
        finally:
            sup.close()
        # handlers restored: default disposition again
        assert signal.getsignal(signal.SIGTERM) in (
            signal.SIG_DFL, signal.default_int_handler, signal.SIG_IGN) \
            or not callable(signal.getsignal(signal.SIGTERM)) \
            or signal.getsignal(signal.SIGTERM).__qualname__.find(
                "handler") < 0

    def test_lr_scale_added_to_batch_only_with_rewarm(self, tmp_path):
        sup = _mk_sup(tmp_path, rewarm_steps=0)
        batch = {"x": np.zeros(2)}
        assert "lr_scale" not in sup.pre_step(0, batch)
        sup2 = _mk_sup(tmp_path, rewarm_steps=3)
        out = sup2.pre_step(0, {"x": np.zeros(2)})
        assert float(out["lr_scale"]) == 1.0


# ---------------------------------------------------------------------------
# auto-resume discovery: newest VALID checkpoint by training progress
# ---------------------------------------------------------------------------

class TestFindAutoResume:
    def test_step_ckpt_beats_older_epoch_ckpt(self, tmp_path):
        d = str(tmp_path)
        ckpt.save(ckpt.ckpt_path(d, "vae", 0), _dummy_params(),
                  meta={"epoch": 0, "global_step": 2})
        ckpt.save(ckpt.step_ckpt_path(d, "vae", 3), _dummy_params(),
                  meta={"epoch": 1, "step_in_epoch": 1, "global_step": 3})
        path, manifest = find_auto_resume(d, "vae")
        assert path.endswith("vae-step3")
        assert manifest["meta"]["step_in_epoch"] == 1

    def test_epoch_ckpt_beats_step_ckpt_it_superseded(self, tmp_path):
        d = str(tmp_path)
        ckpt.save(ckpt.step_ckpt_path(d, "vae", 3), _dummy_params(),
                  meta={"epoch": 1, "step_in_epoch": 1, "global_step": 3})
        ckpt.save(ckpt.ckpt_path(d, "vae", 1), _dummy_params(),
                  meta={"epoch": 1, "global_step": 4})
        path, _ = find_auto_resume(d, "vae")
        assert path.endswith("vae-1")

    def test_corrupt_newest_falls_back_to_previous_valid(self, tmp_path):
        d = str(tmp_path)
        ckpt.save(ckpt.ckpt_path(d, "vae", 0), _dummy_params(),
                  meta={"epoch": 0, "global_step": 2})
        bad = ckpt.save(ckpt.step_ckpt_path(d, "vae", 3), _dummy_params(),
                        meta={"epoch": 1, "step_in_epoch": 1,
                              "global_step": 3})
        faults.truncate_params(bad)
        path, _ = find_auto_resume(d, "vae")
        assert path.endswith("vae-0")

    def test_interrupted_save_staging_dir_is_ignored(self, tmp_path):
        d = str(tmp_path)
        ckpt.save(ckpt.ckpt_path(d, "vae", 0), _dummy_params(),
                  meta={"epoch": 0, "global_step": 2})
        faults.simulate_interrupted_save(d)
        path, _ = find_auto_resume(d, "vae")
        assert path.endswith("vae-0")

    def test_empty_dir_returns_none(self, tmp_path):
        assert find_auto_resume(str(tmp_path), "vae") is None


# ---------------------------------------------------------------------------
# satellite: pp_param_specs must not silently drop requested ep sharding
# ---------------------------------------------------------------------------

class TestPPParamSpecsEpGuard:
    def test_ep_without_moe_subtree_raises(self):
        from dalle_pytorch_tpu.parallel import pp_param_specs
        params = {"transformer": {"attn": {"w": np.zeros((2, 4, 4))},
                                  "ff": {"w1": np.zeros((2, 4, 8))}},
                  "emb": {"w": np.zeros((10, 4))}}
        with pytest.raises(ValueError, match="no .*moe.* subtree"):
            pp_param_specs(params, ep="ep")
        # without ep the same tree is fine
        specs = pp_param_specs(params)
        assert specs["emb"]["w"] is not None

    def test_ep_with_moe_subtree_shards_experts(self):
        from jax.sharding import PartitionSpec as P

        from dalle_pytorch_tpu.parallel import pp_param_specs
        params = {"transformer": {
            "attn": {"w": np.zeros((2, 4, 4))},
            "ff": {"moe": {"w1": np.zeros((2, 4, 4, 8)),
                           "w2": np.zeros((2, 4, 8, 4)),
                           "router": {"w": np.zeros((2, 4, 4))}}}}}
        specs = pp_param_specs(params, ep="ep")
        assert specs["transformer"]["ff"]["moe"]["w1"] == P("pp", "ep")
        assert specs["transformer"]["ff"]["moe"]["w2"] == P("pp", "ep")
