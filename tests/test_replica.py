"""Replica-set serving tests (ISSUE 7 acceptance criteria).

The load-bearing ones are the zero-loss failover contracts: a replica
KILLED or HUNG mid-decode costs zero requests, and every migrated
request's token stream is BYTE-IDENTICAL to the undisturbed
single-replica same-seed run (deterministic sampling makes in-flight
requests migratable — the same replay paged eviction uses, generalized
to replica death). Plus: hang detection fences within the heartbeat
deadline, a circuit-broken replica recovers and rejoins routing,
migration composes with paged eviction, operator drain, graceful
degradation (typed QueueFull, queued deadlines still reaped with zero
live replicas), the replica server end-to-end, and shutdown with a
replica outliving the join (callers never stranded).

Fault-injected tests are marked ``faults`` (the serve-side rows of the
fault catalog, docs/RESILIENCE.md); the rest of the file covers the
routing/observability surface. All CPU, tiny model (total_len 24).
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V
from dalle_pytorch_tpu.resilience import faults
from dalle_pytorch_tpu.resilience.retry import RetryPolicy
from dalle_pytorch_tpu.serve import (CANCELLED, DEADLINE_EXCEEDED, OK,
                                     QueueFull, Request, RequestQueue,
                                     SamplingParams)
from dalle_pytorch_tpu.serve.replica import (BROKEN, DRAINED, RETIRED,
                                             RUNNING, ReplicaSet,
                                             ReplayVersionMismatch,
                                             ScaleError, UpgradeAborted)

VCFG = V.VAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                   num_layers=2, hidden_dim=8)
CFG = D.DALLEConfig(dim=16, depth=2, vae=VCFG, num_text_tokens=50,
                    text_seq_len=8, heads=2, dim_head=8)

# short first-retry backoff so circuit-breaker tests run in milliseconds
FAST_BRINGUP = RetryPolicy(max_attempts=1, deadline_s=None,
                           base_backoff_s=0.01, backoff_multiplier=2.0,
                           max_backoff_s=0.1, jitter=0.0)


@pytest.fixture(scope="module")
def bundle():
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG)
    params = D.dalle_init(key, CFG, vae_params)
    return params, vae_params


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.deactivate()
    yield
    faults.deactivate()


_REF_CACHE: dict = {}


def reference_tokens(params, vae_params, req: Request) -> np.ndarray:
    """generate_images at batch 1 — the undisturbed single-replica
    same-seed run every migrated request must reproduce byte-for-byte
    (memoized: params are the module-scoped bundle everywhere)."""
    key = (req.codes, req.seed, req.sampling.temperature,
           req.sampling.filter_thres, req.sampling.top_p)
    if key not in _REF_CACHE:
        text = jnp.asarray([req.codes], jnp.int32)
        _, img_seq = D.generate_images(
            params, vae_params, text, cfg=CFG,
            rng=jax.random.PRNGKey(req.seed),
            filter_thres=req.sampling.filter_thres,
            top_p=req.sampling.top_p,
            temperature=req.sampling.temperature, return_img_seq=True)
        _REF_CACHE[key] = np.asarray(img_seq)[0]
    return _REF_CACHE[key]


REQS = [
    Request(codes=(3, 7, 9), seed=11),
    Request(codes=(5, 2, 8, 1, 4), seed=23,
            sampling=SamplingParams(temperature=0.7, filter_thres=0.8)),
    Request(codes=(6, 6), seed=5,
            sampling=SamplingParams(temperature=1.3, top_p=0.9)),
    Request(codes=(2, 4, 4), seed=7),
    Request(codes=(1, 5), seed=13),
    Request(codes=(4, 4, 4, 4), seed=17),
]


def assert_all_token_exact(params, vae_params, handles, reqs):
    for h, r in zip(handles, reqs):
        res = h.result(timeout=10)
        assert res.status == OK, (r, res.status, res.reason)
        np.testing.assert_array_equal(
            np.asarray(res.tokens),
            reference_tokens(params, vae_params, r))


def wait_all_ready(rs, timeout=180.0):
    """Drive the set until every process replica's worker reached READY.
    The chunk-keyed fault tests need this: children come up seconds
    apart (async spawn + jax import), and with an empty queue the
    first-ready replica's 2x-slot admission window can swallow a whole
    small burst — leaving the fault's target replica idle, its chunk
    counter at 0, and the injected fault never firing. Waiting costs
    nothing (no work queued = no chunks) and makes routing alternate
    deterministically at submit."""
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        rs.step_once()
        live = [r for r in rs.replicas if r.state == RUNNING
                and r.engine is not None]
        if len(live) == rs.n_replicas and all(
                getattr(r.engine, "ready", True) for r in live):
            return
        time.sleep(0.01)
    raise AssertionError("replicas never all became ready")


class TestCrashFailover:
    pytestmark = pytest.mark.faults

    def test_kill_replica_1_of_2_mid_decode_zero_loss_token_exact(
            self, bundle):
        """THE acceptance criterion: replica 1 of 2 crashes mid-decode
        (fault-injected after its 2nd fused chunk); every request —
        including the ones it held — completes with tokens
        byte-identical to the undisturbed single-replica run, and the
        failover is visible in the supervisor's counters."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, bringup_policy=FAST_BRINGUP)
        handles = [queue.submit(r) for r in REQS]
        with faults.injected(fault_replica=1, replica_crash_at_chunk=2):
            rs.run_until_idle()
        assert rs.failovers == 1
        assert rs.reclaimed >= 1, "the kill must have stranded work"
        assert_all_token_exact(params, vae_params, handles, REQS)
        stats = rs.stats()
        assert stats["completed"] == len(REQS)
        assert stats["failovers"] == 1
        # the replaced engine is a fresh program (own compile); every
        # LIVE replica still holds exactly one decode program
        assert all(c == 1 for c in rs.decode_compiles_per_replica())
        # distinct-delivered-tokens accounting survives the failover:
        # reclaimed prefixes were un-credited, replay re-credited them
        assert stats["tokens_decoded"] == sum(
            CFG.seq_len - len(r.codes) for r in REQS)

    def test_crash_with_single_replica_recovers_via_restart(self,
                                                            bundle):
        """replicas can be 1: the supervisor restarts the one engine and
        replays its work — slower than N>1, still zero-loss."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=8)
        rs = ReplicaSet(params, CFG, queue, replicas=1, num_slots=2,
                        chunk_steps=4, bringup_policy=FAST_BRINGUP)
        handles = [queue.submit(r) for r in REQS[:2]]
        with faults.injected(fault_replica=0, replica_crash_at_chunk=1):
            rs.run_until_idle()
        assert rs.failovers == 1
        assert_all_token_exact(params, vae_params, handles, REQS[:2])


class TestHangFailover:
    pytestmark = pytest.mark.faults

    def test_hang_is_fenced_within_heartbeat_deadline(self, bundle):
        """A replica whose loop stalls (injected sleep where a wedged
        device sync would sit) must be fenced by the supervisor within
        the heartbeat deadline — WITHOUT the wedged thread's
        cooperation — and its requests must replay token-exact on the
        survivor while the hung thread is still asleep."""
        params, vae_params = bundle
        events = []

        class Sink:
            def event(self, **rec):
                events.append(rec)

        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, heartbeat_s=0.25, metrics=Sink(),
                        bringup_policy=FAST_BRINGUP)
        # warm both replicas' programs OUTSIDE the timed window (cold
        # compiles are seconds — the timing below must measure the
        # failover, not XLA)
        warm = [queue.submit(Request(codes=(1, 1), seed=90 + i))
                for i in range(4)]
        rs.run_until_idle()
        for h in warm:
            assert h.result(timeout=60).status == OK
        rs.start()
        try:
            hang_s = 20.0               # far past any load-induced slop
            with faults.injected(fault_replica=0,
                                 replica_hang_at_chunk=1,
                                 replica_hang_s=hang_s):
                handles = [queue.submit(r) for r in REQS[:4]]
                t0 = time.perf_counter()
                # the supervisor must fence the hung replica off its
                # stalled heartbeat — without the wedged thread's
                # cooperation, and LONG before the wedge clears (the
                # deadline is 0.25s; the bound leaves room for CI load)
                while rs.failovers < 1 \
                        and time.perf_counter() - t0 < hang_s:
                    time.sleep(0.01)
                t_fence = time.perf_counter() - t0
                assert rs.failovers >= 1, "hang never detected"
                assert t_fence < hang_s / 2, \
                    f"fence took {t_fence:.2f}s against a 0.25s deadline"
                fenced = [e for e in events
                          if e.get("kind") == "serve_replica_fenced"]
                assert fenced and "heartbeat" in fenced[0]["reason"]
                # and the reclaimed requests replay to completion while
                # the hung thread is STILL asleep
                for h in handles:
                    assert h.result(timeout=60).status == OK
                assert time.perf_counter() - t0 < hang_s, \
                    "completion waited out the hang"
            assert_all_token_exact(params, vae_params, handles, REQS[:4])
        finally:
            rs.close()

    def test_close_with_hung_replica_never_strands_callers(self, bundle):
        """The Server.close() ordering contract on the replica path: a
        replica thread that outlives the join deadline (hung) must not
        strand callers — its in-flight handles are fenced + fulfilled
        ``cancelled``, and the shared-queue drain catches the rest."""
        params, vae_params = bundle
        from dalle_pytorch_tpu.serve.server import InferenceServer
        server = InferenceServer(params, vae_params, CFG, num_slots=2,
                                 queue_depth=16, replicas=2,
                                 heartbeat_s=30.0,  # hang NOT detected:
                                 decode_images=False)  # close must cope
        server.start()
        with faults.injected(fault_replica=0, replica_hang_at_chunk=1,
                             replica_hang_s=4.0):
            handles = [server.submit(r.codes, seed=r.seed)
                       for r in REQS]
            time.sleep(0.5)             # replica 0 is asleep mid-loop
            t0 = time.perf_counter()
            server.close(timeout=1.0)
            assert time.perf_counter() - t0 < 3.0
            for h in handles:
                res = h.result(timeout=1)   # never strands: ok (done
                assert res.status in (OK, CANCELLED)  # before close)
                #                                 or typed cancelled


class TestCircuitBreaker:
    pytestmark = pytest.mark.faults

    def test_flaky_bringup_circuit_breaks_then_rejoins_routing(
            self, bundle):
        """A replica failing bring-up repeatedly is circuit-broken with
        exponential backoff while the set serves degraded; the attempt
        that succeeds re-joins it to routing (it completes real work
        afterwards)."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        with faults.injected(fault_replica=1, replica_flaky_bringup=2):
            rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                            chunk_steps=4, bringup_policy=FAST_BRINGUP)
            r1 = rs.replicas[1]
            assert r1.state == BROKEN       # attempt 0 failed at init
            assert rs.bringup_failures == 1
            assert rs.replicas[0].state == RUNNING
            # degraded but serving: work completes on replica 0 alone
            h = queue.submit(REQS[0])
            rs.run_until_idle()
            assert h.result(timeout=10).status == OK
            # wait out the backoff; attempt 1 fails too (flaky=2),
            # attempt 2 succeeds and the replica rejoins
            deadline = time.perf_counter() + 10
            while r1.state != RUNNING and time.perf_counter() < deadline:
                time.sleep(0.02)
                rs.step_once()
            assert r1.state == RUNNING
            assert rs.bringup_failures == 2
            assert r1.bringups == 3
            # rejoined ROUTING, not just alive: with both replicas'
            # slots needed for the burst, the recovered one completes
            # a share of it
            handles = [queue.submit(r) for r in REQS[:4]]
            rs.run_until_idle()
            assert_all_token_exact(params, vae_params, handles, REQS[:4])
            assert r1.engine.completed >= 1

    def test_all_replicas_down_degrades_to_typed_backpressure(self,
                                                              bundle):
        """Zero live replicas must never hang anyone: submits past the
        queue bound get typed QueueFull, and a queued request whose
        deadline passes gets its typed result from the ROUTER (no
        engine needed to reap it)."""
        params, _ = bundle
        queue = RequestQueue(max_depth=2)
        with faults.injected(fault_replica=0, replica_flaky_bringup=99):
            rs = ReplicaSet(params, CFG, queue, replicas=1, num_slots=2,
                            bringup_policy=FAST_BRINGUP)
            assert rs.replicas[0].state == BROKEN
            assert not rs.alive()
            h_dead = queue.submit(Request(codes=(1, 2), seed=0,
                                          deadline_s=0.0))
            queue.submit(Request(codes=(2, 2), seed=1))
            with pytest.raises(QueueFull):
                queue.submit(Request(codes=(3, 3), seed=2))
            time.sleep(0.01)
            rs.step_once()      # router reaps expired with 0 replicas
            assert h_dead.result(timeout=1).status == DEADLINE_EXCEEDED


class TestPagedMigration:
    pytestmark = pytest.mark.faults

    def test_migration_composes_with_paged_eviction(self, bundle):
        """The two replay mechanisms stack: on a pool that cannot hold
        two full sequences (page eviction guaranteed mid-decode), a
        replica crash reclaims BOTH the evicted-and-requeued victim and
        the in-flight survivor — and every request still lands
        token-exact after migrating to the other replica."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        # 6 usable pages at page_size 4 = exactly ONE full sequence:
        # two slots deep in decode MUST evict (same shape as
        # test_serve's eviction test, per replica)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, kv="paged", page_size=4,
                        num_pages=7, bringup_policy=FAST_BRINGUP)
        handles = [queue.submit(r) for r in REQS]
        with faults.injected(fault_replica=0, replica_crash_at_chunk=4):
            rs.run_until_idle()
        stats = rs.stats()
        assert rs.failovers == 1
        assert stats["evicted"] >= 1, \
            "pool was sized to force eviction before the crash"
        assert_all_token_exact(params, vae_params, handles, REQS)
        # every live pool drained back to empty
        for r in rs.replicas:
            if r.engine is not None:
                assert r.engine.alloc.in_use == 0


class TestDrain:
    def test_operator_drain_migrates_inflight_and_undrain_rejoins(
            self, bundle):
        """Planned maintenance: drain fences the replica and replays
        its in-flight work on the survivor (zero loss, token-exact);
        undrain brings it back into routing."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, bringup_policy=FAST_BRINGUP)
        handles = [queue.submit(r) for r in REQS[:4]]
        for _ in range(2):              # both replicas mid-decode
            rs.step_once()
        assert rs.replicas[0].engine.active_slots() > 0
        reclaimed = rs.drain_replica(0)
        assert reclaimed >= 1
        assert rs.replicas[0].state == DRAINED
        rs.run_until_idle()             # survivor finishes everything
        assert_all_token_exact(params, vae_params, handles, REQS[:4])
        assert rs.replicas[0].state == DRAINED      # stays down
        assert rs.undrain_replica(0)
        assert rs.replicas[0].state == RUNNING
        h = queue.submit(REQS[4])
        rs.run_until_idle()
        assert h.result(timeout=10).status == OK


class TestProcessIsolation:
    """isolation='process': replicas are spawned child processes behind
    the typed IPC layer (serve/ipc.py + serve/worker.py). Base
    coverage: the set serves token-exact through the pipe, the operator
    surface reports child PIDs/RSS/restarts, and drain/undrain cycles a
    child process. Hard-kill failover lives in TestProcessHardKill."""

    def test_process_set_serves_token_exact_and_drain_cycles(
            self, bundle):
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, isolation="process",
                        bringup_policy=FAST_BRINGUP)
        try:
            # both READY before submitting: the [1, 1] compile assert
            # needs BOTH replicas to decode, and the first-ready
            # replica's 2x-slot admission window would otherwise
            # swallow the whole 4-request burst
            wait_all_ready(rs)
            handles = [queue.submit(r) for r in REQS[:4]]
            rs.run_until_idle(max_steps=500_000)
            assert_all_token_exact(params, vae_params, handles, REQS[:4])
            stats = rs.stats()
            assert stats["isolation"] == "process"
            assert stats["completed"] == 4
            assert stats["failovers"] == 0
            # distinct-delivered-token accounting across the pipe:
            # counters mirror the children's frames exactly
            assert stats["tokens_decoded"] == sum(
                CFG.seq_len - len(r.codes) for r in REQS[:4])
            assert rs.decode_compiles_per_replica() == [1, 1]
            pids = [p["pid"] for p in stats["per_replica"]]
            assert len(set(pids)) == 2
            assert all(isinstance(p, int) and p > 0 for p in pids)
            assert all(p["rss_mb"] > 0 for p in stats["per_replica"])
            # the transport observability block (PR 10) rides along in
            # pipe mode too: kind, peer, frame staleness, reconnects
            for p in stats["per_replica"]:
                assert p["transport"] == "pipe"
                assert p["peer"].startswith("pipe")
                assert p["last_frame_age_s"] >= 0.0
                assert p["reconnects"] == 0
            # operator drain kills the child; undrain spawns a fresh one
            old_pid = pids[0]
            rs.drain_replica(0)
            assert rs.replicas[0].state == DRAINED
            assert rs.undrain_replica(0)
            h = queue.submit(REQS[4])
            rs.run_until_idle(max_steps=500_000)
            assert h.result(timeout=10).status == OK
            new_pid = rs.replicas[0].engine.pid
            assert new_pid != old_pid, "undrain must be a fresh process"
        finally:
            rs.close()

    def test_process_server_end_to_end_health_and_stats(self, bundle):
        """The full threaded server over process replicas: /healthz
        carries the supervised-child fields (PID, restart count, last
        exit, child RSS) and 503 only when all replicas are dead."""
        params, vae_params = bundle
        from dalle_pytorch_tpu.serve.server import InferenceServer
        with pytest.raises(ValueError, match="replicas"):
            InferenceServer(params, vae_params, CFG, replicas=1,
                            isolation="process", decode_images=False)
        server = InferenceServer(params, vae_params, CFG, num_slots=2,
                                 queue_depth=16, replicas=2,
                                 isolation="process",
                                 decode_images=False).start()
        try:
            res = server.generate(REQS[0].codes, seed=REQS[0].seed,
                                  timeout=120)
            assert res.status == OK
            np.testing.assert_array_equal(
                np.asarray(res.tokens),
                reference_tokens(params, vae_params, REQS[0]))
            health = server.health()
            assert health["ok"] is True
            assert len(health["replicas"]) == 2
            for rep in health["replicas"]:
                assert rep["alive"]
                assert rep["pid"] > 0
                assert rep["restarts"] == 0
                assert rep["rss_mb"] > 0
            stats = server.stats()
            assert stats["isolation"] == "process"
            assert stats["completed"] == 1
        finally:
            server.close()


@pytest.mark.parametrize("transport", ["pipe", "socket"])
class TestProcessHardKill:
    """THE acceptance criterion of the process-isolation PR: a child
    replica killed for real — SIGKILL, SIGSEGV, a crash, an OOM kill,
    or a corrupted pipe — mid-decode loses ZERO requests; everything it
    held replays byte-identically on the survivor (reclaimed from the
    parent's shadow bookkeeping, never from the corpse), aggregate
    counters keep counting distinct delivered tokens, and the dead
    replica rejoins routing through the circuit-breaker backoff.

    Parameterized over BOTH frame transports (PR 10): the socket leg
    runs the identical suite over dial-back TCP workers, because the
    zero-loss contract must hold when the frames cross a network, not
    just a pipe. Socket-only failure modes (reset, torn frame, stalled
    link) live in TestSocketFaults."""

    pytestmark = pytest.mark.faults

    def _run_kill(self, bundle, plan_kwargs, expect_exit,
                  transport="pipe"):
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        with faults.injected(fault_replica=1, **plan_kwargs):
            # construct INSIDE the plan: hard-fault plans cross the
            # process boundary at spawn (faults.child_plan_for), once
            # per activation, so the restarted child comes up clean
            rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                            chunk_steps=4, isolation="process",
                            transport=transport,
                            bringup_policy=FAST_BRINGUP)
            try:
                wait_all_ready(rs)
                handles = [queue.submit(r) for r in REQS]
                rs.run_until_idle(max_steps=500_000)
                assert rs.failovers == 1
                assert rs.reclaimed >= 1, "the kill stranded no work?"
                assert_all_token_exact(params, vae_params, handles, REQS)
                stats = rs.stats()
                assert stats["completed"] == len(REQS)
                assert stats["tokens_decoded"] == sum(
                    CFG.seq_len - len(r.codes) for r in REQS), \
                    "distinct-token accounting broke across the kill"
                r1 = rs.replicas[1]
                assert expect_exit in r1.last_exit, \
                    (r1.last_exit, expect_exit)
                # rejoined routing after the circuit-breaker backoff
                assert r1.bringups >= 2
                assert r1.state == RUNNING
                assert rs.alive()
            finally:
                rs.close()

    def test_sigkill_mid_decode_zero_loss_token_exact(self, bundle,
                                                      transport):
        """kill -9 of a child replica mid-decode: the headline. The
        child dies with no goodbye; the parent decodes the exit signal,
        salvages the transport, replays the shadow."""
        self._run_kill(bundle, {"replica_sigkill_at_chunk": 2},
                       expect_exit="SIGKILL", transport=transport)

    def test_segv_mid_decode_zero_loss_token_exact(self, bundle,
                                                   transport):
        """SIGSEGV — the XLA-bug shape of death — decodes as its own
        signal and fails over identically."""
        self._run_kill(bundle, {"replica_segv_at_chunk": 2},
                       expect_exit="SIGSEGV", transport=transport)

    def test_child_crash_frame_zero_loss_token_exact(self, bundle,
                                                     transport):
        """A Python-level crash in the child ships a CRASH frame before
        exit 1 — the soft half of the catalog, process-drivable."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        with faults.injected(fault_replica=1, replica_crash_at_chunk=2):
            rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                            chunk_steps=4, isolation="process",
                            transport=transport,
                            bringup_policy=FAST_BRINGUP)
            try:
                wait_all_ready(rs)
                handles = [queue.submit(r) for r in REQS[:4]]
                rs.run_until_idle(max_steps=500_000)
                assert rs.failovers == 1
                assert_all_token_exact(params, vae_params, handles,
                                       REQS[:4])
            finally:
                rs.close()

    def test_oom_killed_child_fenced_and_replayed(self, bundle,
                                                  transport):
        """The child-side RSS limit: the injected OOM allocates real
        memory until the worker's watchdog crosses child_rss_limit_mb
        and dies with exit 137 (the container OOM-kill convention) —
        abruptly, no goodbye frame — and the failover replays its work
        token-exact."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        with faults.injected(fault_replica=1, replica_oom_at_chunk=1):
            rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                            chunk_steps=4, isolation="process",
                            transport=transport,
                            child_rss_limit_mb=1408,
                            bringup_policy=FAST_BRINGUP)
            try:
                wait_all_ready(rs)
                handles = [queue.submit(r) for r in REQS[:4]]
                rs.run_until_idle(max_steps=500_000)
                assert rs.failovers == 1
                assert "oom" in rs.replicas[1].last_exit
                assert_all_token_exact(params, vae_params, handles,
                                       REQS[:4])
            finally:
                rs.close()

    def test_garbage_frame_fences_not_deadlocks(self, bundle,
                                                transport):
        """A child that corrupts its stream (injected garbage frame) is
        FENCED on the protocol error — hard-killed, salvaged, replayed
        — rather than deadlocking the parent or mis-parsing the lie."""
        params, vae_params = bundle
        events = []

        class Sink:
            def event(self, **rec):
                events.append(rec)

        queue = RequestQueue(max_depth=16)
        with faults.injected(fault_replica=1,
                             replica_garbage_frame_at_chunk=1):
            rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                            chunk_steps=4, isolation="process",
                            transport=transport,
                            metrics=Sink(), bringup_policy=FAST_BRINGUP)
            try:
                wait_all_ready(rs)
                handles = [queue.submit(r) for r in REQS[:4]]
                rs.run_until_idle(max_steps=500_000)
                assert rs.failovers == 1
                fenced = [e for e in events
                          if e.get("kind") == "serve_replica_fenced"]
                assert fenced and "protocol error" in \
                    fenced[0]["reason"], fenced
                assert_all_token_exact(params, vae_params, handles,
                                       REQS[:4])
            finally:
                rs.close()

    def test_hung_child_hard_killed_within_heartbeat_deadline(
            self, bundle, transport):
        """A child that is alive but silent (injected 20s stall where a
        wedged device sync would sit) is hard-killed off the missed-
        frame deadline — the hang detection working over the pipe, with
        known compiles exempted via the compiling-heartbeat — and its
        work replays long before the stall would have cleared."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        hang_s = 20.0
        with faults.injected(fault_replica=1, replica_hang_at_chunk=1,
                             replica_hang_s=hang_s):
            rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                            chunk_steps=4, isolation="process",
                            transport=transport, heartbeat_s=0.5,
                            bringup_policy=FAST_BRINGUP)
            try:
                wait_all_ready(rs)
                handles = [queue.submit(r) for r in REQS[:4]]
                t0 = time.perf_counter()
                rs.run_until_idle(max_steps=500_000)
                assert rs.failovers == 1
                assert time.perf_counter() - t0 < hang_s, \
                    "completion waited out the hang instead of fencing"
                # supervisor-initiated kill is labelled as such (and
                # names the deadline that expired), never dressed up
                # as an OS-delivered SIGKILL
                assert "hard-killed by supervisor" in \
                    rs.replicas[1].last_exit
                assert "heartbeat" in rs.replicas[1].last_exit
                assert_all_token_exact(params, vae_params, handles,
                                       REQS[:4])
            finally:
                rs.close()


class TestSocketFaults:
    """The NETWORK half of the fault catalog (PR 10) — the failure
    modes only a socket can exhibit, each of which must fence the
    replica via a TYPED error and replay its work byte-identically on
    a survivor, never deadlock, never double-deliver."""

    pytestmark = pytest.mark.faults

    def _run_socket_fault(self, bundle, plan_kwargs, **set_kwargs):
        params, vae_params = bundle
        events = []

        class Sink:
            def event(self, **rec):
                events.append(rec)

        queue = RequestQueue(max_depth=16)
        with faults.injected(fault_replica=1, **plan_kwargs):
            rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                            chunk_steps=4, isolation="process",
                            transport="socket", metrics=Sink(),
                            bringup_policy=FAST_BRINGUP, **set_kwargs)
            try:
                wait_all_ready(rs)
                handles = [queue.submit(r) for r in REQS[:4]]
                rs.run_until_idle(max_steps=500_000)
                assert rs.failovers == 1
                assert_all_token_exact(params, vae_params, handles,
                                       REQS[:4])
            finally:
                rs.close()
        return rs, events

    def test_conn_reset_mid_frame_zero_loss_token_exact(self, bundle):
        """A connection reset that tears a frame (half a heartbeat on
        the wire, then RST): the parent surfaces a typed mid-frame
        protocol error, fences, and replays — zero requests lost,
        tokens byte-identical."""
        rs, events = self._run_socket_fault(
            bundle, {"replica_conn_reset_at_chunk": 2})
        fenced = [e for e in events
                  if e.get("kind") == "serve_replica_fenced"]
        assert fenced, events
        assert "protocol error" in fenced[0]["reason"], fenced
        assert "mid-frame" in fenced[0]["reason"], fenced

    def test_torn_frame_at_byte_boundary_fences_typed(self, bundle):
        """Half a frame then a clean FIN (peer died between two writes
        of one frame): same typed fence + replay, distinguishable from
        a clean shutdown."""
        rs, events = self._run_socket_fault(
            bundle, {"replica_torn_frame_at_chunk": 2})
        fenced = [e for e in events
                  if e.get("kind") == "serve_replica_fenced"]
        assert fenced, events
        assert "protocol error" in fenced[0]["reason"], fenced

    def test_duplicate_frame_delivery_fences(self, bundle):
        """A transport that re-delivers a frame (same sequence number
        twice) is fenced on the duplicate — results and counters can
        never be silently double-absorbed."""
        rs, events = self._run_socket_fault(
            bundle, {"replica_dup_frame_at_chunk": 2})
        fenced = [e for e in events
                  if e.get("kind") == "serve_replica_fenced"]
        assert fenced and "duplicate or reordered" in \
            fenced[0]["reason"], fenced

    def test_reordered_frame_delivery_fences(self, bundle):
        """Two frames swapped on the wire: the sequence gap at the
        first fences the replica before anything is absorbed out of
        order."""
        rs, events = self._run_socket_fault(
            bundle, {"replica_reorder_frames_at_chunk": 2})
        fenced = [e for e in events
                  if e.get("kind") == "serve_replica_fenced"]
        assert fenced and "gap" in fenced[0]["reason"], fenced

    def test_stalled_socket_fenced_within_heartbeat_deadline(
            self, bundle):
        """The stalled-socket row: the connection stays accepted and
        OPEN but the worker goes silent (20s injected stall). The
        parent must fence off the missed-heartbeat deadline — with no
        thread ever blocking on the unread socket — and the stalled
        replica's work must replay long before the stall clears, with
        no caller stranded."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        hang_s = 20.0
        with faults.injected(fault_replica=1,
                             replica_stall_socket_at_chunk=1,
                             replica_hang_s=hang_s):
            rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                            chunk_steps=4, isolation="process",
                            transport="socket", heartbeat_s=0.5,
                            bringup_policy=FAST_BRINGUP)
            try:
                wait_all_ready(rs)
                handles = [queue.submit(r) for r in REQS[:4]]
                t0 = time.perf_counter()
                rs.run_until_idle(max_steps=500_000)
                assert rs.failovers == 1
                assert time.perf_counter() - t0 < hang_s, \
                    "completion waited out the stall instead of fencing"
                assert "hard-killed by supervisor" in \
                    rs.replicas[1].last_exit
                assert "heartbeat" in rs.replicas[1].last_exit
                assert_all_token_exact(params, vae_params, handles,
                                       REQS[:4])
            finally:
                rs.close()


class TestRemoteAttach:
    """Host-per-engine's defining move: a worker that is NOT a spawned
    child — launched by an operator command (``worker_cmd``) or started
    entirely by hand — dials the parent's endpoint, authenticates, and
    joins the replica set EXACTLY like a spawned child: same shadow
    bookkeeping, same heartbeat supervision, same fence→reclaim→replay
    on death. (The workers here run on localhost; the transport path is
    identical to a cross-host attach, minus the routing table.)"""

    def test_worker_cmd_launched_workers_serve_token_exact(self, bundle):
        """--worker_cmd as the launcher hook: every replica's worker is
        started by the command template (token via env, never argv) and
        the set serves token-exact with the transport fields visible in
        stats."""
        import os
        import sys
        params, vae_params = bundle
        env_before = os.environ.get("PYTHONPATH")
        os.environ["PYTHONPATH"] = os.pathsep.join(
            p for p in (os.getcwd(), env_before) if p)
        queue = RequestQueue(max_depth=16)
        try:
            rs = ReplicaSet(
                params, CFG, queue, replicas=2, num_slots=2,
                chunk_steps=4, isolation="process", transport="socket",
                # {token} pins the placeholder a remote (ssh) launcher
                # needs — a plain env var doesn't cross host boundaries
                worker_cmd=(f"{sys.executable} -m "
                            f"dalle_pytorch_tpu.serve.worker "
                            f"--connect {{endpoint}} --index {{index}} "
                            f"--token {{token}}"),
                bringup_policy=FAST_BRINGUP)
            try:
                handles = [queue.submit(r) for r in REQS[:4]]
                rs.run_until_idle(max_steps=500_000)
                assert_all_token_exact(params, vae_params, handles,
                                       REQS[:4])
                stats = rs.stats()
                assert stats["transport"] == "socket"
                assert stats["attach_rejected"] == 0
                for p in stats["per_replica"]:
                    assert p["transport"] == "socket"
                    assert ":" in p["peer"]
                    assert p["last_frame_age_s"] >= 0.0
            finally:
                rs.close()
        finally:
            if env_before is None:
                os.environ.pop("PYTHONPATH", None)
            else:
                os.environ["PYTHONPATH"] = env_before

    @pytest.mark.faults
    def test_hand_started_worker_attaches_dies_and_is_replaced(
            self, bundle):
        """The full remote-attach story: workers started BY HAND
        (worker_cmd='' — the set spawns nothing) dial in and serve; one
        self-SIGKILLs mid-decode (the fault plan rides the spec over
        the socket, so even a hand-started worker is fault-drivable);
        with no PID to probe, the parent declares it dead off the
        SOCKET, replays its work token-exact on the survivor, and a
        replacement worker started by hand attaches to the broken slot
        and rejoins routing."""
        import os
        import subprocess
        import sys
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (os.getcwd(), env.get("PYTHONPATH")) if p)

        def start_worker(listener, index):
            env2 = dict(env)
            from dalle_pytorch_tpu.serve import transport as T
            env2[T.TOKEN_ENV] = listener.token
            return subprocess.Popen(
                [sys.executable, "-m",
                 "dalle_pytorch_tpu.serve.worker",
                 "--connect", listener.endpoint,
                 "--index", str(index)], env=env2)

        with faults.injected(fault_replica=1,
                             replica_sigkill_at_chunk=2):
            rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                            chunk_steps=4, isolation="process",
                            transport="socket", worker_cmd="",
                            bringup_policy=FAST_BRINGUP)
            procs = []
            try:
                procs.append(start_worker(rs.listener, 0))
                procs.append(start_worker(rs.listener, 1))
                handles = [queue.submit(r) for r in REQS]
                # drive until the victim dies and the survivor finishes
                # everything; replica 1 stays BROKEN/awaiting because
                # nothing respawns a hand-started worker
                deadline = time.perf_counter() + 300
                while time.perf_counter() < deadline:
                    rs.step_once()
                    if rs.failovers >= 1 and all(h.done()
                                                 for h in handles):
                        break
                assert rs.failovers == 1, "worker death never fenced"
                assert_all_token_exact(params, vae_params, handles, REQS)
                # no PID was available: the death was declared off the
                # socket and labelled as the remote shape
                assert "remote worker" in rs.replicas[1].last_exit, \
                    rs.replicas[1].last_exit
                # the slot is waiting for a replacement, not circuit-
                # broken into oblivion: hand-start a new worker and it
                # must rejoin routing and complete fresh work
                deadline = time.perf_counter() + 60
                while time.perf_counter() < deadline:
                    rs.step_once()
                    r1 = rs.replicas[1]
                    if r1.state == RUNNING and r1.engine is not None \
                            and r1.engine.awaiting_operator:
                        break
                procs.append(start_worker(rs.listener, 1))
                h = queue.submit(REQS[0])
                deadline = time.perf_counter() + 300
                while time.perf_counter() < deadline:
                    rs.step_once()
                    if h.done() and rs.replicas[1].engine is not None \
                            and rs.replicas[1].engine.ready:
                        break
                assert h.result(timeout=10).status == OK
                assert rs.replicas[1].engine.ready, \
                    "replacement worker never rejoined"
            finally:
                rs.close()
                for p in procs:
                    if p.poll() is None:
                        p.kill()


class TestRoutingAndStats:
    def test_burst_routes_least_loaded_across_replicas(self, bundle):
        """A burst wider than one replica's slots spreads: both
        replicas complete a share, and the aggregate stats add up."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, bringup_policy=FAST_BRINGUP)
        handles = [queue.submit(r) for r in REQS[:4]]
        rs.step_once()
        assert all(r.engine.active_slots() == 2 for r in rs.replicas)
        rs.run_until_idle()
        assert_all_token_exact(params, vae_params, handles, REQS[:4])
        stats = rs.stats()
        assert stats["completed"] == 4
        assert all(p["completed"] == 2 for p in stats["per_replica"])
        assert stats["decode_compiles"] == 2        # one per replica
        assert stats["alive_replicas"] == 2
        assert stats["failovers"] == 0

    def test_page_aware_routing_prefers_replica_with_free_pages(
            self, bundle):
        """With one paged replica's pool fully claimed, a new request
        routes to the replica that can map its prompt NOW."""
        params, _ = bundle
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=24, kv="paged", page_size=4,
                        num_pages=7, bringup_policy=FAST_BRINGUP)
        queue.submit(REQS[0])
        rs.step_once()      # lands on one replica, maps ALL its pages
        full = [r for r in rs.replicas if r.engine.alloc.free == 0]
        assert len(full) == 1
        queue.submit(REQS[1])
        rs.step_once()
        empty = [r for r in rs.replicas if r is not full[0]][0]
        assert empty.engine.active_slots() == 1, \
            "request routed to the page-starved replica"
        rs.run_until_idle()

    def test_replica_server_end_to_end_stats_and_health(self, bundle):
        """The full replica server: submit through the shared queue,
        aggregate /stats surface, per-replica /healthz body."""
        params, vae_params = bundle
        from dalle_pytorch_tpu.serve.server import InferenceServer
        server = InferenceServer(params, vae_params, CFG, num_slots=2,
                                 queue_depth=16, replicas=2,
                                 decode_images=False).start()
        try:
            res = server.generate(REQS[0].codes, seed=REQS[0].seed,
                                  timeout=60)
            assert res.status == OK
            np.testing.assert_array_equal(
                np.asarray(res.tokens),
                reference_tokens(params, vae_params, REQS[0]))
            stats = server.stats()
            assert stats["completed"] == 1
            assert stats["replicas"] == 2
            assert stats["requests_submitted"] == 1
            health = server.health()
            assert health["ok"] is True
            assert len(health["replicas"]) == 2
            assert all(r["alive"] for r in health["replicas"])
        finally:
            server.close()


# ---------------------------------------------------------------------------
# Elastic fleet (ISSUE 14): runtime scale-out/in, rolling weight hot-swap,
# version-pinned replay, the autoscaler policy loop, and the HOL hand-back
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def bundle_v2(bundle):
    """A SECOND weights generation for upgrade tests: same config, a
    different init key — byte-distinct logits, so same-seed tokens
    differ between generations and 'byte-identical PER version' is a
    real assertion, not a tautology."""
    _, vae_params = bundle
    return D.dalle_init(jax.random.PRNGKey(42), CFG, vae_params), \
        vae_params


_VREF_CACHE: dict = {}


def versioned_reference(params, vae_params, req: Request) -> np.ndarray:
    """Like ``reference_tokens`` but keyed by the params object too —
    upgrade tests compare against the generation that STAMPED each
    result, and two generations must never share a cache row."""
    key = (id(params), req.codes, req.seed, req.sampling.temperature,
           req.sampling.filter_thres, req.sampling.top_p)
    if key not in _VREF_CACHE:
        text = jnp.asarray([req.codes], jnp.int32)
        _, img_seq = D.generate_images(
            params, vae_params, text, cfg=CFG,
            rng=jax.random.PRNGKey(req.seed),
            filter_thres=req.sampling.filter_thres,
            top_p=req.sampling.top_p,
            temperature=req.sampling.temperature, return_img_seq=True)
        _VREF_CACHE[key] = np.asarray(img_seq)[0]
    return _VREF_CACHE[key]


class _Sink:
    def __init__(self):
        self.events = []

    def event(self, **rec):
        self.events.append(rec)

    def of(self, kind):
        return [e for e in self.events if e.get("kind") == kind]


class TestElasticScale:
    def test_add_replica_joins_routing_and_caps_are_typed(self, bundle):
        """Scale-out under load: the new slot serves token-exact, the
        page-budget cap and the last-replica floor are typed
        ScaleErrors, and a retired slot stays retired."""
        params, vae_params = bundle
        sink = _Sink()
        queue = RequestQueue(max_depth=32)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, weights_version="v1",
                        max_replicas=3, metrics=sink,
                        bringup_policy=FAST_BRINGUP)
        handles = [queue.submit(r) for r in REQS[:4]]
        for _ in range(2):              # both replicas mid-decode
            rs.step_once()
        index = rs.add_replica()
        assert index == 2 and rs.n_replicas == 3
        assert rs.replicas[2].state == RUNNING
        rs.run_until_idle()
        assert_all_token_exact(params, vae_params, handles, REQS[:4])
        # the new slot genuinely serves (route a fresh burst wide)
        more = [queue.submit(r) for r in REQS]
        rs.run_until_idle()
        assert_all_token_exact(params, vae_params, more, REQS)
        assert sink.of("serve_scale_out")
        with pytest.raises(ScaleError) as e:
            rs.add_replica()
        assert e.value.record["reason"] == "scale_out_past_cap"
        # scale-in retires; the tombstone is never resurrected
        assert rs.remove_replica(2) >= 0
        assert rs.replicas[2].state == RETIRED
        assert rs.n_replicas == 2
        with pytest.raises(ScaleError) as e:
            rs.remove_replica(2)
        assert e.value.record["reason"] == "replica_retired"
        with pytest.raises(ScaleError) as e:
            rs.drain_replica(2)
        assert e.value.record["reason"] == "replica_retired"
        rs.remove_replica(1)
        with pytest.raises(ScaleError) as e:
            rs.remove_replica(0)
        assert e.value.record["reason"] == "remove_last_replica"
        # the survivor still serves
        h = queue.submit(REQS[0])
        rs.run_until_idle()
        assert h.result(timeout=10).status == OK

    def test_remove_replica_drains_inflight_zero_loss(self, bundle):
        """Scale-in mid-decode: the retired replica's in-flight work
        replays on the survivor byte-identically — retirement is a
        fence+reclaim, never a drop."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, bringup_policy=FAST_BRINGUP)
        handles = [queue.submit(r) for r in REQS[:4]]
        for _ in range(2):
            rs.step_once()
        assert rs.replicas[0].engine.active_slots() > 0
        reclaimed = rs.remove_replica(0, reason="test scale-in")
        assert reclaimed >= 1
        rs.run_until_idle()
        assert_all_token_exact(params, vae_params, handles, REQS[:4])
        assert rs.stats()["scale_ins"] == 1

    @pytest.mark.faults
    def test_scale_out_bringup_kill_circuit_breaks_zero_loss(
            self, bundle):
        """The 'replica killed mid-add_replica bring-up' fault row: the
        scaled-out slot's first bring-up dies, it circuit-breaks and
        retries onto its feet, and the serving survivors (and every
        in-flight request) never notice."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=32)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, max_replicas=3,
                        bringup_policy=FAST_BRINGUP)
        handles = [queue.submit(r) for r in REQS]
        rs.step_once()
        with faults.injected(scale_add_bringup_crash=1):
            index = rs.add_replica()
            assert rs.replicas[index].state == BROKEN, \
                "the injected bring-up kill never fired"
            assert rs.bringup_failures >= 1
            rs.run_until_idle()
            # the retry (attempt 1 >= the 1-attempt plan) must succeed
            deadline = time.perf_counter() + 30
            while rs.replicas[index].state != RUNNING \
                    and time.perf_counter() < deadline:
                rs.step_once()
                time.sleep(0.005)
        assert rs.replicas[index].state == RUNNING
        assert rs.failovers == 0, "survivors must be untouched"
        assert_all_token_exact(params, vae_params, handles, REQS)


class TestRollingUpgrade:
    def test_rolling_upgrade_zero_loss_byte_identical_per_version(
            self, bundle, bundle_v2):
        """THE elastic acceptance criterion: a rolling upgrade with
        traffic in flight loses zero requests, cycles every replica
        canary-gated, stamps every Result with the generation that
        decoded it, and same-seed tokens are byte-identical PER
        weights_version."""
        params, vae_params = bundle
        params2, _ = bundle_v2
        sink = _Sink()
        queue = RequestQueue(max_depth=32)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, weights_version="v1",
                        metrics=sink, bringup_policy=FAST_BRINGUP)
        pre = [queue.submit(r) for r in REQS[:2]]
        rs.run_until_idle()
        for h, r in zip(pre, REQS[:2]):
            res = h.result(timeout=10)
            assert res.status == OK and res.weights_version == "v1"
        mid = [queue.submit(r) for r in REQS]
        record = rs.rolling_upgrade(version="v2", params=params2,
                                    canary_codes=[(1, 2)], canaries=2,
                                    replica_timeout_s=180)
        assert len(record["replicas"]) == 2
        rs.run_until_idle()
        # zero loss through the reshape, and per-version byte-identity:
        # whichever generation answered each request, its tokens match
        # that generation's undisturbed single-engine run exactly
        for h, r in zip(mid, REQS):
            res = h.result(timeout=10)
            assert res.status == OK, (res.status, res.reason)
            assert res.weights_version in ("v1", "v2")
            p = params if res.weights_version == "v1" else params2
            np.testing.assert_array_equal(
                np.asarray(res.tokens),
                versioned_reference(p, vae_params, r))
        # the fleet is promoted: fresh traffic is v2, byte-identical
        post = queue.submit(REQS[0])
        rs.run_until_idle()
        res = post.result(timeout=10)
        assert res.weights_version == "v2"
        np.testing.assert_array_equal(
            np.asarray(res.tokens),
            versioned_reference(params2, vae_params, REQS[0]))
        stats = rs.stats()
        assert stats["weights_version"] == "v2"
        assert stats["upgrades"] == 1
        assert all(p["weights_version"] == "v2"
                   for p in stats["per_replica"])
        assert sink.of("serve_upgrade_begin")
        assert len(sink.of("serve_upgrade_replica")) == 2
        assert sink.of("serve_upgrade_done")
        # scaling mid-upgrade is an illegal transition — verify the
        # typed reject without racing a real upgrade: flip the flag
        rs._upgrading = True
        try:
            with pytest.raises(ScaleError) as e:
                rs.add_replica()
            assert e.value.record["reason"] == "upgrade_in_progress"
        finally:
            rs._upgrading = False

    def test_upgrade_skips_operator_drained_replica(self, bundle,
                                                    bundle_v2):
        """The drain contract outranks the rollout: a replica an
        operator drained stays DOWN through a rolling upgrade (skip
        recorded, structured event), its version label moves with the
        promote, and a later undrain brings it up on the promoted
        weights."""
        params, vae_params = bundle
        params2, _ = bundle_v2
        sink = _Sink()
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=3, num_slots=2,
                        chunk_steps=4, weights_version="v1",
                        metrics=sink, bringup_policy=FAST_BRINGUP)
        rs.drain_replica(2)
        record = rs.rolling_upgrade(version="v2", params=params2,
                                    canary_codes=[(1, 2)], canaries=1,
                                    replica_timeout_s=180)
        assert rs.replicas[2].state == DRAINED, \
            "the upgrade resurrected an operator-drained replica"
        assert {"replica": 2, "skipped": "drained"} \
            in record["replicas"]
        assert sink.of("serve_upgrade_skip_drained")
        assert rs.replicas[2].version == "v2"   # label moved at promote
        assert rs.undrain_replica(2)
        h = queue.submit(REQS[0])
        rs.run_until_idle()
        res = h.result(timeout=10)
        assert res.weights_version == "v2"
        np.testing.assert_array_equal(
            np.asarray(res.tokens),
            versioned_reference(params2, vae_params, REQS[0]))

    @pytest.mark.faults
    def test_canary_failure_aborts_and_rolls_back_whole_fleet(
            self, bundle, bundle_v2):
        """The injected canary health-gate failure: rolling_upgrade
        aborts typed at replica 1, AND replica 0 — already gated onto
        v2 — rolls back, so the whole fleet is left serving v1; live
        traffic survives both reshapes with zero loss."""
        params, vae_params = bundle
        params2, _ = bundle_v2
        sink = _Sink()
        queue = RequestQueue(max_depth=32)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, weights_version="v1",
                        metrics=sink, bringup_policy=FAST_BRINGUP)
        handles = [queue.submit(r) for r in REQS[:4]]
        with faults.injected(upgrade_canary_fail_replica=1):
            with pytest.raises(UpgradeAborted) as e:
                rs.rolling_upgrade(version="v2", params=params2,
                                   canary_codes=[(1, 2)], canaries=1,
                                   replica_timeout_s=180)
        assert e.value.record["fleet_version"] == "v1"
        assert sorted(e.value.record["rolled_back"]) == [0, 1]
        assert all(r.version == "v1" for r in rs.replicas)
        assert all(not r.canary for r in rs.replicas)
        assert rs.weights_version == "v1" and rs.upgrades == 0
        rs.run_until_idle()
        for h in handles:
            assert h.result(timeout=10).status == OK
        # fresh traffic serves v1 byte-identically after the abort
        h = queue.submit(REQS[0])
        rs.run_until_idle()
        res = h.result(timeout=10)
        assert res.weights_version == "v1"
        np.testing.assert_array_equal(
            np.asarray(res.tokens),
            versioned_reference(params, vae_params, REQS[0]))
        assert sink.of("serve_upgrade_abort")
        assert not sink.of("serve_upgrade_done")
        # the abort must not wedge the fleet: a RETRY of the same
        # version (fault gone) succeeds — the aborted attempt's canary
        # reference was dropped with it, and the upgrade lock released
        record = rs.rolling_upgrade(version="v2", params=params2,
                                    canary_codes=[(1, 2)], canaries=1,
                                    replica_timeout_s=180)
        assert len(record["replicas"]) == 2
        assert rs.weights_version == "v2" and rs.upgrades == 1


class TestVersionPinnedReplay:
    def test_weights_version_survives_wire_roundtrip(self):
        """The Result wire satellite: weights_version round-trips
        through to_wire/from_wire exactly, and a frame from a
        pre-upgrade peer (no field) decodes as unversioned instead of
        failing the attach."""
        from dalle_pytorch_tpu.serve.scheduler import Result
        res = Result(status=OK, request_id=7,
                     tokens=np.asarray([1, 2, 3], np.int32),
                     weights_version="ckpt@99", decode_s=0.5)
        rt = Result.from_wire(res.to_wire())
        assert rt.weights_version == "ckpt@99"
        legacy = res.to_wire()
        del legacy["weights_version"]
        assert Result.from_wire(legacy).weights_version == ""

    def test_pick_refuses_cross_version_replay_typed(self, bundle):
        """The invariant guard: a handle pinned to one generation
        offered a replica on another raises the typed
        ReplayVersionMismatch (the router's filter makes this
        unreachable; the guard keeps it impossible, not unlikely)."""
        params, _ = bundle
        queue = RequestQueue(max_depth=8)
        rs = ReplicaSet(params, CFG, queue, replicas=1, num_slots=2,
                        chunk_steps=4, weights_version="v1",
                        bringup_policy=FAST_BRINGUP)
        h = queue.submit(REQS[0])
        (ready, _) = queue.pop_ready(1)
        assert ready == [h]
        h.replay_version = "v0-archaic"
        with pytest.raises(ReplayVersionMismatch):
            rs._pick([rs.replicas[0]], {0: 1}, h)

    @pytest.mark.faults
    def test_failover_replay_holds_for_same_version_replica(
            self, bundle, bundle_v2):
        """Failover replay mid-upgrade is version-pinned: with replica
        1 already on v2, replica 0's (v1) crash must NOT replay its
        work on the v2 survivor — the requests HOLD (structured event)
        until replica 0's circuit-breaker restart brings v1 capacity
        back, and the replayed tokens are byte-identical to v1."""
        params, vae_params = bundle
        params2, _ = bundle_v2
        sink = _Sink()
        queue = RequestQueue(max_depth=32)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, weights_version="v1",
                        metrics=sink, bringup_policy=FAST_BRINGUP)
        # hand-build the mixed-version fleet (replica 1 on v2) without
        # running a full upgrade: drain, override, undrain — exactly
        # what rolling_upgrade does, minus the canary gate. Draining
        # replica 1 FIRST funnels both requests onto replica 0, so
        # both are pinned to v1 before any v2 capacity exists.
        rs.drain_replica(1)
        handles = [queue.submit(r) for r in REQS[:2]]
        for _ in range(2):
            rs.step_once()          # both routed to replica 0 (v1)
        r1 = rs.replicas[1]
        r1.params_override = params2
        r1.version = "v2"
        assert rs.undrain_replica(1)
        # crash replica 0 mid-decode; the flaky restart keeps v1
        # capacity DOWN across routing sweeps, so the pinned replay
        # must visibly HOLD rather than ride the same-sweep restart
        # (replica 0's lifetime bring-up count is 1, so restart
        # attempts 1..2 fail and attempt 3 succeeds)
        with faults.injected(fault_replica=0, replica_crash_at_chunk=1,
                             replica_flaky_bringup=3):
            rs.run_until_idle()
        assert rs.failovers == 1
        holds = sink.of("serve_replay_version_hold")
        assert holds, "pinned replay never HELD for a v1 replica"
        for h, r in zip(handles, REQS[:2]):
            res = h.result(timeout=10)
            assert res.status == OK
            assert res.weights_version == "v1", \
                "pinned replay decoded on the wrong generation"
            np.testing.assert_array_equal(
                np.asarray(res.tokens),
                versioned_reference(params, vae_params, r))

    def test_pin_released_when_generation_leaves_fleet(self, bundle,
                                                       bundle_v2):
        """Zero-loss outranks a stale pin: reclaim work pinned to v1,
        retire every v1 replica, and the router must RELEASE the pin
        (structured event) and replay on v2 — completed, stamped v2,
        byte-identical to v2."""
        params, vae_params = bundle
        params2, _ = bundle_v2
        sink = _Sink()
        queue = RequestQueue(max_depth=32)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, weights_version="v1",
                        metrics=sink, bringup_policy=FAST_BRINGUP)
        rs.drain_replica(1)
        r1 = rs.replicas[1]
        r1.params_override = params2
        r1.version = "v2"
        assert rs.undrain_replica(1)
        handles = [queue.submit(r) for r in REQS[:2]]
        for _ in range(2):
            rs.step_once()          # replica 0 (v1) holds the work
        # retire the v1 replica: its work reclaims pinned v1, but no
        # v1 replica exists anymore (the tombstone doesn't count)
        rs.remove_replica(0, reason="retire the whole v1 generation")
        rs.run_until_idle()
        assert sink.of("serve_replay_version_released")
        for h, r in zip(handles, REQS[:2]):
            res = h.result(timeout=10)
            assert res.status == OK
            assert res.weights_version == "v2"
            np.testing.assert_array_equal(
                np.asarray(res.tokens),
                versioned_reference(params2, vae_params, r))


class TestAutoscaler:
    def test_policy_validation_is_typed(self):
        from dalle_pytorch_tpu.serve.autoscale import AutoscalePolicy
        with pytest.raises(ValueError, match="min_replicas"):
            AutoscalePolicy(min_replicas=0)
        with pytest.raises(ValueError, match="max_replicas"):
            AutoscalePolicy(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError, match="occupancy"):
            AutoscalePolicy(low_occupancy=0.9, high_occupancy=0.8)

    def test_scale_out_in_with_hysteresis_cooldown_and_caps(
            self, bundle):
        """The policy loop end-to-end on a real set, sync-driven: idle
        ticks hold, a sustained burst scales out (after breach_ticks,
        once), saturation at max_replicas is a typed at_max decision,
        and sustained idleness scales back in — never below
        min_replicas."""
        from dalle_pytorch_tpu.serve.autoscale import (AutoscalePolicy,
                                                       Autoscaler)
        params, vae_params = bundle
        sink = _Sink()
        queue = RequestQueue(max_depth=64)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, max_replicas=3, metrics=sink,
                        bringup_policy=FAST_BRINGUP)
        clock = [0.0]
        scaler = Autoscaler(rs, AutoscalePolicy(
            min_replicas=2, max_replicas=3, high_occupancy=0.75,
            low_occupancy=0.10, queue_high=1, breach_ticks=2,
            cooldown_s=1.0), metrics=sink, clock=lambda: clock[0])
        # idle: no decisions, ever
        for _ in range(5):
            clock[0] += 10
            assert scaler.tick() is None
        # a deep queue breaches for breach_ticks consecutive ticks
        handles = [queue.submit(Request(codes=(1 + i % 7, 2), seed=i))
                   for i in range(16)]
        clock[0] += 10
        assert scaler.tick() is None        # breach 1 of 2: hysteresis
        clock[0] += 0.1
        dec = scaler.tick()
        assert dec is not None and dec["action"] == "scale_out"
        assert rs.n_replicas == 3
        # cooldown: still hot, but the scaler must hold its fire
        clock[0] += 0.1
        assert scaler.tick() is None
        # past cooldown and still saturated at the cap: typed at_max
        clock[0] += 2.0
        scaler.tick()                       # breach 1 (counters reset)
        clock[0] += 0.1
        dec = scaler.tick()
        assert dec is not None and dec["action"] == "at_max"
        rs.run_until_idle()
        for h in handles:
            assert h.result(timeout=30).status == OK
        # sustained idle: scale in once, then rest at the floor
        clock[0] += 2.0
        assert scaler.tick() is None        # breach 1 of 2
        clock[0] += 0.1
        dec = scaler.tick()
        assert dec is not None and dec["action"] == "scale_in"
        assert rs.n_replicas == 2
        assert rs.replicas[2].state == RETIRED
        clock[0] += 10
        for _ in range(4):
            clock[0] += 0.1
            assert scaler.tick() is None    # at the floor: quiet
        assert rs.n_replicas == 2
        auto = sink.of("autoscale_decision")
        assert [d["action"] for d in auto] == ["scale_out", "at_max",
                                               "scale_in"]
        # and the reshaped fleet still serves token-exact
        h = queue.submit(REQS[0])
        rs.run_until_idle()
        res = h.result(timeout=10)
        np.testing.assert_array_equal(
            np.asarray(res.tokens),
            reference_tokens(params, vae_params, REQS[0]))


class TestDrainHolHandoff:
    def test_drain_hands_hol_reservation_back_to_shared_queue(
            self, bundle):
        """The drain fix: retiring a replica whose private queue holds
        a page-deferred request must hand the head-of-line page
        reservation back to the shared-queue level (structured
        serve_hol_handoff event, exact pages_needed) instead of letting
        the _hol floor die with the fenced engine — and the deferred
        request completes token-exact on the survivor."""
        params, vae_params = bundle
        sink = _Sink()
        queue = RequestQueue(max_depth=32)
        # 6 usable pages at page_size 4 = ONE full sequence: a second
        # full-prompt request admitted late in the first one's decode
        # MUST defer on pages and become the engine's HOL reservation
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, kv="paged", page_size=4,
                        num_pages=7, metrics=sink,
                        bringup_policy=FAST_BRINGUP)
        first = [Request(codes=(1,) * 8, seed=0),
                 Request(codes=(2,) * 8, seed=1)]
        h1 = [queue.submit(r) for r in first]
        for _ in range(300):
            rs.step_once()
            e0 = rs.replicas[0].engine
            if e0 is not None and e0.alloc.free < 2 \
                    and e0.active_slots() > 0:
                break
        else:
            raise AssertionError("replica 0 never got page-tight")
        second = [Request(codes=(3,) * 8, seed=2),
                  Request(codes=(4,) * 8, seed=3)]
        h2 = [queue.submit(r) for r in second]
        hol = None
        for _ in range(300):
            rs.step_once()
            e0 = rs.replicas[0].engine
            if e0 is not None and e0._hol_rid is not None:
                hol = (e0._hol_rid, e0._hol_need)
                break
        assert hol is not None, "the defer window never produced a HOL"
        rs.drain_replica(0)
        events = sink.of("serve_hol_handoff")
        assert events and events[0]["request_id"] == hol[0] \
            and events[0]["pages_needed"] == hol[1]
        assert rs.hol_handoffs == 1
        rs.run_until_idle()
        assert not rs._hol_handoff, "reservation must clear on routing"
        assert_all_token_exact(params, vae_params, h1 + h2,
                               first + second)


class TestAdminScaleEndpoint:
    def test_admin_scale_http_auth_ops_and_typed_rejects(self, bundle):
        """POST /admin/scale end-to-end: 401 without the token, 200
        with structured bodies for add/remove/drain/undrain/status,
        409 with the typed record for illegal transitions — and the
        reshaped fleet keeps serving through the front door."""
        import http.client
        import json as json_mod

        from dalle_pytorch_tpu.serve.server import (InferenceServer,
                                                    make_http_server)
        params, vae_params = bundle
        server = InferenceServer(params, vae_params, CFG, num_slots=2,
                                 queue_depth=16, replicas=2,
                                 max_replicas=3, weights_version="v1",
                                 admin_token="tok-test",
                                 decode_images=False).start()
        httpd = make_http_server(server, port=0)
        port = httpd.server_address[1]
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()

        def post(path, body, token=None):
            c = http.client.HTTPConnection("127.0.0.1", port,
                                           timeout=60)
            hdrs = {"Content-Type": "application/json"}
            if token:
                hdrs["Authorization"] = f"Bearer {token}"
            c.request("POST", path, json_mod.dumps(body), hdrs)
            r = c.getresponse()
            return r.status, json_mod.loads(r.read())

        try:
            st, body = post("/admin/scale", {"op": "status"})
            assert st == 401
            st, body = post("/admin/scale", {"op": "status"},
                            "wrong-token")
            assert st == 401
            st, body = post("/admin/scale", {"op": "status"},
                            "tok-test")
            assert st == 200 and body["weights_version"] == "v1"
            assert len(body["replicas"]) == 2
            st, body = post("/admin/scale", {"op": "add"}, "tok-test")
            assert st == 200 and body["replicas"] == 3
            st, body = post("/admin/scale", {"op": "add"}, "tok-test")
            assert st == 409 \
                and body["reason"] == "scale_out_past_cap"
            st, body = post("/admin/scale",
                            {"op": "drain", "replica": 1}, "tok-test")
            assert st == 200
            st, body = post("/admin/scale",
                            {"op": "undrain", "replica": 1},
                            "tok-test")
            assert st == 200 and body["ok"] is True
            st, body = post("/admin/scale",
                            {"op": "remove", "replica": 2}, "tok-test")
            assert st == 200 and body["replicas"] == 2
            st, body = post("/admin/scale", {"op": "sideways"},
                            "tok-test")
            assert st == 409 and body["reason"] == "unknown_op"
            # a non-object JSON body is a 400, never a dropped
            # connection (the handler must answer every request)
            st, body = post("/admin/scale", "not-an-object",
                            "tok-test")
            assert st == 400 and "error" in body
            # the reshaped fleet still serves through the front door,
            # and the HTTP body carries the stamping generation
            st, body = post("/generate", {"codes": [3, 7, 9],
                                          "seed": 11})
            assert st == 200 and body["status"] == "ok"
            assert body["weights_version"] == "v1"
            assert server.health()["weights_version"] == "v1"
        finally:
            httpd.shutdown()
            server.close()


@pytest.mark.faults
class TestProcessElasticUpgrade:
    def test_upgrade_drain_sigkill_zero_loss_process(self, bundle,
                                                     bundle_v2):
        """The 'SIGKILL of the draining replica mid-upgrade' fault row
        (process isolation): a real -9 lands on replica 0's child just
        as rolling_upgrade starts draining it — the planned drain races
        an unplanned death, the shadow reclaim still loses nothing, the
        upgrade completes replica-by-replica, and every result is
        byte-identical per its stamped generation."""
        params, vae_params = bundle
        params2, _ = bundle_v2
        queue = RequestQueue(max_depth=32)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, isolation="process",
                        weights_version="v1",
                        bringup_policy=FAST_BRINGUP)
        try:
            wait_all_ready(rs)
            handles = [queue.submit(r) for r in REQS[:3]]
            for _ in range(20):
                rs.step_once()      # get work onto the children
            with faults.injected(upgrade_drain_sigkill_replica=0):
                record = rs.rolling_upgrade(
                    version="v2", params=params2,
                    canary_codes=[(1, 2)], canaries=1,
                    replica_timeout_s=240)
            assert len(record["replicas"]) == 2
            # the kill was real: the drained replica's decoded exit
            # says SIGKILL (it died on its own, before our fence)
            assert "SIGKILL" in rs.replicas[0].last_exit
            rs.run_until_idle(max_steps=500_000)
            for h, r in zip(handles, REQS[:3]):
                res = h.result(timeout=60)
                assert res.status == OK, (res.status, res.reason)
                p = params if res.weights_version == "v1" else params2
                np.testing.assert_array_equal(
                    np.asarray(res.tokens),
                    versioned_reference(p, vae_params, r))
            assert rs.weights_version == "v2"
            # and the upgraded fleet serves v2 byte-identically
            h = queue.submit(REQS[4])
            rs.run_until_idle(max_steps=500_000)
            res = h.result(timeout=60)
            assert res.weights_version == "v2"
            np.testing.assert_array_equal(
                np.asarray(res.tokens),
                versioned_reference(params2, vae_params, REQS[4]))
        finally:
            rs.close()
