"""jaxlint + runtime-guard tests (ISSUE 3 acceptance criteria).

The lint rules are pinned by a fixtures corpus under
``tests/fixtures/jaxlint/``: each ``jl00N_*.py`` file carries
true-positive lines marked ``# expect: JLxxx`` AND must-not-flag
snippets of the neighbouring legal idiom — the parametrized test asserts
EXACT agreement (every expected finding found, nothing else flagged), so
a rule that goes quiet or starts flagging the codebase's own idioms
fails tier-1 either way. Plus: the suppression-comment contract, JSON
output, exit codes, and the ``analysis.guards`` runtime twins.

All CPU and AST-only except the guard tests (tiny jit programs).
"""

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from dalle_pytorch_tpu.analysis import guards
from dalle_pytorch_tpu.analysis import jaxlint

pytestmark = pytest.mark.analysis

FIXTURES = Path(__file__).parent / "fixtures" / "jaxlint"
RULE_FILES = sorted(FIXTURES.glob("jl0*.py"))
_EXPECT_RE = re.compile(r"#\s*expect:\s*(JL\d{3}(?:\s*,\s*JL\d{3})*)")


def expected_findings(path: Path):
    """(line, rule) pairs declared by `# expect: JLxxx` markers."""
    out = set()
    for i, line in enumerate(path.read_text().splitlines(), start=1):
        m = _EXPECT_RE.search(line)
        if m:
            for rule in m.group(1).split(","):
                out.add((i, rule.strip()))
    return out


class TestRuleCorpus:
    @pytest.mark.parametrize(
        "path", RULE_FILES, ids=[p.stem for p in RULE_FILES])
    def test_rule_fixture_exact_agreement(self, path):
        expected = expected_findings(path)
        assert expected, f"{path.name} has no # expect markers"
        actual = {(f.line, f.rule) for f in jaxlint.lint_file(path)}
        missed = expected - actual
        spurious = actual - expected
        assert not missed, f"rule went quiet, missed: {sorted(missed)}"
        assert not spurious, \
            f"flagged legal idiom lines: {sorted(spurious)}"

    def test_corpus_covers_every_rule(self):
        covered = set()
        for path in RULE_FILES:
            covered |= {rule for _, rule in expected_findings(path)}
        assert covered == set(jaxlint.RULES), \
            f"rules without a true-positive fixture: " \
            f"{sorted(set(jaxlint.RULES) - covered)}"

    def test_seeded_violation_fixture_is_dirty(self):
        """The CI gate greps this fixture for a nonzero exit; if someone
        'fixes' it the gate stops proving anything."""
        findings = jaxlint.lint_file(FIXTURES / "seeded_violation.py")
        assert {f.rule for f in findings} >= {"JL001", "JL007"}


class TestSuppression:
    def test_suppressed_corpus_is_clean(self):
        """Every waiver form (trailing, line-above, slug, comma list,
        `all`) silences its finding."""
        assert jaxlint.lint_file(FIXTURES / "suppressed.py") == []

    def test_unwaived_sibling_still_flagged(self):
        """A waiver is line-scoped: the same violation one line later
        without a comment still fires."""
        src = (
            "import time\n"
            "a = time.time()  # jaxlint: disable=JL007 — stamp\n"
            "b = time.time()\n"
        )
        findings = jaxlint.lint_source(src)
        assert [(f.line, f.rule) for f in findings] == [(3, "JL007")]

    def test_unknown_rule_in_waiver_ignored(self):
        src = "import time\nt = time.time()  # jaxlint: disable=JL999\n"
        assert [f.rule for f in jaxlint.lint_source(src)] == ["JL007"]


class TestCLI:
    def test_json_output_and_exit_code(self, capsys):
        rc = jaxlint.main(
            ["--json", "--no-default-excludes",
             str(FIXTURES / "seeded_violation.py")])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert out["files"] == 1
        rules = {f["rule"] for f in out["findings"]}
        assert "JL001" in rules and "JL007" in rules
        for f in out["findings"]:
            assert set(f) == {"rule", "slug", "path", "line", "col",
                              "message"}

    def test_clean_file_exits_zero(self, tmp_path, capsys):
        p = tmp_path / "clean.py"
        p.write_text("import time\nt0 = time.perf_counter()\n")
        assert jaxlint.main([str(p)]) == 0

    def test_default_excludes_skip_own_corpus(self, capsys):
        """`jaxlint tests` must exit 0 on the merged tree even though
        the true-positive corpus lives under tests/ — the corpus is
        excluded by default and reachable via --no-default-excludes."""
        files = jaxlint.iter_py_files([str(FIXTURES)])
        assert files == []
        files = jaxlint.iter_py_files([str(FIXTURES)], excludes=())
        assert len(files) >= 10

    def test_select_and_ignore(self, capsys):
        rc = jaxlint.main(["--json", "--select", "JL007",
                           "--no-default-excludes",
                           str(FIXTURES / "seeded_violation.py")])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert {f["rule"] for f in out["findings"]} == {"JL007"}
        rc = jaxlint.main(["--ignore", "JL001,JL007",
                           "--no-default-excludes",
                           str(FIXTURES / "seeded_violation.py")])
        capsys.readouterr()
        assert rc == 0

    def test_unknown_rule_is_usage_error(self, capsys):
        assert jaxlint.main(["--select", "JL999", "x.py"]) == 2

    @pytest.mark.slow
    def test_module_entrypoint_subprocess(self):
        """The form Makefile/CI invoke: python -m ... exits 1 on the
        seeded fixture, 0 with it excluded by default."""
        proc = subprocess.run(
            [sys.executable, "-m", "dalle_pytorch_tpu.analysis.jaxlint",
             "--no-default-excludes", str(FIXTURES / "seeded_violation.py")],
            capture_output=True, text=True, cwd=Path(__file__).parents[1])
        assert proc.returncode == 1, proc.stderr


class TestCrossModule:
    """Project mode (``jaxlint.lint_files`` — what the CLI and the
    repo-clean test run): JL001/JL009 traced reachability across module
    boundaries. The fixture pair proves both directions — a host sync
    on an IMPORTED module-level jitted program's output, and a host
    sync inside a function that only becomes traced because the SIBLING
    module jits it — and that per-file mode stays blind to both (the
    propagation, not a rule change, is what fires them)."""

    PAIR = [FIXTURES / "cross_module_def.py",
            FIXTURES / "cross_module_use.py"]
    _CROSS_RE = re.compile(r"#\s*cross-expect:\s*(JL\d{3})")

    def _expected(self):
        out = set()
        for p in self.PAIR:
            for i, line in enumerate(p.read_text().splitlines(),
                                     start=1):
                m = self._CROSS_RE.search(line)
                if m:
                    out.add((p.name, i, m.group(1)))
        return out

    def test_solo_mode_is_blind_to_the_pair(self):
        """Each half lints CLEAN alone — the findings exist only in the
        cross-module view, so this pair must stay out of the solo
        fixture corpus loop."""
        for p in self.PAIR:
            assert jaxlint.lint_file(p) == [], p.name

    def test_project_mode_exact_agreement(self):
        expected = self._expected()
        assert expected, "pair has no # cross-expect markers"
        assert {"JL001", "JL009"} <= {r for _, _, r in expected}
        actual = {(Path(f.path).name, f.line, f.rule)
                  for f in jaxlint.lint_files(self.PAIR)}
        missed = expected - actual
        spurious = actual - expected
        assert not missed, f"cross-module propagation went quiet: " \
                           f"{sorted(missed)}"
        assert not spurious, f"flagged legal cross-module idiom: " \
                             f"{sorted(spurious)}"


class TestRepoIsClean:
    def test_package_and_tests_lint_clean(self):
        """The merged-tree acceptance criterion, as a tier-1 test: every
        finding in the package, tests, scripts, and bench — INCLUDING
        project-mode cross-module propagation — is fixed or carries an
        in-line waiver."""
        root = Path(__file__).parents[1]
        files = jaxlint.iter_py_files(
            [str(root / "dalle_pytorch_tpu"), str(root / "tests"),
             str(root / "scripts"), str(root / "bench.py")])
        findings = jaxlint.lint_files(files)
        assert findings == [], "\n".join(x.render() for x in findings)


class TestGuards:
    def test_compile_count_passes_on_cached_calls(self):
        import jax
        import jax.numpy as jnp
        traced = guards.counting(lambda x: x * 2)
        fn = jax.jit(traced)
        with guards.compile_count(lambda: traced.traces, expect=1):
            for i in range(4):
                fn(jnp.float32(i)).block_until_ready()

    def test_compile_count_raises_on_recompile(self):
        import jax
        import jax.numpy as jnp
        traced = guards.counting(lambda x: x + 1)
        fn = jax.jit(traced)
        with pytest.raises(guards.CompileCountError) as ei:
            with guards.compile_count(lambda: traced.traces, expect=1,
                                      label="shape-poly probe"):
                fn(jnp.zeros((2,)))
                fn(jnp.zeros((3,)))      # new shape -> retrace
        assert ei.value.actual == 2
        assert "shape-poly probe" in str(ei.value)

    def test_compile_count_nonraising_records_error(self):
        box = {"n": 0}

        def bump():
            box["n"] += 1

        with guards.compile_count(lambda: box["n"], expect=0,
                                  raise_on_violation=False) as g:
            bump()
        assert isinstance(g.error, guards.CompileCountError)
        assert g.delta() == 1

    def test_compile_count_at_most(self):
        box = {"n": 0}
        with guards.compile_count(lambda: box["n"], at_most=2):
            box["n"] += 2
        with pytest.raises(ValueError):
            with guards.compile_count(lambda: box["n"]):
                pass

    def test_compile_count_body_exception_wins(self):
        box = {"n": 0}
        with pytest.raises(RuntimeError, match="body"):
            with guards.compile_count(lambda: box["n"], expect=0):
                box["n"] += 1
                raise RuntimeError("body")

    def test_no_transfers_allows_explicit(self):
        import jax
        import numpy as np
        fn = jax.jit(lambda x: x + 1)
        fn(jax.device_put(np.zeros((2,), np.float32)))   # compile outside
        with guards.no_transfers():
            x = jax.device_put(np.ones((2,), np.float32))
            y = jax.device_get(fn(x))
        np.testing.assert_array_equal(y, [2.0, 2.0])
