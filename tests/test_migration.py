"""Live KV page migration tests (ISSUE 16 acceptance criteria).

The load-bearing contract: a request moved MID-STREAM between engines
keeps every token it already decoded, and the tokens it emits on the
target are BYTE-IDENTICAL to the undisturbed single-engine run — the
deterministic (rng row, position) sampling makes the continuation
exact, so migration is replay minus the re-decode. Covered here:

  * export_slot -> import_slot byte-identity across the engine matrix
    (K in {1, 8} x gather/kernel paged attention x fp32/int8-KV), and
    a guided CFG pair whose cond+uncond slots move atomically;
  * every typed ``MigrationError`` precondition (dense KV, unknown
    request, page-size / quantization / weights-version mismatch, no
    free target slots) leaves both engines untouched, and a corrupt
    snapshot is discarded WHOLE by the target (pages released) with
    the intact payload still importable afterwards;
  * the replica-set surface: operator drain and scale-in migrate
    in-flight work to survivors (counters, ``serve_migrated`` events,
    flight-ring spans), prefill->decode role handoff, rolling-upgrade
    drains pinned to same-version targets, and the crash-mid-transfer
    / target-reject faults falling back to deterministic replay with
    zero requests lost;
  * THE acceptance drive: a process+socket 2-replica set where
    scale-in migrates a request >= 256 tokens into its decode and the
    survivor finishes it byte-identical.

Fault-injected tests are marked ``faults``. All CPU, tiny model
(total_len 72 — long enough to export mid-stream under K=8's
double-buffered pipeline; the acceptance drive uses total_len 408).
"""

import copy
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V
from dalle_pytorch_tpu.resilience import faults
from dalle_pytorch_tpu.resilience.retry import RetryPolicy
from dalle_pytorch_tpu.serve import (OK, Request, RequestQueue,
                                     SamplingParams)
from dalle_pytorch_tpu.serve.engine import Engine, MigrationError
from dalle_pytorch_tpu.serve.replica import (DRAINED, RUNNING,
                                             ReplicaSet, ScaleError)

# 64 image tokens (total_len 72): wide enough that an export observed
# at >= 8 emitted tokens can never race the fused pipeline's in-flight
# chunks (at most 2 x K = 16 more) past completion
VCFG = V.VAEConfig(image_size=32, num_tokens=32, codebook_dim=16,
                   num_layers=2, hidden_dim=8)
CFG = D.DALLEConfig(dim=16, depth=2, vae=VCFG, num_text_tokens=50,
                    text_seq_len=8, heads=2, dim_head=8)

FAST_BRINGUP = RetryPolicy(max_attempts=1, deadline_s=None,
                           base_backoff_s=0.01, backoff_multiplier=2.0,
                           max_backoff_s=0.1, jitter=0.0)


@pytest.fixture(scope="module")
def bundle():
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG)
    params = D.dalle_init(key, CFG, vae_params)
    return params, vae_params


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.deactivate()
    yield
    faults.deactivate()


_REF_CACHE: dict = {}


def reference_tokens(params, vae_params, req: Request, cfg=CFG,
                     quantize_cache: bool = False) -> np.ndarray:
    """generate_images at batch 1 — the undisturbed same-seed run every
    migrated request must reproduce byte-for-byte (keyed on the params
    object too: the upgrade test compares per weight generation)."""
    key = (id(params), req.codes, req.seed, req.sampling.temperature,
           req.sampling.filter_thres, req.sampling.top_p,
           req.cfg_scale, quantize_cache)
    if key not in _REF_CACHE:
        text = jnp.asarray([req.codes], jnp.int32)
        _, img_seq = D.generate_images(
            params, vae_params, text, cfg=cfg,
            rng=jax.random.PRNGKey(req.seed),
            filter_thres=req.sampling.filter_thres,
            top_p=req.sampling.top_p,
            temperature=req.sampling.temperature,
            guidance=req.cfg_scale,
            quantize_cache=quantize_cache, return_img_seq=True)
        _REF_CACHE[key] = np.asarray(img_seq)[0]
    return _REF_CACHE[key]


REQS = [
    Request(codes=(3, 7, 9), seed=11),
    Request(codes=(5, 2, 8, 1, 4), seed=23,
            sampling=SamplingParams(temperature=0.7, filter_thres=0.8)),
    Request(codes=(6, 6), seed=5,
            sampling=SamplingParams(temperature=1.3, top_p=0.9)),
    Request(codes=(2, 4, 4), seed=7),
    Request(codes=(1, 5), seed=13),
    Request(codes=(4, 4, 4, 4), seed=17),
]


def assert_all_token_exact(params, vae_params, handles, reqs):
    for h, r in zip(handles, reqs):
        res = h.result(timeout=30)
        assert res.status == OK, (r, res.status, res.reason)
        np.testing.assert_array_equal(
            np.asarray(res.tokens),
            reference_tokens(params, vae_params, r))


class _Sink:
    def __init__(self):
        self.events = []

    def event(self, **rec):
        self.events.append(rec)

    def of(self, kind):
        return [e for e in self.events if e.get("kind") == kind]


def wait_all_ready(rs, timeout=180.0):
    """Drive a process set until every worker reached READY — migration
    targets must be serving before work is submitted, or the first
    replica's admission window swallows the burst."""
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        rs.step_once()
        live = [r for r in rs.replicas if r.state == RUNNING
                and r.engine is not None]
        if len(live) == rs.n_replicas and all(
                getattr(r.engine, "ready", True) for r in live):
            return
        time.sleep(0.01)
    raise AssertionError("replicas never all became ready")


def pump_until(stepper, pred, timeout=120.0, what="condition"):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        stepper.step_once()
        if pred():
            return
    raise AssertionError(f"timed out waiting for {what}")


# -- engine-level export/import ---------------------------------------------


def _decode_to(engine: Engine, rid: int, min_tokens: int,
               handle) -> None:
    """Step ``engine`` until ``rid`` has emitted >= min_tokens — and is
    still mid-stream (a request that finished first is a test-shape
    bug, not a migration result)."""
    deadline = time.perf_counter() + 120.0
    while time.perf_counter() < deadline:
        engine.step_once()
        if handle.done():
            raise AssertionError(
                "request finished before the export window")
        if engine.progress_snapshot().get(rid, 0) >= min_tokens:
            return
    raise AssertionError("request never reached the export window")


def _migrate_mid_stream(params, req: Request, *, chunk_steps: int,
                        paged_attn: str, page_size: int,
                        quantize_cache: bool, min_tokens: int = 8):
    """The tentpole drive at engine level: decode on A, export
    mid-stream, import on B, finish on B. Returns (tokens, saved)."""
    kw = dict(num_slots=2, chunk_steps=chunk_steps, kv="paged",
              page_size=page_size, paged_attn=paged_attn,
              quantize_cache=quantize_cache)
    src = Engine(params, CFG, RequestQueue(max_depth=4), **kw)
    dst = Engine(params, CFG, RequestQueue(max_depth=4), **kw)
    h = src.queue.submit(req)
    rid = h.request.request_id
    _decode_to(src, rid, min_tokens, h)
    payload, handle = src.export_request(rid)
    assert handle is h
    saved = len(payload["emitted"])
    assert saved >= min_tokens
    # the slot is VACATED: the source neither holds nor finishes it
    assert src.find_slot(rid) is None
    dst.import_slot(payload, handle)
    dst.run_until_idle()
    res = h.result(timeout=30)
    assert res.status == OK, (res.status, res.reason)
    return np.asarray(res.tokens), saved


class TestExportImportByteIdentity:
    @pytest.mark.parametrize("quantize_cache", [False, True],
                             ids=["fp32", "int8kv"])
    @pytest.mark.parametrize("paged_attn,page_size",
                             [("gather", 4), ("kernel", 8)],
                             ids=["gather", "kernel"])
    @pytest.mark.parametrize("chunk_steps", [1, 8], ids=["K1", "K8"])
    def test_matrix_token_exact(self, bundle, chunk_steps, paged_attn,
                                page_size, quantize_cache):
        """The acceptance matrix: the migrated continuation is
        byte-identical to the undisturbed run across chunk size,
        paged-attention implementation, and KV precision."""
        params, vae_params = bundle
        req = REQS[0]
        tokens, saved = _migrate_mid_stream(
            params, req, chunk_steps=chunk_steps, paged_attn=paged_attn,
            page_size=page_size, quantize_cache=quantize_cache)
        assert saved >= 8
        np.testing.assert_array_equal(
            tokens, reference_tokens(params, vae_params, req,
                                     quantize_cache=quantize_cache))

    def test_cfg_pair_migrates_atomically(self, bundle):
        """A guided request's cond+uncond slots export in ONE payload
        and land together: the guided mix stays exact across the
        move."""
        params, vae_params = bundle
        req = Request(codes=(3, 7, 9), seed=11, cfg_scale=2.0)
        kw = dict(num_slots=2, chunk_steps=4, kv="paged", page_size=4)
        src = Engine(params, CFG, RequestQueue(max_depth=4), **kw)
        dst = Engine(params, CFG, RequestQueue(max_depth=4), **kw)
        h = src.queue.submit(req)
        rid = h.request.request_id
        _decode_to(src, rid, 8, h)
        payload, handle = src.export_request(rid)
        assert payload["uncond"] is not None
        assert payload["uncond"]["cfg_scale"] == pytest.approx(2.0)
        # both halves vacated — no orphaned shadow decodes on
        assert src.active_slots() == 0
        dst.import_slot(payload, handle)
        dst.run_until_idle()
        res = h.result(timeout=30)
        assert res.status == OK
        np.testing.assert_array_equal(
            np.asarray(res.tokens),
            reference_tokens(params, vae_params, req))


class TestMigrationPreconditions:
    def test_dense_kv_export_is_typed(self, bundle):
        params, _ = bundle
        eng = Engine(params, CFG, RequestQueue(max_depth=4),
                     num_slots=2, chunk_steps=4)
        h = eng.queue.submit(REQS[0])
        rid = h.request.request_id
        pump_until(eng, lambda: eng.find_slot(rid) is not None,
                   what="admission")
        with pytest.raises(MigrationError) as ei:
            eng.export_request(rid)
        assert ei.value.reason == "kv_dense"

    def test_unknown_request_is_typed(self, bundle):
        params, _ = bundle
        eng = Engine(params, CFG, RequestQueue(max_depth=4),
                     num_slots=2, chunk_steps=4, kv="paged",
                     page_size=4)
        with pytest.raises(MigrationError) as ei:
            eng.export_request(999_999)
        assert ei.value.reason == "not_found"

    def test_import_mismatches_are_typed_and_leave_target_idle(
            self, bundle):
        """page-size, KV-precision, and weights-version mismatches are
        all typed rejections BEFORE any page is written — the target
        engine stays untouched for every one of them."""
        params, _ = bundle
        src = Engine(params, CFG, RequestQueue(max_depth=4),
                     num_slots=2, chunk_steps=4, kv="paged",
                     page_size=4, weights_version="v1")
        h = src.queue.submit(REQS[0])
        rid = h.request.request_id
        _decode_to(src, rid, 4, h)
        payload, _handle = src.export_request(rid)
        mismatched = [
            ("page_size", dict(page_size=8)),
            ("layout", dict(page_size=4, quantize_cache=True)),
            ("weights_version", dict(page_size=4,
                                     weights_version="v2")),
        ]
        for reason, kw in mismatched:
            dst = Engine(params, CFG, RequestQueue(max_depth=4),
                         num_slots=2, chunk_steps=4, kv="paged",
                         weights_version=kw.pop("weights_version",
                                                "v1"), **kw)
            free0 = dst.alloc.free
            with pytest.raises(MigrationError) as ei:
                dst.import_slot(copy.deepcopy(payload))
            assert ei.value.reason == reason
            assert dst.active_slots() == 0
            assert dst.alloc.free == free0

    def test_full_target_is_typed(self, bundle):
        params, _ = bundle
        src = Engine(params, CFG, RequestQueue(max_depth=4),
                     num_slots=2, chunk_steps=4, kv="paged",
                     page_size=4)
        h = src.queue.submit(REQS[0])
        rid = h.request.request_id
        _decode_to(src, rid, 4, h)
        payload, _handle = src.export_request(rid)
        dst = Engine(params, CFG, RequestQueue(max_depth=4),
                     num_slots=1, chunk_steps=4, kv="paged",
                     page_size=4)
        own = dst.queue.submit(REQS[1])
        pump_until(dst,
                   lambda: dst.find_slot(own.request.request_id)
                   is not None, what="target admission")
        with pytest.raises(MigrationError) as ei:
            dst.import_slot(copy.deepcopy(payload))
        assert ei.value.reason == "target_slots"

    def test_corrupt_snapshot_discarded_whole_then_intact_lands(
            self, bundle):
        """A torn page mid-install must not wedge the target: the
        partial import is discarded WHOLE (grants released, block
        table zeroed), and the intact payload still imports and
        finishes byte-identical afterwards."""
        params, vae_params = bundle
        kw = dict(num_slots=2, chunk_steps=4, kv="paged", page_size=4)
        src = Engine(params, CFG, RequestQueue(max_depth=4), **kw)
        dst = Engine(params, CFG, RequestQueue(max_depth=4), **kw)
        h = src.queue.submit(REQS[0])
        rid = h.request.request_id
        _decode_to(src, rid, 8, h)
        payload, handle = src.export_request(rid)
        torn = copy.deepcopy(payload)
        page0 = torn["cond"]["pages"][0]
        first = next(iter(page0))
        page0[first]["data"] = page0[first]["data"][: len(
            page0[first]["data"]) // 2]
        free0 = dst.alloc.free
        with pytest.raises(MigrationError) as ei:
            dst.import_slot(torn, handle)
        assert ei.value.reason == "transfer"
        assert dst.active_slots() == 0
        assert dst.alloc.free == free0
        dst.import_slot(payload, handle)
        dst.run_until_idle()
        res = h.result(timeout=30)
        assert res.status == OK
        np.testing.assert_array_equal(
            np.asarray(res.tokens),
            reference_tokens(params, vae_params, REQS[0]))


# -- replica-set surface ------------------------------------------------------


class TestSetMigration:
    def test_drain_migrates_in_flight_mid_stream(self, bundle):
        """Operator drain prefers the live move: the drained replica's
        in-flight request lands on the survivor with its decoded
        prefix intact — counted, evented, and token-exact."""
        params, vae_params = bundle
        sink = _Sink()
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, kv="paged", page_size=4,
                        metrics=sink, bringup_policy=FAST_BRINGUP)
        handles = [queue.submit(r) for r in REQS[:2]]
        pump_until(
            rs, lambda: any(
                v >= 2 for v in
                rs.replicas[0].engine.progress_snapshot().values()),
            what="mid-stream work on replica 0")
        moved = rs.drain_replica(0)
        assert moved >= 1
        assert rs.replicas[0].state == DRAINED
        assert rs.migrations >= 1
        assert rs.migrated_tokens_saved >= 2
        assert rs.migrate_fallbacks == 0
        migrated = sink.of("serve_migrated")
        assert migrated and migrated[0]["src"] == 0
        assert migrated[0]["tokens_saved"] >= 2
        rs.run_until_idle()
        assert_all_token_exact(params, vae_params, handles, REQS[:2])
        stats = rs.stats()
        assert stats["migrations"] >= 1
        assert stats["migrated_tokens_saved"] >= 2
        assert all("role" in rec for rec in stats["per_replica"])
        # distinct-delivered-tokens accounting survives the move: the
        # prefix stays credited at the source, the continuation at the
        # target — no token counted twice, none dropped
        assert stats["tokens_decoded"] == sum(
            CFG.seq_len - len(r.codes) for r in REQS[:2])

    def test_scale_in_migrates_and_records_flight_span(self, bundle):
        """remove_replica(drain=True) live-migrates before the fence;
        the ``serve_scale_in`` event carries the migrated count and
        the set flight ring shows the migration."""
        params, vae_params = bundle
        sink = _Sink()
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, kv="paged", page_size=4,
                        metrics=sink, bringup_policy=FAST_BRINGUP)
        handles = [queue.submit(r) for r in REQS[:2]]
        pump_until(
            rs, lambda: any(
                v >= 2 for v in
                rs.replicas[0].engine.progress_snapshot().values()),
            what="mid-stream work on replica 0")
        rs.remove_replica(0, drain=True)
        scale_in = sink.of("serve_scale_in")
        assert scale_in and scale_in[0]["migrated"] >= 1
        assert rs.migrations >= 1
        assert any(e.get("kind") == "serve_migrated"
                   for e in rs.flight.tail(64))
        rs.run_until_idle()
        assert_all_token_exact(params, vae_params, handles, REQS[:2])

    def test_replay_only_scale_in_skips_migration(self, bundle):
        """drain=False names the operator's replay-only intent: zero
        migrations, the fence's deterministic replay still loses
        nothing."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, kv="paged", page_size=4,
                        bringup_policy=FAST_BRINGUP)
        handles = [queue.submit(r) for r in REQS[:2]]
        pump_until(
            rs, lambda: any(
                v >= 2 for v in
                rs.replicas[0].engine.progress_snapshot().values()),
            what="mid-stream work on replica 0")
        rs.remove_replica(0, drain=False)
        assert rs.migrations == 0
        rs.run_until_idle()
        assert_all_token_exact(params, vae_params, handles, REQS[:2])


class TestReplicaRoles:
    def test_role_validation_is_typed(self, bundle):
        params, _ = bundle
        with pytest.raises(ValueError, match="role"):
            ReplicaSet(params, CFG, RequestQueue(max_depth=4),
                       replicas=2, kv="paged", page_size=4,
                       roles=("prefill", "bogus"))
        with pytest.raises(ValueError, match="roles names"):
            ReplicaSet(params, CFG, RequestQueue(max_depth=4),
                       replicas=2, kv="paged", page_size=4,
                       roles=("prefill",))
        # disaggregated roles ship KV pages; dense has none to ship
        with pytest.raises(ValueError, match="paged"):
            ReplicaSet(params, CFG, RequestQueue(max_depth=4),
                       replicas=2, roles=("prefill", "decode"))

    def test_add_replica_role_rejections_are_typed(self, bundle):
        params, _ = bundle
        rs = ReplicaSet(params, CFG, RequestQueue(max_depth=4),
                        replicas=1, num_slots=2, chunk_steps=4,
                        bringup_policy=FAST_BRINGUP)
        with pytest.raises(ScaleError) as ei:
            rs.add_replica(role="bogus")
        assert ei.value.record["reason"] == "unknown_role"
        with pytest.raises(ScaleError) as ei:
            rs.add_replica(role="decode")
        assert ei.value.record["reason"] == "roles_need_paged_kv"

    def test_prefill_to_decode_handoff(self, bundle):
        """Disaggregated serving: the prefill replica admits + prefills
        and hands warm requests to the decode replica mid-stream; the
        decode replica finishes them token-exact."""
        params, vae_params = bundle
        sink = _Sink()
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, kv="paged", page_size=4,
                        roles=("prefill", "decode"), metrics=sink,
                        bringup_policy=FAST_BRINGUP)
        # a burst that FITS the prefill replica's slots: admission
        # prefers prefill, so both requests land there and the sweep
        # hands them to the (idle) decode replica (an overflow burst
        # would spill straight to the decode replica — the preference
        # is routing, not a wall)
        handles = [queue.submit(r) for r in REQS[:2]]
        pump_until(rs, lambda: rs.migrations >= 1, timeout=120.0,
                   what="a prefill->decode handoff")
        rs.run_until_idle()
        assert_all_token_exact(params, vae_params, handles, REQS[:2])
        moved = sink.of("serve_migrated")
        assert moved and all(e["reason"] == "prefill_handoff"
                             and e["dst"] == 1 for e in moved)
        # the decode replica actually finished migrated work
        assert rs.replicas[1].engine.completed >= 1
        roles = [rec["role"]
                 for rec in rs.stats()["per_replica"]]
        assert roles == ["prefill", "decode"]


class TestUpgradeMigration:
    def test_rolling_upgrade_drain_migrates_version_pinned(
            self, bundle):
        """The upgrade's drain live-migrates to SAME-version survivors
        (tokens are byte-identical per weight generation only); every
        request finishes token-exact against the generation that
        stamped its result."""
        params, vae_params = bundle
        params2 = D.dalle_init(jax.random.PRNGKey(42), CFG,
                               vae_params)
        by_version = {"v1": params, "v2": params2}
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, kv="paged", page_size=4,
                        weights_version="v1",
                        bringup_policy=FAST_BRINGUP)
        handles = [queue.submit(r) for r in REQS[:2]]
        pump_until(
            rs, lambda: any(
                v >= 2 for v in
                rs.replicas[0].engine.progress_snapshot().values()),
            what="mid-stream work on replica 0")
        record = rs.rolling_upgrade(version="v2", params=params2,
                                    canary_codes=[(1, 2)], canaries=1,
                                    replica_timeout_s=120.0)
        assert sum(int(e.get("migrated", 0))
                   for e in record["replicas"]) >= 1
        assert rs.migrations >= 1
        rs.run_until_idle()
        for h, r in zip(handles, REQS[:2]):
            res = h.result(timeout=30)
            assert res.status == OK, (res.status, res.reason)
            np.testing.assert_array_equal(
                np.asarray(res.tokens),
                reference_tokens(by_version[res.weights_version],
                                 vae_params, r))


class TestMigrationFaults:
    pytestmark = pytest.mark.faults

    def test_target_reject_falls_back_to_replay(self, bundle):
        """The target refusing the import (fault: allocation failure)
        must cost nothing: typed fallback, deterministic replay on the
        survivor, zero loss, and the un-credit keeps distinct-token
        accounting exact."""
        params, vae_params = bundle
        sink = _Sink()
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, kv="paged", page_size=4,
                        metrics=sink, bringup_policy=FAST_BRINGUP)
        handles = [queue.submit(r) for r in REQS[:2]]
        pump_until(
            rs, lambda: any(
                v >= 2 for v in
                rs.replicas[0].engine.progress_snapshot().values()),
            what="mid-stream work on replica 0")
        with faults.injected(migrate_reject_target=1):
            rs.drain_replica(0)
        assert rs.migrations == 0
        assert rs.migrate_fallbacks >= 1
        fb = sink.of("serve_migrate_fallback")
        assert fb and fb[0]["reason"] == "target_pages"
        rs.run_until_idle()
        assert_all_token_exact(params, vae_params, handles, REQS[:2])
        stats = rs.stats()
        assert stats["completed"] == 2
        assert stats["tokens_decoded"] == sum(
            CFG.seq_len - len(r.codes) for r in REQS[:2])

    @pytest.mark.parametrize("transport", ["pipe", "socket"])
    def test_crash_source_mid_transfer_falls_back(self, bundle,
                                                  transport):
        """SIGKILL the source child exactly at the transfer point: the
        export dies, the fallback replays from the parent's shadow —
        zero requests lost, tokens byte-identical."""
        params, vae_params = bundle
        sink = _Sink()
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, kv="paged", page_size=4,
                        isolation="process", transport=transport,
                        metrics=sink, bringup_policy=FAST_BRINGUP)
        try:
            wait_all_ready(rs)
            handles = [queue.submit(r) for r in REQS]
            # in-flight work on child 0 (the parent's shadow is the
            # authority; the tiny model decodes faster than a heartbeat
            # interval, so the progress mirror may never show a
            # mid-stream value — the crash fires at the transfer point
            # regardless of depth)
            pump_until(
                rs, lambda: any(
                    not h.done() for h in
                    rs.replicas[0].engine.shadow.values()),
                what="in-flight work on child 0")
            with faults.injected(migrate_crash_source_at_transfer=0):
                rs.remove_replica(0, drain=True)
            assert rs.migrations == 0
            assert rs.migrate_fallbacks >= 1
            fb = sink.of("serve_migrate_fallback")
            assert fb and fb[0]["reason"] == "source_dead"
            rs.run_until_idle()
            assert_all_token_exact(params, vae_params, handles, REQS)
            assert rs.stats()["completed"] == len(REQS)
        finally:
            rs.close()


# -- THE acceptance drive -----------------------------------------------------

# 1024 image tokens (total_len 1032): deep enough that a request can
# be observed >= 256 tokens into decode with a wide window left before
# completion — the scale-in's migration must save >= 256 tokens
VCFG_BIG = V.VAEConfig(image_size=128, num_tokens=32, codebook_dim=16,
                       num_layers=2, hidden_dim=8)
CFG_BIG = D.DALLEConfig(dim=16, depth=2, vae=VCFG_BIG,
                        num_text_tokens=50, text_seq_len=8, heads=2,
                        dim_head=8)


@pytest.fixture(scope="module")
def bundle_big():
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG_BIG)
    params = D.dalle_init(key, CFG_BIG, vae_params)
    return params, vae_params


class TestAcceptanceDeepMigration:
    def test_socket_scale_in_migrates_256_deep_token_exact(
            self, bundle_big):
        """ISSUE 16 acceptance: a process+socket 2-replica set where
        ``remove_replica`` migrates a request >= 256 tokens into its
        decode; the survivor finishes it BYTE-IDENTICAL to the
        undisturbed run and the set counts >= 256 tokens saved."""
        params, vae_params = bundle_big
        reqs = [Request(codes=(3, 7, 9), seed=11),
                Request(codes=(5, 2), seed=23)]
        queue = RequestQueue(max_depth=8)
        rs = ReplicaSet(params, CFG_BIG, queue, replicas=2,
                        num_slots=2, chunk_steps=8, kv="paged",
                        page_size=8, isolation="process",
                        transport="socket",
                        bringup_policy=FAST_BRINGUP)
        try:
            wait_all_ready(rs)
            handles = [queue.submit(r) for r in reqs]
            pump_until(
                rs, lambda: any(
                    v >= 256 for v in
                    rs.replicas[0].engine.progress.values()),
                timeout=300.0,
                what="a request 256 tokens into decode on child 0")
            saved0 = rs.migrated_tokens_saved
            rs.remove_replica(0, drain=True)
            assert rs.migrations >= 1
            assert rs.migrated_tokens_saved - saved0 >= 256
            rs.run_until_idle()
            for h, r in zip(handles, reqs):
                res = h.result(timeout=60)
                assert res.status == OK, (res.status, res.reason)
                np.testing.assert_array_equal(
                    np.asarray(res.tokens),
                    reference_tokens(params, vae_params, r,
                                     cfg=CFG_BIG))
        finally:
            rs.close()
