"""Test configuration: force an 8-device CPU platform BEFORE jax initialises.

Multi-chip behaviour (DP/TP/SP meshes, collectives) is tested on a virtual
8-device CPU mesh — the standard JAX substitute for a pod (SURVEY.md §4e).
Must run before any jax import in the test process.
"""

import os

# Force, don't setdefault: the session environment pins JAX_PLATFORMS to the
# real TPU tunnel (and its sitecustomize re-pins it at interpreter start, so
# the env var alone is not enough — the jax.config update below is the one
# that sticks). Tests must run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import atexit  # noqa: E402
import shutil  # noqa: E402
import tempfile  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

# Share XLA executables across the run via the persistent compilation
# cache (fresh per-run dir — nothing leaks between runs). Many tests
# build identical programs from DISTINCT jit objects (every serve test
# constructs its own Engine, whose fused decode program re-traces but
# compiles to the same HLO), and on the CPU backend XLA compilation
# dominates tier-1 wall time. Trace-count contracts are unaffected:
# guards.compile_count and Engine.decode_traces count TRACES, which
# still happen once per jit object.
_cache_dir = tempfile.mkdtemp(prefix="jaxcache-")
atexit.register(shutil.rmtree, _cache_dir, ignore_errors=True)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

# ... and through the ENVIRONMENT too: process-isolated serving tests
# spawn child workers (serve/worker.py) that build their own jax from
# env vars, not this process's jax.config — sharing the per-run cache
# dir means every child's tiny engine compiles once across the whole
# suite instead of once per spawned process.
os.environ["JAX_COMPILATION_CACHE_DIR"] = _cache_dir
os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0.5"
os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "0"
