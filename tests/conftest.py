"""Test configuration: force an 8-device CPU platform BEFORE jax initialises.

Multi-chip behaviour (DP/TP/SP meshes, collectives) is tested on a virtual
8-device CPU mesh — the standard JAX substitute for a pod (SURVEY.md §4e).
Must run before any jax import in the test process.
"""

import os

# Force, don't setdefault: the session environment pins JAX_PLATFORMS to the
# real TPU tunnel (and its sitecustomize re-pins it at interpreter start, so
# the env var alone is not enough — the jax.config update below is the one
# that sticks). Tests must run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
