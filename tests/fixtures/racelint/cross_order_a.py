"""Half A of the cross-module lock-order cycle. Alone this file lints
CLEAN — ``PeerB`` is not defined here, so the call under ``_la`` cannot
be resolved and contributes no edge. Only project mode, with
``cross_order_b.py`` in the same run, sees ``PeerA._la -> PeerB._lb``
meet its reverse and closes the cycle (anchored here, the first edge
site in path order).
"""

import threading


class PeerA:
    def __init__(self):
        self._la = threading.Lock()

    def ping(self, b: "PeerB"):
        with self._la:
            b.pong_inner()          # cross-expect: RL002

    def ping_inner(self):
        with self._la:
            pass
