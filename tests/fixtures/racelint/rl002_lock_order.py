"""RL002 true positives + must-not-flag idioms: lock ordering.

Two halves. (1) A cycle in the whole-program acquires-while-holding
graph — here within one file, through method calls on typed receivers —
is a potential deadlock; the single finding anchors at the cycle's
first edge site. (2) A lexical reentrant acquire of a non-reentrant
``threading.Lock`` the same thread already holds is a certain deadlock.
Timed acquires (``acquire(timeout=...)``) are excluded from the cycle
graph: a bounded wait cannot wedge, it fails over.
"""

import threading


class Alpha:
    """Cycle regression shape: the replica control plane holds its lock
    and reaches into the engine, while an engine-side path reaches back
    into the control plane — each direction alone is fine, together
    they deadlock under load."""

    def __init__(self):
        self._la = threading.Lock()

    def forward(self):
        b = Beta()
        with self._la:
            b.backward_inner()      # expect: RL002

    def finish_inner(self):
        with self._la:
            pass


class Beta:
    def __init__(self):
        self._lb = threading.Lock()

    def backward_inner(self):
        with self._lb:
            pass

    def reverse(self):
        a = Alpha()
        with self._lb:
            a.finish_inner()        # the other half of the cycle


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._rlock = threading.RLock()

    def double_acquire(self):
        with self._lock:
            with self._lock:        # expect: RL002
                pass

    # must not flag: RLock is reentrant — same-thread re-acquire is
    # exactly what it is for
    def reentrant_ok(self):
        with self._rlock:
            with self._rlock:
                pass


class Gamma:
    def __init__(self):
        self._lg = threading.Lock()

    def ordered(self, d: "Delta"):
        with self._lg:
            d.touch_inner()


class Delta:
    def __init__(self):
        self._ld = threading.Lock()

    def touch_inner(self):
        with self._ld:
            pass


def also_ordered(g: Gamma, d: Delta):
    # must not flag: both paths take Gamma._lg BEFORE Delta._ld — one
    # consistent direction is the fix for a cycle, not an instance of it
    with g._lg:
        with d._ld:
            pass


class Sweeper:
    """Must not flag: the replica reclaim-sweep idiom — the reverse
    direction exists but uses a TIMED acquire precisely so a wedged
    peer cannot wedge the sweep; timed edges stay out of the cycle."""

    def __init__(self):
        self._ctl = threading.Lock()

    def sweep(self, e: "EngineLike"):
        with self._ctl:
            if e._elock.acquire(timeout=0.2):
                e._elock.release()


class EngineLike:
    def __init__(self):
        self._elock = threading.Lock()

    def steplike(self, s: Sweeper):
        with self._elock:
            with s._ctl:
                pass
