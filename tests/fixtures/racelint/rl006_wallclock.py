"""RL006 true positives + must-not-flag idioms: time discipline.

``time.time()`` is the WALL clock: NTP slew and DST steps move it, so
deadline/duration arithmetic built on it misfires — a timeout can
expire instantly or never. Deadline math belongs on
``time.monotonic()`` (or ``perf_counter``); ``time.time()`` stays
legal as a plain timestamp (log records, wire metadata).
"""

import time


def deadline_bad(timeout):
    """Regression shape: the gateway's first hedge-timer draft armed
    hedges off the wall clock — an NTP step-back during a deploy made
    every in-flight request hedge at once."""
    deadline = time.time() + timeout        # expect: RL006
    while time.time() < deadline:           # expect: RL006
        pass


def age_bad(start_wall):
    return time.time() - start_wall         # expect: RL006


# must not flag: a bare timestamp (no arithmetic) is what the wall
# clock is for
def stamp_ok():
    return {"ts": time.time()}


# must not flag: deadline math on the monotonic clock is the fix
def deadline_ok(timeout):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        pass


# must not flag: perf_counter durations are monotonic too
def duration_ok(t0):
    return time.perf_counter() - t0
