"""Half B of the cross-module lock-order cycle — holds its own lock
and calls back into ``PeerA`` (see cross_order_a.py). Clean alone for
the same reason: the reverse edge only exists when both halves are in
one project-mode run.
"""

import threading


class PeerB:
    def __init__(self):
        self._lb = threading.Lock()

    def pong_inner(self):
        with self._lb:
            pass

    def pong(self, a: "PeerA"):
        with self._lb:
            a.ping_inner()
