"""RL001 true positives + must-not-flag idioms: lock-guard inference.

The rule infers each attribute's guard from the writes themselves — if
SOME writes to ``self.x`` happen under an own-instance lock and others
under none, the unguarded sites are data-race candidates. ``__init__``
writes never count (no other thread can hold a reference yet), and a
private helper only ever CALLED under the lock is guarded too (the
entry-held fixpoint), so the serve tier's ``_reject``-style helpers
stay clean.
"""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0
        self.total = 0
        self.label = ""
        self.backlog = []

    def record(self, n):
        with self._lock:
            self.hits += 1
            self.total += n
            self.backlog.append(n)

    def reset(self):
        self.hits = 0       # expect: RL001
        self.total = 0      # expect: RL001

    def enqueue_racy(self, n):
        self.backlog.append(n)      # expect: RL001

    # must not flag: no write to `label` ever happens under a lock, so
    # there is no inferred guard to violate (single-writer by design)
    def rename(self, label):
        self.label = label

    # must not flag: the write in _apply is lexically bare, but _apply
    # is only ever called with the lock held — the entry-held fixpoint
    # marks it guarded
    def flush(self):
        with self._lock:
            self._apply()

    def _apply(self):
        self.hits = 0
        self.total = 0


class Upgrader:
    """Regression shape: replica.rolling_upgrade set the in-progress
    flag under the control lock but cleared it bare in its ``finally``
    block — exactly the asymmetry this rule exists to catch."""

    def __init__(self):
        self._ctl = threading.Lock()
        self._upgrading = False

    def rolling(self):
        with self._ctl:
            self._upgrading = True
        try:
            self._step()
        finally:
            self._upgrading = False     # expect: RL001

    def _step(self):
        pass


class EventHolder:
    """Must not flag: threading.Event/queue.Queue/Thread attributes are
    their own synchronization — writes to them are excluded."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()

    def arm(self):
        with self._lock:
            self._stop = threading.Event()

    def rearm_bare(self):
        self._stop = threading.Event()
