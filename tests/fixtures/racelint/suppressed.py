"""Every waiver form racelint honors, each silencing a real finding:
trailing comment, standalone line above, slug instead of id, comma
list, and ``all``. The paired test asserts this file lints CLEAN — a
parser regression that drops any form turns a waiver back into a
finding and fails it.
"""

import threading
import time


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def guarded(self):
        with self._lock:
            self.n += 1

    def trailing(self):
        self.n = 0  # racelint: disable=RL001 — snapshot reset, single-threaded by contract

    def line_above(self):
        # racelint: disable=lock-guard — slug form: bench teardown, no peers
        self.n = 5

    def comma_list(self, timeout):
        self.n = int(time.time() + timeout)  # racelint: disable=RL001,RL006 — epoch bucket id, not a deadline

    def all_form(self):
        self.n = 7  # racelint: disable=all — kitchen-sink waiver


def sleepy(box: Box):
    with box._lock:
        # racelint: disable=RL003 — 10ms settling nap in a test-only path
        time.sleep(0.01)
