"""RL003 true positives + must-not-flag idioms: blocking under a lock.

A blocking operation — transport/socket I/O, sleep, select, subprocess,
an unbounded ``get()``/``join()``/``wait()``, a host-device sync —
reached while a lock is held stalls every thread contending on that
lock. The finding lands where the lock is LEXICALLY held: a helper
that sleeps is fine on its own, the caller that invokes it under a
lock owns the hazard.
"""

import queue
import subprocess
import threading
import time


class Transport:
    """Regression shape: the live-migration path shipped KV pages to a
    peer while holding the control lock — one stalled peer froze every
    control-plane operation in the fleet (fixed by moving the send
    outside the critical section)."""

    def __init__(self):
        self._ctl = threading.Lock()
        self.peer = None
        self.inbox = queue.Queue()

    def migrate(self, pages):
        with self._ctl:
            for p in pages:
                self.peer.send_frame(p)     # expect: RL003

    def poll(self):
        with self._ctl:
            return self.inbox.get()         # expect: RL003

    def nap_locked(self):
        with self._ctl:
            time.sleep(0.5)                 # expect: RL003

    def shell_locked(self, cmd):
        with self._ctl:
            return subprocess.run(cmd)      # expect: RL003

    def drain(self):
        with self._ctl:
            self._pump()                    # expect: RL003

    def _pump(self):
        # must not flag HERE: no lock is lexically held in this frame —
        # the caller holding _ctl owns the finding (see drain above)
        time.sleep(0.05)

    # must not flag: bounded get — backpressure with a timeout is the
    # sanctioned idiom (the scheduler's pop path does exactly this)
    def poll_bounded(self):
        with self._ctl:
            return self.inbox.get(timeout=0.1)

    # must not flag: the sleep happens after the lock is released
    def nap_unlocked(self):
        with self._ctl:
            n = len(str(self.peer))
        time.sleep(0.01)
        return n


class DeviceSync:
    def __init__(self):
        self._lock = threading.Lock()
        self.buf = None

    def export_locked(self, jax):
        with self._lock:
            return jax.device_get(self.buf)     # expect: RL003

    # must not flag: the device sync runs outside the critical section
    def export_ok(self, jax):
        with self._lock:
            buf = self.buf
        return jax.device_get(buf)
