"""RL004 true positives + must-not-flag idioms: Condition discipline.

``wait()`` must re-test its predicate in a ``while`` (spurious wakeups
and stolen wakeups make a plain ``if`` wrong), and both ``wait()`` and
``notify()`` require the condition's lock (CPython raises RuntimeError;
the lost-wakeup race is the deeper bug). A ``wait()`` while HOLDING an
unrelated lock additionally parks that lock for the whole sleep — that
half reports as RL003.
"""

import threading


class Mailbox:
    def __init__(self):
        self._cv = threading.Condition()
        self._aux = threading.Lock()
        self.items = []

    # must not flag: the canonical producer/consumer shape
    def put(self, x):
        with self._cv:
            self.items.append(x)
            self._cv.notify()

    def take_ok(self):
        with self._cv:
            while not self.items:
                self._cv.wait()
            return self.items.pop(0)

    # must not flag: wait_for re-tests the predicate internally
    def take_waitfor(self):
        with self._cv:
            self._cv.wait_for(lambda: self.items)
            return self.items.pop(0)

    def take_racy(self):
        """Regression shape: a stolen wakeup (two consumers, one item)
        returns from wait() with the predicate false — the `if` version
        then pops an empty list."""
        with self._cv:
            if not self.items:
                self._cv.wait()             # expect: RL004
            return self.items.pop(0)

    def poke_unlocked(self):
        self._cv.notify()                   # expect: RL004

    def wait_unlocked(self):
        self._cv.wait()                     # expect: RL004

    def wait_holding_aux(self):
        with self._aux:
            with self._cv:
                while not self.items:
                    self._cv.wait()         # expect: RL003
