"""Deliberately dirty — DO NOT FIX. The CI static-analysis job lints
this file expecting a nonzero exit: it is the liveness canary proving
the racelint gate can still fail. 'Fixing' these lines would turn the
gate into a rubber stamp.
"""

import threading
import time

_lock = threading.Lock()


def seeded(timeout):
    deadline = time.time() + timeout    # RL006: wall-clock deadline
    with _lock:
        time.sleep(timeout)             # RL003: sleep under the lock
    return deadline
