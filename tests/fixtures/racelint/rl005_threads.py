"""RL005 true positives + must-not-flag idioms: thread lifecycle.

A non-daemon thread that is never joined outlives shutdown: the
interpreter refuses to exit while it runs, and Ctrl-C hangs the
process. Every long-lived thread in the serve tier is either
``daemon=True`` (the engine run loop, heartbeats) or joined on the
shutdown path (worker drains) — anything else is a leak.
"""

import threading


def work():
    pass


def spawn_leaky():
    """Regression shape: an early flight-recorder draft started its
    writer thread without daemon=True and without a join on close() —
    every test process hung at exit until it was killed."""
    leaked = threading.Thread(target=work)          # expect: RL005
    leaked.start()
    return leaked


def spawn_timer_leaky():
    ticker = threading.Timer(5.0, work)             # expect: RL005
    ticker.start()
    return ticker


# must not flag: daemon at construction — dies with the process
def spawn_daemon():
    t = threading.Thread(target=work, daemon=True)
    t.start()
    return t


# must not flag: joined in the same module (the shutdown-path idiom)
def spawn_joined():
    worker = threading.Thread(target=work)
    worker.start()
    worker.join()


# must not flag: daemonized by attribute assignment before start
def spawn_daemoned_later():
    bg = threading.Thread(target=work)
    bg.daemon = True
    bg.start()
    return bg
