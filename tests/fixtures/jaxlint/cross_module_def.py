"""Cross-module corpus, defining half (pairs with cross_module_use.py;
driven by tests/test_analysis.py::TestCrossModule, NOT by the solo
per-file fixture loop — every finding here needs project mode).

Exports a MODULE-LEVEL jitted program (``fused_step``) and a plain
helper whose body holds a host sync. Solo, this file is clean: nothing
in it jits ``helper_with_sync``. Project mode must flag the sync once
cross_module_use.py wraps the helper in ``jax.jit`` — traced
reachability across the file boundary, the shape the serve replica
layer takes when it drives jitted engine internals from another module.
"""

import jax
import jax.numpy as jnp
import numpy as np


def _step_math(x):
    return jnp.tanh(x) * 2.0


def helper_with_sync(x):
    # flagged (JL001) ONLY when the sibling module jits this function —
    # the marker below is asserted by the project-mode test, and its
    # ABSENCE by the solo-mode test
    return np.asarray(x) + 1          # cross-expect: JL001


fused_step = jax.jit(_step_math)
