"""JL004 corpus: jit constructions that retrace per call."""

import jax


def bad_jit_in_loop(fns, x):
    outs = []
    for fn in fns:
        outs.append(jax.jit(fn)(x))  # expect: JL004
    return outs


def bad_lambda_then_jit(fns, x):
    outs = []
    for fn in fns:
        # a lambda earlier in the statement must not hide the jit()
        outs.append(((lambda v: v), jax.jit(fn)(x)))  # expect: JL004
    return outs


def bad_static_argnums(fn):
    return jax.jit(fn, static_argnums=("name",))  # expect: JL004


def bad_static_and_donated(fn):
    return jax.jit(fn, static_argnums=(0,), donate_argnums=(0, 1))  # expect: JL004


# --- must not flag -------------------------------------------------------

def ok_constructed_outside(fn, xs):
    step = jax.jit(fn)
    return [step(x) for x in xs]


def ok_static_ints(fn):
    return jax.jit(fn, static_argnums=(0, 2), donate_argnums=(1,))
