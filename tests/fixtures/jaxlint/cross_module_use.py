"""Cross-module corpus, using half (pairs with cross_module_def.py).

Imports the sibling's module-level jitted program. Solo, this file is
clean — nothing HERE is assigned from a jit expression, so the per-file
pass has no idea ``fused_step`` is a jitted callable. Project mode must
flag the host round-trip on its output (JL001) and the eager
``lax.cond`` dispatched on it (JL009), and must mark the imported
``helper_with_sync`` as traced over in the defining module.
"""

import jax
import numpy as np
from jax import lax

from cross_module_def import fused_step, helper_with_sync


def drive(x):
    out = fused_step(x)
    return np.asarray(out)            # cross-expect: JL001


def eager_control(x):
    out = fused_step(x)
    return lax.cond(out[0] > 0,       # cross-expect: JL009
                    lambda: 1, lambda: 0)


def rebound_is_clean(x):
    out = fused_step(x)
    out = np.zeros(3)                 # rebound to host data: no finding
    return np.asarray(out)


jitted_helper = jax.jit(helper_with_sync)
