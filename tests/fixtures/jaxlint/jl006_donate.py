"""JL006 corpus: buffers referenced after donate_argnums donation."""

import jax


def tree_norm(t):
    return t


def apply_update(params, grads):
    return params


def bad_use_after_donate(params, grads):
    step = jax.jit(apply_update, donate_argnums=(0,))
    new_params = step(params, grads)
    norm = tree_norm(params)  # expect: JL006
    return new_params, norm


# --- must not flag -------------------------------------------------------

def ok_rebind(params, grads):
    step = jax.jit(apply_update, donate_argnums=(0,))
    params = step(params, grads)     # rebound to the NEW buffer
    return tree_norm(params)


def ok_not_donated(params, grads):
    step = jax.jit(apply_update)
    new_params = step(params, grads)
    return new_params, tree_norm(params)
