"""JL002 corpus: python control flow on traced values."""

import jax


@jax.jit
def bad_if(x):
    if x > 0:  # expect: JL002
        return x
    return -x


@jax.jit
def bad_while(x):
    while x < 10:  # expect: JL002
        x = x * 2
    return x


# --- must not flag -------------------------------------------------------

@jax.jit
def ok_none_check(x, mask=None):
    if mask is None:            # trace-time python fact
        return x
    return x * mask


@jax.jit
def ok_kwonly_config(x, *, causal=True):
    if causal:                  # kwonly args are trace-time config
        return x
    return x + 1


@jax.jit
def ok_scalar_annotation(x, p: float = 0.5):
    if p > 0:                   # scalar-annotated: python value
        return x * p
    return x


@jax.jit
def ok_static(x, n, *, _static=None):
    if len(x) > 2:              # len() is a static shape fact
        return x
    return x + n


@jax.jit
def ok_pytree_membership(x, cache):
    if "k_scale" in cache:      # pytree STRUCTURE, fixed at trace time
        return x + cache["k_scale"]
    return x


@jax.jit
def bad_membership_on_traced(x, xs):
    if x in xs:  # expect: JL002
        return xs
    return xs + x
