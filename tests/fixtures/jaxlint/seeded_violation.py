"""The CI gate's canary: a deliberately seeded violation with NO waiver.

.github/workflows/ci.yml runs jaxlint over this file and FAILS the build
if the exit code is zero — proving the lint gate is actually live, not
silently skipping files or rules. Do not "fix" this file."""

import time

import jax


@jax.jit
def seeded_host_sync(x):
    # a host sync inside a jitted decode step: the exact bug class the
    # serving engine's one-compile contract exists to prevent
    return x.item()


def seeded_wallclock_duration():
    t0 = time.time()
    return time.time() - t0
