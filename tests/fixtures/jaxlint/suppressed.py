"""Suppression corpus: every violation here carries a waiver, so jaxlint
must report ZERO findings for this file — in each supported form
(trailing comment, standalone comment above, slug instead of id,
comma list, `all`)."""

import time

import jax
import numpy as np


@jax.jit
def trailing_form(x):
    return x.item()  # jaxlint: disable=JL001 — corpus: trailing waiver


@jax.jit
def line_above_form(x):
    # jaxlint: disable=JL001 — corpus: waiver on its own line, then a
    # second comment line before the statement it covers
    return np.asarray(x)


@jax.jit
def slug_form(x):
    if x > 0:  # jaxlint: disable=traced-branch — corpus: slug waiver
        return x
    return -x


def comma_list_form(key):
    t0 = time.time()  # jaxlint: disable=JL007,JL003 — corpus: list waiver
    a = jax.random.normal(key, (2,))
    b = jax.random.normal(key, (2,))  # jaxlint: disable=JL003 — corpus
    return a + b, t0


def all_form():
    return time.time()  # jaxlint: disable=all — corpus: blanket waiver
