"""JL005 corpus: jitted defs closing over loop variables."""

import jax


def bad_closure():
    fns = []
    for i in range(3):
        @jax.jit
        def f(x):  # expect: JL005
            return x + i
        fns.append(f)
    return fns


# --- must not flag -------------------------------------------------------

def ok_default_bound():
    fns = []
    for i in range(3):
        @jax.jit
        def f(x, i=i):          # early-bound: each f sees its own i
            return x + i
        fns.append(f)
    return fns


def ok_not_jitted():
    fns = []
    for i in range(3):
        def f(x):               # plain closure: python semantics, no jit
            return x + i
        fns.append(f)
    return fns
