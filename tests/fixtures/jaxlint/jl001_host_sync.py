"""JL001 corpus: host syncs in traced code + round-trips on jit output.

Parsed by tests/test_analysis.py, never executed. `# expect: JLxxx`
marks a line jaxlint MUST flag; everything unmarked must stay clean.
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad_item(x):
    return x.item()  # expect: JL001


@jax.jit
def bad_np_asarray(x):
    return np.asarray(x)  # expect: JL001


@jax.jit
def bad_concretize(x):
    return int(x)  # expect: JL001


def helper_sync(x):
    return x.tolist()  # expect: JL001


@jax.jit
def calls_helper(x):
    # helper_sync is traced-reachable from here, so ITS sync is flagged
    return helper_sync(x)


def host_round_trip(params, x):
    step = jax.jit(lambda p, v: p + v)
    out = step(params, x)
    return np.asarray(out)  # expect: JL001


# --- must not flag -------------------------------------------------------

@jax.jit
def ok_jnp(x):
    return jnp.asarray(x) + 1


@jax.jit
def ok_np_literal(x):
    return x + np.asarray([1.0, 2.0])   # constant table, hoisted by jit


def ok_host_code(x):
    # not reachable from any traced function: host syncs are legal here
    return np.asarray(x).item()


def ok_sync_before_jit_bind(raw, x):
    # flow-sensitive: y is plain host data when converted; it becomes a
    # jit output only on the LAST line, after which nothing syncs it
    step2 = jax.jit(lambda v: v * 2)
    y = np.asarray(raw)
    z = np.asarray(y)
    y = step2(x)
    return y, z


def ok_rebound_to_host(params, x):
    step3 = jax.jit(lambda p, v: p + v)
    out = step3(params, x)
    out = [1, 2, 3]              # rebound to host data
    return np.asarray(out)
