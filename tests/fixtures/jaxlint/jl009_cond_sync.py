"""JL009 corpus: eager lax control flow on device-derived operands.

True positives carry the expect-marker comment; everything else is the
neighbouring LEGAL idiom (control flow inside jit, python branches on
host data, operands rebound to host values) and must NOT be flagged.
"""

import jax
import jax.numpy as jnp
from jax import lax

step = jax.jit(lambda x: x + 1)


def eager_cond_on_jit_output(x):
    y = step(x)
    return lax.cond(y[0] > 0, lambda: 1.0, lambda: 2.0)  # expect: JL009


def eager_while_on_jit_carry(x):
    y = step(x)
    return lax.while_loop(lambda c: c[0] < 3, lambda c: c + 1, y)  # expect: JL009


def eager_switch_on_jit_index(x):
    idx = step(x)
    return lax.switch(idx, [lambda: 0, lambda: 1])  # expect: JL009


def eager_cond_on_direct_jit_call(x):
    return lax.cond(step(x)[0] > 0, lambda: 1.0, lambda: 2.0)  # expect: JL009


@jax.jit
def legal_cond_inside_jit(x):
    # traced region: the conditional compiles into the program, no sync
    return lax.cond(x[0] > 0, lambda: x, lambda: -x)


def legal_scan_body_while(x):
    # referenced by jax.jit below -> trace root, not eager dispatch
    return lax.while_loop(lambda c: c[0] < 3, lambda c: c + 1, step(x))


_jitted_wrapper = jax.jit(legal_scan_body_while)


def legal_python_branch_on_host_flag(flag, x):
    # the predicate is a plain python value, not device data
    if flag:
        return x
    return lax.cond(flag, lambda: 1.0, lambda: 2.0)


def legal_rebound_to_host_value(x):
    y = step(x)
    y = 3  # rebound to host data before the control op
    return lax.cond(y > 0, lambda: 1.0, lambda: 2.0)
