"""JL008 corpus: print/time side effects inside traced code."""

import time

import jax


@jax.jit
def bad_print(x):
    print("tracing", x)  # expect: JL008
    return x + 1


@jax.jit
def bad_perf_counter(x):
    t0 = time.perf_counter()  # expect: JL008
    return x + t0


@jax.jit
def bad_wallclock(x):
    return x + time.time()  # expect: JL008


# --- must not flag -------------------------------------------------------

def ok_host_print(x):
    print("host-side logging is fine", x)
    return x


@jax.jit
def ok_debug_print(x):
    jax.debug.print("traced-safe: {}", x)
    return x + 1
