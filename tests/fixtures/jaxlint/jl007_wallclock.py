"""JL007 corpus: time.time() in duration math vs waived epoch stamps."""

import time


def work():
    pass


def bad_duration():
    t0 = time.time()  # expect: JL007
    work()
    return time.time() - t0  # expect: JL007


# --- must not flag -------------------------------------------------------

def ok_epoch_stamp():
    return {"time": time.time()}  # jaxlint: disable=JL007 — epoch stamp


def ok_perf_counter():
    t0 = time.perf_counter()
    work()
    return time.perf_counter() - t0
