"""JL003 corpus: PRNG key reuse across draws."""

import jax


def bad_straight_line(key):
    a = jax.random.normal(key, (2,))
    b = jax.random.uniform(key, (2,))  # expect: JL003
    return a + b


def bad_loop_reuse(key):
    out = []
    for _ in range(3):
        out.append(jax.random.normal(key, (2,)))  # expect: JL003
    return out


# --- must not flag -------------------------------------------------------

def ok_split(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (2,))
    b = jax.random.uniform(k2, (2,))
    return a + b


def ok_fold_in(key):
    a = jax.random.normal(jax.random.fold_in(key, 0), (2,))
    b = jax.random.uniform(jax.random.fold_in(key, 1), (2,))
    return a + b


def ok_loop_split(key):
    out = []
    for _ in range(3):
        key, sub = jax.random.split(key)
        out.append(jax.random.normal(sub, (2,)))
    return out


def ok_exclusive_branches(key, flag: bool):
    if flag:
        return jax.random.normal(key, (2,))
    return jax.random.uniform(key, (2,))


def ok_branch_rotation(key, flag: bool):
    # every path re-derives the key, so the draw after the `if` is fresh
    a = jax.random.normal(key, (2,))
    if flag:
        key = jax.random.fold_in(key, 1)
    else:
        key = jax.random.fold_in(key, 2)
    return a + jax.random.normal(key, (2,))
