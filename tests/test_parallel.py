"""Parallelism tests on the virtual 8-device CPU mesh (SURVEY.md §4e).

Ring/Ulysses attention parity vs the dense oracle; data-parallel step
equivalence vs single-device; tp/fsdp sharded DALLE step runs and matches
the replicated step's loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V
from dalle_pytorch_tpu.parallel import (make_mesh, make_train_step,
                                        replicate, ring_attention,
                                        shard_batch, ulysses_attention)
from dalle_pytorch_tpu.parallel.train import (dalle_loss_fn,
                                              dalle_param_specs,
                                              setup_sharded, vae_loss_fn)


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


def dense_oracle(q, k, v, causal):
    s = jnp.einsum("bhid,bhjd->bhij", q, k) * (q.shape[-1] ** -0.5)
    if causal:
        n = s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((n, n), bool))[None, None], s,
                      -jnp.inf)
    return jnp.einsum("bhij,bhjd->bhid", jax.nn.softmax(s, -1), v)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
def test_ring_attention_matches_dense(key, causal):
    mesh = make_mesh({"sp": 8})
    q, k, v = jax.random.normal(key, (3, 2, 4, 64, 16))
    out = ring_attention(q, k, v, mesh=mesh, axis="sp", causal=causal)
    np.testing.assert_allclose(np.array(out),
                               np.array(dense_oracle(q, k, v, causal)),
                               atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
def test_ulysses_attention_matches_dense(key, causal):
    mesh = make_mesh({"sp": 8})
    q, k, v = jax.random.normal(key, (3, 2, 8, 64, 16))
    out = ulysses_attention(q, k, v, mesh=mesh, axis="sp", causal=causal)
    np.testing.assert_allclose(np.array(out),
                               np.array(dense_oracle(q, k, v, causal)),
                               atol=2e-5)


@pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
def test_ring_attention_2d_mesh_with_dp(key):
    mesh = make_mesh({"dp": 2, "sp": 4})
    q, k, v = jax.random.normal(key, (3, 2, 4, 32, 16))
    out = ring_attention(q, k, v, mesh=mesh, axis="sp", causal=True,
                         batch_axis="dp")
    np.testing.assert_allclose(np.array(out),
                               np.array(dense_oracle(q, k, v, True)),
                               atol=2e-5)


def test_ulysses_rejects_indivisible_heads(key):
    mesh = make_mesh({"sp": 8})
    q = k = v = jnp.zeros((1, 4, 16, 8))
    with pytest.raises(ValueError):
        ulysses_attention(q, k, v, mesh=mesh, axis="sp")


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
def test_ulysses_chunked_matches_dense(key, causal):
    """The long-context kv_chunks path (online-softmax folding, no (n, n)
    score matrix) is exact vs the dense oracle, pad mask included."""
    mesh = make_mesh({"sp": 8})
    q, k, v = jax.random.normal(key, (3, 2, 8, 64, 16))
    out = ulysses_attention(q, k, v, mesh=mesh, axis="sp", causal=causal,
                            kv_chunks=8)
    np.testing.assert_allclose(np.array(out),
                               np.array(dense_oracle(q, k, v, causal)),
                               atol=2e-5)
    # with a ragged pad mask: chunked must equal the dense ulysses path
    mask = jnp.ones((2, 64), bool).at[0, 37:].set(False).at[1, 9:].set(False)
    a = ulysses_attention(q, k, v, mesh=mesh, axis="sp", causal=causal,
                          mask=mask, kv_chunks=8)
    b = ulysses_attention(q, k, v, mesh=mesh, axis="sp", causal=causal,
                          mask=mask, kv_chunks=1)
    np.testing.assert_allclose(np.array(a), np.array(b), atol=2e-5)


def test_mesh_validation():
    with pytest.raises(ValueError):
        make_mesh({"dp": 3})


VCFG = V.VAEConfig(image_size=16, num_tokens=32, codebook_dim=32,
                   num_layers=2, hidden_dim=8)
DCFG = D.DALLEConfig(dim=32, depth=2, vae=VCFG, num_text_tokens=50,
                     text_seq_len=8, heads=2, dim_head=16)


def _dalle_batch(key, b=8):
    kt, ki = jax.random.split(key)
    return {
        "text": jax.random.randint(kt, (b, DCFG.text_seq_len), 0, 50),
        "image": jax.random.randint(ki, (b, DCFG.image_seq_len), 0, 32),
    }


@pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
def test_dp_step_matches_single_device(key):
    """Same global batch, dp=8 vs no mesh: identical loss and params."""
    params = D.dalle_init(key, DCFG)
    opt = optax.adam(1e-3)
    loss_fn = dalle_loss_fn(DCFG)
    batch = _dalle_batch(key)

    # single-device reference
    step1 = make_train_step(loss_fn, opt)
    p1, s1, l1 = step1(jax.tree.map(jnp.copy, params), opt.init(params),
                       batch, key)

    mesh = make_mesh({"dp": 8})
    p, s = setup_sharded(jax.tree.map(jnp.copy, params), opt, mesh)
    sharded_batch = shard_batch(mesh, batch)
    step = make_train_step(loss_fn, opt)
    p2, s2, l2 = step(p, s, sharded_batch, key)

    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.array(a), np.array(b), atol=1e-5), p1, p2)


@pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
def test_tp_fsdp_sharded_step_matches_replicated(key):
    params = D.dalle_init(key, DCFG)
    opt = optax.adam(1e-3)
    loss_fn = dalle_loss_fn(DCFG)
    batch = _dalle_batch(key)

    mesh = make_mesh({"dp": 2, "tp": 2, "fsdp": 2})
    specs = dalle_param_specs(params, tp="tp", fsdp="fsdp", mesh=mesh)
    p, s = setup_sharded(jax.tree.map(jnp.copy, params), opt, mesh, specs)
    sharded_batch = shard_batch(mesh, batch)
    step = make_train_step(loss_fn, opt)
    p2, s2, l2 = step(p, s, sharded_batch, key)

    step1 = make_train_step(loss_fn, opt)
    _, _, l1 = step1(jax.tree.map(jnp.copy, params), opt.init(params),
                     batch, key)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-4)
    # sharded params remain finite and correctly shaped
    for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(params)):
        assert a.shape == b.shape
        assert np.isfinite(np.array(a)).all()


@pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
def test_vae_dp_step_runs(key):
    params = V.vae_init(key, VCFG)
    opt = optax.adam(1e-3)
    mesh = make_mesh({"dp": 8})
    p, s = setup_sharded(params, opt, mesh)
    batch = shard_batch(mesh, {
        "images": jax.random.uniform(key, (8, 16, 16, 3), minval=-1,
                                     maxval=1)})
    step = make_train_step(vae_loss_fn(VCFG, smooth_l1=True), opt)
    p, s, loss = step(p, s, batch, key)
    assert np.isfinite(float(loss))


def test_replicate_helper(key):
    mesh = make_mesh({"dp": 8})
    tree = {"a": jnp.ones((4, 4))}
    out = replicate(mesh, tree)
    assert out["a"].sharding.is_fully_replicated


def test_bare_transformer_param_specs_shard(key):
    """A bare transformer tree (no 'transformer' ancestor) gets real tp
    specs — ADVICE r1: the rule used to silently replicate everything."""
    from jax.sharding import PartitionSpec as P

    from dalle_pytorch_tpu.ops.transformer import (TransformerConfig,
                                                   transformer_init)
    cfg = TransformerConfig(dim=32, depth=2, seq_len=16, heads=2,
                            dim_head=16)
    params = transformer_init(key, cfg)
    specs = dalle_param_specs(params, tp="tp")
    assert specs["attn"]["qkv"]["w"] == P(None, None, "tp")
    assert specs["attn"]["out"]["w"] == P(None, "tp", None)
    assert specs["ff"]["w1"]["w"] == P(None, None, "tp")
    assert specs["ff"]["w2"]["w"] == P(None, "tp", None)


def test_setup_sharded_optstate_by_path_not_shape():
    """Restored opt-state moments follow each param's OWN spec even when two
    params share a shape (VERDICT r2 item 7: the old shape-keyed lookup let
    the last equal-shaped param's sharding win for both)."""
    mesh = make_mesh({"tp": 2, "dp": 4})
    params = {"a": jnp.ones((8, 16)), "b": jnp.ones((8, 16))}  # equal shapes
    specs = {"a": P("tp", None), "b": P(None, "tp")}           # different specs
    opt = optax.adam(1e-3)

    # init path establishes the ground-truth placement
    p_init, s_init = setup_sharded(jax.tree.map(jnp.copy, params), opt,
                                   mesh, specs)
    # restore path: host-side opt state placed from scratch
    host_state = jax.device_get(s_init)
    p2, s2 = setup_sharded(jax.tree.map(jnp.copy, params), opt, mesh,
                           specs, opt_state=host_state)

    adam_state = s2[0]
    for moments in (adam_state.mu, adam_state.nu):
        assert moments["a"].sharding.spec == P("tp", None)
        assert moments["b"].sharding.spec == P(None, "tp")
    # scalar counter replicated
    assert adam_state.count.sharding.spec == P()
    # and the step still runs with the restored state
    step = make_train_step(lambda p, b, r: jnp.sum(p["a"]) + jnp.sum(p["b"]),
                           opt)
    batch = shard_batch(mesh, {"x": jnp.zeros((8, 1))})
    p3, s3, loss = step(p2, s2, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# pipeline parallelism
# ---------------------------------------------------------------------------

from dalle_pytorch_tpu.parallel import pipeline_transformer
from dalle_pytorch_tpu.ops.transformer import (TransformerConfig,
                                               transformer_apply,
                                               transformer_init)

_PP_CFG = TransformerConfig(dim=32, depth=4, seq_len=16, heads=2, dim_head=16)


def _pp_setup(depth_cfg=_PP_CFG, batch=8):
    key = jax.random.PRNGKey(0)
    params = transformer_init(key, depth_cfg)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (batch, depth_cfg.seq_len, depth_cfg.dim))
    return params, x


@pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
def test_pipeline_matches_single_device():
    mesh = make_mesh({"pp": 4}, jax.devices()[:4])
    params, x = _pp_setup()
    y_ref = transformer_apply(params, x, cfg=_PP_CFG)
    y_pp = jax.jit(lambda p, x: pipeline_transformer(
        p, x, cfg=_PP_CFG, mesh=mesh))(params, x)
    np.testing.assert_allclose(np.array(y_pp), np.array(y_ref), atol=1e-5)


@pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
def test_pipeline_with_mask_and_more_microbatches():
    mesh = make_mesh({"pp": 2}, jax.devices()[:2])
    params, x = _pp_setup()
    mask = jnp.ones((8, 16), bool).at[:, 12:].set(False)
    y_ref = transformer_apply(params, x, cfg=_PP_CFG, mask=mask)
    y_pp = pipeline_transformer(params, x, cfg=_PP_CFG, mesh=mesh,
                                num_microbatches=4, mask=mask)
    np.testing.assert_allclose(np.array(y_pp), np.array(y_ref), atol=1e-5)


@pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
def test_pipeline_gradients_match():
    mesh = make_mesh({"pp": 4}, jax.devices()[:4])
    params, x = _pp_setup()

    def loss_pp(p):
        return jnp.sum(pipeline_transformer(p, x, cfg=_PP_CFG,
                                            mesh=mesh) ** 2)

    def loss_ref(p):
        return jnp.sum(transformer_apply(p, x, cfg=_PP_CFG) ** 2)

    g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_ref = jax.grad(loss_ref)(params)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.array(a), np.array(b), atol=1e-4)


@pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
def test_pipeline_times_data_parallel():
    mesh = make_mesh({"pp": 2, "dp": 4})
    params, x = _pp_setup()
    y_ref = transformer_apply(params, x, cfg=_PP_CFG)
    y_pp = pipeline_transformer(params, x, cfg=_PP_CFG, mesh=mesh,
                                num_microbatches=2, dp_axis="dp")
    np.testing.assert_allclose(np.array(y_pp), np.array(y_ref), atol=1e-5)


@pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
def test_pipeline_sparse_pattern_stage_invariance():
    cfg = TransformerConfig(
        dim=32, depth=4, seq_len=32, heads=2, dim_head=16,
        sparse_attn=(True, False, True, False), sparse_block=16)
    mesh = make_mesh({"pp": 2}, jax.devices()[:2])
    key = jax.random.PRNGKey(0)
    params = transformer_init(key, cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32))
    y_ref = transformer_apply(params, x, cfg=cfg)
    y_pp = pipeline_transformer(params, x, cfg=cfg, mesh=mesh)
    np.testing.assert_allclose(np.array(y_pp), np.array(y_ref), atol=1e-5)

    # a non-stage-invariant pattern must be rejected loudly
    bad = TransformerConfig(dim=32, depth=4, seq_len=32, heads=2, dim_head=16,
                            sparse_attn=(True, True, False, False))
    params_bad = transformer_init(key, bad)
    with pytest.raises(ValueError, match="stage-invariant"):
        pipeline_transformer(params_bad, x, cfg=bad, mesh=mesh)


@pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
def test_pipeline_dropout_trains():
    """train=True with dropout: deterministic for a fixed rng, differs from
    eval, and the idle-tick cond-skip keeps gradients finite."""
    import dataclasses
    cfg = dataclasses.replace(_PP_CFG, attn_dropout=0.2, ff_dropout=0.2)
    mesh = make_mesh({"pp": 4}, jax.devices()[:4])
    params, x = _pp_setup(cfg)
    rng = jax.random.PRNGKey(3)
    y1 = pipeline_transformer(params, x, cfg=cfg, mesh=mesh, rng=rng,
                              train=True)
    y2 = pipeline_transformer(params, x, cfg=cfg, mesh=mesh, rng=rng,
                              train=True)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    y_eval = pipeline_transformer(params, x, cfg=cfg, mesh=mesh)
    assert not np.allclose(np.asarray(y1), np.asarray(y_eval), atol=1e-3)
    with pytest.raises(ValueError, match="rng"):
        pipeline_transformer(params, x, cfg=cfg, mesh=mesh, train=True)

    g = jax.grad(lambda p: jnp.sum(pipeline_transformer(
        p, x, cfg=cfg, mesh=mesh, rng=rng, train=True) ** 2))(params)
    assert all(bool(jnp.isfinite(leaf).all()) for leaf in jax.tree.leaves(g))


class TestPipelineDALLE:
    def _setup(self):
        from dalle_pytorch_tpu.models import dalle as D
        from dalle_pytorch_tpu.models import vae as V
        vcfg = V.VAEConfig(image_size=16, num_tokens=12, codebook_dim=16,
                           num_layers=2, hidden_dim=8)
        cfg = D.DALLEConfig(dim=16, depth=4, vae=vcfg, num_text_tokens=20,
                            text_seq_len=8, heads=4, dim_head=4)
        params = D.dalle_init(jax.random.PRNGKey(0), cfg)
        key = jax.random.PRNGKey(1)
        # batch 8 over M=4 microbatches of 2, each sharded over dp=2
        batch = {
            "text": jax.random.randint(jax.random.fold_in(key, 1),
                                       (8, 8), 0, 20),
            "image": jax.random.randint(jax.random.fold_in(key, 2),
                                        (8, 16), 0, 12),
        }
        return cfg, params, batch, key

    @pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
    def test_pp_train_step_matches_dense(self):
        """One jit pp train step on a dp x pp mesh with the transformer
        stage-sharded: loss AND gradients match the single-device dense
        path (dropout 0), and the updated params stay finite."""
        import optax
        from dalle_pytorch_tpu.parallel import (make_mesh, make_train_step,
                                                pp_dalle_loss_fn,
                                                pp_param_specs, shard_batch)
        from dalle_pytorch_tpu.parallel.train import (dalle_loss_fn,
                                                      setup_sharded)
        cfg, params, batch, key = self._setup()
        mesh = make_mesh({"dp": 2, "pp": 4})
        opt = optax.adam(1e-3)
        dense_loss, dense_grads = jax.value_and_grad(dalle_loss_fn(cfg))(
            params, batch, key)

        params, opt_state = setup_sharded(params, opt, mesh,
                                          param_specs=pp_param_specs(params))
        loss_fn = pp_dalle_loss_fn(cfg, mesh, dp_axis="dp")
        pp_loss, pp_grads = jax.jit(jax.value_and_grad(loss_fn))(
            params, shard_batch(mesh, batch, axis="dp"), key)
        np.testing.assert_allclose(float(pp_loss), float(dense_loss),
                                   rtol=1e-5)
        for a, b in zip(jax.tree.leaves(pp_grads),
                        jax.tree.leaves(dense_grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)

        step = make_train_step(loss_fn, opt)
        new_params, _, loss = step(params, opt_state,
                                   shard_batch(mesh, batch, axis="dp"), key)
        np.testing.assert_allclose(float(loss), float(dense_loss), rtol=1e-5)
        assert all(bool(jnp.isfinite(leaf).all())
                   for leaf in jax.tree.leaves(new_params))

    def test_pp_rejects_reversible(self):
        import dataclasses
        from dalle_pytorch_tpu.parallel import make_mesh, pp_dalle_loss_fn
        cfg, _, _, _ = self._setup()
        cfg = dataclasses.replace(cfg, reversible=True)
        mesh = make_mesh({"pp": 4}, jax.devices()[:4])
        with pytest.raises(NotImplementedError):
            pp_dalle_loss_fn(cfg, mesh)

    @pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
    def test_pp_moe_three_axis_matches_dense(self):
        """dp x pp x ep in ONE program (VERDICT r4 weak item 6: pp
        excluded MoE): the GPipe tick scan threads the MoE aux loss,
        the expert axis rides the pipeline's shard_map as a GSPMD auto
        axis, and loss + grads match the single-device dense MoE path."""
        import dataclasses

        import optax
        from dalle_pytorch_tpu.parallel import (make_mesh, make_train_step,
                                                pp_dalle_loss_fn,
                                                pp_param_specs, shard_batch)
        from dalle_pytorch_tpu.parallel.train import (dalle_loss_fn,
                                                      setup_sharded)
        cfg, _, batch, key = self._setup()
        cfg = dataclasses.replace(cfg, moe_experts=4, moe_k=2)
        params = D.dalle_init(jax.random.PRNGKey(0), cfg)
        mesh = make_mesh({"dp": 2, "pp": 2, "ep": 2})
        opt = optax.adam(1e-3)
        dense_loss, dense_grads = jax.value_and_grad(dalle_loss_fn(cfg))(
            params, batch, key)

        params, opt_state = setup_sharded(
            params, opt, mesh,
            param_specs=pp_param_specs(params, ep="ep"))
        loss_fn = pp_dalle_loss_fn(cfg, mesh, dp_axis="dp")
        pp_loss, pp_grads = jax.jit(jax.value_and_grad(loss_fn))(
            params, shard_batch(mesh, batch, axis="dp"), key)
        np.testing.assert_allclose(float(pp_loss), float(dense_loss),
                                   rtol=1e-5)
        for a, b in zip(jax.tree.leaves(pp_grads),
                        jax.tree.leaves(dense_grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4)


# ---------------------------------------------------------------------------
# sequence-parallel transformer stack (parallel/sequence.py)
# ---------------------------------------------------------------------------

class TestSequenceParallelStack:
    def _stack(self, depth=2, dim=16, seq=32):
        from dalle_pytorch_tpu.ops.transformer import (TransformerConfig,
                                                       transformer_init)
        cfg = TransformerConfig(dim=dim, depth=depth, seq_len=seq, heads=4,
                                dim_head=8, causal=True)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, seq, dim))
        return cfg, params, x

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    @pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
    def test_matches_single_device_stack(self, impl):
        from dalle_pytorch_tpu.ops.transformer import transformer_apply
        from dalle_pytorch_tpu.parallel import (make_mesh,
                                                sp_transformer_apply)
        cfg, params, x = self._stack()
        mesh = make_mesh({"sp": 4}, jax.devices()[:4])
        y_sp = sp_transformer_apply(params, x, cfg=cfg, mesh=mesh,
                                    impl=impl)
        y_ref = transformer_apply(params, x, cfg=cfg)
        np.testing.assert_allclose(np.asarray(y_sp), np.asarray(y_ref),
                                   atol=2e-5)

    @pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
    def test_dp_times_sp_mesh(self):
        from dalle_pytorch_tpu.ops.transformer import transformer_apply
        from dalle_pytorch_tpu.parallel import (make_mesh,
                                                sp_transformer_apply)
        cfg, params, x = self._stack()
        mesh = make_mesh({"dp": 2, "sp": 4})
        y_sp = sp_transformer_apply(params, x, cfg=cfg, mesh=mesh,
                                    batch_axis="dp")
        y_ref = transformer_apply(params, x, cfg=cfg)
        np.testing.assert_allclose(np.asarray(y_sp), np.asarray(y_ref),
                                   atol=2e-5)

    @pytest.mark.parametrize("mode", ["save_ln", "dots", "full"])
    @pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
    def test_remat_composes_with_sp(self, mode):
        """Long-context training needs sequence sharding AND activation
        thrift in one program (VERDICT r4 item 7): under every remat mode
        the sp stack's loss AND grads match the un-rematerialized
        single-device path (f32, so the recompute is deterministic)."""
        import dataclasses
        from dalle_pytorch_tpu.ops.transformer import transformer_apply
        from dalle_pytorch_tpu.parallel import (make_mesh,
                                                sp_transformer_apply)
        cfg, params, x = self._stack()
        cfg_r = dataclasses.replace(cfg, remat=mode)
        mesh = make_mesh({"sp": 4}, jax.devices()[:4])

        def loss_sp(p):
            return jnp.sum(sp_transformer_apply(p, x, cfg=cfg_r,
                                                mesh=mesh) ** 2)

        def loss_ref(p):
            return jnp.sum(transformer_apply(p, x, cfg=cfg) ** 2)

        l1, g1 = jax.value_and_grad(loss_ref)(params)
        # jit is required: a named-policy jax.checkpoint inside shard_map
        # cannot evaluate eagerly (closed_call), and real training always
        # runs the step under jit anyway
        l2, g2 = jax.jit(jax.value_and_grad(loss_sp))(params)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5), g1, g2)

    @pytest.mark.skipif(
        not __import__("dalle_pytorch_tpu.parallel._compat",
                       fromlist=["x"]).SUPPORTS_PARTIAL_MANUAL,
        reason="partial-manual shard_map (tp as auto axis) requires "
               "jax>=0.8 (parallel/_compat.py)")
    def test_three_axis_dp_tp_sp(self):
        """dp x tp x sp in ONE program (VERDICT r4 item 7): the shard_map
        is manual over dp/sp only, so Megatron-tp param shardings ride
        through as GSPMD auto axes — output matches the single-device
        dense stack."""
        from jax.sharding import NamedSharding

        from dalle_pytorch_tpu.ops.transformer import transformer_apply
        from dalle_pytorch_tpu.parallel import (make_mesh,
                                                sp_transformer_apply)
        from dalle_pytorch_tpu.parallel.train import dalle_param_specs
        cfg, params, x = self._stack()
        mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
        specs = dalle_param_specs(params, tp="tp")
        params = jax.tree.map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
            params, specs)
        x = jax.device_put(x, NamedSharding(mesh, P("dp", "sp", None)))
        y_sp = jax.jit(lambda p, x: sp_transformer_apply(
            p, x, cfg=cfg, mesh=mesh, batch_axis="dp"))(params, x)
        y_ref = transformer_apply(jax.device_get(params),
                                  jax.device_get(x), cfg=cfg)
        np.testing.assert_allclose(np.asarray(y_sp), np.asarray(y_ref),
                                   atol=2e-5)

    def test_rejects_sparse_reversible(self):
        import dataclasses
        from dalle_pytorch_tpu.parallel import (make_mesh,
                                                sp_transformer_apply)
        cfg, params, x = self._stack()
        mesh = make_mesh({"sp": 4}, jax.devices()[:4])
        for bad in ({"sparse_attn": True}, {"reversible": True}):
            with pytest.raises(ValueError):
                sp_transformer_apply(params, x,
                                     cfg=dataclasses.replace(cfg, **bad),
                                     mesh=mesh)

    def test_dropout_requires_rng(self):
        import dataclasses
        from dalle_pytorch_tpu.parallel import (make_mesh,
                                                sp_transformer_apply)
        cfg, params, x = self._stack()
        mesh = make_mesh({"sp": 4}, jax.devices()[:4])
        with pytest.raises(ValueError, match="rng"):
            sp_transformer_apply(
                params, x, cfg=dataclasses.replace(cfg, ff_dropout=0.1),
                mesh=mesh, train=True)

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    @pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
    def test_dropout_invariant_to_sp_degree(self, impl):
        """Same rng -> bit-identical dropout masks on sp=2 and sp=4 (the
        positional key discipline), so outputs agree to float tolerance."""
        import dataclasses
        from dalle_pytorch_tpu.parallel import (make_mesh,
                                                sp_transformer_apply)
        cfg, params, x = self._stack()
        cfg = dataclasses.replace(cfg, attn_dropout=0.2, ff_dropout=0.2)
        rng = jax.random.PRNGKey(7)
        outs = []
        for sp in (2, 4):
            mesh = make_mesh({"sp": sp}, jax.devices()[:sp])
            outs.append(sp_transformer_apply(params, x, cfg=cfg, mesh=mesh,
                                             impl=impl, rng=rng, train=True))
        np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(outs[1]),
                                   atol=2e-5)
        # dropout actually fired: train=False differs
        y_eval = sp_transformer_apply(
            params, x, cfg=cfg, mesh=make_mesh({"sp": 4}, jax.devices()[:4]),
            impl=impl)
        assert not np.allclose(np.asarray(outs[1]), np.asarray(y_eval),
                               atol=1e-3)


class TestSequenceParallelDALLE:
    @pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
    def test_sp_train_step_matches_dense_loss(self):
        """One jit sp train step on a dp x sp mesh: loss equals the
        single-device dense loss on the same params/batch, and params
        update finitely."""
        import optax
        from dalle_pytorch_tpu.models import dalle as D
        from dalle_pytorch_tpu.models import vae as V
        from dalle_pytorch_tpu.parallel import (make_mesh, make_train_step,
                                                shard_batch,
                                                sp_dalle_loss_fn)
        from dalle_pytorch_tpu.parallel.train import (dalle_loss_fn,
                                                      setup_sharded)
        vcfg = V.VAEConfig(image_size=16, num_tokens=12, codebook_dim=16,
                           num_layers=2, hidden_dim=8)
        cfg = D.DALLEConfig(dim=16, depth=2, vae=vcfg, num_text_tokens=20,
                            text_seq_len=8, heads=4, dim_head=4)
        # seq_len = 8 + 16 = 24, sp=4 -> 6-token shards
        mesh = make_mesh({"dp": 2, "sp": 4})
        params = D.dalle_init(jax.random.PRNGKey(0), cfg)
        opt = optax.adam(1e-3)
        params, opt_state = setup_sharded(params, opt, mesh)
        key = jax.random.PRNGKey(1)
        batch = {
            "text": jax.random.randint(jax.random.fold_in(key, 1),
                                       (4, 8), 0, 20),
            "image": jax.random.randint(jax.random.fold_in(key, 2),
                                        (4, 16), 0, 12),
        }
        dense = dalle_loss_fn(cfg)(params, batch, key)

        batch_sp = shard_batch(mesh, batch, axis="dp")
        step = make_train_step(
            sp_dalle_loss_fn(cfg, mesh, batch_axis="dp"), opt)
        new_params, _, loss = step(params, opt_state, batch_sp, key)
        np.testing.assert_allclose(float(loss), float(dense), rtol=1e-5)
        assert all(bool(jnp.isfinite(leaf).all())
                   for leaf in jax.tree.leaves(new_params))


class TestSequenceParallelMask:
    """Pad-mask semantics under SP must match the dense path bit-for-bit:
    pair fill is the finite -fmax, causal fill is -inf (masked rows
    degrade to a causal-prefix average)."""

    @pytest.mark.parametrize("impl", ["ring", "ulysses"])
    @pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
    def test_masked_stack_matches_dense(self, impl):
        from dalle_pytorch_tpu.ops.transformer import (TransformerConfig,
                                                       transformer_apply,
                                                       transformer_init)
        from dalle_pytorch_tpu.parallel import (make_mesh,
                                                sp_transformer_apply)
        cfg = TransformerConfig(dim=16, depth=2, seq_len=32, heads=4,
                                dim_head=8, causal=True)
        params = transformer_init(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 16))
        # ragged pad masks crossing shard boundaries
        mask = jnp.ones((2, 32), bool).at[0, 5:].set(False) \
                                      .at[1, 19:].set(False)
        mesh = make_mesh({"sp": 4}, jax.devices()[:4])
        y_sp = sp_transformer_apply(params, x, cfg=cfg, mesh=mesh,
                                    impl=impl, mask=mask)
        y_ref = transformer_apply(params, x, cfg=cfg, mask=mask)
        np.testing.assert_allclose(np.asarray(y_sp), np.asarray(y_ref),
                                   atol=2e-5)

    @pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
    def test_masked_sp_dalle_loss_matches_dense(self):
        from dalle_pytorch_tpu.models import dalle as D
        from dalle_pytorch_tpu.models import vae as V
        from dalle_pytorch_tpu.parallel import (make_mesh, shard_batch,
                                                sp_dalle_loss_fn)
        from dalle_pytorch_tpu.parallel.train import dalle_loss_fn
        vcfg = V.VAEConfig(image_size=16, num_tokens=12, codebook_dim=16,
                           num_layers=2, hidden_dim=8)
        cfg = D.DALLEConfig(dim=16, depth=2, vae=vcfg, num_text_tokens=20,
                            text_seq_len=8, heads=4, dim_head=4)
        mesh = make_mesh({"dp": 2, "sp": 4})
        params = D.dalle_init(jax.random.PRNGKey(0), cfg)
        key = jax.random.PRNGKey(1)
        batch = {
            "text": jax.random.randint(jax.random.fold_in(key, 1),
                                       (4, 8), 0, 20),
            "image": jax.random.randint(jax.random.fold_in(key, 2),
                                        (4, 16), 0, 12),
            "mask": jnp.ones((4, 8), bool).at[:, 5:].set(False),
        }
        dense = dalle_loss_fn(cfg)(params, batch, key)
        sp = sp_dalle_loss_fn(cfg, mesh, batch_axis="dp")(
            params, shard_batch(mesh, batch, axis="dp"), key)
        np.testing.assert_allclose(float(sp), float(dense), rtol=1e-5)


class TestGradAccumulation:
    def test_accum_step_matches_full_batch(self):
        """grad_accum=2 must produce the same update as the full batch (the
        loss is an example mean), scalars passing through unsplit."""
        import optax
        from dalle_pytorch_tpu.parallel import make_mesh, make_train_step
        from dalle_pytorch_tpu.parallel.train import setup_sharded

        def loss_fn(params, batch, rng):
            pred = batch["x"] @ params["w"] * batch["scale"]
            return jnp.mean((pred - batch["y"]) ** 2)

        opt = optax.sgd(0.1)
        mesh = make_mesh({"dp": 1}, jax.devices()[:1])
        # fresh buffers per run: device_put aliases identical arrays and
        # the steps donate their inputs
        p1, s1 = setup_sharded({"w": jnp.ones((4, 3)) * 0.5}, opt, mesh)
        p2, s2 = setup_sharded({"w": jnp.ones((4, 3)) * 0.5}, opt, mesh)
        key = jax.random.PRNGKey(0)
        batch = {"x": jax.random.normal(key, (8, 4)),
                 "y": jax.random.normal(jax.random.PRNGKey(1), (8, 3)),
                 "scale": jnp.float32(2.0)}

        full = make_train_step(loss_fn, opt)
        accum = make_train_step(loss_fn, opt, grad_accum=2)
        p1, _, l1 = full(p1, s1, batch, key)
        p2, _, l2 = accum(p2, s2, batch, key)
        # microbatch mean-of-means == full mean for equal microbatches
        np.testing.assert_allclose(float(l2), float(l1), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(p2["w"]), np.asarray(p1["w"]),
                                   atol=1e-6)

    @pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
    def test_sp_with_chunked_ce_matches_dense(self):
        """loss_chunk composes with sequence parallelism (the chunked head
        runs under GSPMD on the sp-sharded activations)."""
        import dataclasses
        from dalle_pytorch_tpu.models import dalle as D
        from dalle_pytorch_tpu.models import vae as V
        from dalle_pytorch_tpu.parallel import (make_mesh, shard_batch,
                                                sp_dalle_loss_fn)
        from dalle_pytorch_tpu.parallel.train import dalle_loss_fn
        vcfg = V.VAEConfig(image_size=16, num_tokens=12, codebook_dim=16,
                           num_layers=2, hidden_dim=8)
        cfg = D.DALLEConfig(dim=16, depth=2, vae=vcfg, num_text_tokens=20,
                            text_seq_len=8, heads=4, dim_head=4,
                            loss_chunk=5)
        mesh = make_mesh({"dp": 2, "sp": 4})
        params = D.dalle_init(jax.random.PRNGKey(0), cfg)
        key = jax.random.PRNGKey(1)
        batch = {"text": jax.random.randint(jax.random.fold_in(key, 1),
                                            (4, 8), 0, 20),
                 "image": jax.random.randint(jax.random.fold_in(key, 2),
                                             (4, 16), 0, 12)}
        dense = dalle_loss_fn(dataclasses.replace(cfg, loss_chunk=0))(
            params, batch, key)
        sp = sp_dalle_loss_fn(cfg, mesh, batch_axis="dp")(
            params, shard_batch(mesh, batch, axis="dp"), key)
        np.testing.assert_allclose(float(sp), float(dense), rtol=1e-5)


class TestShardedGeneration:
    @pytest.mark.slow  # tier-1 time budget: compile-heavy on the single-core CPU container (full parity kept in CI's full run)
    def test_generate_images_shards_over_dp(self):
        """The rerank workflow at reference scale (sample many, keep best —
        reference README samples 512) runs the jit KV-cache sampler with
        the candidate batch sharded over dp; GSPMD partitions the whole
        program (prefill, decode scan, VAE decode) with no code changes."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        from dalle_pytorch_tpu.models import dalle as D
        from dalle_pytorch_tpu.models import vae as V
        from dalle_pytorch_tpu.parallel import make_mesh

        vcfg = V.VAEConfig(image_size=16, num_tokens=12, codebook_dim=16,
                           num_layers=2, hidden_dim=8)
        cfg = D.DALLEConfig(dim=16, depth=2, vae=vcfg, num_text_tokens=20,
                            text_seq_len=6, heads=2, dim_head=8)
        params = D.dalle_init(jax.random.PRNGKey(0), cfg)
        vae_params = V.vae_init(jax.random.PRNGKey(1), vcfg)
        mesh = make_mesh({"dp": 8})

        text = jnp.tile(jnp.arange(6)[None, :], (16, 1))   # 16 candidates
        text = jax.device_put(text, NamedSharding(mesh, P("dp", None)))
        params = jax.device_put(params, NamedSharding(mesh, P()))
        vae_params = jax.device_put(vae_params, NamedSharding(mesh, P()))

        gen = jax.jit(lambda p, vp, t, rng: D.generate_images(
            p, vp, t, cfg=cfg, rng=rng, return_img_seq=True))
        images, img_seq = gen(params, vae_params, text,
                              jax.random.PRNGKey(2))
        assert images.shape == (16, 16, 16, 3)
        # the program ran across all 8 mesh devices, not gathered to one
        assert len(images.sharding.device_set) == 8
        assert bool(jnp.isfinite(images).all())
