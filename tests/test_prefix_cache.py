"""Cross-request prefix cache + per-request CFG tests (ISSUE 13).

The load-bearing contracts:

  * WARM-HIT BYTE-IDENTITY: a prompt admitted through the prefix cache's
    warm path (shared pages mapped refcounted, boundary page forked
    copy-on-write, first token sampled from the cached last hidden row —
    zero prefill FLOPs) emits tokens byte-identical to a cold run of the
    same request, across fused chunk sizes K, both paged-attention
    impls (gather / Pallas kernel in interpret mode), and both cache
    dtypes (fp32 / int8) — with ``decode_traces == 1`` and the warm
    steady state transfer-clean under ``guards.no_transfers``.
  * REFCOUNTED COW SAFETY: a page mapped by several block tables (or
    held by the index) returns to the free list only at refcount zero —
    eviction of one sharer must never hand a sibling's page to the next
    allocation (the satellite bugfix), and release past zero is the
    typed ``PageReleaseUnderflow``.
  * PER-REQUEST CFG: ``Request.cfg_scale > 0`` admits a cond/uncond
    slot pair whose emitted tokens are byte-identical to
    ``generate_images(guidance=scale)``, with the guided mix inside the
    ONE fused decode program, pair-atomic teardown, and (with the
    prefix cache) physical sharing of every cacheable prompt span.
  * FAULT COMPOSITION: a replica crash mid-decode replays a CFG pair on
    a survivor with byte-identical tokens (the fault-catalog row the
    satellite names).

All CPU, tiny model (total_len 24) so the file stays cheap in tier-1.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.analysis import guards
from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V
from dalle_pytorch_tpu.serve import (ERROR, OK, PageAllocator,
                                     PageReleaseUnderflow, PrefixEntry,
                                     PrefixIndex, Request, RequestQueue,
                                     SamplingParams, pages_for)
from dalle_pytorch_tpu.serve.engine import Engine

VCFG = V.VAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                   num_layers=2, hidden_dim=8)
CFG = D.DALLEConfig(dim=16, depth=2, vae=VCFG, num_text_tokens=50,
                    text_seq_len=8, heads=2, dim_head=8)

# len-8 prompt: two FULL pages at page_size 4 (physical sharing), one
# full page at page_size 8 (the kernel's tile minimum); len-5 prompt:
# exercises the partial-boundary COW snapshot at both page sizes
P8 = (4, 1, 2, 3, 5, 6, 7, 2)
P5 = (5, 2, 8, 1, 4)


@pytest.fixture(scope="module")
def bundle():
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG)
    params = D.dalle_init(key, CFG, vae_params)
    return params, vae_params


_REF_CACHE: dict = {}


def reference_tokens(params, vae_params, req: Request,
                     quantize_cache: bool = False) -> np.ndarray:
    """generate_images at batch 1 (``guidance=req.cfg_scale``) — the
    one-shot stream warm hits, cold runs, and guided pairs must all
    reproduce token-for-token. Memoized on the sampling identity."""
    key = (req.codes, req.seed, req.sampling.temperature,
           req.sampling.filter_thres, req.sampling.top_p,
           req.cfg_scale, quantize_cache)
    if key not in _REF_CACHE:
        text = jnp.asarray([req.codes], jnp.int32)
        _, img_seq = D.generate_images(
            params, vae_params, text, cfg=CFG,
            rng=jax.random.PRNGKey(req.seed),
            filter_thres=req.sampling.filter_thres,
            top_p=req.sampling.top_p,
            temperature=req.sampling.temperature,
            guidance=req.cfg_scale,
            quantize_cache=quantize_cache, return_img_seq=True)
        _REF_CACHE[key] = np.asarray(img_seq)[0]
    return _REF_CACHE[key]


def drain_tokens(engine, queue, reqs, timeout=30):
    handles = [queue.submit(r) for r in reqs]
    engine.run_until_idle()
    out = []
    for h in handles:
        res = h.result(timeout=timeout)
        assert res.status == OK, (res.status, res.reason)
        out.append(np.asarray(res.tokens))
    return out


class TestRefcountedAllocator:
    def test_retain_release_frees_only_at_zero(self):
        alloc = PageAllocator(6)
        pages = alloc.alloc(3)
        assert alloc.in_use == 3 and alloc.pages_shared == 0
        alloc.retain(pages[:2])
        assert alloc.pages_shared == 2
        assert alloc.refs_saved == 2
        # in_use counts PHYSICAL pages: sharing never inflates it
        assert alloc.in_use == 3
        alloc.release(pages)            # first reference drops
        assert alloc.in_use == 2        # only the unshared page freed
        assert alloc.free == 3
        alloc.release(pages[:2])        # second reference drops
        assert alloc.in_use == 0 and alloc.free == 5

    def test_release_past_zero_is_typed_underflow(self):
        alloc = PageAllocator(4)
        pages = alloc.alloc(1)
        alloc.release(pages)
        with pytest.raises(PageReleaseUnderflow, match="double release"):
            alloc.release(pages)
        rec = pytest.raises(
            PageReleaseUnderflow, alloc.release, pages).value.record
        assert rec["kind"] == "serve_page_release_underflow"
        assert rec["page"] == pages[0]
        # the underflow is still a ValueError: pre-refcount callers that
        # matched the double-release guard keep matching
        assert isinstance(PageReleaseUnderflow(rec), ValueError)

    def test_retain_of_free_page_is_hard_error(self):
        alloc = PageAllocator(4)
        pages = alloc.alloc(1)
        alloc.release(pages)
        with pytest.raises(ValueError, match="retain of free page"):
            alloc.retain(pages)
        with pytest.raises(ValueError, match="never allocatable"):
            alloc.retain([0])           # the trash page

    def test_shared_page_survives_one_owners_release(self):
        """The eviction-victim bugfix in allocator form: two owners map
        one page; the first teardown must NOT return it to the free
        list — the next alloc must hand out a DIFFERENT page."""
        alloc = PageAllocator(8)
        (shared,) = alloc.alloc(1)
        alloc.retain([shared])
        alloc.release([shared])         # owner 1 (the eviction victim)
        fresh = alloc.alloc(3)
        assert shared not in fresh, \
            "a still-referenced page was handed to a new owner"
        alloc.release([shared])         # owner 2 -> now truly free


class TestPrefixIndexUnit:
    def _entry(self, alloc, key, codes, pages):
        return PrefixEntry(key, codes, len(codes), pages, None,
                           h_last=None)

    def test_collision_reads_as_miss_never_wrong_kv(self):
        alloc = PageAllocator(8)
        idx = PrefixIndex(alloc)
        pages = alloc.alloc(2)
        idx.insert(self._entry(alloc, "k1", (1, 2, 3), pages))
        assert idx.lookup("k1", (1, 2, 3)) is not None
        # same key, different tokens (a hash collision): MISS — the
        # stored tuple verifies what the hash only addresses
        assert idx.lookup("k1", (9, 9, 9)) is None

    def test_lru_capacity_and_shrink_release_references(self):
        alloc = PageAllocator(16)
        idx = PrefixIndex(alloc, max_entries=2)
        held = []
        for i in range(3):
            pages = alloc.alloc(2)
            held.append(pages)
            idx.insert(self._entry(alloc, f"k{i}", (i,), pages))
            alloc.release(pages)        # the "slot" reference drops
        # capacity 2: k0 was evicted LRU, its pages truly freed
        assert len(idx) == 2
        assert idx.lookup("k0", (0,)) is None
        assert alloc.in_use == 4
        # shrink until 20 pages would be free -> drops everything
        idx.shrink(20)
        assert len(idx) == 0 and alloc.in_use == 0

    def test_engine_gate_prefix_requires_paged(self, bundle):
        params, _ = bundle
        with pytest.raises(ValueError, match="prefix_cache requires"):
            Engine(params, CFG, RequestQueue(max_depth=2), num_slots=1,
                   prefix_cache=True)


class TestWarmHitEquivalence:
    """The tentpole acceptance: warm-hit tokens byte-identical to a
    cold run, across K x paged-attention impl x cache dtype — and the
    warm path genuinely skips prefill (``prefill_runs`` frozen)."""

    @pytest.mark.parametrize("k,impl,quant", [
        (1, "gather", False),
        (8, "gather", False),
        (1, "kernel", False),
        (8, "kernel", False),
        (8, "gather", True),
        (8, "kernel", True),
    ])
    def test_warm_hit_tokens_byte_identical_to_cold(self, bundle, k,
                                                    impl, quant):
        params, vae_params = bundle
        # gather at page_size 4 exercises 2-full-page sharing AND the
        # boundary snapshot (P5); the kernel's 8-row tile floor makes
        # P8 one full shared page and P5 snapshot-only
        ps = 4 if impl == "gather" else 8
        reqs = [Request(codes=P8, seed=3), Request(codes=P5, seed=7),
                Request(codes=P8, seed=11), Request(codes=P5, seed=13)]
        cold_q = RequestQueue(max_depth=8)
        cold_e = Engine(params, CFG, cold_q, num_slots=2, chunk_steps=k,
                        kv="paged", page_size=ps, paged_attn=impl,
                        quantize_cache=quant)
        cold = drain_tokens(cold_e, cold_q, reqs)

        q = RequestQueue(max_depth=8)
        e = Engine(params, CFG, q, num_slots=2, chunk_steps=k,
                   kv="paged", page_size=ps, paged_attn=impl,
                   quantize_cache=quant, prefix_cache=True)
        # cold pass populates the index...
        warm0 = drain_tokens(e, q, reqs[:2])
        runs_after_cold = e.prefill_runs
        # ...and the second pass of the SAME prompts admits warm: zero
        # prefill dispatches, tokens byte-identical to the cold engine
        warm1 = drain_tokens(e, q, reqs[2:])
        assert e.prefill_runs == runs_after_cold, \
            "warm hits must not dispatch prefill"
        assert e.prefix_hits == 2
        assert e.warm_admits == 2
        assert e.decode_traces == 1
        assert e.warm_admit_traces == 1
        for got, want in zip(warm0 + warm1, cold):
            np.testing.assert_array_equal(got, want)
        # fp32 gather additionally pins the one-shot oracle directly
        if impl == "gather" and not quant:
            for got, r in zip(warm0 + warm1, reqs):
                np.testing.assert_array_equal(
                    got, reference_tokens(params, vae_params, r))

    def test_warm_admission_is_transfer_clean(self, bundle):
        """Steady state with a WARM mid-stream join under
        ``guards.no_transfers``: shared-page mapping, the COW boundary
        fork, and the warm-admission program are all explicit device
        traffic — and the fused decode program never retraces."""
        params, vae_params = bundle
        q = RequestQueue(max_depth=8)
        e = Engine(params, CFG, q, num_slots=2, chunk_steps=4,
                   kv="paged", page_size=4, prefix_cache=True)
        drain_tokens(e, q, [Request(codes=P8, seed=1)])   # seed index
        drain_tokens(e, q, [Request(codes=P8, seed=2)])   # warm compile
        h_a = q.submit(Request(codes=(3, 7, 9), seed=3))
        e.step_once()               # a admitted, chunk 1 in flight
        with guards.no_transfers():
            h_b = q.submit(Request(codes=P8, seed=4))
            e.step_once()           # WARM join + chunk + harvest
            e.step_once()           # pure steady-state chunk
        e.run_until_idle()
        np.testing.assert_array_equal(
            np.asarray(h_b.result(timeout=5).tokens),
            reference_tokens(params, vae_params,
                             Request(codes=P8, seed=4)))
        assert h_a.result(timeout=5).status == OK
        assert e.decode_traces == 1

    def test_fanout_same_batch_shares_prompt_span_once(self, bundle):
        """N samples of ONE prompt submitted together: the first row
        prefills cold and inserts; its siblings admit warm IN THE SAME
        admission — the shared span is allocated once, and peak pages
        obey pages(1 request) + N x pages(private span)."""
        params, vae_params = bundle
        ps, n = 4, 3
        q = RequestQueue(max_depth=8)
        e = Engine(params, CFG, q, num_slots=n, kv="paged", page_size=ps,
                   prefix_cache=True)
        reqs = [Request(codes=P8, seed=s) for s in (1, 2, 3)]
        handles = [q.submit(r) for r in reqs]
        e.step_once()
        assert e.active_slots() == n
        assert e.prefix_hits == n - 1      # one cold, two warm-after
        shared_full = len(P8) // ps
        st = e.stats()
        assert st["pages_shared"] == shared_full
        full = pages_for(CFG.seq_len, ps)
        # physical accounting mid-decode: never more than one full map
        # plus (n-1) private spans (map-ahead grows lazily below that)
        assert e.alloc.in_use <= full + (n - 1) * (full - shared_full)
        e.run_until_idle()
        # peak: the shared span was allocated ONCE — one full request
        # plus n-1 private (generated + boundary) spans, strictly under
        # the refcount-blind n x full
        assert e.alloc.peak_in_use \
            == full + (n - 1) * (full - shared_full)
        assert e.alloc.peak_in_use <= full + n * (full - shared_full)
        for h, r in zip(handles, reqs):
            np.testing.assert_array_equal(
                np.asarray(h.result(timeout=5).tokens),
                reference_tokens(params, vae_params, r))
        # drained: only the index's own references remain resident
        assert e.alloc.in_use == shared_full
        assert e.prefix.pages_held == shared_full

    def test_cow_fork_under_mid_decode_eviction(self, bundle):
        """The COW fork x eviction composition (satellite): two sharers
        of one prompt span on a pool too small for both to finish — the
        victim's release must NOT free the still-shared pages (the
        sibling keeps decoding against them), and the victim replays to
        the exact cold stream after re-admission."""
        params, vae_params = bundle
        reqs = [Request(codes=P8, seed=1),
                Request(codes=P8, seed=2, priority=7)]   # the victim
        q = RequestQueue(max_depth=8)
        # 6 pages/full sequence at ps 4; 9 usable is a genuine
        # overcommit for two mid-sequence requests sharing 2
        e = Engine(params, CFG, q, num_slots=2, chunk_steps=4,
                   kv="paged", page_size=4, num_pages=10,
                   prefix_cache=True)
        handles = [q.submit(r) for r in reqs]
        with guards.compile_count(lambda: e.decode_traces, expect=1,
                                  label="decode under COW eviction"):
            e.run_until_idle()
        assert e.evicted >= 1, "pool was sized to force eviction"
        for h, r in zip(handles, reqs):
            res = h.result(timeout=5)
            assert res.status == OK
            np.testing.assert_array_equal(
                np.asarray(res.tokens),
                reference_tokens(params, vae_params, r))
        # the shared span survived every teardown exactly as the
        # index's references say it should
        assert e.alloc.in_use == e.prefix.pages_held

    def test_index_shrinks_before_live_request_eviction(self, bundle):
        """Page pressure drops cached prefixes (LRU) FIRST: with the
        pool nearly full of index-held entries, a fresh admission must
        shrink the cache instead of deferring or evicting live work."""
        params, vae_params = bundle
        q = RequestQueue(max_depth=8)
        e = Engine(params, CFG, q, num_slots=2, chunk_steps=24,
                   kv="paged", page_size=4, num_pages=8,
                   prefix_cache=True)
        drain_tokens(e, q, [Request(codes=P8, seed=1)])
        assert len(e.prefix) == 1
        # capacity 7, index holds 2; a full-sequence admission needs 6
        got = drain_tokens(e, q, [Request(codes=(1, 2, 3, 4, 5, 6),
                                          seed=9)])[0]
        np.testing.assert_array_equal(
            got, reference_tokens(params, vae_params,
                                  Request(codes=(1, 2, 3, 4, 5, 6),
                                          seed=9)))
        assert e.evicted == 0, \
            "cache entries must be dropped before live work"


class TestPerRequestCFG:
    def test_guided_tokens_match_one_shot_guidance(self, bundle):
        """cfg_scale through the engine == generate_images(guidance=s),
        byte-for-byte, on both KV layouts — with one decode compile."""
        params, vae_params = bundle
        req = Request(codes=P5, seed=11, cfg_scale=2.0)
        ref = reference_tokens(params, vae_params, req)
        for kw in (dict(kv="paged", page_size=4, prefix_cache=True),
                   dict()):
            q = RequestQueue(max_depth=4)
            e = Engine(params, CFG, q, num_slots=2, **kw)
            with guards.compile_count(lambda: e.decode_traces, expect=1,
                                      label="guided decode program"):
                got = drain_tokens(e, q, [req])[0]
            np.testing.assert_array_equal(got, ref)
            assert e.cfg_pairs == 1
            assert e.stats()["cfg_pairs"] == 1

    def test_guided_and_plain_share_the_pool(self, bundle):
        """A guided pair and plain requests decode side by side in one
        slot pool — each stream exact, shadow tokens never credited."""
        params, vae_params = bundle
        reqs = [Request(codes=P5, seed=11, cfg_scale=1.5),
                Request(codes=(3, 7, 9), seed=5),
                Request(codes=(6, 6), seed=23,
                        sampling=SamplingParams(temperature=0.7))]
        q = RequestQueue(max_depth=8)
        e = Engine(params, CFG, q, num_slots=3, kv="paged", page_size=4)
        got = drain_tokens(e, q, reqs)
        for g, r in zip(got, reqs):
            np.testing.assert_array_equal(
                g, reference_tokens(params, vae_params, r))
        # tokens_decoded counts DELIVERED tokens: the uncond shadow's
        # mirrored stream must not double-count
        assert e.tokens_decoded == sum(
            CFG.seq_len - len(r.codes) for r in reqs)
        assert e.alloc.in_use == 0

    def test_second_guided_request_shares_prompt_and_null_spans(
            self, bundle):
        """The affordability claim: with the prefix cache, a repeat
        guided request admits BOTH pair members warm — the null caption
        is one cache entry for all guided traffic of that length."""
        params, vae_params = bundle
        r1 = Request(codes=P8, seed=5, cfg_scale=1.5)
        r2 = Request(codes=P8, seed=9, cfg_scale=1.5)
        q = RequestQueue(max_depth=8)
        e = Engine(params, CFG, q, num_slots=2, kv="paged", page_size=4,
                   prefix_cache=True)
        np.testing.assert_array_equal(
            drain_tokens(e, q, [r1])[0],
            reference_tokens(params, vae_params, r1))
        assert e.prefix_hits == 0
        np.testing.assert_array_equal(
            drain_tokens(e, q, [r2])[0],
            reference_tokens(params, vae_params, r2))
        assert e.prefix_hits == 2      # cond AND uncond admitted warm
        assert e.cfg_pairs == 2
        assert e.prefill_runs == 1     # one cold group, ever

    def test_pair_expires_and_tears_down_atomically(self, bundle):
        """A guided request's deadline mid-decode kills BOTH slots and
        frees both page sets; a plain neighbour is untouched."""
        params, vae_params = bundle
        ref = reference_tokens(params, vae_params,
                               Request(codes=(3, 7, 9), seed=5))
        q = RequestQueue(max_depth=4)
        e = Engine(params, CFG, q, num_slots=3, kv="paged", page_size=4)
        h_ok = q.submit(Request(codes=(3, 7, 9), seed=5))
        h_dead = q.submit(Request(codes=P5, seed=1, cfg_scale=2.0,
                                  deadline_s=0.005))
        e.step_once()
        assert e.active_slots() == 3       # plain + cond + shadow
        time.sleep(0.02)
        e.run_until_idle()
        res = h_dead.result(timeout=5)
        assert res.status == "deadline_exceeded"
        assert e.active_slots() == 0
        assert e.alloc.in_use == 0         # both members' pages freed
        np.testing.assert_array_equal(
            np.asarray(h_ok.result(timeout=5).tokens), ref)

    def test_guidance_needs_two_slots_typed_error(self, bundle):
        params, _ = bundle
        q = RequestQueue(max_depth=4)
        e = Engine(params, CFG, q, num_slots=1)
        h = q.submit(Request(codes=(1, 2), seed=0, cfg_scale=2.0))
        e.run_until_idle()
        res = h.result(timeout=5)
        assert res.status == ERROR
        assert "cfg_scale" in res.reason

    def test_negative_cfg_scale_rejected_at_construction(self):
        with pytest.raises(ValueError, match="cfg_scale"):
            Request(codes=(1, 2), cfg_scale=-0.5)

    def test_server_submit_and_default_scale(self, bundle):
        """The server surface: per-request cfg_scale and the server-wide
        default both reach the engine."""
        params, vae_params = bundle
        from dalle_pytorch_tpu.serve.server import InferenceServer
        req = Request(codes=P5, seed=11, cfg_scale=2.0)
        ref = reference_tokens(params, vae_params, req)
        server = InferenceServer(params, vae_params, CFG, num_slots=2,
                                 queue_depth=8, kv="paged", page_size=4,
                                 prefix_cache=True,
                                 default_cfg_scale=2.0,
                                 decode_images=False).start()
        try:
            res = server.generate(req.codes, seed=req.seed, timeout=60)
            assert res.status == OK            # default scale applied
            np.testing.assert_array_equal(np.asarray(res.tokens), ref)
            res2 = server.generate(req.codes, seed=req.seed,
                                   cfg_scale=0.0, timeout=60)
            np.testing.assert_array_equal(
                np.asarray(res2.tokens),
                reference_tokens(params, vae_params,
                                 Request(codes=P5, seed=11)))
            stats = server.stats()
            assert stats["cfg_pairs"] == 1
            assert stats["prefix_cache"] is True
        finally:
            server.close()


class TestCFGFailover:
    pytestmark = pytest.mark.faults

    def test_guided_pair_replays_on_survivor_replica(self, bundle):
        """The fault-catalog row the satellite names: replica 1 of 2
        crashes mid-decode while guided and plain requests are in
        flight; every request — the CFG pair included — completes on a
        survivor with tokens byte-identical to the undisturbed run."""
        from dalle_pytorch_tpu.resilience import faults
        from dalle_pytorch_tpu.resilience.retry import RetryPolicy
        from dalle_pytorch_tpu.serve.replica import ReplicaSet
        params, vae_params = bundle
        faults.deactivate()
        reqs = [Request(codes=P5, seed=11, cfg_scale=2.0),
                Request(codes=(3, 7, 9), seed=5),
                Request(codes=P8, seed=7, cfg_scale=1.5),
                Request(codes=(6, 6), seed=13)]
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4, kv="paged", page_size=4,
                        prefix_cache=True,
                        bringup_policy=RetryPolicy(
                            max_attempts=1, deadline_s=None,
                            base_backoff_s=0.01, backoff_multiplier=2.0,
                            max_backoff_s=0.1, jitter=0.0))
        handles = [queue.submit(r) for r in reqs]
        try:
            with faults.injected(fault_replica=1,
                                 replica_crash_at_chunk=2):
                rs.run_until_idle()
        finally:
            faults.deactivate()
        assert rs.failovers == 1
        for h, r in zip(handles, reqs):
            res = h.result(timeout=10)
            assert res.status == OK, (r, res.status, res.reason)
            np.testing.assert_array_equal(
                np.asarray(res.tokens),
                reference_tokens(params, vae_params, r))


class TestStatsSurface:
    def test_prefix_and_sharing_stats(self, bundle):
        """/stats counts a shared page ONCE and carries the new gauges
        (the satellite): prefix_hits / pages_shared / cfg_pairs, with
        pages_in_use and kv_hbm_bytes refcount-aware — the live pool
        bytes equal the layout model regardless of sharing."""
        from dalle_pytorch_tpu.serve import kv_pool as KV
        from dalle_pytorch_tpu.serve.mesh_engine import hbm_report
        params, _ = bundle
        q = RequestQueue(max_depth=8)
        e = Engine(params, CFG, q, num_slots=3, kv="paged", page_size=4,
                   prefix_cache=True)
        for s in (1, 2, 3):
            q.submit(Request(codes=P8, seed=s))
        e.step_once()
        st = e.stats()
        assert st["prefix_cache"] is True
        assert st["prefix_hits"] == 2
        assert st["pages_shared"] == 2
        # 2 pages x 3 extra refs each (two warm slots + the index)
        assert st["pages_shared_saved"] == 6
        assert st["prefill_runs"] == 1
        assert st["warm_admits"] == 2
        # physical accounting: the pool's resident bytes are the
        # ALLOCATED arrays, invariant under sharing, and equal to the
        # config model — sharing shows up as fewer pages_in_use, never
        # as phantom bytes
        assert st["kv_hbm_bytes"] == KV.modeled_kv_bytes(
            CFG.transformer, kv="paged", num_slots=3,
            total_len=CFG.seq_len, page_size=4)
        assert st["pages_in_use"] == e.alloc.in_use
        rep = hbm_report(e)
        assert rep["kv_hbm_bytes"] == st["kv_hbm_bytes"]
        e.run_until_idle()

    def test_admission_timing_surface(self, bundle):
        """time_admissions records cold-prefill and warm-admission p50s
        — the numbers bench's prefix_compare asserts the 10x win on."""
        params, _ = bundle
        q = RequestQueue(max_depth=8)
        e = Engine(params, CFG, q, num_slots=2, kv="paged", page_size=4,
                   prefix_cache=True, time_admissions=True)
        # 1st: cold (compile — untimed); 2nd: first warm (its program
        # compiles — untimed); 3rd: steady-state warm (timed)
        for s in (1, 2, 3):
            q.submit(Request(codes=P8, seed=s))
            e.run_until_idle()
        st = e.stats()
        assert e.warm_admit_times, "warm admissions must be timed"
        assert st["warm_admit_p50_ms"] > 0
