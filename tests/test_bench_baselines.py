"""The analytic baseline/roofline estimators behind every ``vs_baseline``
field (VERDICT r4 item 8: no config may emit a null). The constants are
estimates, but the FORMULAS are checked: the generalized A100 estimator
must reproduce the historical 2.9e5 north constant, the sparse count
must charge attention only to dense layers, and the decode roofline must
track the quant arithmetic in ops/quant.py.
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


@pytest.fixture(scope="module")
def north_cfg():
    return bench.build_cfg(False)


def test_a100_estimator_reproduces_north_constant(north_cfg):
    """2.9e5 was hand-derived as 40% of 312 TFLOPs over ~433 MFLOP/token;
    the generalized function must land on the same number (1%)."""
    est = bench.a100_tokens_per_sec_est(north_cfg)
    assert est == pytest.approx(bench.A100_TOKENS_PER_SEC_EST, rel=0.01)


def test_sparse_attention_charged_to_dense_layers_only():
    """The depth-64 (True, False)*32 config must count attention FLOPs on
    the 32 dense layers only — making the A100 estimate FASTER and our
    vs_baseline lower (conservative)."""
    dense = bench.build_cfg(False, depth=64)
    sparse = bench.build_cfg(False, depth=64, sparse=True)
    f_dense = bench.dalle_train_flops_per_token(dense)
    f_sparse = bench.dalle_train_flops_per_token(sparse)
    assert f_sparse < f_dense
    # exactly half the attention term: 32 of 64 layers are sparse
    dh = dense.heads * dense.dim_head
    attn_term = 3.0 * 32 * 2 * (2 * dense.seq_len * dh)
    assert f_dense - f_sparse == pytest.approx(attn_term, rel=1e-9)
    assert bench.a100_tokens_per_sec_est(sparse) \
        > bench.a100_tokens_per_sec_est(dense)


def test_vae_flops_scale_with_resolution():
    from dalle_pytorch_tpu.models import vae as V
    small = V.VAEConfig(image_size=128, num_tokens=2048, codebook_dim=256,
                        num_layers=3, hidden_dim=128)
    big = V.VAEConfig(image_size=256, num_tokens=2048, codebook_dim=256,
                      num_layers=3, hidden_dim=128)
    r = bench.vae_train_flops_per_image(big) \
        / bench.vae_train_flops_per_image(small)
    # conv cost is ~quadratic in resolution (the 1x1 heads dilute it a bit)
    assert 3.0 < r < 4.5
    assert bench.a100_images_per_sec_est(big) \
        < bench.a100_images_per_sec_est(small)


def test_decode_roofline_matches_quant_arithmetic(north_cfg):
    """ops/quant.py:5-13 argues ~113 MB of bf16 weights/token ~= 0.14 ms
    at v5e bandwidth and int8 halves the weight share. The roofline
    function is that arithmetic finished (streamed weights + KV cache;
    embedding gathers excluded): bf16 floor ~ 0.18 ms, int8 strictly
    cheaper but > half (cache stays bf16)."""
    bf16 = bench.decode_roofline_ms_per_token(north_cfg)
    int8 = bench.decode_roofline_ms_per_token(north_cfg, quantize="int8")
    assert 0.15 < bf16 < 0.25
    assert int8 < bf16
    assert int8 > bf16 / 2          # the KV cache doesn't quantize
    # the measured 0.524 ms/token (BENCH r4) sits above the floor —
    # the roofline must never claim the chip beat physics
    assert bf16 < 0.524
    # a batched step amortizes weights but multiplies KV reads: the floor
    # must grow with batch, sublinearly
    b4 = bench.decode_roofline_ms_per_token(north_cfg, batch=4)
    assert bf16 < b4 < 4 * bf16


def test_vs_baseline_fields_emitted_on_tiny_cpu_bench():
    """--tiny --config vae,sparse on CPU: the records must carry numeric
    vs_baseline (the whole point of item 8: no nulls anywhere)."""
    import json
    import subprocess
    # strip the conftest's 8-device forcing: the tiny vae batch (4) must
    # divide the dp mesh, and this test wants the plain single-device path
    env = {**os.environ, "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": ""}
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--tiny",
         "--config", "vae", "--steps", "2", "--warmup", "1"],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    d = json.loads(proc.stdout.strip().splitlines()[-1])
    assert isinstance(d["vs_baseline"], float)
