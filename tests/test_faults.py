"""End-to-end fault-injection tests (acceptance criteria, ISSUE 1):

1. a training run killed MID-EPOCH by a simulated SIGTERM, restarted with
   ``--auto_resume``, finishes with params equal to a never-interrupted
   run — zero duplicated, zero skipped steps (metrics prove it);
2. an injected NaN-loss step rolls back to the last good checkpoint and
   the run converges past the spike;
3. a NaN with nothing to roll back to fails fast as TrainingDiverged.

All CPU-only, deterministic (fault hooks fire exactly once), and fast: the
VAE CLI on an 8x8 synthetic dataset (the smallest model the CLI accepts).
The wedged-backend-init acceptance test lives in test_resilience.py
(TestBackendBringup) — same `faults` marker group.
"""

import json
import math
import os

import numpy as np
import pytest

from dalle_pytorch_tpu import checkpoint as ckpt
from dalle_pytorch_tpu.resilience import TrainingDiverged, faults

pytestmark = pytest.mark.faults

IMG = 8           # 2 conv layers -> 2x2 = 4 image tokens: minimal compile


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.deactivate()
    yield
    faults.deactivate()


def make_dataset(root):
    from PIL import Image
    img_dir = root / "imagedata" / "0"
    img_dir.mkdir(parents=True)
    rng = np.random.default_rng(0)
    for i in range(8):
        arr = np.zeros((IMG, IMG, 3), np.uint8)
        arr[:, :, i % 3] = 255
        arr[i % 4:i % 4 + 3, i % 4:i % 4 + 3] = rng.integers(
            0, 255, (3, 3, 3))
        Image.fromarray(arr).save(img_dir / f"img{i}.png")
    (root / "models").mkdir()
    (root / "results").mkdir()


def vae_args(root, extra=()):
    # 8 images / batch 4 -> 2 steps per epoch
    return [
        "--dataPath", str(root / "imagedata"),
        "--imageSize", str(IMG), "--batchSize", "4",
        "--num_layers", "2", "--num_tokens", "8", "--codebook_dim", "8",
        "--hidden_dim", "4", "--lr", "3e-3",
        "--models_dir", str(root / "models"),
        "--results_dir", str(root / "results"),
        "--metrics", str(root / "metrics.jsonl"),
        "--log_interval", "1", "--dp", "1",
    ] + list(extra)


def read_metrics(root):
    recs = []
    with open(root / "metrics.jsonl") as f:
        for line in f:
            recs.append(json.loads(line))
    return recs


def final_params(root, epoch):
    path = ckpt.ckpt_path(str(root / "models"), "vae", epoch)
    params, manifest = ckpt.restore_params(path)
    return params, manifest


class TestPreemptResumeExactness:
    def test_sigterm_mid_epoch_then_auto_resume_matches_uninterrupted(
            self, tmp_path):
        from dalle_pytorch_tpu.cli.train_vae import main

        # reference run: 2 epochs (4 steps), never interrupted
        ref = tmp_path / "ref"
        ref.mkdir()
        make_dataset(ref)
        main(vae_args(ref, ["--n_epochs", "2"]))
        ref_params, ref_manifest = final_params(ref, 1)

        # interrupted run: SIGTERM injected just before step 2 (the first
        # step of epoch 1) — the step completes, the preemption checkpoint
        # commits mid-epoch, main returns cleanly
        run = tmp_path / "run"
        run.mkdir()
        make_dataset(run)
        with faults.injected(sigterm_at_step=2):
            main(vae_args(run, ["--n_epochs", "2"]))
        step_ckpts = ckpt.step_checkpoints(str(run / "models"), "vae")
        assert step_ckpts, "preemption must leave a step checkpoint"
        steps_done, preempt_path = step_ckpts[-1]
        assert steps_done == 3                 # steps 0, 1, 2 committed
        manifest = ckpt.load_manifest(preempt_path)
        assert manifest["meta"]["epoch"] == 1
        assert manifest["meta"]["step_in_epoch"] == 1
        recs = read_metrics(run)
        assert any(r.get("kind") == "preempted" for r in recs)

        # restart the same command with --auto_resume: runs only step 3
        main(vae_args(run, ["--n_epochs", "1", "--auto_resume"]))
        got_params, got_manifest = final_params(run, 1)

        # params match the uninterrupted run (f32 on CPU: tight tolerance)
        flat_ref = jax_flat(ref_params)
        flat_got = jax_flat(got_params)
        assert flat_ref.keys() == flat_got.keys()
        for k in flat_ref:
            np.testing.assert_allclose(flat_got[k], flat_ref[k],
                                       rtol=1e-6, atol=1e-7, err_msg=k)
        # and the epoch summary covers every step exactly once
        assert got_manifest["meta"]["avg_loss"] == pytest.approx(
            ref_manifest["meta"]["avg_loss"], rel=1e-6)

        # zero duplicated or skipped steps across both invocations
        recs = read_metrics(run)
        trained = [r["step"] for r in recs
                   if "loss" in r and "step" in r and "kind" not in r]
        assert sorted(trained) == [0, 1, 2, 3]
        resumed = [r for r in recs if r.get("kind") == "resume"]
        assert resumed and resumed[0]["step_in_epoch"] == 1


class TestNaNRollback:
    def test_injected_nan_rolls_back_and_converges_past_spike(self,
                                                              tmp_path):
        from dalle_pytorch_tpu.cli.train_vae import main
        root = tmp_path
        make_dataset(root)
        # save_every 1: a good checkpoint exists before the poisoned step.
        # NaN at step 1; steps 2, 3 (epoch 1) continue after rollback.
        with faults.injected(nan_at_step=1):
            main(vae_args(root, ["--n_epochs", "2", "--save_every", "1",
                                 "--rewarm_steps", "2"]))
        recs = read_metrics(root)
        rollbacks = [r for r in recs if r.get("kind") == "rollback"]
        assert len(rollbacks) == 1
        assert rollbacks[0]["step"] == 1
        assert "non-finite" in rollbacks[0]["reason"]

        # the run converged past the spike: later steps trained on finite
        # losses and the final checkpoint is valid and finite
        trained = {r["step"]: r["loss"] for r in recs
                   if "loss" in r and "step" in r and "kind" not in r}
        assert 1 not in trained               # the poisoned step never counts
        assert all(math.isfinite(v) for v in trained.values())
        assert {2, 3} <= set(trained)
        params, manifest = final_params(root, 1)
        for k, v in jax_flat(params).items():
            assert np.isfinite(v).all(), k
        assert math.isfinite(manifest["meta"]["avg_loss"])
        # converging: the post-rollback epoch improved on the first epoch
        e0 = next(r["avg_loss"] for r in recs
                  if r.get("event") == "checkpoint" and r.get("epoch") == 0)
        e1 = manifest["meta"]["avg_loss"]
        assert e1 < e0 * 1.5     # not diverging after the spike

    def test_nan_right_after_resume_rolls_back_to_resumed_ckpt(
            self, tmp_path):
        """The checkpoint a run resumes from must itself be a rollback
        anchor: a NaN on the very first post-resume step (before any new
        cadence/epoch save exists) rolls back to it instead of raising
        TrainingDiverged."""
        from dalle_pytorch_tpu.cli.train_vae import main
        root = tmp_path
        make_dataset(root)
        with faults.injected(sigterm_at_step=2):
            main(vae_args(root, ["--n_epochs", "2"]))
        with faults.injected(nan_at_step=3):
            main(vae_args(root, ["--n_epochs", "1", "--auto_resume"]))
        recs = read_metrics(root)
        rollbacks = [r for r in recs if r.get("kind") == "rollback"]
        assert len(rollbacks) == 1
        assert rollbacks[0]["checkpoint"].endswith("vae-step3")
        params, _ = final_params(root, 1)
        for k, v in jax_flat(params).items():
            assert np.isfinite(v).all(), k

    def test_nan_with_no_checkpoint_fails_fast(self, tmp_path):
        from dalle_pytorch_tpu.cli.train_vae import main
        root = tmp_path
        make_dataset(root)
        with faults.injected(nan_at_step=0):
            with pytest.raises(TrainingDiverged,
                               match="no valid checkpoint"):
                main(vae_args(root, ["--n_epochs", "1"]))


def jax_flat(tree):
    """{path: np.ndarray} for comparing param trees."""
    import jax
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        out[jax.tree_util.keystr(path)] = np.asarray(leaf)
    return out


# ---------------------------------------------------------------------------
# loss-level NaN injection: the hook that reaches train_dalle/train_clip,
# whose integer-token batches have no float leaves for corrupt_batch
# (ROADMAP open item; faults.corrupt_loss via TrainSupervisor.check_step)
# ---------------------------------------------------------------------------

def make_caption_dataset(root):
    """8 images + caption files, the train_dalle/train_clip data
    contract, at the same minimal scale as make_dataset."""
    make_dataset(root)
    names = [f"img{i}.png" for i in range(8)]
    colors = ["red", "blue", "green", "gray"]
    (root / "only.txt").write_text(
        "".join(f"a {colors[i % 4]} square\n" for i in range(8)))
    (root / "pairs.txt").write_text(
        "".join(f"{n} : a {colors[i % 4]} square\n"
                for i, n in enumerate(names)))


def caption_args(root, extra=()):
    # 8 pairs / batch 4 -> 2 steps per epoch (same cadence as vae_args)
    return [
        "--dataPath", str(root / "imagedata"),
        "--imageSize", str(IMG), "--batchSize", "4",
        "--captions_only", str(root / "only.txt"),
        "--captions", str(root / "pairs.txt"),
        "--num_text_tokens", "20", "--text_seq_len", "4",
        "--lr", "1e-3",
        "--models_dir", str(root / "models"),
        "--results_dir", str(root / "results"),
        "--metrics", str(root / "metrics.jsonl"),
        "--log_interval", "1", "--dp", "1",
    ] + list(extra)


def assert_rolled_back_and_finished(root, name, epochs=2):
    recs = read_metrics(root)
    rollbacks = [r for r in recs if r.get("kind") == "rollback"]
    assert len(rollbacks) == 1
    assert rollbacks[0]["step"] == 1
    assert "non-finite" in rollbacks[0]["reason"]
    trained = {r["step"]: r["loss"] for r in recs
               if "loss" in r and "step" in r and "kind" not in r}
    assert 1 not in trained               # the poisoned step never counts
    assert all(math.isfinite(v) for v in trained.values())
    path, epoch = ckpt.latest(str(root / "models"), name)
    assert epoch == epochs - 1
    params, manifest = ckpt.restore_params(path)
    for k, v in jax_flat(params).items():
        assert np.isfinite(v).all(), k
    assert math.isfinite(manifest["meta"]["avg_loss"])


class TestNaNLossInjection:
    def test_corrupt_loss_fires_exactly_once(self):
        with faults.injected(nan_loss_at_step=3):
            assert faults.corrupt_loss(1.0, 2) == 1.0
            assert math.isnan(faults.corrupt_loss(1.0, 3))
            assert faults.corrupt_loss(1.0, 3) == 1.0   # one-shot
        assert faults.corrupt_loss(1.0, 3) == 1.0       # no active plan

    def test_integer_batch_corrupt_batch_still_fails_loudly(self):
        """corrupt_batch on a float-free batch keeps raising (the guard
        that motivated the loss-level hook)."""
        with faults.injected(nan_at_step=0):
            with pytest.raises(faults.FaultInjected,
                               match="nan_loss_at_step"):
                faults.corrupt_batch({"text": np.zeros((2, 4), np.int32)},
                                     0)

    def test_nan_loss_rolls_back_train_dalle(self, tmp_path):
        """The full rollback loop on the DALLE CLI: a good cadence
        checkpoint at step 0, a NaN loss reported at step 1, training
        restores the anchor and finishes both epochs finite."""
        from dalle_pytorch_tpu.cli.train_dalle import main as dalle_main
        from dalle_pytorch_tpu.cli.train_vae import main as vae_main
        root = tmp_path
        make_caption_dataset(root)
        vae_main(vae_args(root, ["--n_epochs", "1", "--num_tokens", "8",
                                 "--codebook_dim", "16"]))
        os.remove(root / "metrics.jsonl")    # keep only the DALLE records
        with faults.injected(nan_loss_at_step=1):
            dalle_main(caption_args(root, [
                "--vaename", "vae", "--vae_epoch", "0", "--name", "toy",
                "--n_epochs", "2", "--dim", "16", "--depth", "1",
                "--heads", "2", "--dim_head", "8", "--attn_dropout", "0",
                "--ff_dropout", "0", "--sample_every", "0",
                "--save_every", "1"]))
        assert_rolled_back_and_finished(root, "toy_dalle")

    def test_nan_loss_rolls_back_train_clip(self, tmp_path):
        from dalle_pytorch_tpu.cli.train_clip import main as clip_main
        root = tmp_path
        make_caption_dataset(root)
        with faults.injected(nan_loss_at_step=1):
            clip_main(caption_args(root, [
                "--name", "clip", "--n_epochs", "2",
                "--dim_text", "16", "--dim_image", "16",
                "--dim_latent", "16", "--text_enc_depth", "1",
                "--text_heads", "2", "--visual_enc_depth", "1",
                "--visual_heads", "2", "--visual_patch_size", "4",
                "--dense", "--save_every", "1"]))
        assert_rolled_back_and_finished(root, "clip")
