"""DiscreteVAE tests: shapes, contracts, gradient flow, torch golden checks.

Contracts from SURVEY.md §5: token grid = (image_size / 2**num_layers)²,
get_codebook_indices = channel argmax flattened row-major, decode assumes a
square grid, recon loss is MSE, Gumbel path is the soft relaxation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models.vae import (DiscreteVAE, VAEConfig, decode,
                                          get_codebook_indices, vae_apply,
                                          vae_init)
from dalle_pytorch_tpu.ops import core

CFG = VAEConfig(image_size=32, num_tokens=64, codebook_dim=32, num_layers=2,
                hidden_dim=16)


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


@pytest.fixture
def params(key):
    return vae_init(key, CFG)


def test_config_validation():
    with pytest.raises(ValueError):
        VAEConfig(image_size=100)
    with pytest.raises(ValueError):
        VAEConfig(num_layers=0)


def test_recon_shapes_and_loss(key, params):
    imgs = jax.random.uniform(key, (2, 32, 32, 3), minval=-1, maxval=1)
    recon = vae_apply(params, imgs, cfg=CFG, rng=key)
    assert recon.shape == imgs.shape
    loss = vae_apply(params, imgs, cfg=CFG, rng=key, return_recon_loss=True)
    assert loss.shape == ()
    # loss is the plain MSE of the same forward (reference dalle_pytorch.py:156)
    np.testing.assert_allclose(
        float(loss), float(jnp.mean((imgs - recon) ** 2)), rtol=1e-5)


def test_logits_grid_shape(key, params):
    imgs = jax.random.uniform(key, (2, 32, 32, 3))
    logits = vae_apply(params, imgs, cfg=CFG, rng=key, return_logits=True)
    g = CFG.grid_size
    assert logits.shape == (2, g, g, CFG.num_tokens)
    assert CFG.image_seq_len == g * g == 64


def test_codebook_indices_argmax_rowmajor(key, params):
    imgs = jax.random.uniform(key, (2, 32, 32, 3))
    ids = get_codebook_indices(params, imgs)
    assert ids.shape == (2, CFG.image_seq_len)
    logits = vae_apply(params, imgs, cfg=CFG, rng=key, return_logits=True)
    manual = np.argmax(np.array(logits), axis=-1).reshape(2, -1)
    np.testing.assert_array_equal(np.array(ids), manual)


def test_decode_roundtrip_shape(key, params):
    ids = jax.random.randint(key, (2, CFG.image_seq_len), 0, CFG.num_tokens)
    imgs = decode(params, ids)
    assert imgs.shape == (2, 32, 32, 3)


def test_decode_codebook_override(key, params):
    """DALLE owns the tied codebook after training; decode must honor an
    external table (reference tying, dalle_pytorch.py:283)."""
    ids = jax.random.randint(key, (1, CFG.image_seq_len), 0, CFG.num_tokens)
    alt = jax.random.normal(jax.random.fold_in(key, 1),
                            (CFG.num_tokens, CFG.codebook_dim))
    a = decode(params, ids)
    b = decode(params, ids, codebook=alt)
    assert not np.allclose(np.array(a), np.array(b))


def test_gradients_flow_to_all_params(key, params):
    imgs = jax.random.uniform(key, (2, 32, 32, 3), minval=-1, maxval=1)

    def loss_fn(p):
        return vae_apply(p, imgs, cfg=CFG, rng=key, return_recon_loss=True)

    grads = jax.grad(loss_fn)(params)
    for path, g in jax.tree_util.tree_flatten_with_path(grads)[0]:
        assert np.isfinite(np.array(g)).all(), path
        assert float(jnp.abs(g).sum()) > 0, f"zero grad at {path}"


def test_resnet_blocks_variant(key):
    cfg = VAEConfig(image_size=32, num_tokens=32, codebook_dim=24,
                    num_layers=2, num_resnet_blocks=2, hidden_dim=16)
    params = vae_init(key, cfg)
    imgs = jax.random.uniform(key, (1, 32, 32, 3))
    recon = vae_apply(params, imgs, cfg=cfg, rng=key)
    assert recon.shape == imgs.shape
    loss = vae_apply(params, imgs, cfg=cfg, rng=key, return_recon_loss=True)
    assert np.isfinite(float(loss))


def test_temperature_override_no_recompile_semantics(key, params):
    imgs = jax.random.uniform(key, (1, 32, 32, 3))
    a = vae_apply(params, imgs, cfg=CFG, rng=key, temperature=0.9)
    b = vae_apply(params, imgs, cfg=CFG, rng=key, temperature=0.1)
    # colder temperature sharpens the mix => different recon
    assert not np.allclose(np.array(a), np.array(b))


def test_straight_through_uses_hard_onehot(key):
    cfg = VAEConfig(image_size=32, num_tokens=32, codebook_dim=24,
                    num_layers=2, hidden_dim=16, straight_through=True)
    params = vae_init(key, cfg)
    imgs = jax.random.uniform(key, (1, 32, 32, 3))
    # straight-through recon == decoding the hard argmax of noisy logits;
    # still differentiable
    g = jax.grad(lambda p: vae_apply(p, imgs, cfg=cfg, rng=key,
                                     return_recon_loss=True))(params)
    assert float(jnp.abs(g["codebook"]["w"]).sum()) > 0


def test_oo_wrapper_parity(key):
    vae = DiscreteVAE(key, image_size=32, num_tokens=64, codebook_dim=32,
                      num_layers=2, hidden_dim=16)
    assert vae.image_size == 32 and vae.num_tokens == 64
    imgs = jax.random.uniform(key, (1, 32, 32, 3))
    ids = vae.get_codebook_indices(imgs)
    np.testing.assert_array_equal(
        np.array(ids), np.array(get_codebook_indices(vae.params, imgs)))


def test_conv_transpose_matches_torch():
    """Golden primitive check: our input-dilated conv == torch's
    ConvTranspose2d(k=4, stride=2, padding=1) — the dVAE upsampler shape."""
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 8, 8, 5), dtype=np.float32)
    w = rng.standard_normal((4, 4, 5, 7), dtype=np.float32) * 0.1
    b = rng.standard_normal(7, dtype=np.float32)

    ours = core.conv2d_transpose({"w": jnp.asarray(w), "b": jnp.asarray(b)},
                                 jnp.asarray(x), stride=2, padding=1)

    tx = torch.from_numpy(x.transpose(0, 3, 1, 2))
    # torch ConvTranspose2d weight layout: (in, out, kh, kw)
    tw = torch.from_numpy(w.transpose(2, 3, 0, 1))
    ty = torch.nn.functional.conv_transpose2d(
        tx, tw, torch.from_numpy(b), stride=2, padding=1)
    np.testing.assert_allclose(np.array(ours),
                               ty.numpy().transpose(0, 2, 3, 1), atol=1e-4)


def test_conv2d_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(1)
    x = rng.standard_normal((2, 9, 9, 4), dtype=np.float32)
    w = rng.standard_normal((4, 4, 4, 6), dtype=np.float32) * 0.1
    b = rng.standard_normal(6, dtype=np.float32)
    ours = core.conv2d({"w": jnp.asarray(w), "b": jnp.asarray(b)},
                       jnp.asarray(x), stride=2, padding=1)
    tx = torch.from_numpy(x.transpose(0, 3, 1, 2))
    tw = torch.from_numpy(w.transpose(3, 2, 0, 1))  # (out, in, kh, kw)
    ty = torch.nn.functional.conv2d(tx, tw, torch.from_numpy(b), stride=2,
                                    padding=1)
    np.testing.assert_allclose(np.array(ours),
                               ty.numpy().transpose(0, 2, 3, 1), atol=1e-4)
