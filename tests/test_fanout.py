"""Best-of-N fan-out tests (serve/fanout.py + the group lifecycle
through engine, replica set, and COW page sharing).

The load-bearing one is the equivalence matrix: every member of a
best-of-N group is an ORDINARY request — its tokens byte-identical to
a standalone request submitted with the derived ``sample_seed(seed,
i)`` — across dense/paged KV, gather/kernel paged reads, and fp32/int8
KV. That identity is what makes groups compose with eviction replay,
failover, and migration for free. Plus: COW accounting (a group's
lifetime page peak is bounded by ONE prompt span + N generation
spans), atomic admission (a mid-group queue reject cancels the
already-admitted prefix), group-atomic completion and ranked assembly,
and THE resilience criterion — a replica killed mid-group loses zero
samples, and the multiplexed stream's high-water marks dedupe the
replay so every position still arrives exactly once.

Tiny model (test_serve's 24-position config), all CPU, tier-1 cheap.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V
from dalle_pytorch_tpu.resilience import faults
from dalle_pytorch_tpu.serve import (OK, QueueFull, Request,
                                     RequestQueue, pages_for)
from dalle_pytorch_tpu.serve import scheduler as S
from dalle_pytorch_tpu.serve.engine import Engine
from dalle_pytorch_tpu.serve.fanout import (group_pages_saved,
                                            rank_samples, sample_seed,
                                            submit_group)

VCFG = V.VAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                   num_layers=2, hidden_dim=8)
CFG = D.DALLEConfig(dim=16, depth=2, vae=VCFG, num_text_tokens=50,
                    text_seq_len=8, heads=2, dim_head=8)


@pytest.fixture(scope="module")
def bundle():
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG)
    params = D.dalle_init(key, CFG, vae_params)
    return params, vae_params


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.deactivate()
    yield
    faults.deactivate()


_REF_CACHE: dict = {}


def reference_tokens(params, vae_params, req: Request,
                     quantize_cache=False) -> np.ndarray:
    key = (req.codes, req.seed, quantize_cache)
    if key not in _REF_CACHE:
        text = jnp.asarray([req.codes], jnp.int32)
        _, img_seq = D.generate_images(
            params, vae_params, text, cfg=CFG,
            rng=jax.random.PRNGKey(req.seed), return_img_seq=True,
            quantize_cache=quantize_cache)
        _REF_CACHE[key] = np.asarray(img_seq)[0]
    return _REF_CACHE[key]


# ---------------------------------------------------------------------------
# pure functions
# ---------------------------------------------------------------------------


class TestSampleSeed:
    def test_index_zero_is_identity(self):
        """best-of-1 must be byte-identical to a plain request."""
        for seed in (0, 1, 42, 2**31, 2**32 - 1):
            assert sample_seed(seed, 0) == seed

    def test_distinct_and_deterministic(self):
        seeds = [sample_seed(42, i) for i in range(64)]
        assert len(set(seeds)) == 64
        assert seeds == [sample_seed(42, i) for i in range(64)]
        assert all(0 <= s < 2**32 for s in seeds)

    def test_different_base_seeds_diverge(self):
        a = {sample_seed(1, i) for i in range(32)}
        b = {sample_seed(2, i) for i in range(32)}
        assert len(a & b) <= 1      # avalanche: essentially disjoint


class TestPagesSaved:
    def test_cow_dividend(self):
        assert group_pages_saved(4, 32, 8) == 3 * 4
        # partial boundary page saves nothing (forked private)
        assert group_pages_saved(4, 35, 8) == 3 * 4
        assert group_pages_saved(1, 32, 8) == 0     # singleton
        assert group_pages_saved(4, 32, 0) == 0     # dense: no pages


class TestRank:
    def test_ok_first_clip_desc_index_tiebreak(self):
        rs = [
            S.Result(status=S.OK, request_id=0, clip_score=0.1),
            S.Result(status=S.ERROR, request_id=1, clip_score=9.0),
            S.Result(status=S.OK, request_id=2, clip_score=0.7),
            S.Result(status=S.OK, request_id=3, clip_score=0.1),
        ]
        got = [r.request_id for r in rank_samples(rs)]
        assert got == [2, 0, 3, 1]

    def test_all_scores_none_keeps_sample_order(self):
        rs = [S.Result(status=S.OK, request_id=i) for i in range(3)]
        assert [r.request_id for r in rank_samples(rs)] == [0, 1, 2]


# ---------------------------------------------------------------------------
# admission + group future (no backend)
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_members_are_ordinary_requests(self):
        queue = RequestQueue(max_depth=16)
        g = submit_group(queue, Request(codes=(1, 2), seed=42,
                                        n_samples=3, stream=True))
        assert len(g.members) == 3 and len(g.sinks) == 3
        for i, m in enumerate(g.members):
            assert m.request.n_samples == 1
            assert m.request.seed == sample_seed(42, i)
            assert m.sink is g.sinks[i]
            assert g.sinks[i].request_id == m.request.request_id
        # the group is addressed by its leader
        assert g.request.request_id == g.members[0].request.request_id
        assert g.sink is g.sinks[0]

    def test_atomic_admission_mid_group_reject(self):
        """Member 3 of 4 hits a full queue: the typed reject propagates
        AND the already-admitted prefix is cancelled — a failed group
        never leaks half its samples into the engine."""
        queue = RequestQueue(max_depth=2)
        with pytest.raises(QueueFull):
            submit_group(queue, Request(codes=(1,), seed=7,
                                        n_samples=4, stream=True))
        # the admitted prefix is already terminal: an engine popping
        # them skips done handles, and no caller can hang on them
        for h in queue.drain():
            assert h.done()
            assert h.result(timeout=1).status == S.CANCELLED

    def test_non_streamed_group_has_no_sinks(self):
        queue = RequestQueue(max_depth=8)
        g = submit_group(queue, Request(codes=(1,), seed=0,
                                        n_samples=2))
        assert g.sinks == [] and g.sink is None

    def test_group_cancel_fans_out_and_closes_channel(self):
        queue = RequestQueue(max_depth=8)
        g = submit_group(queue, Request(codes=(1,), seed=0,
                                        n_samples=2, stream=True))
        assert g.fulfill(S.Result(status=S.CANCELLED,
                                  request_id=g.request.request_id,
                                  reason="client disconnected"))
        assert g.done()
        for m in g.members:
            assert m.result(timeout=1).status == S.CANCELLED
        # every member's fulfill closed its sink: the channel ended
        kinds = [e["event"] for e in g.sink.events()]
        assert kinds.count("sample_done") == 2
        # first-write-wins like the handle it imitates
        assert not g.fulfill(S.Result(status=S.OK, request_id=0))
        assert g.result(timeout=1).status == S.CANCELLED


# ---------------------------------------------------------------------------
# the equivalence matrix
# ---------------------------------------------------------------------------


MATRIX = [
    ("dense", "gather", False),
    ("dense", "gather", True),
    ("paged", "gather", False),
    ("paged", "gather", True),
    ("paged", "kernel", False),
]


class TestEquivalence:
    @pytest.mark.parametrize("kv,paged_attn,int8", MATRIX)
    def test_members_byte_identical_to_standalone(self, bundle, kv,
                                                  paged_attn, int8):
        """Every member of a best-of-3 group reproduces the one-shot
        sampler at its derived seed — across KV layouts, paged-read
        implementations, and KV dtypes. The group machinery must not
        touch what the device computes."""
        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        engine = Engine(params, CFG, queue, num_slots=4, chunk_steps=4,
                        kv=kv, page_size=8 if kv == "paged" else 0,
                        paged_attn=paged_attn, quantize_cache=int8)
        g = submit_group(queue, Request(codes=(3, 7, 9), seed=11,
                                        n_samples=3))
        engine.run_until_idle()
        res = g.result(timeout=60)
        assert res.ok and len(res.samples) == 3
        for i, m in enumerate(g.members):
            ref = reference_tokens(
                params, vae_params,
                Request(codes=(3, 7, 9), seed=sample_seed(11, i)),
                quantize_cache=int8)
            np.testing.assert_array_equal(
                np.asarray(m.result(timeout=1).tokens), ref,
                err_msg=f"member {i} diverged ({kv}/{paged_attn}/"
                        f"{'int8' if int8 else 'fp32'})")

    def test_group_result_assembles_ranked(self, bundle):
        params, _ = bundle
        queue = RequestQueue(max_depth=16)
        engine = Engine(params, CFG, queue, num_slots=4, chunk_steps=4)
        g = submit_group(queue, Request(codes=(6, 6), seed=5,
                                        n_samples=3))
        engine.run_until_idle()
        res = g.result(timeout=60)
        assert res.status == OK
        assert [s.request_id for s in res.samples] \
            == [m.request.request_id for m in g.members]  # None scores:
        #                                      sample order is the rank
        np.testing.assert_array_equal(np.asarray(res.tokens),
                                      np.asarray(res.samples[0].tokens))
        assert res.total_s >= max(s.total_s for s in res.samples)


class TestCOWSharing:
    def test_group_pays_prompt_once(self, bundle):
        """Paged + prefix cache: a best-of-4 group's lifetime page peak
        is bounded by ONE prompt span + 4 generation spans, the warm
        siblings' retains prove the leader's span was shared, and every
        stream still matches its standalone reference."""
        params, vae_params = bundle
        page_size = 8
        prompt = tuple(1 + (i % 7) for i in range(CFG.text_seq_len))
        n = 4
        queue = RequestQueue(max_depth=16)
        engine = Engine(params, CFG, queue, num_slots=n, chunk_steps=4,
                        kv="paged", page_size=page_size,
                        prefix_cache=True)
        g = submit_group(queue, Request(codes=prompt, seed=9,
                                        n_samples=n))
        engine.run_until_idle()
        assert g.result(timeout=60).ok
        full = pages_for(CFG.seq_len, page_size)
        shared = len(prompt) // page_size
        assert engine.alloc.peak_in_use <= shared + n * (full - shared)
        assert engine.stats()["prefix_hits"] >= n - 1
        assert engine.alloc.retains >= (n - 1) * shared
        for i, m in enumerate(g.members):
            np.testing.assert_array_equal(
                np.asarray(m.result(timeout=1).tokens),
                reference_tokens(params, vae_params,
                                 Request(codes=prompt,
                                         seed=sample_seed(9, i))))


# ---------------------------------------------------------------------------
# THE resilience criterion: replica death mid-group
# ---------------------------------------------------------------------------


class TestGroupFailover:
    pytestmark = pytest.mark.faults

    def test_replica_kill_mid_group_zero_samples_lost(self, bundle):
        """Replica 1 of 2 crashes after its 2nd fused chunk while a
        best-of-4 streamed group is in flight: every sample completes
        token-exact against its standalone reference, the multiplexed
        channel still closes group-atomically, and the replayed
        positions are deduped — each absolute position arrives in the
        stream exactly once."""
        from dalle_pytorch_tpu.serve.replica import ReplicaSet

        params, vae_params = bundle
        queue = RequestQueue(max_depth=16)
        rs = ReplicaSet(params, CFG, queue, replicas=2, num_slots=2,
                        chunk_steps=4)
        g = submit_group(queue, Request(codes=(3, 7, 9), seed=11,
                                        n_samples=4, stream=True))
        with faults.injected(fault_replica=1, replica_crash_at_chunk=2):
            rs.run_until_idle()
        assert rs.failovers == 1
        res = g.result(timeout=60)
        assert res.ok, (res.status, res.reason)
        assert all(s.ok for s in res.samples) and len(res.samples) == 4

        streamed: dict = {i: {} for i in range(4)}
        for ev in g.sink.events():
            if ev["event"] == "tokens":
                seen = streamed[ev["sample"]]
                for off, tok in enumerate(ev["tokens"]):
                    pos = ev["pos"] + off
                    assert pos not in seen, \
                        f"position {pos} delivered twice after replay"
                    seen[pos] = tok
        for i, m in enumerate(g.members):
            ref = reference_tokens(
                params, vae_params,
                Request(codes=(3, 7, 9), seed=sample_seed(11, i)))
            mres = m.result(timeout=1)
            np.testing.assert_array_equal(np.asarray(mres.tokens), ref)
            toks = [streamed[i][p] for p in sorted(streamed[i])]
            np.testing.assert_array_equal(
                np.asarray(toks[-len(ref):], np.int32), ref,
                err_msg=f"sample {i}'s streamed positions diverged")


# ---------------------------------------------------------------------------
# variable resolution riding the same buckets
# ---------------------------------------------------------------------------


class TestShortGrid:
    def test_override_is_causal_prefix(self, bundle):
        """image_seq_len_override truncates the SAME sampling stream:
        the short grid's tokens are the full run's prefix, it completes
        early (fewer decode steps), and it composes with a group."""
        params, _ = bundle
        queue = RequestQueue(max_depth=16)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=4)
        L = CFG.image_seq_len // 2
        h_short = queue.submit(Request(codes=(3, 7, 9), seed=11,
                                       image_seq_len_override=L))
        h_full = queue.submit(Request(codes=(3, 7, 9), seed=11))
        engine.run_until_idle()
        short, full = h_short.result(timeout=30), \
            h_full.result(timeout=30)
        assert short.status == OK and len(short.tokens) == L
        np.testing.assert_array_equal(np.asarray(short.tokens),
                                      np.asarray(full.tokens)[:L])

    def test_override_composes_with_group(self, bundle):
        params, _ = bundle
        queue = RequestQueue(max_depth=16)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=4)
        L = CFG.image_seq_len // 2
        g = submit_group(queue, Request(codes=(6, 6), seed=5,
                                        n_samples=2,
                                        image_seq_len_override=L))
        engine.run_until_idle()
        res = g.result(timeout=60)
        assert res.ok
        assert all(len(s.tokens) == L for s in res.samples)
