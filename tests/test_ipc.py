"""The process-isolation IPC layer (serve/ipc.py + serve/transport.py +
serve/worker.py).

Four layers of proof, matching the layer's trust model:

  * the SERIALIZER is exact: framed round trips for every queue/result
    type — fuzzed requests (every sampling knob, priorities, deadlines)
    and results of every terminal status come back bit-identical,
    because deterministic replay across the process boundary depends on
    the decoded request being the same request;
  * the TRANSPORT survives the stream: a socket legally delivers a
    frame in arbitrary fragments, so the receive path is fuzzed over a
    full split-point matrix (every byte boundary, plus random chunk
    sizes) — and every way the stream can LIE (mid-frame EOF, torn
    frame at any truncation point, reset, oversize length) surfaces as
    a typed ``IPCError``, never a hang or a partial parse;
  * CORRUPTION and DISORDER are typed, never trusted: truncated frames,
    bad magic, version skew, flipped payload bytes (CRC), garbage JSON,
    malformed snapshot/result fields, and broken frame SEQUENCES (gap,
    duplicate, reorder) all raise ``IPCError`` — and a client fed any
    of them marks itself poisoned (the supervisor's fence signal)
    instead of deadlocking or mis-parsing;
  * the HELLO handshake gates attach: a dialing worker with the right
    token joins and receives its spec over the socket; a bad token, an
    unexpected index, or a silent dialer is dropped without touching
    any replica's state.

The process-level failover semantics (SIGKILL mid-decode, OOM kills,
network faults, shadow reclaim) live in tests/test_replica.py's process
classes; this file owns the protocol itself.
"""

import multiprocessing as mp
import pickle
import random
import socket
import struct
import threading
import time

import numpy as np
import pytest

from dalle_pytorch_tpu.serve import ipc
from dalle_pytorch_tpu.serve import scheduler as S
from dalle_pytorch_tpu.serve import transport as T

# ---------------------------------------------------------------------------
# frame codec
# ---------------------------------------------------------------------------


class TestFrameCodec:
    def test_round_trip_every_kind(self):
        for i, kind in enumerate(ipc.KINDS):
            payload = {"kind": kind, "n": 3, "x": [1, 2.5, None, "s"]}
            k2, p2, seq = ipc.decode_frame(
                ipc.encode_frame(kind, payload, seq=i))
            assert k2 == kind
            assert p2 == payload
            assert seq == i

    def test_empty_and_truncated_frames_raise(self):
        with pytest.raises(ipc.IPCError, match="truncated"):
            ipc.decode_frame(b"")
        frame = ipc.encode_frame(ipc.HEARTBEAT, {"a": 1})
        with pytest.raises(ipc.IPCError, match="truncated"):
            ipc.decode_frame(frame[:4])

    def test_truncated_payload_fails_checksum(self):
        frame = ipc.encode_frame(ipc.HARVEST, {"results": [1, 2, 3]})
        with pytest.raises(ipc.IPCError, match="checksum"):
            ipc.decode_frame(frame[:-2])

    def test_garbage_bytes_raise(self):
        with pytest.raises(ipc.IPCError):
            ipc.decode_frame(b"\xde\xad\xbe\xef not a frame")

    def test_bad_magic(self):
        frame = bytearray(ipc.encode_frame(ipc.BYE, {}))
        frame[0] ^= 0xFF
        with pytest.raises(ipc.IPCError, match="magic"):
            ipc.decode_frame(bytes(frame))

    def test_version_skew(self):
        frame = bytearray(ipc.encode_frame(ipc.BYE, {}))
        frame[1] += 1
        with pytest.raises(ipc.IPCError, match="version skew"):
            ipc.decode_frame(bytes(frame))

    def test_unknown_kind(self):
        frame = bytearray(ipc.encode_frame(ipc.BYE, {}))
        frame[2] = 250
        with pytest.raises(ipc.IPCError, match="kind"):
            ipc.decode_frame(bytes(frame))

    def test_flipped_payload_byte_fails_checksum(self):
        frame = bytearray(ipc.encode_frame(ipc.HEARTBEAT, {"t": 1.5}))
        frame[-3] ^= 0x10
        with pytest.raises(ipc.IPCError, match="checksum"):
            ipc.decode_frame(bytes(frame))

    def test_non_object_payload_rejected(self):
        # a frame whose body parses but is not a JSON object is as
        # untrustworthy as garbage — build one by hand
        import json
        import zlib
        body = json.dumps([1, 2, 3]).encode()
        frame = struct.Struct("<BBBxII").pack(
            0xD5, ipc.PROTOCOL_VERSION, 4, 0, zlib.crc32(body)) + body
        with pytest.raises(ipc.IPCError, match="object"):
            ipc.decode_frame(frame)

    def test_seq_check_gap_and_duplicate_are_typed(self):
        assert ipc.seq_check(5, 5) == 6
        with pytest.raises(ipc.IPCError, match="duplicate or reordered"):
            ipc.seq_check(4, 5)
        with pytest.raises(ipc.IPCError, match="gap"):
            ipc.seq_check(7, 5)


# ---------------------------------------------------------------------------
# socket transport: short reads, torn frames, resets (the stream matrix)
# ---------------------------------------------------------------------------


def _pair():
    a, b = socket.socketpair()
    return a, T.SocketTransport(b)


def _framed(frame: bytes) -> bytes:
    return struct.pack("<I", len(frame)) + frame


class TestSocketTransport:
    FRAME = None    # built once; the matrix walks every byte of it

    @classmethod
    def setup_class(cls):
        cls.FRAME = ipc.encode_frame(
            ipc.HARVEST, {"results": [{"k": i} for i in range(4)],
                          "snap": None}, seq=7)

    def test_split_point_matrix_every_byte_boundary(self):
        """THE short-read contract: deliver the framed bytes split at
        EVERY possible byte boundary (two writes per split point); the
        receiver must never surface a frame early, never lose bytes,
        and decode the identical frame whatever the fragmentation."""
        framed = _framed(self.FRAME)
        for split in range(1, len(framed)):
            a, tb = _pair()
            a.sendall(framed[:split])
            assert not tb.poll(0), f"frame surfaced early at {split}"
            a.sendall(framed[split:])
            assert tb.poll(0.5)
            kind, payload, seq = ipc.decode_frame(tb.recv_bytes())
            assert (kind, seq) == (ipc.HARVEST, 7)
            assert payload["results"] == [{"k": i} for i in range(4)]
            a.close()

    def test_fuzzed_random_fragmentation_many_frames(self):
        """Random chunking over a multi-frame stream: 50 frames written
        in random 1..17-byte slices arrive intact, in order, with
        sequence numbers consecutive — however the network fragments."""
        rng = random.Random(0xF4A6)
        frames = [ipc.encode_frame(ipc.HEARTBEAT, {"i": i}, seq=i)
                  for i in range(50)]
        stream = b"".join(_framed(f) for f in frames)
        a, tb = _pair()

        def dribble():
            off = 0
            while off < len(stream):
                n = rng.randrange(1, 18)
                a.sendall(stream[off:off + n])
                off += n
            a.close()

        t = threading.Thread(target=dribble)
        t.start()
        got, expected_seq = [], 0
        while len(got) < len(frames):
            assert tb.poll(2.0), "stream stalled mid-fuzz"
            kind, payload, seq = ipc.decode_frame(tb.recv_bytes())
            expected_seq = ipc.seq_check(seq, expected_seq)
            got.append(payload["i"])
        t.join()
        assert got == list(range(50))

    def test_mid_frame_eof_every_truncation_point_is_typed(self):
        """A peer dying between two writes of one frame: truncate the
        framed bytes at every point AFTER the length prefix and close —
        the receiver must raise ``IPCError`` (torn frame), never hand
        up a partial parse and never wait forever."""
        framed = _framed(self.FRAME)
        # a handful of spread points plus both edges of the body keeps
        # the matrix meaningful without quadratic test time
        points = sorted({1, 2, 3, 5, 8, len(framed) // 2,
                         len(framed) - 2, len(framed) - 1})
        for cut in points:
            a, tb = _pair()
            a.sendall(framed[:cut])
            a.close()
            assert tb.poll(0.5)
            if cut < len(framed):
                with pytest.raises((T.IPCError, EOFError)) as ei:
                    tb.recv_bytes()
                if cut > 4:     # inside the frame proper: typed tear
                    assert isinstance(ei.value, T.IPCError)
                    assert "mid-frame EOF" in str(ei.value)

    def test_clean_eof_at_frame_boundary_is_eoferror(self):
        """A peer that closes BETWEEN frames is a death, not a lie:
        plain ``EOFError`` — liveness decides what happened."""
        a, tb = _pair()
        a.sendall(_framed(self.FRAME))
        a.close()
        assert tb.poll(0.5)
        ipc.decode_frame(tb.recv_bytes())
        assert tb.poll(0.5)
        with pytest.raises(EOFError):
            tb.recv_bytes()
        assert not tb.alive()

    def test_reset_mid_frame_is_typed(self):
        """The conn-reset fault's receive side: half a frame then an
        abortive close (RST where TCP allows it) raises ``IPCError``
        with the partial-frame context."""
        a, tb = _pair()
        ta = T.SocketTransport(a)
        ta.send_partial_frame(self.FRAME, len(self.FRAME) // 2)
        ta.reset_hard()
        assert tb.poll(0.5)
        with pytest.raises(T.IPCError, match="mid-frame EOF"):
            tb.recv_bytes()

    def test_oversize_length_prefix_is_typed_not_allocated(self):
        a, tb = _pair()
        a.sendall(struct.pack("<I", T.MAX_FRAME_BYTES + 1) + b"x" * 64)
        assert tb.poll(0.5)
        with pytest.raises(T.IPCError, match="cap"):
            tb.recv_bytes()

    def test_poll_timeout_never_blocks_past_deadline(self):
        """A stalled peer (accepted, silent) costs at most the poll
        timeout — the no-deadlock half of the stalled-socket fault."""
        _, tb = _pair()
        t0 = time.perf_counter()
        assert not tb.poll(0.1)
        assert time.perf_counter() - t0 < 1.0


# ---------------------------------------------------------------------------
# wire round trips (the replay-identity contract)
# ---------------------------------------------------------------------------


def _random_request(rng: random.Random, rid: int) -> S.RequestHandle:
    req = S.Request(
        codes=tuple(rng.randrange(1, 50)
                    for _ in range(rng.randrange(1, 9))),
        seed=rng.randrange(-2**31, 2**31),
        sampling=S.SamplingParams(
            temperature=rng.uniform(0.05, 3.0),
            filter_thres=rng.uniform(0.0, 0.99),
            top_p=rng.choice([0.0, rng.uniform(0.1, 1.0)])),
        priority=rng.randrange(-3, 4),
        deadline_s=rng.choice([None, rng.uniform(0.001, 1e4)]),
        request_id=rid,
        submit_t=rng.uniform(0, 1e6))
    h = S.RequestHandle(req)
    h.queue_seq = rng.randrange(0, 10**9)
    return h


class TestWireRoundTrip:
    def test_request_handles_fuzzed(self):
        """200 random handles through an encoded frame: every field
        that feeds deterministic replay — codes, seed, every sampling
        float, priority, queue_seq — comes back EXACTLY (floats ride
        JSON repr, which round-trips bit-exact in Python)."""
        rng = random.Random(0xDA11E)
        now = 123.25
        for i in range(200):
            h = _random_request(rng, i)
            frame = ipc.encode_frame(
                ipc.ADMIT, {"requests": [h.to_wire(now)]}, seq=i)
            _, payload, _ = ipc.decode_frame(frame)
            h2 = S.RequestHandle.from_wire(payload["requests"][0],
                                           now=now)
            r, r2 = h.request, h2.request
            assert r2.codes == r.codes
            assert r2.seed == r.seed
            assert r2.sampling.temperature == r.sampling.temperature
            assert r2.sampling.filter_thres == r.sampling.filter_thres
            assert r2.sampling.top_p == r.sampling.top_p
            assert r2.priority == r.priority
            assert r2.request_id == r.request_id
            assert h2.queue_seq == h.queue_seq

    def test_deadline_ships_as_remaining_budget(self):
        req = S.Request(codes=(1, 2), deadline_s=10.0, request_id=7,
                        submit_t=100.0)
        h = S.RequestHandle(req)
        h.queue_seq = 3
        wire = h.to_wire(now=104.0)         # 6s of budget left
        assert wire["deadline_left_s"] == pytest.approx(6.0)
        h2 = S.RequestHandle.from_wire(wire, now=50.0)
        assert h2.request.deadline_t == pytest.approx(56.0)
        # and a deadline already blown ships as zero, not negative
        assert S.RequestHandle(req).to_wire(
            now=1000.0)["deadline_left_s"] == 0.0

    def test_results_every_status(self):
        rng = random.Random(7)
        cases = [
            S.Result(status=S.OK, request_id=1,
                     tokens=np.asarray(
                         [rng.randrange(0, 512) for _ in range(48)],
                         np.int32),
                     text_tokens=np.asarray([3, 1, 4, 1, 5], np.int32),
                     queued_s=0.125, decode_s=1.5, total_s=1.625),
            S.Result(status=S.ERROR, request_id=2,
                     reason="prefill failed: boom"),
            S.Result(status=S.DEADLINE_EXCEEDED, request_id=3,
                     reason="deadline_s=1 exceeded (queued)",
                     queued_s=1.0, total_s=1.0),
            S.Result(status=S.CANCELLED, request_id=4,
                     reason="server shutdown"),
            S.Result(status=S.REJECTED, request_id=5,
                     reason="queue_full"),
        ]
        for res in cases:
            _, payload, _ = ipc.decode_frame(ipc.encode_frame(
                ipc.HARVEST, {"results": [res.to_wire()], "snap": None}))
            res2 = S.Result.from_wire(payload["results"][0])
            assert res2.status == res.status
            assert res2.request_id == res.request_id
            assert res2.reason == res.reason
            assert res2.queued_s == res.queued_s
            assert res2.decode_s == res.decode_s
            assert res2.total_s == res.total_s
            if res.tokens is None:
                assert res2.tokens is None
            else:
                np.testing.assert_array_equal(res2.tokens, res.tokens)
                assert res2.tokens.dtype == np.int32
                np.testing.assert_array_equal(res2.text_tokens,
                                              res.text_tokens)

    def test_unknown_status_rejected(self):
        wire = S.Result(status=S.OK, request_id=1).to_wire()
        wire["status"] = "mystery"
        with pytest.raises(ValueError, match="status"):
            S.Result.from_wire(wire)

    def test_streaming_fields_round_trip(self):
        """The streaming/fan-out schema additions ship over the wire:
        stream, n_samples, image_seq_len_override survive a framed
        round trip exactly (a child-process engine must see the same
        short-grid budget the parent admitted)."""
        req = S.Request(codes=(1, 2, 3), seed=9, stream=True,
                        n_samples=1, image_seq_len_override=8,
                        request_id=5, submit_t=10.0)
        h = S.RequestHandle(req)
        h.queue_seq = 1
        _, payload, _ = ipc.decode_frame(ipc.encode_frame(
            ipc.ADMIT, {"requests": [h.to_wire(now=10.0)]}))
        r2 = S.RequestHandle.from_wire(payload["requests"][0],
                                       now=10.0).request
        assert r2.stream is True
        assert r2.n_samples == 1
        assert r2.image_seq_len_override == 8

    def test_legacy_frame_without_streaming_fields_decodes(self):
        """Version tolerance (the PR-14 idiom): a frame encoded by a
        pre-streaming peer — same header version, payload simply
        missing the new fields — decodes as a plain one-shot request
        with the defaults, not a KeyError. The header version pins the
        FRAME layout; payload schema evolves by field tolerance."""
        req = S.Request(codes=(4, 5), seed=3, request_id=8,
                        submit_t=20.0)
        h = S.RequestHandle(req)
        h.queue_seq = 2
        wire = h.to_wire(now=20.0)
        for k in ("stream", "n_samples", "image_seq_len_override"):
            assert k in wire        # the new encoder ships them...
            del wire[k]             # ...a legacy encoder did not
        _, payload, _ = ipc.decode_frame(ipc.encode_frame(
            ipc.ADMIT, {"requests": [wire]}))
        r2 = S.RequestHandle.from_wire(payload["requests"][0],
                                       now=20.0).request
        assert r2.stream is False
        assert r2.n_samples == 1
        assert r2.image_seq_len_override == 0
        assert r2.codes == req.codes and r2.seed == req.seed

    def test_result_samples_stay_parent_side(self):
        """A group's ranked ``samples`` list never crosses the IPC
        boundary: members ship as ordinary results and the parent
        assembles the group — so a legacy child needs no schema
        change. The encoder must therefore not emit the field."""
        res = S.Result(status=S.OK, request_id=1,
                       samples=[S.Result(status=S.OK, request_id=2)])
        wire = res.to_wire()
        assert "samples" not in wire
        assert S.Result.from_wire(wire).samples is None


# ---------------------------------------------------------------------------
# the client's poisoned-not-deadlocked contract (no process needed)
# ---------------------------------------------------------------------------


class _FakeConn:
    """Stands in for the parent end of the transport: scripted frames."""

    kind = "fake"

    def __init__(self, frames):
        self.frames = list(frames)

    def poll(self, timeout=0):
        return bool(self.frames)

    def recv_bytes(self):
        if not self.frames:
            raise EOFError
        return self.frames.pop(0)

    def send_bytes(self, data):
        pass

    def close(self):
        pass


def _client_shell():
    """A ChildEngineClient with the spawn bypassed: protocol-state unit
    tests only need the dispatch machinery, not a live child."""
    c = ipc.ChildEngineClient.__new__(ipc.ChildEngineClient)
    c.clock = time.perf_counter
    c.index = 0
    c.num_slots, c.chunk_steps, c.kv = 2, 4, "dense"
    c.on_done = None
    c.ready = True
    c.fenced = c.crashed = c.poisoned = c.bye = False
    c.last_error = ""
    c.shadow = {}
    c.counter_state = {k: 0 for k in ipc.COUNTERS}
    c.progress = {}
    c.active = c.queued = c.chunks = c.rss_mb = 0
    c.compiling = False
    c.pages_free = -1
    c.last_heartbeat = time.perf_counter()
    c.last_frame_t = time.perf_counter()
    c.stats_reply = None
    c.transport_kind = "pipe"
    c.peer = "fake"
    c.remote_host = ""
    c.awaiting_operator = False
    c.pid = 1
    c._listener = None
    c._proc = None
    c._popen = None
    c._tx_seq = 0
    c._rx_seq = 0
    from collections import deque
    c.ipc_lag_s = deque(maxlen=100)
    return c


def _frames(*kind_payloads, start_seq=0):
    return [ipc.encode_frame(k, p, seq=start_seq + i)
            for i, (k, p) in enumerate(kind_payloads)]


class TestClientPoisoning:
    def test_garbage_frame_poisons_instead_of_deadlocking(self):
        c = _client_shell()
        c._conn = _FakeConn([b"\xde\xad garbage"])
        t0 = time.perf_counter()
        assert c.pump() is True
        assert time.perf_counter() - t0 < 1.0      # returned, not hung
        assert c.poisoned
        assert "protocol error" in c.last_error

    def test_malformed_snapshot_poisons(self):
        c = _client_shell()
        c._conn = _FakeConn(_frames(
            (ipc.HEARTBEAT, {"snap": {"counters": "nope"}})))
        c.pump()
        assert c.poisoned and "malformed snapshot" in c.last_error

    def test_malformed_result_poisons(self):
        c = _client_shell()
        c._conn = _FakeConn(_frames(
            (ipc.HARVEST,
             {"results": [{"id": 1, "status": 5}], "snap": None})))
        c.pump()
        assert c.poisoned and "malformed result" in c.last_error

    def test_duplicate_frame_seq_poisons(self):
        """A transport that re-delivers: the same frame (same seq)
        twice — the first absorbs, the second fences. Nothing is ever
        double-absorbed."""
        c = _client_shell()
        frame = ipc.encode_frame(ipc.HEARTBEAT, {"snap": None}, seq=0)
        c._conn = _FakeConn([frame, frame])
        c.pump()
        assert c.poisoned
        assert "duplicate or reordered" in c.last_error

    def test_seq_gap_poisons(self):
        """A transport that LOST a frame: the gap is detected at the
        next frame and the replica is fenced — counters that rode the
        lost frame can never be silently skipped."""
        c = _client_shell()
        c._conn = _FakeConn([
            ipc.encode_frame(ipc.HEARTBEAT, {"snap": None}, seq=0),
            ipc.encode_frame(ipc.HEARTBEAT, {"snap": None}, seq=2)])
        c.pump()
        assert c.poisoned
        assert "gap" in c.last_error

    def test_reordered_frames_poison(self):
        c = _client_shell()
        c._conn = _FakeConn([
            ipc.encode_frame(ipc.HEARTBEAT, {"snap": None}, seq=1),
            ipc.encode_frame(ipc.HEARTBEAT, {"snap": None}, seq=0)])
        c.pump()
        assert c.poisoned       # the gap at seq 1 fences immediately

    def test_fenced_client_drops_frames(self):
        """A zombie child's late result must never fulfil a handle the
        failover already reclaimed — the client-side fence guard."""
        req = S.Request(codes=(1,), request_id=9)
        h = S.RequestHandle(req)
        c = _client_shell()
        c.shadow[9] = h
        res = S.Result(status=S.OK, request_id=9,
                       tokens=np.asarray([1, 2], np.int32))
        frame = ipc.encode_frame(
            ipc.HARVEST, {"results": [res.to_wire()], "snap": None})
        c._conn = _FakeConn([frame])
        c.fence()
        assert c.pump() is False
        assert not h.done()

    def test_salvaged_results_fulfil_and_leave_shadow(self):
        """The kill->salvage order: frames the child wrote before dying
        fulfil their handles and are NOT part of the reclaim set."""
        done_h = S.RequestHandle(S.Request(codes=(1,), request_id=1))
        open_h = S.RequestHandle(S.Request(codes=(2,), request_id=2))
        c = _client_shell()
        c.shadow = {1: done_h, 2: open_h}
        res = S.Result(status=S.OK, request_id=1,
                       tokens=np.asarray([5], np.int32))
        snap = {"counters": {k: (3 if k == "tokens_decoded" else 0)
                             for k in ipc.COUNTERS},
                "progress": {"2": 2}, "active_slots": 1, "queued": 0,
                "chunks": 1, "compiling": False, "rss_mb": 10,
                "t": time.perf_counter(), "pages_free": -1}
        c._conn = _FakeConn([ipc.encode_frame(
            ipc.HARVEST, {"results": [res.to_wire()], "snap": snap})])
        c.salvage()
        c.fence()
        assert done_h.done() and done_h.result(0).status == S.OK
        reclaimed = c.reclaim()
        assert reclaimed == [open_h]
        # retire math un-credits the reclaimed request's 2-token prefix
        retired = c.retire_counters(reclaimed)
        assert retired["tokens_decoded"] == 1


# ---------------------------------------------------------------------------
# the HELLO handshake (listener-side auth gate; no engine needed)
# ---------------------------------------------------------------------------


class TestHelloHandshake:
    def test_good_token_attaches_and_receives_spec(self):
        listener = T.WorkerListener("127.0.0.1", 0,
                                    handshake_timeout_s=5.0)
        try:
            spec = {"index": 3, "hello": "world", "n": [1, 2, 3]}
            listener.expect(3, pickle.dumps(spec))
            transport, got = T.dial_parent(
                "127.0.0.1", listener.port, listener.token, 3,
                timeout_s=10.0)
            assert got == spec
            deadline = time.perf_counter() + 5
            attached = None
            while attached is None and time.perf_counter() < deadline:
                attached = listener.take(3)
                time.sleep(0.01)
            assert attached is not None, "handshake never registered"
            assert attached.hello.get("pid") == __import__("os").getpid()
            # the attached pair is a live duplex stream
            transport.send_bytes(ipc.encode_frame(
                ipc.READY, {"pid": 1, "rss_mb": 1}, seq=1))
            assert attached.poll(2.0)
            kind, _, seq = ipc.decode_frame(attached.recv_bytes())
            assert (kind, seq) == (ipc.READY, 1)
            transport.close()
        finally:
            listener.close()

    def test_bad_token_rejected_without_attaching(self):
        listener = T.WorkerListener("127.0.0.1", 0,
                                    handshake_timeout_s=5.0)
        try:
            listener.expect(0, pickle.dumps({"x": 1}))
            with pytest.raises(T.IPCError):
                T.dial_parent("127.0.0.1", listener.port,
                              "wrong-token", 0, timeout_s=5.0)
            deadline = time.perf_counter() + 1
            while listener.rejected < 1 \
                    and time.perf_counter() < deadline:
                time.sleep(0.01)
            assert listener.rejected >= 1
            assert listener.take(0) is None
        finally:
            listener.close()

    def test_unexpected_index_rejected(self):
        listener = T.WorkerListener("127.0.0.1", 0,
                                    handshake_timeout_s=5.0)
        try:
            listener.expect(0, pickle.dumps({"x": 1}))
            with pytest.raises(T.IPCError):
                T.dial_parent("127.0.0.1", listener.port,
                              listener.token, 7, timeout_s=5.0)
            assert listener.take(7) is None
            assert listener.take(0) is None     # 0 still unattached
        finally:
            listener.close()

    def test_silent_dialer_times_out_without_blocking_others(self):
        """The stalled-socket shape at the handshake: a connection that
        says nothing is dropped on the handshake deadline while a
        well-behaved worker attaches concurrently."""
        listener = T.WorkerListener("127.0.0.1", 0,
                                    handshake_timeout_s=0.3)
        try:
            listener.expect(0, pickle.dumps({"ok": True}))
            silent = socket.create_connection(
                ("127.0.0.1", listener.port))
            transport, got = T.dial_parent(
                "127.0.0.1", listener.port, listener.token, 0,
                timeout_s=10.0)
            assert got == {"ok": True}
            deadline = time.perf_counter() + 2
            while listener.rejected < 1 \
                    and time.perf_counter() < deadline:
                time.sleep(0.02)
            assert listener.rejected >= 1       # the silent one
            silent.close()
            transport.close()
        finally:
            listener.close()


# ---------------------------------------------------------------------------
# worker: parent death -> child exit (no leaked interpreters)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_bundle():
    import jax

    from dalle_pytorch_tpu.models import dalle as D
    from dalle_pytorch_tpu.models import vae as V
    vcfg = V.VAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                       num_layers=2, hidden_dim=8)
    cfg = D.DALLEConfig(dim=16, depth=2, vae=vcfg, num_text_tokens=50,
                        text_seq_len=8, heads=2, dim_head=8)
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), vcfg)
    params = jax.tree.map(np.asarray, D.dalle_init(key, cfg, vae_params))
    return params, cfg


class TestWorkerLifecycle:
    def test_worker_exits_when_parent_end_closes(self, tiny_bundle):
        """The no-leak contract: a worker whose parent vanishes (both
        parent pipe handles gone — what a parent SIGKILL leaves behind)
        must notice EOF and exit on its own, not idle forever holding a
        device. Exit code 3 is the worker's parent-gone path."""
        from dalle_pytorch_tpu.serve import worker as worker_mod
        params, cfg = tiny_bundle
        spec = {"index": 0, "params": params, "cfg": cfg,
                "engine_kwargs": {"num_slots": 2, "chunk_steps": 4},
                "device_index": 0, "place": False,
                "heartbeat_interval_s": 0.05, "rss_limit_mb": 0,
                "faults": None, "idle_sleep_s": 0.002}
        ctx = mp.get_context("spawn")
        parent_end, child_end = ctx.Pipe(duplex=True)
        proc = ctx.Process(target=worker_mod.worker_main,
                           args=(spec, child_end), daemon=True)
        proc.start()
        child_end.close()
        # wait for READY — the worker is fully up, in its idle loop
        deadline = time.perf_counter() + 120
        ready = False
        while time.perf_counter() < deadline:
            if parent_end.poll(0.1):
                kind, _, _ = ipc.decode_frame(parent_end.recv_bytes())
                if kind == ipc.READY:
                    ready = True
                    break
        assert ready, "worker never came up"
        parent_end.close()              # the parent "dies"
        proc.join(30)
        assert proc.exitcode == 3, \
            f"worker leaked (exitcode={proc.exitcode})"

    def test_socket_worker_exits_when_parent_closes_connection(
            self, tiny_bundle):
        """Same no-leak contract over the network transport: a dialed-
        back worker whose socket EOFs (parent gone, or a fence closing
        the transport under a remote worker) exits 3 on its own."""
        from dalle_pytorch_tpu.serve import worker as worker_mod
        params, cfg = tiny_bundle
        spec = {"index": 0, "params": params, "cfg": cfg,
                "engine_kwargs": {"num_slots": 2, "chunk_steps": 4},
                "device_index": 0, "place": False,
                "heartbeat_interval_s": 0.05, "rss_limit_mb": 0,
                "faults": None, "idle_sleep_s": 0.002}
        listener = T.WorkerListener("127.0.0.1", 0)
        ctx = mp.get_context("spawn")
        proc = ctx.Process(
            target=worker_mod.worker_main_dial,
            args=("127.0.0.1", listener.port, listener.token, 0),
            daemon=True)
        try:
            listener.expect(0, pickle.dumps(spec))
            proc.start()
            deadline = time.perf_counter() + 120
            conn = None
            while conn is None and time.perf_counter() < deadline:
                conn = listener.take(0)
                time.sleep(0.02)
            assert conn is not None, "worker never attached"
            ready = False
            deadline = time.perf_counter() + 120
            while time.perf_counter() < deadline:
                if conn.poll(0.1):
                    kind, _, _ = ipc.decode_frame(conn.recv_bytes())
                    if kind == ipc.READY:
                        ready = True
                        break
            assert ready, "worker never came up over the socket"
            conn.close()                # the parent "dies"
            proc.join(30)
            assert proc.exitcode == 3, \
                f"worker leaked (exitcode={proc.exitcode})"
        finally:
            listener.close()
            if proc.is_alive():
                proc.kill()

    def test_wrong_token_worker_exits_rejected(self):
        """A worker dialing with a bad token is turned away at HELLO
        and exits 4 — it never gets a spec, never touches a replica."""
        from dalle_pytorch_tpu.serve import worker as worker_mod
        listener = T.WorkerListener("127.0.0.1", 0,
                                    handshake_timeout_s=5.0)
        ctx = mp.get_context("spawn")
        proc = ctx.Process(
            target=worker_mod.worker_main_dial,
            args=("127.0.0.1", listener.port, "not-the-token", 0),
            daemon=True)
        try:
            proc.start()
            proc.join(60)
            assert proc.exitcode == worker_mod.REJECTED_EXIT, \
                f"exitcode={proc.exitcode}"
        finally:
            listener.close()
            if proc.is_alive():
                proc.kill()
