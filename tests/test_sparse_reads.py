"""Sparsity-aware decode reads (ISSUE 12): engine + step-math coverage.

The contract: with ``sparse_reads=True`` every emitted token is
BYTE-IDENTICAL to ``generate_images`` (and therefore to the dense-read
engine) — sparse layers skip only pages whose every token the trained
VariableSparsity layout masks, and under the finite ``neg_inf`` fill
those pages carry exactly-zero softmax weight — while the per-token KV
read traffic drops by the visibility ratio. Pinned here across
K ∈ {1, 8} × gather/kernel × fp32/int8-KV, through a transfer-guarded
mid-stream join (the static visibility tables must not retrace the one
fused decode program), at the direct step-math level (the sparse-reads
kernel walk is BIT-equal to the prefix walk), and at the typed-
validation level (paged-only, sparse-layers-only, periodic-only).

The config uses ``sparse_block=4`` so the window (4 blocks = 16 tokens)
is narrower than the 24-token sequence — at the reference block 16 the
tiny sequence fits one window and visibility degenerates to
everything-visible. All CPU (the kernel runs under the Pallas
interpreter), tiny model, inside tier-1.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.analysis import guards
from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V
from dalle_pytorch_tpu.ops import decode as decode_ops
from dalle_pytorch_tpu.serve import (Request, RequestQueue,
                                     SamplingParams)
from dalle_pytorch_tpu.serve import kv_pool as KV
from dalle_pytorch_tpu.serve.engine import Engine

VCFG = V.VAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                   num_layers=2, hidden_dim=8)
CFG = D.DALLEConfig(dim=16, depth=2, vae=VCFG, num_text_tokens=50,
                    text_seq_len=8, heads=2, dim_head=8,
                    sparse_attn=(True, False), sparse_block=4)

REQS = [
    Request(codes=(3, 7, 9), seed=11),
    Request(codes=(5, 2, 8, 1, 4), seed=23,
            sampling=SamplingParams(temperature=0.7, filter_thres=0.8)),
    Request(codes=(6, 6), seed=5,
            sampling=SamplingParams(temperature=1.3, top_p=0.9)),
]


@pytest.fixture(scope="module")
def bundle():
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG)
    params = D.dalle_init(key, CFG, vae_params)
    return params, vae_params


_REF_CACHE: dict = {}


def reference_tokens(params, vae_params, req: Request,
                     quantize_cache: bool = False) -> np.ndarray:
    """Memoized generate_images at batch 1 over the SPARSE config — the
    one-shot dense-cache stream sparse reads must reproduce."""
    key = (quantize_cache, req.codes, req.seed, req.sampling.temperature,
           req.sampling.filter_thres, req.sampling.top_p)
    if key not in _REF_CACHE:
        text = jnp.asarray([req.codes], jnp.int32)
        _, img_seq = D.generate_images(
            params, vae_params, text, cfg=CFG,
            rng=jax.random.PRNGKey(req.seed),
            filter_thres=req.sampling.filter_thres,
            top_p=req.sampling.top_p,
            temperature=req.sampling.temperature,
            quantize_cache=quantize_cache, return_img_seq=True)
        _REF_CACHE[key] = np.asarray(img_seq)[0]
    return _REF_CACHE[key]


def _random_pool(key, page_size, num_pages, quantized):
    tcfg = CFG.transformer
    shape = (tcfg.depth, num_pages, tcfg.heads, page_size, tcfg.dim_head)
    if quantized:
        return {
            "k": jax.random.randint(jax.random.fold_in(key, 0), shape,
                                    -127, 128, jnp.int8),
            "v": jax.random.randint(jax.random.fold_in(key, 1), shape,
                                    -127, 128, jnp.int8),
            "k_scale": jax.random.uniform(jax.random.fold_in(key, 2),
                                          shape[:-1], minval=0.01,
                                          maxval=0.1),
            "v_scale": jax.random.uniform(jax.random.fold_in(key, 3),
                                          shape[:-1], minval=0.01,
                                          maxval=0.1),
        }
    return {"k": jax.random.normal(jax.random.fold_in(key, 0), shape),
            "v": jax.random.normal(jax.random.fold_in(key, 1), shape)}


class TestStepMathParity:
    """Direct ``_decode_step_math(sparse_reads=True)`` against the two
    established oracles, at ragged per-slot positions (last row /
    mid-sequence with a padded-off prompt row / parked dead at 0)."""

    @pytest.mark.parametrize("quantized", [False, True])
    @pytest.mark.parametrize("pattern", [(True, False), (True, True)])
    def test_sparse_reads_matches_oracles(self, bundle, quantized,
                                          pattern):
        params, _ = bundle
        cfg = D.DALLEConfig(dim=16, depth=2, vae=VCFG, num_text_tokens=50,
                            text_seq_len=8, heads=2, dim_head=8,
                            sparse_attn=pattern,
                            sparse_block=4).transformer
        L, ps = CFG.seq_len, 8
        mp = KV.pages_for(L, ps)
        pool = _random_pool(jax.random.PRNGKey(7), ps, 2 * mp + 1,
                            quantized)
        bt = np.zeros((3, mp), np.int32)
        bt[0] = np.arange(1, mp + 1)
        bt[1] = np.arange(mp + 1, 2 * mp + 1)
        bt = jnp.asarray(bt)
        pos = jnp.asarray([L - 1, 17, 0], jnp.int32)
        key_mask = jnp.ones((3, L), bool).at[1, 1].set(False)
        x_tok = jax.random.normal(jax.random.PRNGKey(9), (3, CFG.dim))
        kw = dict(cfg=cfg, key_mask=key_mask)

        view = decode_ops.paged_view(pool, bt, L)
        h_ref, ks_ref, vs_ref = decode_ops._decode_step_math(
            params["transformer"], x_tok, pos, view, **kw)
        h_k, ks_k, _ = decode_ops._decode_step_math(
            params["transformer"], x_tok, pos, pool, attn_impl="kernel",
            block_tables=bt, **kw)

        h_sk, ks_sk, _ = decode_ops._decode_step_math(
            params["transformer"], x_tok, pos, pool, attn_impl="kernel",
            block_tables=bt, sparse_reads=True, **kw)
        # the sparse-reads kernel walk is BIT-equal to the PREFIX walk
        # (every skipped page is an exact identity of the online
        # softmax); vs the gather oracle it inherits the kernel's
        # summation-order allclose bound
        np.testing.assert_array_equal(np.asarray(h_sk), np.asarray(h_k))
        np.testing.assert_array_equal(np.asarray(ks_sk),
                                      np.asarray(ks_k))
        np.testing.assert_allclose(np.asarray(h_sk), np.asarray(h_ref),
                                   rtol=2e-5, atol=2e-6)

        h_sg, ks_sg, vs_sg = decode_ops._decode_step_math(
            params["transformer"], x_tok, pos, pool, attn_impl="gather",
            block_tables=bt, sparse_reads=True, **kw)
        np.testing.assert_allclose(np.asarray(h_sg), np.asarray(h_ref),
                                   rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(np.asarray(ks_sg),
                                   np.asarray(ks_ref),
                                   rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(np.asarray(vs_sg),
                                   np.asarray(vs_ref),
                                   rtol=2e-5, atol=2e-6)


class TestSparseReadsEngineTokens:
    """End-to-end: the sparse-reads engine must emit byte-identical
    tokens to ``generate_images`` in the same one-compile fused-K
    emit-ring regime — K x impl x cache-dtype full cross."""

    @pytest.mark.parametrize("quantize_cache", [False, True])
    @pytest.mark.parametrize("k", [1, 8])
    @pytest.mark.parametrize("impl", ["gather", "kernel"])
    def test_tokens_byte_identical(self, bundle, impl, k,
                                   quantize_cache):
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r, quantize_cache)
                for r in REQS]
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=k,
                        kv="paged", page_size=8, paged_attn=impl,
                        sparse_reads=True,
                        quantize_cache=quantize_cache)
        handles = [queue.submit(r) for r in REQS]
        with guards.compile_count(lambda: engine.decode_traces, expect=1,
                                  label=f"sparse-reads {impl} decode"):
            engine.run_until_idle()
        for h, ref in zip(handles, refs):
            res = h.result(timeout=5)
            assert res.status == "ok", res.reason
            np.testing.assert_array_equal(np.asarray(res.tokens), ref)
        assert engine.alloc.in_use == 0
        stats = engine.stats()
        assert stats["sparse_reads"] is True
        assert stats["kv_read_bytes_per_token"] \
            < stats["kv_read_bytes_per_token_dense_reads"]

    @pytest.mark.parametrize("impl", ["gather", "kernel"])
    def test_transfer_clean_midstream_join(self, bundle, impl):
        """Sparse visibility must not retrace or transfer: the tables
        are trace-time constants, so a mid-stream join (paged prefill +
        block-table growth) stays inside the one compiled program with
        no implicit host<->device traffic."""
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r)
                for r in REQS[:2]]
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=4,
                        kv="paged", page_size=8, paged_attn=impl,
                        sparse_reads=True)
        for r in REQS[:2]:              # warm: compile decode + buckets
            queue.submit(r)
        engine.run_until_idle()
        h_a = queue.submit(REQS[0])
        engine.step_once()              # a admitted, chunk 1 in flight
        with guards.no_transfers():
            h_b = queue.submit(REQS[1])
            engine.step_once()          # join + chunk 2 + harvest 1
            engine.step_once()          # pure steady-state chunk
        engine.run_until_idle()
        np.testing.assert_array_equal(
            np.asarray(h_a.result(timeout=5).tokens), refs[0])
        np.testing.assert_array_equal(
            np.asarray(h_b.result(timeout=5).tokens), refs[1])
        assert engine.decode_traces == 1


class TestSparseReadsComposition:
    @pytest.mark.parametrize("impl", ["gather", "kernel"])
    def test_eviction_replay_stays_token_exact(self, bundle, impl):
        """Sparse reads compose with paged EVICTION: an overcommitted
        pool evicts mid-decode, the victim replays on re-admission, and
        every stream still equals the one-shot reference — visibility
        is positional, so block-table remapping churn cannot touch it."""
        params, vae_params = bundle
        reqs = [REQS[0],
                Request(codes=REQS[1].codes, seed=REQS[1].seed,
                        sampling=REQS[1].sampling, priority=7),
                REQS[2]]
        refs = [reference_tokens(params, vae_params, r) for r in reqs]
        queue = RequestQueue(max_depth=8)
        # seq 24 at page_size 8 = 3 pages/request; 4 usable pages with
        # 2 slots is a genuine overcommit (two mid-sequence requests
        # need up to 6)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=4,
                        kv="paged", page_size=8, num_pages=5,
                        paged_attn=impl, sparse_reads=True)
        handles = [queue.submit(r) for r in reqs]
        with guards.compile_count(lambda: engine.decode_traces, expect=1,
                                  label=f"sparse-reads {impl} eviction"):
            engine.run_until_idle()
        assert engine.evicted >= 1, "pool was sized to force eviction"
        for h, ref in zip(handles, refs):
            res = h.result(timeout=5)
            assert res.status == "ok", res.reason
            np.testing.assert_array_equal(np.asarray(res.tokens), ref)
        assert engine.alloc.in_use == 0


class TestSparseReadsValidation:
    """The flag's preconditions are typed at construction, naming the
    constraint — never a trace-time surprise."""

    def test_requires_paged_kv(self, bundle):
        params, _ = bundle
        with pytest.raises(ValueError, match="paged"):
            Engine(params, CFG, RequestQueue(max_depth=2), num_slots=1,
                   kv="dense", sparse_reads=True)

    def test_requires_sparse_layers(self, bundle):
        params, _ = bundle
        dense_cfg = D.DALLEConfig(dim=16, depth=2, vae=VCFG,
                                  num_text_tokens=50, text_seq_len=8,
                                  heads=2, dim_head=8)
        with pytest.raises(ValueError, match="no sparse layers"):
            Engine(params, dense_cfg, RequestQueue(max_depth=2),
                   num_slots=1, kv="paged", page_size=8,
                   sparse_reads=True)

    def test_requires_periodic_pattern(self):
        cfg5 = D.DALLEConfig(dim=16, depth=5, vae=VCFG,
                             num_text_tokens=50, text_seq_len=8,
                             heads=2, dim_head=8,
                             sparse_attn=(True, False, False, False,
                                          True), sparse_block=4)
        params5 = D.dalle_init(jax.random.PRNGKey(2), cfg5)
        with pytest.raises(ValueError, match="periodic"):
            Engine(params5, cfg5, RequestQueue(max_depth=2),
                   num_slots=1, kv="paged", page_size=8,
                   sparse_reads=True)

    def test_off_by_default_and_stats_report_it(self, bundle):
        params, _ = bundle
        engine = Engine(params, CFG, RequestQueue(max_depth=2),
                        num_slots=1, kv="paged", page_size=8)
        stats = engine.stats()
        assert stats["sparse_reads"] is False
        # with sparse reads off the two modeled numbers coincide
        assert stats["kv_read_bytes_per_token"] \
            == stats["kv_read_bytes_per_token_dense_reads"]
