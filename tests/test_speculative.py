"""Speculative decode tests (ISSUE 19 acceptance criteria).

The load-bearing contract is BYTE-IDENTITY: draft-and-verify
speculation changes how many sequential full-depth passes each token
costs, never which token is emitted. Deterministic per-position
sampling (``fold_in(rng, pos)``) makes the k-wide verify compute
exactly the token the eager loop would emit at every offset, so
acceptance is an equality test — the emitted stream equals
``generate_images``' at every acceptance rate, not just in
distribution. Covered here:

  * the speculative-vs-eager identity matrix: K in {1, 8} x
    dense / paged-gather / paged-kernel x fp32 / int8-KV, under a
    SHALLOW draft (draft_layers=1 — rejection-heavy, the hard case),
    with ``decode_traces == 1`` (one verify program per k, ever);
  * a full-depth draft (draft_layers == depth) accepting every
    proposal — the acceptance-rate ceiling, pinned at exactly 1.0;
  * a mid-stream slot join under ``guards.no_transfers`` — the
    speculative steady state is as transfer-clean as the eager one;
  * the rejection-at-every-offset sweep, driving
    ``ops.decode.speculative_verify`` directly with handcrafted
    corrupted drafts: rejection at offset j accepts exactly j+1
    tokens, all byte-equal to eager, and the verify sample at the
    rejected offset is itself the correct continuation;
  * token accounting through a rejection-heavy run: rejected drafts
    never reach ``tokens_decoded``/occupancy — delivered tokens are
    counted exactly;
  * crash-mid-speculation failover (replay on a survivor) and live
    migration mid-speculation: both byte-identical — speculation is
    invisible to the replay contract;
  * a 2-device MeshEngine with speculation: the spec loop keeps the
    pinned replicated/sharded output structure, so sharded serving
    composes unchanged.

All CPU, tiny model (total_len 24; the migration row uses the same
config with chunk_steps=1 to hold a mid-stream export window).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_pytorch_tpu.analysis import guards
from dalle_pytorch_tpu.models import dalle as D
from dalle_pytorch_tpu.models import vae as V
from dalle_pytorch_tpu.ops import decode as decode_ops
from dalle_pytorch_tpu.serve import (OK, Request, RequestQueue,
                                     SamplingParams)
from dalle_pytorch_tpu.serve.engine import Engine

VCFG = V.VAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                   num_layers=2, hidden_dim=8)
CFG = D.DALLEConfig(dim=16, depth=2, vae=VCFG, num_text_tokens=50,
                    text_seq_len=8, heads=2, dim_head=8)

REQS = [
    Request(codes=(3, 7, 9), seed=11),
    Request(codes=(5, 2, 8, 1, 4), seed=23,
            sampling=SamplingParams(temperature=0.7, filter_thres=0.8)),
    Request(codes=(6, 6), seed=5,
            sampling=SamplingParams(temperature=1.3, top_p=0.9)),
]


@pytest.fixture(scope="module")
def bundle():
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), VCFG)
    params = D.dalle_init(key, CFG, vae_params)
    return params, vae_params


_REF_CACHE: dict = {}


def reference_tokens(params, vae_params, req: Request,
                     quantize_cache: bool = False) -> np.ndarray:
    """Memoized generate_images at batch 1 — the one-shot stream every
    speculative run must reproduce byte-for-byte."""
    key = (req.codes, req.seed, req.sampling.temperature,
           req.sampling.filter_thres, req.sampling.top_p,
           quantize_cache)
    if key not in _REF_CACHE:
        text = jnp.asarray([req.codes], jnp.int32)
        _, img_seq = D.generate_images(
            params, vae_params, text, cfg=CFG,
            rng=jax.random.PRNGKey(req.seed),
            filter_thres=req.sampling.filter_thres,
            top_p=req.sampling.top_p,
            temperature=req.sampling.temperature,
            quantize_cache=quantize_cache, return_img_seq=True)
        _REF_CACHE[key] = np.asarray(img_seq)[0]
    return _REF_CACHE[key]


def _kv_kwargs(layout: str) -> dict:
    return {"dense": dict(kv="dense"),
            "paged_gather": dict(kv="paged", page_size=4,
                                 paged_attn="gather"),
            "paged_kernel": dict(kv="paged", page_size=8,
                                 paged_attn="kernel")}[layout]


# tier-1 time budget: the k=8 rows are compile-heavy on the single-core
# CPU container (the interpret-mode kernel rows alone cost ~90s), so
# tier-1 keeps every k=1 row plus two representative k=8 rows —
# dense/fp32 (the canonical wide verify) and paged_gather/int8kv (paged
# write path + quantized scales) — and marks the rest slow. Full-matrix
# parity is kept in CI's serve-perf speculative leg, which runs this
# file unfiltered.
_TIER1_K8 = {("dense", False), ("paged_gather", True)}
_MATRIX = [
    pytest.param(k, layout, qc,
                 id=f"{k}-{layout}-{'int8kv' if qc else 'fp32'}",
                 marks=[pytest.mark.slow]
                 if k == 8 and (layout, qc) not in _TIER1_K8 else [])
    for k in (1, 8)
    for layout in ("dense", "paged_gather", "paged_kernel")
    for qc in (False, True)
]


class TestSpeculativeByteIdentity:
    @pytest.mark.parametrize("k,layout,quantize_cache", _MATRIX)
    def test_matrix(self, bundle, k, layout, quantize_cache):
        """The acceptance matrix: every (k, KV layout, cache dtype)
        combination emits the eager stream byte-for-byte under the
        SHALLOW 1-layer draft (low acceptance — every round exercises
        the rejection path), and the fused verify program compiles
        exactly once. k=1 is the degenerate no-draft round: speculation
        reduces to the eager step exactly."""
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r, quantize_cache)
                for r in REQS]
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=2,
                        speculative=k, draft_layers=1,
                        quantize_cache=quantize_cache,
                        **_kv_kwargs(layout))
        handles = [queue.submit(r) for r in REQS]
        with guards.compile_count(lambda: engine.decode_traces,
                                  expect=1,
                                  label=f"speculative decode k={k}"):
            engine.run_until_idle()
        for h, ref in zip(handles, refs):
            res = h.result(timeout=5)
            assert res.status == OK
            np.testing.assert_array_equal(np.asarray(res.tokens), ref)
        st = engine.stats()
        assert st["speculative"] == k and st["draft_layers"] == 1
        # the verify sample always lands, so acceptance never drops
        # below the 1/k total-rejection floor
        assert 1.0 / k <= st["spec_acceptance_rate"] <= 1.0

    def test_full_depth_draft_accepts_everything(self, bundle):
        """With draft_layers == depth the draft IS the target model run
        through the same sampler, so every proposal verifies — the
        acceptance rate is exactly 1.0 (bitwise, not approximately:
        both sides compute the identical program)."""
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r) for r in REQS]
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=2,
                        speculative=4,
                        draft_layers=CFG.transformer.depth)
        handles = [queue.submit(r) for r in REQS]
        engine.run_until_idle()
        for h, ref in zip(handles, refs):
            np.testing.assert_array_equal(
                np.asarray(h.result(timeout=5).tokens), ref)
        st = engine.stats()
        assert st["spec_acceptance_rate"] == 1.0
        # tokens/round sits just under k: only the clamped final round
        # of each request (sequence end mid-window) delivers fewer
        assert 3.5 <= st["spec_tokens_per_round"] <= 4.0

    def test_guided_pair_under_speculation(self, bundle):
        """A CFG pair's uncond shadow drafts and verifies partner
        copies of the cond stream, so both slots accept identical
        lengths every round and stay in lockstep — the guided stream
        equals the non-speculative engine's guided stream."""
        params, _ = bundle

        def run(spec):
            queue = RequestQueue(max_depth=8)
            engine = Engine(params, CFG, queue, num_slots=4,
                            chunk_steps=2, speculative=spec,
                            draft_layers=1 if spec else 0)
            h = queue.submit(Request(codes=(3, 7, 9), seed=11,
                                     cfg_scale=1.5))
            engine.run_until_idle()
            res = h.result(timeout=5)
            assert res.status == OK
            return np.asarray(res.tokens)

        np.testing.assert_array_equal(run(4), run(0))

    def test_midstream_join_is_transfer_clean(self, bundle):
        """Speculative steady state — k-wide chunks, double-buffered
        harvest, a slot joining mid-stream — runs under
        ``guards.no_transfers()``: the wider emit ring is still the one
        explicit device_get per chunk, and nothing else crosses."""
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r)
                for r in REQS[:2]]
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=2,
                        speculative=4, draft_layers=1)
        # warm run compiles the verify program + both prefill buckets
        for r in REQS[:2]:
            queue.submit(r)
        engine.run_until_idle()
        h_a = queue.submit(REQS[0])
        engine.step_once()          # a admitted, spec chunk 1 in flight
        with guards.no_transfers():
            h_b = queue.submit(REQS[1])
            engine.step_once()      # join + chunk 2 + harvest chunk 1
            engine.step_once()      # pure speculative steady state
        engine.run_until_idle()
        np.testing.assert_array_equal(
            np.asarray(h_a.result(timeout=5).tokens), refs[0])
        np.testing.assert_array_equal(
            np.asarray(h_b.result(timeout=5).tokens), refs[1])
        assert engine.decode_traces == 1

    def test_accounting_exact_under_rejection_heavy_run(self, bundle):
        """Rejected draft tokens never inflate the delivered-token
        accounting: after a rejection-heavy run (1-layer draft, k=8)
        ``tokens_decoded`` equals the exact number of tokens the
        requests needed — same invariant the eviction/migration
        un-credit paths enforce — and the speculative counters agree
        with it."""
        params, _ = bundle
        queue = RequestQueue(max_depth=8)
        engine = Engine(params, CFG, queue, num_slots=2, chunk_steps=2,
                        speculative=8, draft_layers=1)
        handles = [queue.submit(r) for r in REQS]
        engine.run_until_idle()
        for h in handles:
            assert h.result(timeout=5).status == OK
        st = engine.stats()
        exact = sum(CFG.seq_len - len(r.codes) for r in REQS)
        assert st["tokens_decoded"] == exact
        assert engine.occupancy_sum == exact
        assert engine.spec_delivered == exact
        # rounds ran: delivered = sum of per-round accepted lengths,
        # each in [1, k] — both bounds must hold exactly
        assert engine.spec_rounds >= -(-exact // 8)
        assert engine.spec_rounds <= exact


class TestRejectionSweep:
    def test_rejection_at_every_offset(self, bundle):
        """Drive ``speculative_verify`` directly: drafts that match the
        eager continuation for the first j offsets and are corrupted at
        offset j must accept EXACTLY j+1 tokens (positions pos..pos+j,
        every one byte-equal to eager), and the next-round token is the
        verify sample at the rejected offset — the free token that
        makes even total rejection advance one position."""
        params, _ = bundle
        tc = CFG.transformer
        b, k, t0 = len(REQS), 6, 4
        total_len = CFG.seq_len
        key_mask = jnp.ones((b, total_len), bool)
        rng = jnp.stack([jax.random.PRNGKey(r.seed) for r in REQS])
        temp = jnp.asarray([r.sampling.temperature for r in REQS])
        topk = jnp.asarray(
            [max(1, int(33 * (1 - r.sampling.filter_thres)))
             for r in REQS], jnp.int32)
        topp = jnp.asarray([r.sampling.top_p for r in REQS])
        partner = jnp.arange(b)
        cfgs = jnp.zeros((b,))
        uncond = jnp.zeros((b,), bool)

        def embed_fn(tok, p):
            return D.decode_token_embed(params, CFG, tok, p)

        def sample_fn(h, pred_pos):
            return D.sample_per_slot(
                D.to_logits(params, h), pred_pos, rng, temp, topk,
                topp, CFG, partner=partner, cfg_scale=cfgs,
                uncond=uncond)

        # seed a cache with t0 narrow steps, then compute the EAGER
        # continuation (the next k tokens) from a copy
        cache = decode_ops.init_cache(tc, b, total_len,
                                      dtype=jnp.float32)
        pos = jnp.zeros((b,), jnp.int32)
        cur = jnp.full((b,), 5, jnp.int32)
        for _ in range(t0):
            x = embed_fn(cur, pos)
            h, cache = decode_ops.decode_step(
                params["transformer"], x, pos, cache, cfg=tc,
                key_mask=key_mask)
            cur = sample_fn(h, pos + 1)
            pos = pos + 1
        act = jnp.ones((b,), bool)
        _, _, _, _, ring = decode_ops.decode_loop(
            params["transformer"], cur, pos, act,
            jax.tree.map(lambda a: a.copy(), cache), cfg=tc,
            key_mask=key_mask, steps=k, embed_fn=embed_fn,
            sample_fn=sample_fn)
        eager = np.asarray(ring)            # (b, k): tokens pos..pos+k-1
        # the eager token at pos+k (what cur_new must be on a clean
        # accept of all k-1 drafts): one more narrow step
        cache2 = jax.tree.map(lambda a: a.copy(), cache)
        c2, p2 = cur, pos
        for _ in range(k):
            x = embed_fn(c2, p2)
            h, cache2 = decode_ops.decode_step(
                params["transformer"], x, p2, cache2, cfg=tc,
                key_mask=key_mask)
            c2 = sample_fn(h, p2 + 1)
            p2 = p2 + 1
        eager_next = np.asarray(c2)         # token at pos+k

        good = jnp.asarray(eager[:, 1:k])   # perfect drafts (k-1 wide)
        for j in range(k):
            if j < k - 1:
                drafts = good.at[:, j].add(1)   # corrupt offset j
            else:
                drafts = good                   # full acceptance
            emit, cur_new, pos_new, act_new, _, _ = \
                decode_ops.speculative_verify(
                    params["transformer"], cur, drafts, pos, act,
                    jax.tree.map(lambda a: a.copy(), cache), cfg=tc,
                    key_mask=key_mask, total_len=total_len,
                    embed_fn=embed_fn, sample_fn=sample_fn)
            emit = np.asarray(emit)
            accepted = j + 1
            for i in range(b):
                assert (emit[i] >= 0).sum() == accepted, (j, i)
                np.testing.assert_array_equal(
                    emit[i, :accepted], eager[i, :accepted])
                assert emit[i, accepted:].tolist() == \
                    [-1] * (k - accepted)
            np.testing.assert_array_equal(np.asarray(pos_new),
                                          np.asarray(pos) + accepted)
            # the continuation token is the eager token at the first
            # un-emitted position — the rejected offset's verify
            # sample IS correct, rejection costs only the draft work
            want = eager[:, accepted] if accepted < k else eager_next
            np.testing.assert_array_equal(np.asarray(cur_new), want)
            assert bool(act_new.all())


class TestSpeculativeResilience:
    def test_crash_mid_speculation_failover_replays_identical(
            self, bundle):
        """An engine abandoned mid-speculation (chunks in flight,
        rounds half-accepted) loses nothing the replay contract needs:
        a survivor re-running the same request from token zero — with
        OR without speculation — emits the byte-identical stream.
        Speculation holds no hidden sampling state; (codes, seed) fully
        determine the tokens."""
        params, vae_params = bundle
        ref = reference_tokens(params, vae_params, REQS[0])
        crashed = Engine(params, CFG, RequestQueue(max_depth=4),
                         num_slots=2, chunk_steps=2, speculative=4,
                         draft_layers=1)
        h0 = crashed.queue.submit(REQS[0])
        crashed.step_once()
        crashed.step_once()         # chunks in flight, mid-speculation
        assert not h0.done()
        crashed.fenced = True       # the supervisor's kill switch —
        #                             this engine never fulfils h0
        for spec in (4, 0):
            survivor = Engine(params, CFG, RequestQueue(max_depth=4),
                              num_slots=2, chunk_steps=2,
                              speculative=spec,
                              draft_layers=1 if spec else 0)
            h = survivor.queue.submit(Request(codes=REQS[0].codes,
                                              seed=REQS[0].seed))
            survivor.run_until_idle()
            np.testing.assert_array_equal(
                np.asarray(h.result(timeout=5).tokens), ref)

    def test_migration_mid_speculation_byte_identical(self, bundle):
        """Live migration out of a SPECULATIVE paged engine mid-stream:
        the export payload (emitted prefix + pos + rng row + KV pages)
        fully describes the stream — rejected-draft rows past pos are
        stale by the write-before-read invariant and never ship — so
        the target (itself speculative) finishes byte-identical."""
        params, vae_params = bundle
        ref = reference_tokens(params, vae_params, REQS[0])
        kw = dict(num_slots=2, chunk_steps=1, kv="paged", page_size=4,
                  speculative=4, draft_layers=1)
        src = Engine(params, CFG, RequestQueue(max_depth=4), **kw)
        dst = Engine(params, CFG, RequestQueue(max_depth=4), **kw)
        h = src.queue.submit(REQS[0])
        rid = h.request.request_id
        import time as _time
        deadline = _time.perf_counter() + 120.0
        while _time.perf_counter() < deadline:
            src.step_once()
            if h.done():
                raise AssertionError("finished before export window")
            if src.progress_snapshot().get(rid, 0) >= 4:
                break
        payload, handle = src.export_request(rid)
        assert len(payload["emitted"]) >= 4
        dst.import_slot(payload, handle)
        dst.run_until_idle()
        res = h.result(timeout=30)
        assert res.status == OK
        np.testing.assert_array_equal(np.asarray(res.tokens), ref)


class TestSpeculativeMesh:
    def test_mesh_engine_speculative_identity(self, bundle):
        """The spec loop returns the same (cur_tok, pos, active, cache,
        ring) structure the mesh engine pins replicated/sharded output
        shardings onto, so a 2-device MeshEngine speculates unchanged —
        and byte-identical to the single-device eager stream."""
        from dalle_pytorch_tpu.serve.mesh_engine import MeshEngine
        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip("needs 2 devices (conftest forces 8 on CPU)")
        params, vae_params = bundle
        refs = [reference_tokens(params, vae_params, r) for r in REQS]
        queue = RequestQueue(max_depth=8)
        engine = MeshEngine(params, CFG, queue, devices=devs[:2],
                            num_slots=2, chunk_steps=2, speculative=4,
                            draft_layers=1)
        handles = [queue.submit(r) for r in REQS]
        engine.run_until_idle()
        for h, ref in zip(handles, refs):
            np.testing.assert_array_equal(
                np.asarray(h.result(timeout=5).tokens), ref)
        assert engine.decode_traces == 1


class TestSpeculativeValidation:
    def test_rejects_sparse_reads_combo(self, bundle):
        params, _ = bundle
        sp_cfg = D.DALLEConfig(
            dim=16, depth=2, vae=VCFG, num_text_tokens=50,
            text_seq_len=8, heads=2, dim_head=8,
            sparse_attn=(False, True), sparse_block=4)
        sp_params = D.dalle_init(jax.random.PRNGKey(0), sp_cfg)
        with pytest.raises(ValueError, match="sparse_reads"):
            Engine(sp_params, sp_cfg, RequestQueue(max_depth=4),
                   kv="paged", page_size=8, sparse_reads=True,
                   speculative=4)

    def test_rejects_bad_draft_depth(self, bundle):
        params, _ = bundle
        with pytest.raises(ValueError, match="draft_layers"):
            Engine(params, CFG, RequestQueue(max_depth=4),
                   speculative=4, draft_layers=3)
        with pytest.raises(ValueError, match="speculative"):
            Engine(params, CFG, RequestQueue(max_depth=4),
                   speculative=-1)

    def test_draft_helpers_slice_consistently(self, bundle):
        params, _ = bundle
        d = 1
        dcfg = D.draft_transformer_config(CFG.transformer, d)
        assert dcfg.depth == d
        assert dcfg.sparse_pattern == CFG.transformer.sparse_pattern[:d]
        dp = D.draft_transformer_params(params["transformer"], d)
        for leaf, full in zip(jax.tree.leaves(dp),
                              jax.tree.leaves(params["transformer"])):
            assert leaf.shape[0] == d
            np.testing.assert_array_equal(np.asarray(leaf),
                                          np.asarray(full[:d]))
        with pytest.raises(ValueError):
            D.draft_transformer_config(CFG.transformer, 0)
