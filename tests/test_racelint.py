"""racelint + lock-order-sanitizer tests (ISSUE 18 acceptance criteria).

Same contract shape as test_analysis.py pins for jaxlint: the rule
corpus under ``tests/fixtures/racelint/`` carries true-positive lines
marked ``# expect: RLxxx`` AND must-not-flag snippets of the
neighbouring legal idiom, and the parametrized test asserts EXACT
agreement — a rule that goes quiet or starts flagging the serve tier's
own idioms fails tier-1 either way. Plus: the shared-lintcore
suppression contract, JSON/CLI/exit codes, cross-module cycle
detection, the repo-clean gate, and the ``guards`` runtime lock-order
sanitizer validated against the statically exported graph.

All AST-only and pure-Python — no jax, no device.
"""

import json
import re
import subprocess
import sys
import threading
from pathlib import Path

import pytest

from dalle_pytorch_tpu.analysis import guards
from dalle_pytorch_tpu.analysis import racelint

pytestmark = pytest.mark.analysis

FIXTURES = Path(__file__).parent / "fixtures" / "racelint"
RULE_FILES = sorted(FIXTURES.glob("rl0*.py"))
_EXPECT_RE = re.compile(r"#\s*expect:\s*(RL\d{3}(?:\s*,\s*RL\d{3})*)")


def expected_findings(path: Path):
    """(line, rule) pairs declared by `# expect: RLxxx` markers."""
    out = set()
    for i, line in enumerate(path.read_text().splitlines(), start=1):
        m = _EXPECT_RE.search(line)
        if m:
            for rule in m.group(1).split(","):
                out.add((i, rule.strip()))
    return out


class TestRuleCorpus:
    @pytest.mark.parametrize(
        "path", RULE_FILES, ids=[p.stem for p in RULE_FILES])
    def test_rule_fixture_exact_agreement(self, path):
        expected = expected_findings(path)
        assert expected, f"{path.name} has no # expect markers"
        actual = {(f.line, f.rule) for f in racelint.lint_file(path)}
        missed = expected - actual
        spurious = actual - expected
        assert not missed, f"rule went quiet, missed: {sorted(missed)}"
        assert not spurious, \
            f"flagged legal idiom lines: {sorted(spurious)}"

    def test_corpus_covers_every_rule(self):
        covered = set()
        for path in RULE_FILES:
            covered |= {rule for _, rule in expected_findings(path)}
        # RL002's cycle half needs two modules; the cross pair below
        # covers it too, but the solo corpus must already hit each rule
        assert covered == set(racelint.RULES), \
            f"rules without a true-positive fixture: " \
            f"{sorted(set(racelint.RULES) - covered)}"

    def test_seeded_violation_fixture_is_dirty(self):
        """The CI gate lints this fixture expecting a nonzero exit; if
        someone 'fixes' it the gate stops proving anything."""
        findings = racelint.lint_file(FIXTURES / "seeded_violation.py")
        assert {f.rule for f in findings} >= {"RL003", "RL006"}


class TestSuppression:
    def test_suppressed_corpus_is_clean(self):
        """Every waiver form (trailing, line-above, slug, comma list,
        `all`) silences its finding."""
        assert racelint.lint_file(FIXTURES / "suppressed.py") == []

    def test_unwaived_sibling_still_flagged(self):
        """A waiver is line-scoped: the same violation one line later
        without a comment still fires."""
        src = (
            "import time\n"
            "def f(t):\n"
            "    a = time.time() + t  # racelint: disable=RL006 — ok\n"
            "    b = time.time() + t\n"
            "    return a, b\n"
        )
        findings = racelint.lint_source(src)
        assert [(f.line, f.rule) for f in findings] == [(4, "RL006")]

    def test_unknown_rule_in_waiver_ignored(self):
        src = ("import time\n"
               "def f(t):\n"
               "    return time.time() + t  # racelint: disable=RL999\n")
        assert [f.rule for f in racelint.lint_source(src)] == ["RL006"]

    def test_jaxlint_waiver_does_not_silence_racelint(self):
        """The two tools share one parser but each only honors its own
        tool name — a jaxlint waiver on a racelint finding is inert."""
        src = ("import time\n"
               "def f(t):\n"
               "    return time.time() + t  # jaxlint: disable=JL007\n")
        assert [f.rule for f in racelint.lint_source(src)] == ["RL006"]


class TestCLI:
    def test_json_output_and_exit_code(self, capsys):
        rc = racelint.main(
            ["--json", "--no-default-excludes",
             str(FIXTURES / "seeded_violation.py")])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert out["files"] == 1
        rules = {f["rule"] for f in out["findings"]}
        assert "RL003" in rules and "RL006" in rules
        for f in out["findings"]:
            assert set(f) == {"rule", "slug", "path", "line", "col",
                              "message"}

    def test_clean_file_exits_zero(self, tmp_path, capsys):
        p = tmp_path / "clean.py"
        p.write_text("import time\nt0 = time.monotonic()\n")
        assert racelint.main([str(p)]) == 0

    def test_default_excludes_skip_own_corpus(self, capsys):
        """`racelint tests` must exit 0 on the merged tree even though
        the true-positive corpus lives under tests/ — the corpus is
        excluded by default and reachable via --no-default-excludes."""
        files = racelint.iter_py_files([str(FIXTURES)])
        assert files == []
        files = racelint.iter_py_files([str(FIXTURES)], excludes=())
        assert len(files) >= 10

    def test_select_and_ignore(self, capsys):
        rc = racelint.main(["--json", "--select", "RL006",
                            "--no-default-excludes",
                            str(FIXTURES / "seeded_violation.py")])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert {f["rule"] for f in out["findings"]} == {"RL006"}
        rc = racelint.main(["--ignore", "RL003,RL006",
                            "--no-default-excludes",
                            str(FIXTURES / "seeded_violation.py")])
        capsys.readouterr()
        assert rc == 0

    def test_unknown_rule_is_usage_error(self, capsys):
        assert racelint.main(["--select", "RL999", "x.py"]) == 2

    def test_list_rules(self, capsys):
        assert racelint.main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rid in racelint.RULES:
            assert rid in out

    @pytest.mark.slow
    def test_module_entrypoint_subprocess(self):
        """The form Makefile/CI invoke: python -m ... exits 1 on the
        seeded fixture, 0 with it excluded by default."""
        proc = subprocess.run(
            [sys.executable, "-m", "dalle_pytorch_tpu.analysis.racelint",
             "--no-default-excludes", str(FIXTURES / "seeded_violation.py")],
            capture_output=True, text=True, cwd=Path(__file__).parents[1])
        assert proc.returncode == 1, proc.stderr


class TestCrossModule:
    """Project mode (``racelint.lint_files`` — what the CLI and the
    repo-clean test run): the lock-order cycle spans two modules, each
    half clean alone because the peer class resolves only when both
    files are in one run. The propagation, not a rule change, is what
    fires the finding."""

    PAIR = [FIXTURES / "cross_order_a.py",
            FIXTURES / "cross_order_b.py"]
    _CROSS_RE = re.compile(r"#\s*cross-expect:\s*(RL\d{3})")

    def _expected(self):
        out = set()
        for p in self.PAIR:
            for i, line in enumerate(p.read_text().splitlines(),
                                     start=1):
                m = self._CROSS_RE.search(line)
                if m:
                    out.add((p.name, i, m.group(1)))
        return out

    def test_solo_mode_is_blind_to_the_pair(self):
        for p in self.PAIR:
            assert racelint.lint_file(p) == [], p.name

    def test_project_mode_exact_agreement(self):
        expected = self._expected()
        assert expected, "pair has no # cross-expect markers"
        assert {"RL002"} == {r for _, _, r in expected}
        actual = {(Path(f.path).name, f.line, f.rule)
                  for f in racelint.lint_files(self.PAIR)}
        missed = expected - actual
        spurious = actual - expected
        assert not missed, f"cross-module cycle went quiet: " \
                           f"{sorted(missed)}"
        assert not spurious, f"flagged legal cross-module idiom: " \
                             f"{sorted(spurious)}"

    def test_pair_edges_exported(self):
        edges = racelint.lock_order_edges(self.PAIR)
        assert ("PeerA._la", "PeerB._lb") in edges
        assert ("PeerB._lb", "PeerA._la") in edges


class TestRepoIsClean:
    def test_package_and_tests_lint_clean(self):
        """The merged-tree acceptance criterion, as a tier-1 test: every
        concurrency finding in the package, tests, scripts, and bench —
        including whole-program lock-order and blocking propagation —
        is fixed or carries an in-line reasoned waiver."""
        root = Path(__file__).parents[1]
        files = racelint.iter_py_files(
            [str(root / "dalle_pytorch_tpu"), str(root / "tests"),
             str(root / "scripts"), str(root / "bench.py")])
        findings = racelint.lint_files(files)
        assert findings == [], "\n".join(x.render() for x in findings)


class TestSanitizer:
    """guards.py's LockOrderRecorder/TrackedLock — racelint RL002's
    runtime twin."""

    def test_inverted_order_raises(self):
        rec = guards.LockOrderRecorder()
        a = guards.TrackedLock("A._la", rec)
        b = guards.TrackedLock("B._lb", rec)
        with a:
            with b:
                pass
        with pytest.raises(guards.LockOrderError) as ei:
            with b:
                with a:
                    pass
        assert ei.value.first == "B._lb"
        assert ei.value.second == "A._la"

    def test_transitive_inversion_caught(self):
        """A->B and B->C observed; C->A closes a 3-cycle even though
        the pair (C, A) was never seen directly."""
        rec = guards.LockOrderRecorder()
        la = guards.TrackedLock("A", rec)
        lb = guards.TrackedLock("B", rec)
        lc = guards.TrackedLock("C", rec)
        with la:
            with lb:
                pass
        with lb:
            with lc:
                pass
        with pytest.raises(guards.LockOrderError) as ei:
            with lc:
                with la:
                    pass
        assert ei.value.chain == ["A", "B", "C"]

    def test_consistent_order_is_silent(self):
        rec = guards.LockOrderRecorder()
        a = guards.TrackedLock("A", rec)
        b = guards.TrackedLock("B", rec)
        for _ in range(3):
            with a:
                with b:
                    pass
        assert rec.edges() == {("A", "B")}

    def test_tracked_lock_passthrough(self):
        rec = guards.LockOrderRecorder()
        lk = guards.TrackedLock("X", rec)
        assert lk.acquire(True, 0.1)
        assert lk.locked()
        # contended timed acquire fails without recording
        assert not lk.acquire(False)
        lk.release()
        assert not lk.locked()
        assert rec.edges() == set()

    def test_instrument_locks_names_and_wraps(self):
        class Thing:
            def __init__(self):
                self._lock = threading.Lock()
                self.data = []
        t = Thing()
        rec = guards.LockOrderRecorder()
        names = guards.instrument_locks(t, rec)
        assert names == ["Thing._lock"]
        assert isinstance(t._lock, guards.TrackedLock)
        with t._lock:
            pass
        # cls_name override: racelint names locks after the DEFINING
        # class, so a subclass instance must be instrumentable under
        # its base's name
        t2 = Thing()
        assert guards.instrument_locks(t2, rec, cls_name="Base") \
            == ["Base._lock"]

    def test_assert_consistent_with(self):
        rec = guards.LockOrderRecorder()
        with guards.TrackedLock("A", rec):
            with guards.TrackedLock("B", rec):
                pass
        rec.assert_consistent_with({("A", "B"), ("B", "C")})
        with pytest.raises(AssertionError, match="A -> B"):
            rec.assert_consistent_with({("B", "C")})

    def test_serve_drive_matches_static_graph(self):
        """The acceptance check: instrument real serve objects, drive a
        requeue-after-drain (which fulfils the handle and summarizes
        its trace UNDER the queue lock), and assert every runtime edge
        was predicted by ``racelint.lock_order_edges`` over the
        package. A hole in the static call-graph resolution — or a new
        nested acquire racelint cannot see — fails here, not in
        production."""
        from dalle_pytorch_tpu.serve import scheduler
        rec = guards.LockOrderRecorder()
        q = scheduler.RequestQueue(max_depth=4)
        guards.instrument_locks(q, rec)
        h = q.submit(scheduler.Request(codes=(1, 2, 3)))
        guards.instrument_locks(h, rec)
        assert h.trace is not None
        guards.instrument_locks(h.trace, rec)
        q.close()
        q.drain()
        q.requeue(h)          # post-drain: fulfils under RequestQueue._lock
        assert h.done()
        observed = rec.edges()
        assert ("RequestQueue._lock", "RequestHandle._fulfill_lock") \
            in observed
        root = Path(__file__).parents[1]
        files = racelint.iter_py_files([str(root / "dalle_pytorch_tpu")])
        rec.assert_consistent_with(racelint.lock_order_edges(files))

    def test_sanitizer_catches_seeded_inversion_against_static(self):
        """An edge the static graph does NOT predict fails the
        consistency check — the gate half of the contract."""
        rec = guards.LockOrderRecorder()
        with guards.TrackedLock("RequestHandle._fulfill_lock", rec):
            with guards.TrackedLock("RequestQueue._lock", rec):
                pass
        root = Path(__file__).parents[1]
        files = racelint.iter_py_files([str(root / "dalle_pytorch_tpu")])
        with pytest.raises(AssertionError, match="not predicted"):
            rec.assert_consistent_with(racelint.lock_order_edges(files))
