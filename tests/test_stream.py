"""Streaming tier tests (serve/stream.py + the engine's sink feed).

Two layers, matching the module's trust model:

  * the CHANNEL is exact without a backend: absolute-position replay
    dedupe (eviction/failover replay delivers every position at most
    once), the typed drop-oldest overflow policy (the engine never
    blocks, the gap is named, terminals survive any backlog),
    group-atomic close countdown, heartbeat synthesis, SSE wire
    framing, and bit-exact image packing — all jax-free unit tests;
  * the ENGINE feed preserves identity: a streamed request's token
    events, reassembled by position, are byte-identical to the same
    seed's non-streamed result (streaming moves observation, never
    computation); a torn SSE connection mid-stream cancels the request
    and the engine's done-handle reap frees its slot AND its KV pages;
    a slow consumer costs dropped events (typed), never engine
    progress and never a truncated terminal result.

Tiny model (test_serve's 24-position config), all CPU, tier-1 cheap.
"""

import threading
import time

import numpy as np
import pytest

from dalle_pytorch_tpu.serve import scheduler as S
from dalle_pytorch_tpu.serve import stream as st
from dalle_pytorch_tpu.serve.stream import TokenSink

# ---------------------------------------------------------------------------
# channel semantics (no jax)
# ---------------------------------------------------------------------------


class TestSinkBasics:
    def test_events_in_order_and_tagged(self):
        sink = TokenSink(request_id=9)
        sink.push_tokens(0, [1, 2])
        sink.push_tokens(2, [3])
        sink.close(S.Result(status=S.OK, request_id=9,
                            tokens=np.asarray([1, 2, 3])))
        evs = list(sink.events())
        assert [e["event"] for e in evs] == ["tokens", "tokens",
                                            "sample_done"]
        assert evs[0]["pos"] == 0 and evs[0]["tokens"] == [1, 2]
        assert evs[1]["pos"] == 2 and evs[1]["tokens"] == [3]
        assert all(e["request_id"] == 9 for e in evs)
        assert evs[-1]["status"] == S.OK and evs[-1]["n_tokens"] == 3
        assert sink.done

    def test_replay_duplicate_prefix_dropped(self):
        """Failover replay re-pushes from position zero; the high-water
        mark delivers every position exactly once."""
        sink = TokenSink()
        sink.push_tokens(0, [1, 2, 3])
        sink.push_tokens(0, [1, 2, 3])          # full replay duplicate
        sink.push_tokens(1, [2, 3, 4, 5])       # overlapping: only 4,5 new
        sink.push_tokens(3, [4, 5])             # already delivered
        got = []
        while (ev := sink.get(timeout=0)) is not None:
            got.append((ev["pos"], ev["tokens"]))
        assert got == [(0, [1, 2, 3]), (3, [4, 5])]

    def test_push_after_close_is_dropped(self):
        sink = TokenSink()
        sink.close(S.Result(status=S.OK, request_id=0))
        sink.push_tokens(0, [1])
        evs = list(sink.events())
        assert [e["event"] for e in evs] == ["sample_done"]

    def test_close_is_idempotent_first_wins(self):
        sink = TokenSink()
        sink.close(S.Result(status=S.OK, request_id=0))
        sink.close(S.Result(status=S.ERROR, request_id=0, reason="late"))
        evs = list(sink.events())
        assert len(evs) == 1 and evs[0]["status"] == S.OK
        assert sink.result.status == S.OK

    def test_replayable_ignores_nonforced_cancel(self):
        """A gateway-owned sink survives the cell-side failover cancel:
        only the owner's forced close (or a genuine completion) is
        terminal."""
        sink = TokenSink()
        sink.replayable = True
        sink.close(S.Result(status=S.CANCELLED, request_id=0,
                            reason="cell died"))
        assert not sink.closed
        sink.push_tokens(0, [1])                # replay still lands
        sink.close(S.Result(status=S.OK, request_id=0), force=True)
        assert sink.closed and sink.result.status == S.OK


class TestOverflow:
    def test_slow_consumer_typed_not_blocking(self):
        """A consumer that never reads: pushes past the ring shed the
        OLDEST droppable event and return immediately; the next read is
        prefixed with a synthetic overflow event naming the gap; the
        terminal still lands."""
        sink = TokenSink(max_events=4)
        t0 = time.perf_counter()
        for i in range(20):
            sink.push_tokens(i, [i])
        assert time.perf_counter() - t0 < 0.5   # never blocked
        sink.close(S.Result(status=S.OK, request_id=0))
        evs = list(sink.events())
        assert evs[0]["event"] == "overflow"
        # 16 shed by the push storm + 1 more when the terminal claimed
        # its slot in the full ring
        assert evs[0]["dropped"] == 17
        assert evs[0]["total_dropped"] == sink.dropped == 17
        # the oldest were shed: the survivors are the NEWEST positions
        poss = [e["pos"] for e in evs if e["event"] == "tokens"]
        assert poss == [17, 18, 19]
        assert evs[-1]["event"] == "sample_done"

    def test_terminal_never_dropped(self):
        sink = TokenSink(max_events=4)
        for i in range(10):
            sink.push_tokens(i, [i])
        sink.close(S.Result(status=S.OK, request_id=0))
        for i in range(10, 20):                 # after close: dropped
            sink.push_tokens(i, [i])
        kinds = [e["event"] for e in sink.events()]
        assert kinds.count("sample_done") == 1

    def test_min_ring_size_enforced(self):
        with pytest.raises(ValueError, match="max_events"):
            TokenSink(max_events=2)


class TestGroupChannel:
    def test_group_atomic_close(self):
        """N sinks over one channel: events carry their sample tag and
        the multiplexed stream ends only after ALL members close."""
        sinks = TokenSink.group(3)
        sinks[1].push_tokens(0, [7])
        sinks[0].close(S.Result(status=S.OK, request_id=0))
        sinks[2].close(S.Result(status=S.OK, request_id=2))
        assert not sinks[0].done                # member 1 still live
        sinks[1].close(S.Result(status=S.ERROR, request_id=1,
                                reason="boom"))
        evs = list(sinks[0].events())
        assert [e["event"] for e in evs] == [
            "tokens", "sample_done", "sample_done", "sample_done"]
        assert evs[0]["sample"] == 1
        assert sorted(e["sample"] for e in evs[1:]) == [0, 1, 2]
        assert all(s.done for s in sinks)

    def test_heartbeat_synthesized_when_quiet(self):
        sink = TokenSink()

        def close_late():
            time.sleep(0.12)
            sink.close(S.Result(status=S.OK, request_id=0))

        t = threading.Thread(target=close_late)
        t.start()
        kinds = [e["event"] for e in sink.events(heartbeat_s=0.03)]
        t.join()
        assert "heartbeat" in kinds
        assert kinds[-1] == "sample_done"


class TestWireForms:
    def test_sse_framing(self):
        b = st.sse_bytes({"event": "tokens", "pos": 3, "tokens": [1]})
        assert b.startswith(b"event: tokens\ndata: ")
        assert b.endswith(b"\n\n")
        import json
        payload = json.loads(
            b.split(b"data: ", 1)[1].strip().decode())
        assert payload == {"pos": 3, "tokens": [1]}

    def test_pack_unpack_image_bit_exact(self):
        rng = np.random.default_rng(0)
        for dtype in (np.float32, np.uint8):
            img = rng.standard_normal((4, 4, 3)).astype(dtype)
            out = st.unpack_image(st.pack_image(img))
            assert out.dtype == img.dtype and out.shape == img.shape
            np.testing.assert_array_equal(out, img)


# ---------------------------------------------------------------------------
# the engine feed (tiny model)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def bundle():
    import jax

    from dalle_pytorch_tpu.models import dalle as D
    from dalle_pytorch_tpu.models import vae as V

    vcfg = V.VAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                       num_layers=2, hidden_dim=8)
    cfg = D.DALLEConfig(dim=16, depth=2, vae=vcfg, num_text_tokens=50,
                        text_seq_len=8, heads=2, dim_head=8)
    key = jax.random.PRNGKey(0)
    vae_params = V.vae_init(jax.random.fold_in(key, 1), vcfg)
    params = D.dalle_init(key, cfg, vae_params)
    return params, cfg


def _engine(params, cfg, **kw):
    from dalle_pytorch_tpu.serve import RequestQueue
    from dalle_pytorch_tpu.serve.engine import Engine

    queue = RequestQueue(max_depth=16)
    return Engine(params, cfg, queue, num_slots=2, chunk_steps=4,
                  **kw), queue


class TestEngineFeed:
    def test_streamed_tokens_byte_identical_to_result(self, bundle):
        """THE identity: reassemble the sink's token events by absolute
        position — the suffix of length len(result.tokens) must equal
        the terminal result byte-for-byte, and that result must equal
        the same request run WITHOUT a sink (streaming is observation
        only)."""
        params, cfg = bundle
        engine, queue = _engine(params, cfg)
        req = S.Request(codes=(3, 7, 9), seed=11, stream=True)
        sink = TokenSink()
        h = queue.submit(req, sink=sink)
        engine.run_until_idle()
        res = h.result(timeout=30)
        assert res.status == S.OK
        by_pos = {}
        for ev in sink.events():
            if ev["event"] == "tokens":
                by_pos[ev["pos"]] = ev["tokens"]
        toks = []
        for pos in sorted(by_pos):
            toks.extend(by_pos[pos])
        np.testing.assert_array_equal(
            np.asarray(toks[-len(res.tokens):], np.int32),
            np.asarray(res.tokens))
        # and the terminal sample_done rode the fulfill funnel
        assert sink.result is res

        plain = queue.submit(S.Request(codes=(3, 7, 9), seed=11))
        engine.run_until_idle()
        np.testing.assert_array_equal(
            np.asarray(plain.result(timeout=30).tokens),
            np.asarray(res.tokens))

    def test_torn_connection_cancels_and_frees_pages(self, bundle):
        """The SSE writer's disconnect path fulfils CANCELLED
        mid-stream; the engine's done-handle reap must kill the slot
        and return every KV page — no generation into the void, no
        leaked pages."""
        params, cfg = bundle
        engine, queue = _engine(params, cfg, kv="paged", page_size=8)
        sink = TokenSink()
        h = queue.submit(S.Request(codes=(3, 7, 9), seed=11,
                                   stream=True), sink=sink)
        # drive until the stream is genuinely live (first chunk landed)
        deadline = time.perf_counter() + 30
        while sink.get(timeout=0) is None:
            engine.step_once()
            assert time.perf_counter() < deadline
        assert engine.alloc.in_use > 0
        # the disconnect: exactly what Handler._stream_sse does
        h.fulfill(S.Result(status=S.CANCELLED,
                           request_id=h.request.request_id,
                           reason="client disconnected mid-stream"))
        engine.run_until_idle()
        assert engine.reaped >= 1
        assert engine.alloc.in_use == 0, "cancel must free the KV pages"
        assert sink.closed and sink.result.status == S.CANCELLED
        # the channel ended cleanly for the (gone) consumer too
        assert list(sink.events())[-1]["event"] == "sample_done"

    def test_slow_consumer_overflow_result_still_complete(self, bundle):
        """A tiny ring and a consumer that reads nothing until the end:
        the engine completes normally, the overflow is typed, and the
        terminal result still carries the COMPLETE token sequence."""
        params, cfg = bundle
        engine, queue = _engine(params, cfg)
        sink = TokenSink(max_events=4)
        h = queue.submit(S.Request(codes=(6, 6), seed=5, stream=True),
                         sink=sink)
        engine.run_until_idle()
        res = h.result(timeout=30)
        assert res.status == S.OK
        assert len(res.tokens) == cfg.image_seq_len
        evs = list(sink.events())
        assert evs[0]["event"] == "overflow" and sink.dropped > 0
        assert evs[-1]["event"] == "sample_done"
