"""Pipeline parallelism: GPipe-style microbatched transformer over a ``pp``
mesh axis.

The reference has no pipeline (or any) parallelism (SURVEY.md §2.12/§2b);
this is new TPU-native design: the depth-stacked layer tree is sharded so
each of the P pipeline stages holds ``depth/P`` consecutive layers, the
batch splits into M microbatches, and activations flow stage-to-stage with
``lax.ppermute`` over ICI inside one ``shard_map`` program. The schedule is
the classic (M + P - 1)-tick pipeline: at tick t, stage s runs microbatch
``t - s`` (when in range) through its layer slice; XLA overlaps each tick's
neighbor transfer with compute.

Everything is a single jit-compiled SPMD program — no userland send/recv
runtime — and it is differentiable end to end: the scan-over-ticks
transposes into the reverse pipeline schedule and the ``ppermute`` into the
reverse rotation.

Composes with data parallelism by sharding the microbatch dimension over a
``dp`` axis of the same mesh (``dp_axis=``); tensor/sequence parallelism
apply within a stage exactly as without pp.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map            # jax >= 0.8
except ImportError:                      # pragma: no cover
    from jax.experimental.shard_map import shard_map

Array = jax.Array


def _stage_pattern(cfg, num_stages: int):
    """Per-stage sparse pattern — must be identical across stages (the
    stage body is one SPMD program; a stage-dependent pattern would need a
    traced cond, which the static-unroll design deliberately avoids)."""
    depth_per = cfg.depth // num_stages
    pattern = cfg.sparse_pattern
    slices = {pattern[s * depth_per:(s + 1) * depth_per]
              for s in range(num_stages)}
    if len(slices) != 1:
        raise ValueError(
            f"sparse pattern {pattern} is not stage-invariant over "
            f"{num_stages} pipeline stages of {depth_per} layers — every "
            "stage must see the same dense/sparse slice")
    return next(iter(slices))


def pipeline_transformer(params, x: Array, *, cfg, mesh: Mesh,
                         axis: str = "pp",
                         num_microbatches: Optional[int] = None,
                         dp_axis: Optional[str] = None,
                         mask: Optional[Array] = None) -> Array:
    """Run the transformer stack pipelined over ``mesh.shape[axis]`` stages.

    params: depth-stacked layer tree (leading axis ``cfg.depth``).
    x: (b, n, dim); b must divide into ``num_microbatches`` (default = the
    stage count P; more microbatches shrink the P-1-tick bubble).
    mask: optional (b, n) pad mask, routed to attention per microbatch.
    dp_axis: additionally shard the microbatch dimension over this mesh
    axis (pipeline x data parallel in one program).

    Returns the same (b, n, dim) as ``transformer_apply`` on one device —
    parity-tested on the CPU mesh. Eval semantics (dropout inert, as with
    ``train=False``); ``reversible=True`` is rejected (different math).
    """
    from dalle_pytorch_tpu.ops.transformer import transformer_apply

    num_stages = mesh.shape[axis]
    if cfg.depth % num_stages:
        raise ValueError(f"depth {cfg.depth} not divisible by pipeline "
                         f"stages {num_stages}")
    if cfg.reversible:
        # the reversible engine's two-stream math differs from the plain
        # stack — running it as sequential stages would silently change the
        # function; pp + reversible is a future combination
        raise NotImplementedError(
            "pipeline_transformer does not support reversible=True")
    depth_per = cfg.depth // num_stages
    # eval semantics: dropout rates in the config are inert (no train path),
    # exactly as transformer_apply(train=False)
    stage_cfg = dataclasses.replace(
        cfg, depth=depth_per, sparse_attn=_stage_pattern(cfg, num_stages))

    M = num_microbatches or num_stages
    b, n, d = x.shape
    if b % M:
        raise ValueError(f"batch {b} not divisible by {M} microbatches")
    mb = b // M

    # stage-major layer stack: (P, depth/P, ...), stage axis sharded on pp
    stacked = jax.tree.map(
        lambda a: a.reshape(num_stages, depth_per, *a.shape[1:]), params)
    xm = x.reshape(M, mb, n, d)
    has_mask = mask is not None
    maskm = (mask.reshape(M, mb, n) if has_mask
             else jnp.ones((M, 1, 1), bool))              # dead placeholder

    def stage_fn(stage_params, xm, maskm):
        sp = jax.tree.map(lambda a: a[0], stage_params)   # local layer slice
        P_ = lax.axis_size(axis)
        idx = lax.axis_index(axis)
        ticks = M + P_ - 1
        # pad the input stream so ticks beyond M feed (ignored) zeros
        pad = jnp.zeros((P_ - 1, *xm.shape[1:]), xm.dtype)
        stream = jnp.concatenate([xm, pad], axis=0)
        # the microbatch at this stage at tick t is t - idx: pre-gather each
        # tick's pad mask per stage (clipped; out-of-range ticks are idle
        # and their outputs never selected)
        masks = jax.vmap(
            lambda t: maskm[jnp.clip(t - idx, 0, M - 1)])(jnp.arange(ticks))

        def tick(state, xs):
            inp, m_in = xs
            # stage 0 ingests the next microbatch; others use the handoff
            h = jnp.where(idx == 0, inp, state)
            m = m_in if has_mask else None
            out = transformer_apply(sp, h, cfg=stage_cfg, mask=m)
            nxt = lax.ppermute(out, axis,
                               [(i, (i + 1) % P_) for i in range(P_)])
            return nxt, out

        # the carry is device-varying over pp (each stage holds a different
        # microbatch's activations) — mark the zero init accordingly
        state0 = lax.pcast(jnp.zeros_like(xm[0]), (axis,), to="varying")
        _, outs = lax.scan(tick, state0, (stream[:ticks], masks))
        # stage s finishes microbatch m at tick m + s: the last stage's
        # outputs at ticks P-1 .. M+P-2 are the final activations, in order
        final = outs[P_ - 1:]
        final = jnp.where(idx == P_ - 1, final, jnp.zeros_like(final))
        return lax.psum(final, axis)                      # select last stage

    data_spec = P(None, dp_axis) if dp_axis else P()
    mask_spec = data_spec if has_mask else P()    # placeholder: replicate
    out = shard_map(stage_fn, mesh=mesh,
                    in_specs=(P(axis), data_spec, mask_spec),
                    out_specs=data_spec)(stacked, xm, maskm)
    return out.reshape(b, n, d)
