"""Pipeline parallelism: GPipe-style microbatched transformer over a ``pp``
mesh axis.

The reference has no pipeline (or any) parallelism (SURVEY.md §2.12/§2b);
this is new TPU-native design: the depth-stacked layer tree is sharded so
each of the P pipeline stages holds ``depth/P`` consecutive layers, the
batch splits into M microbatches, and activations flow stage-to-stage with
``lax.ppermute`` over ICI inside one ``shard_map`` program. The schedule is
the classic (M + P - 1)-tick pipeline: at tick t, stage s runs microbatch
``t - s`` (when in range) through its layer slice; XLA overlaps each tick's
neighbor transfer with compute.

Everything is a single jit-compiled SPMD program — no userland send/recv
runtime — and it is differentiable end to end: the scan-over-ticks
transposes into the reverse pipeline schedule and the ``ppermute`` into the
reverse rotation.

Composes with data parallelism by sharding the microbatch dimension over a
``dp`` axis of the same mesh (``dp_axis=``); tensor/sequence parallelism
apply within a stage exactly as without pp. MoE layers compose too (r5):
the tick scan threads the Switch load-balance aux through to the loss,
and ``pp_param_specs(ep=...)`` shards each stage's expert stacks over an
``ep`` mesh axis that rides the shard_map as a GSPMD auto axis —
dp x pp x ep in one program (dryrun-proven with loss parity).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

# jax >= 0.8 required (pyproject pin): shard_map(axis_names=...) keeps
# non-pipeline mesh axes (e.g. 'ep') as GSPMD auto axes
from dalle_pytorch_tpu.parallel._compat import pcast_varying, shard_map

Array = jax.Array


def _stage_pattern(cfg, num_stages: int):
    """Per-stage sparse pattern — must be identical across stages (the
    stage body is one SPMD program; a stage-dependent pattern would need a
    traced cond, which the static-unroll design deliberately avoids)."""
    depth_per = cfg.depth // num_stages
    pattern = cfg.sparse_pattern
    slices = {pattern[s * depth_per:(s + 1) * depth_per]
              for s in range(num_stages)}
    if len(slices) != 1:
        raise ValueError(
            f"sparse pattern {pattern} is not stage-invariant over "
            f"{num_stages} pipeline stages of {depth_per} layers — every "
            "stage must see the same dense/sparse slice")
    return next(iter(slices))


def pipeline_transformer(params, x: Array, *, cfg, mesh: Mesh,
                         axis: str = "pp",
                         num_microbatches: Optional[int] = None,
                         dp_axis: Optional[str] = None,
                         mask: Optional[Array] = None,
                         rng=None, train: bool = False,
                         with_aux: bool = False):
    """Run the transformer stack pipelined over ``mesh.shape[axis]`` stages.

    params: depth-stacked layer tree (leading axis ``cfg.depth``).
    x: (b, n, dim); b must divide into ``num_microbatches`` (default = the
    stage count P; more microbatches shrink the P-1-tick bubble).
    mask: optional (b, n) pad mask, routed to attention per microbatch.
    dp_axis: additionally shard the microbatch dimension over this mesh
    axis (pipeline x data parallel in one program).
    rng/train: dropout, keyed per (stage, microbatch) — deterministic for a
    given rng, stage count, and microbatch split.

    Returns the same (b, n, dim) as ``transformer_apply`` on one device —
    parity-tested on the CPU mesh (grad parity too: the scan-over-ticks and
    the ppermute both transpose). ``reversible=True`` is rejected
    (different math). Idle ramp-up/ramp-down ticks skip the stage compute
    with ``lax.cond`` (local control flow is legal inside shard_map; the
    collective stays outside the branch).
    """
    from dalle_pytorch_tpu.ops.transformer import transformer_apply

    num_stages = mesh.shape[axis]
    if cfg.depth % num_stages:
        raise ValueError(f"depth {cfg.depth} not divisible by pipeline "
                         f"stages {num_stages}")
    if cfg.reversible:
        # the reversible engine's two-stream math differs from the plain
        # stack — running it as sequential stages would silently change the
        # function; pp + reversible is a future combination
        raise NotImplementedError(
            "pipeline_transformer does not support reversible=True")
    dropout_on = train and (cfg.attn_dropout > 0 or cfg.ff_dropout > 0)
    if dropout_on and rng is None:
        raise ValueError(
            "pipeline_transformer(train=True) with nonzero dropout requires "
            "an explicit `rng` key — JAX has no global RNG state")
    depth_per = cfg.depth // num_stages
    stage_cfg = dataclasses.replace(
        cfg, depth=depth_per, sparse_attn=_stage_pattern(cfg, num_stages))

    M = num_microbatches or num_stages
    b, n, d = x.shape
    if b % M:
        raise ValueError(f"batch {b} not divisible by {M} microbatches")
    mb = b // M

    # stage-major layer stack: (P, depth/P, ...), stage axis sharded on pp
    stacked = jax.tree.map(
        lambda a: a.reshape(num_stages, depth_per, *a.shape[1:]), params)
    xm = x.reshape(M, mb, n, d)
    has_mask = mask is not None
    maskm = (mask.reshape(M, mb, n) if has_mask
             else jnp.ones((M, 1, 1), bool))              # dead placeholder
    if rng is None:
        rng = jax.random.PRNGKey(0)          # dead value (dropout off)

    def stage_fn(stage_params, xm, maskm, rng):
        sp = jax.tree.map(lambda a: a[0], stage_params)   # local layer slice
        # static stage count from the enclosing mesh (== the manual axis
        # size; lax.axis_size is a jax>=0.8 addition — see parallel._compat)
        P_ = num_stages
        idx = lax.axis_index(axis)
        ticks = M + P_ - 1
        # pad the input stream so ticks beyond M feed (ignored) zeros
        pad = jnp.zeros((P_ - 1, *xm.shape[1:]), xm.dtype)
        stream = jnp.concatenate([xm, pad], axis=0)
        # the microbatch at this stage at tick t is t - idx: pre-gather each
        # tick's pad mask per stage (clipped; out-of-range ticks are idle
        # and their outputs never selected)
        masks = jax.vmap(
            lambda t: maskm[jnp.clip(t - idx, 0, M - 1)])(jnp.arange(ticks))
        rng_stage = jax.random.fold_in(rng, idx)

        def tick(state, xs):
            t, inp, m_in = xs
            # stage 0 ingests the next microbatch; others use the handoff
            h = jnp.where(idx == 0, inp, state)
            m = m_in if has_mask else None
            mb_idx = t - idx
            key_mb = jax.random.fold_in(rng_stage,
                                        jnp.clip(mb_idx, 0, M - 1))

            def run(h):
                return transformer_apply(sp, h, cfg=stage_cfg, mask=m,
                                         rng=key_mb, train=train,
                                         with_aux=True)

            # ramp-up/down ticks where this stage holds no microbatch skip
            # the layer slice entirely (identity); the ppermute below runs
            # unconditionally so the collective stays program-aligned
            active = jnp.logical_and(mb_idx >= 0, mb_idx < M)
            # the idle branch's zero aux must carry the same varying axes
            # as the active branch's: a real MoE aux inherits (pp, dp)
            # from the activations, while the dense stack's aux is a
            # literal 0.0 constant (non-varying) — match each case
            if cfg.moe_experts:
                zero_aux = pcast_varying(
                    jnp.float32(0.0),
                    tuple(a for a in (axis, dp_axis) if a is not None))
            else:
                zero_aux = jnp.float32(0.0)
            out, aux = lax.cond(active, run, lambda h: (h, zero_aux), h)
            nxt = lax.ppermute(out, axis,
                               [(i, (i + 1) % P_) for i in range(P_)])
            return nxt, (out, aux)

        # the carry is device-varying over pp (each stage holds a different
        # microbatch's activations) — mark the zero init accordingly
        state0 = pcast_varying(jnp.zeros_like(xm[0]), (axis,))
        _, (outs, auxs) = lax.scan(tick, state0,
                                   (jnp.arange(ticks), stream[:ticks],
                                    masks))
        # stage s finishes microbatch m at tick m + s: the last stage's
        # outputs at ticks P-1 .. M+P-2 are the final activations, in order
        final = outs[P_ - 1:]
        final = jnp.where(idx == P_ - 1, final, jnp.zeros_like(final))
        # MoE load-balance aux: every stage contributes its layer slice's
        # aux for each ACTIVE tick (idle ticks contribute the cond's 0).
        # Match the dense path's normalization (one batch-wide MEAN per
        # layer, summed over layers — moe.py:124): sum stages via psum
        # over pp, average the M microbatch means, and pmean over dp so
        # the scalar leaves the shard_map replicated
        aux_total = lax.psum(auxs.sum(), axis) / M
        if dp_axis is not None:
            aux_total = lax.pmean(aux_total, dp_axis)
        return lax.psum(final, axis), aux_total           # select last stage

    data_spec = P(None, dp_axis) if dp_axis else P()
    mask_spec = data_spec if has_mask else P()    # placeholder: replicate
    # manual only over pp (+ dp for the data specs): any OTHER mesh axis
    # (e.g. 'ep' sharding each stage's expert stacks) stays a GSPMD auto
    # axis and composes without this file knowing it exists — the same
    # partial-manual discipline as parallel.sequence
    manual = frozenset(a for a in (axis, dp_axis) if a is not None)
    out, aux = shard_map(stage_fn, mesh=mesh,
                         in_specs=(P(axis), data_spec, mask_spec, P()),
                         out_specs=(data_spec, P()),
                         axis_names=manual)(stacked, xm, maskm, rng)
    out = out.reshape(b, n, d)
    return (out, aux) if with_aux else out


def pp_param_specs(params, axis: str = "pp", ep: Optional[str] = None):
    """PartitionSpecs that shard the depth-stacked transformer over the
    pipeline axis (each stage stores only its own depth/P layer slice; the
    contiguous leading-axis shard is exactly the stage-major reshape inside
    ``pipeline_transformer``) and replicate everything else. Feed to
    ``parallel.train.setup_sharded(param_specs=...)``.

    ``ep`` additionally shards the MoE expert axis of each stage's layer
    slice over that mesh axis — dp x pp x ep in one program (the expert
    axis is a GSPMD auto axis inside the pipeline's shard_map)."""
    specs = {k: (jax.tree.map(lambda _: P(axis), v) if k == "transformer"
                 else jax.tree.map(lambda _: P(), v))
             for k, v in params.items()}
    if ep is not None:
        if "moe" not in specs.get("transformer", {}).get("ff", {}):
            # a layout drift must surface, not silently degrade to
            # replicated experts (ADVICE r5 #3): the caller asked for
            # expert parallelism and would quietly lose it
            raise ValueError(
                f"ep={ep!r} requested but the param tree has no "
                "['transformer']['ff']['moe'] subtree — the model was "
                "built without MoE (moe_experts=0) or the MoE param "
                "layout moved; update pp_param_specs' path to match")
        moe = specs["transformer"]["ff"]["moe"]
        moe["w1"] = P(axis, ep)          # (depth, E, dim, hidden)
        moe["w2"] = P(axis, ep)
    return specs


def pp_dalle_loss_fn(cfg, mesh: Mesh, *, axis: str = "pp",
                     dp_axis: Optional[str] = None,
                     num_microbatches: Optional[int] = None):
    """DALLE training loss with the transformer pipelined over ``axis`` —
    the pp counterpart of ``parallel.sequence.sp_dalle_loss_fn``.

    Batch = {'text': (b, t) ids, 'image': (b, n_img) token ids, 'mask':
    optional (b, t) text pad mask, extended all-True over the image span
    like the dense path (reference dalle_pytorch.py:384-388)}. Embedding
    lookups and the CE head run under GSPMD outside the pipeline;
    ``cfg.loss_chunk`` caps the head's logits memory as usual. Signature
    matches ``parallel.train.make_train_step``'s
    ``loss_fn(params, batch, rng)``.
    """
    from dalle_pytorch_tpu.models import dalle as D
    if cfg.transformer.reversible:
        raise NotImplementedError(
            "pipeline parallelism does not support reversible=True")

    def loss(params, batch, rng):
        text, image_ids = batch["text"], batch["image"]
        tokens = D.embed_prompt(params, cfg, text, image_ids)
        mask = batch.get("mask")
        if mask is not None:
            pad = jnp.ones((mask.shape[0], image_ids.shape[1]), bool)
            mask = jnp.concatenate([mask, pad], axis=1)
        h, aux = pipeline_transformer(params["transformer"], tokens,
                                      cfg=cfg.transformer, mesh=mesh,
                                      axis=axis, dp_axis=dp_axis,
                                      num_microbatches=num_microbatches,
                                      mask=mask, rng=rng, train=True,
                                      with_aux=True)
        # same loss tail as dalle_apply — one definition of the contract
        loss_val = D.ce_from_hidden(params, h, text, image_ids, cfg=cfg)
        if cfg.moe_experts:
            # GPipe sums aux over stages x microbatches; dalle_apply's
            # dense scan sums over layers for the whole batch — same
            # total, same coefficient (models/dalle.py:281-282)
            loss_val = loss_val + cfg.moe_aux_coef * aux
        return loss_val

    return loss
