"""shard_map across jax versions — the one import the parallel package
gates instead of letting version drift break every downstream import.

The code targets jax >= 0.8 (``jax.shard_map`` with ``axis_names=``:
partial-manual lowering where unnamed mesh axes stay GSPMD auto axes).
Containers pinned to jax 0.4.x ship the same capability under
``jax.experimental.shard_map.shard_map`` with the COMPLEMENT parameter:
``auto=`` names the axes that stay automatic, and replication checking
must be off for them. One adapter here keeps ring/sequence/pipeline
importable on both — before this gate, a 0.4.x environment lost the
entire parallel package (and everything importing it) to a single
top-level ImportError.
"""

from __future__ import annotations

try:                                    # jax >= 0.8: top-level export
    from jax import shard_map as _shard_map
    _AXIS_NAMES_KW = True
except ImportError:                     # jax 0.4.x fallback
    from jax.experimental.shard_map import shard_map as _shard_map
    _AXIS_NAMES_KW = False

# True when partial-manual lowering (auto axes riding through a manual
# shard_map) is usable — callers (e.g. __graft_entry__.dryrun_multichip)
# drop to fully-manual meshes when it is not.
SUPPORTS_PARTIAL_MANUAL = _AXIS_NAMES_KW


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None, **kw):
    """``jax.shard_map``-compatible wrapper. ``axis_names`` is the set of
    MANUAL axes (None = all of them, both APIs' default)."""
    if _AXIS_NAMES_KW:
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)
    # 0.4.x's replication checker predates primitives this codebase uses
    # (e.g. the remat ``name`` tag from checkpoint_name: "No replication
    # rule for name"); it is a static checker only, so disable it on the
    # legacy path rather than lose shard_map entirely
    kw.setdefault("check_rep", False)
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            # 0.4.x's experimental ``auto=`` can hard-ABORT inside XLA
            # compile (observed on 0.4.37: partial-manual over a
            # dp x tp x sp mesh kills the interpreter, taking a whole
            # test session with it). Refuse cleanly instead: the caller
            # sees a normal exception, the process survives.
            raise NotImplementedError(
                "partial-manual shard_map (auto axes "
                f"{sorted(map(str, auto))}) requires jax>=0.8; this "
                "environment has the 0.4.x experimental API, whose "
                "auto-axis lowering is unstable")
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def pcast_varying(x, axes):
    """``lax.pcast(x, axes, to="varying")`` on jax >= 0.8 (the varying-
    manual-axes marking its replication checker requires); identity on
    0.4.x, whose shard_map tracks replication without explicit casts."""
    from jax import lax
    if hasattr(lax, "pcast"):
        return lax.pcast(x, tuple(axes), to="varying")
    return x


def donate_if_accelerator(*argnums: int) -> tuple:
    """``donate_argnums`` for jit, gated to real accelerators: ``()`` on
    the CPU backend. CPU "donation" is a warning at best, and under the
    persistent compilation cache it can MIS-ALIAS sharded buffers —
    donated params came back as garbage in a resumed-run checkpoint
    before every donation site adopted this gate. One definition keeps
    the hazard and its fix in one place; the next donation site should
    call this, not hand-roll the backend check."""
    import jax
    return tuple(argnums) if jax.default_backend() != "cpu" else ()
