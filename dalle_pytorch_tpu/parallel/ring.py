"""Sequence/context-parallel attention: ring (ppermute) and Ulysses
(all-to-all) kernels.

Long-context support the reference lacks entirely (SURVEY.md §5.7: no ring
attention, no context parallel — it scales sequence cost only by reversible
layers and block-sparse attention on ONE device). Here the sequence axis is
sharded over a mesh axis and attention runs as an SPMD program:

  * ``ring_attention`` — each device holds a sequence shard of q/k/v. K/V
    blocks rotate around the ring with ``lax.ppermute`` (ICI
    neighbor-to-neighbor, bandwidth-optimal) while each device folds one
    block per step into a numerically-stable online-softmax accumulator
    (the flash-attention recurrence, so no (n, n) matrix ever exists).
    Causal masking is block-aware: blocks wholly in the future contribute
    nothing (their weights underflow to exactly zero via the -inf mask).
  * ``ulysses_attention`` — all-to-all re-shards sequence -> heads, attends
    over the full sequence for the local head group, and all-to-alls back.
    One collective round-trip instead of a ring of size-1 hops; better when
    heads >= mesh axis size. At long context the local attention folds the
    key axis in chunks through the same online-softmax recurrence as the
    ring (``kv_chunks``), so no (n, n) score matrix ever materializes on
    either path.

Both are exact (same math as dense attention) — parity tests drive them on
the virtual CPU mesh against the single-device oracle.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

# jax >= 0.8 required (pyproject pin) — same discipline as
# parallel.sequence / parallel.pipeline
from dalle_pytorch_tpu.parallel._compat import shard_map


def _online_block(carry, kb, vb, q, scale, allow, pair_ok=None):
    """Fold one K/V block into the online-softmax state.

    carry: (m, l, acc) with m,l (b,h,nl,1) and acc (b,h,nl,d).
    allow: (nl_q, nl_k) bool — True where attention is permitted (causal).
    pair_ok: optional (b, nl_q, nl_k) pad mask — False entries fill with
    the FINITE -fmax (reference transformer.py:74-77), so a fully-padded
    row degrades to a uniform average over its causal prefix exactly like
    the dense path (ops.attention.dense_attention_weights).
    """
    m, l, acc = carry
    s = jnp.einsum("bhid,bhjd->bhij", q, kb) * scale
    if pair_ok is not None:
        fmax = jnp.asarray(-jnp.finfo(s.dtype).max, s.dtype)
        s = jnp.where(pair_ok[:, None], s, fmax)
    neg = jnp.asarray(-jnp.inf, s.dtype)
    s = jnp.where(allow[None, None], s, neg)

    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    # rows with no allowed key yet keep m=-inf; shift with 0 to avoid nans
    shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - shift)
    p = jnp.where(allow[None, None], p, 0.0)   # causal zeros only; pad rows
    #                                            keep their uniform exp(0)=1
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - shift), 0.0)
    l = l * alpha + p.sum(axis=-1, keepdims=True)
    acc = acc * alpha + jnp.einsum("bhij,bhjd->bhid", p, vb)
    return m_new, l, acc


def ring_attention_local(q, k, v, *, axis: str, size: int,
                         causal: bool = True,
                         scale: Optional[float] = None,
                         mask=None):
    """Per-shard ring attention body — call INSIDE a ``shard_map`` whose
    mesh has axis ``axis`` of ``size``; q, k, v are the LOCAL (b, h, n/size,
    d) sequence shards. Exposed separately so higher layers (the
    sequence-parallel transformer stack in parallel.sequence) can fuse the
    ring into their own shard_map instead of nesting one per attention.

    ``mask`` is this shard's (b, n/size) pad mask; its blocks rotate around
    the ring with k/v, and pad pairs fill with the finite -fmax so the
    semantics match the dense path bit-for-bit (reference
    transformer.py:74-77 pair mask)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    nl = q.shape[2]
    rank = lax.axis_index(axis)
    rows = rank * nl + jnp.arange(nl)

    # init the accumulators FROM q so they carry the same device-varying
    # type as the scan's rotating kb/vb under shard_map
    m = q[..., :1] * 0.0 - jnp.inf
    l = q[..., :1] * 0.0
    acc = q * 0.0
    perm = [(i, (i + 1) % size) for i in range(size)]
    q_mask = mask

    def step(s, state):
        m, l, acc, kb, vb, mb = state
        src = (rank - s) % size          # who produced the block we hold
        cols = src * nl + jnp.arange(nl)
        allow = (cols[None, :] <= rows[:, None]) if causal else \
            jnp.ones((nl, nl), bool)
        pair_ok = None
        if mb is not None:
            pair_ok = q_mask[:, :, None] & mb[:, None, :]   # (b, nl, nl)
        m, l, acc = _online_block((m, l, acc), kb, vb, q, scale, allow,
                                  pair_ok)
        kb = lax.ppermute(kb, axis, perm)
        vb = lax.ppermute(vb, axis, perm)
        if mb is not None:
            mb = lax.ppermute(mb, axis, perm)
        return m, l, acc, kb, vb, mb

    if mask is None:
        # fori_loop needs a fixed-structure carry: run the maskless variant
        def step_nomask(s, state):
            m, l, acc, kb, vb = state
            m, l, acc, kb, vb, _ = step(s, (m, l, acc, kb, vb, None))
            return m, l, acc, kb, vb
        m, l, acc, _, _ = lax.fori_loop(
            0, size, step_nomask, (m, l, acc, k, v), unroll=True)
    else:
        m, l, acc, _, _, _ = lax.fori_loop(
            0, size, step, (m, l, acc, k, v, mask), unroll=True)
    return acc / jnp.where(l == 0.0, 1.0, l)


def ring_attention(q, k, v, *, mesh: Mesh, axis: str = "sp",
                   causal: bool = True, scale: Optional[float] = None,
                   batch_axis: Optional[str] = None, mask=None):
    """Exact attention with the sequence axis sharded over ``axis``.

    q, k, v: (b, h, n, d) GLOBAL shapes; n divides by the axis size.
    ``mask``: optional (b, n) global pad mask (True = keep), dense-path
    semantics. Returns (b, h, n, d) sharded the same way. ``batch_axis``
    optionally names a mesh axis the batch dim is sharded over (pure SPMD
    pass-through).
    """
    size = mesh.shape[axis]

    def local(q, k, v, *m):
        return ring_attention_local(q, k, v, axis=axis, size=size,
                                    causal=causal, scale=scale,
                                    mask=m[0] if m else None)

    return _sharded_attn(local, mesh, axis, batch_axis, q, k, v, mask)


def ulysses_attention(q, k, v, *, mesh: Mesh, axis: str = "sp",
                      causal: bool = True, scale: Optional[float] = None,
                      batch_axis: Optional[str] = None, mask=None,
                      kv_chunks: Optional[int] = None):
    """Exact attention via head<->sequence all-to-all re-sharding.

    q, k, v: (b, h, n, d) global; h divides by the axis size. Inside the
    shard_map each device swaps its sequence shard for a head shard
    (all_to_all over ICI), attends over the FULL sequence for its heads,
    then swaps back. ``kv_chunks`` as in ``ulysses_attention_local``.
    """
    size = mesh.shape[axis]
    if q.shape[1] % size != 0:
        raise ValueError(f"heads {q.shape[1]} not divisible by mesh axis "
                         f"{axis} ({size})")

    def local(q, k, v, *m):
        return ulysses_attention_local(q, k, v, axis=axis, causal=causal,
                                       scale=scale,
                                       mask=m[0] if m else None,
                                       kv_chunks=kv_chunks)

    return _sharded_attn(local, mesh, axis, batch_axis, q, k, v, mask)


def _sharded_attn(local, mesh: Mesh, axis: str, batch_axis, q, k, v, mask):
    """Shared shard_map plumbing for the standalone wrappers: q/k/v
    sequence-sharded over ``axis``, the optional (b, n) mask alongside."""
    spec = P(batch_axis, None, axis, None)
    in_specs = [spec, spec, spec]
    args = [q, k, v]
    if mask is not None:
        in_specs.append(P(batch_axis, axis))
        args.append(mask)
    return shard_map(local, mesh=mesh, in_specs=tuple(in_specs),
                     out_specs=spec)(*args)


# full-sequence length at/above which the Ulysses body switches from the
# one-einsum dense score matrix to the chunked online-softmax (the (n, n)
# buffer is fine at bench scale but contradicts the long-context purpose)
_ULYSSES_DENSE_MAX = 4096


def ulysses_attention_local(q, k, v, *, axis: str, causal: bool = True,
                            scale: Optional[float] = None, mask=None,
                            kv_chunks: Optional[int] = None):
    """Per-shard Ulysses body — call INSIDE a ``shard_map``; q, k, v are
    LOCAL (b, h, n/size, d) shards with h divisible by the axis size.
    ``mask`` is this shard's (b, n/size) pad mask; it is all-gathered to
    the full sequence (the heads are local here anyway) and applied with
    dense-path semantics.

    ``kv_chunks`` bounds score memory: the key/value axis is folded in that
    many chunks through the same online-softmax recurrence as the ring path
    (peak (b, h/size, n, n/kv_chunks) instead of (b, h/size, n, n)). None =
    auto: dense below ``_ULYSSES_DENSE_MAX`` total sequence, one chunk per
    ring rank at or above it. 1 = always dense."""
    if scale is None:
        scale = q.shape[-1] ** -0.5

    # local shapes: (b, h, nl, d) -> all_to_all -> (b, h/size, n, d)
    def seq_to_heads(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    n = qh.shape[2]
    size = n // q.shape[2]                       # static: n = nl * size
    full = (lax.all_gather(mask, axis, axis=1, tiled=True)
            if mask is not None else None)       # (b, n)
    if kv_chunks is None:
        kv_chunks = 1 if n < _ULYSSES_DENSE_MAX else size
    if kv_chunks > 1 and n % kv_chunks:
        raise ValueError(f"kv_chunks {kv_chunks} must divide the full "
                         f"sequence {n}")

    if kv_chunks == 1:
        s = jnp.einsum("bhid,bhjd->bhij", qh, kh) * scale
        if full is not None:
            pair = full[:, :, None] & full[:, None, :]
            fmax = jnp.asarray(-jnp.finfo(s.dtype).max, s.dtype)
            s = jnp.where(pair[:, None], s, fmax)
        if causal:
            tri = jnp.tril(jnp.ones((n, n), bool))
            s = jnp.where(tri[None, None], s, -jnp.inf)
        out = jnp.einsum("bhij,bhjd->bhid", jax.nn.softmax(s, axis=-1), vh)
        return heads_to_seq(out)

    ck = n // kv_chunks
    b, hl, _, d = qh.shape
    ks = jnp.moveaxis(kh.reshape(b, hl, kv_chunks, ck, d), 2, 0)
    vs = jnp.moveaxis(vh.reshape(b, hl, kv_chunks, ck, d), 2, 0)
    rows = jnp.arange(n)
    m0 = qh[..., :1] * 0.0 - jnp.inf
    l0 = qh[..., :1] * 0.0
    acc0 = qh * 0.0

    def fold(carry, xs):
        j, kb, vb = xs
        cols = j * ck + jnp.arange(ck)
        allow = (cols[None, :] <= rows[:, None]) if causal else \
            jnp.ones((n, ck), bool)
        pair_ok = None
        if full is not None:
            mb = lax.dynamic_slice_in_dim(full, j * ck, ck, axis=1)
            pair_ok = full[:, :, None] & mb[:, None, :]
        return _online_block(carry, kb, vb, qh, scale, allow, pair_ok), None

    (m, l, acc), _ = lax.scan(fold, (m0, l0, acc0),
                              (jnp.arange(kv_chunks), ks, vs))
    return heads_to_seq(acc / jnp.where(l == 0.0, 1.0, l))
