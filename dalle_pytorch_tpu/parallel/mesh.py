"""Device mesh construction and sharding helpers.

Axis conventions used across the framework:

  * ``dp`` — data parallel: batch sharded, gradients psum'd over ICI
    (replaces the reference's absent NCCL data-parallel per BASELINE
    config 5);
  * ``fsdp`` — parameter sharding axis for ZeRO-style fully-sharded DP;
  * ``tp`` — tensor parallel: attention heads / FF hidden sharded;
  * ``sp`` — sequence/context parallel: the sequence axis sharded, attention
    via ring or all-to-all kernels (parallel.ring).

On a pod slice the mesh axes map onto the ICI torus by construction order
(jax places the fastest-varying axis on the innermost ring); multi-slice
deployments put ``dp`` outermost so its gradient psum is the only collective
that rides DCN.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axis_sizes: Optional[Mapping[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh from ``{axis: size}``. Sizes must multiply to the device
    count; a single ``{'dp': len(devices)}`` axis is the default."""
    if devices is None:
        devices = jax.devices()
    if axis_sizes is None:
        axis_sizes = {"dp": len(devices)}
    names = tuple(axis_sizes)
    sizes = tuple(axis_sizes[n] for n in names)
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(f"mesh {dict(axis_sizes)} needs "
                         f"{int(np.prod(sizes))} devices, have "
                         f"{len(devices)}")
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, names)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicate(mesh: Mesh, tree):
    """Fully replicate a pytree across the mesh."""
    s = NamedSharding(mesh, P())
    return jax.device_put(tree, s)


def shard_batch(mesh: Mesh, batch, axis: str = "dp"):
    """Shard every leaf's leading (batch) dim over ``axis``.

    Multi-host aware: in a multi-process cluster each host passes its OWN
    (host-local) slice of the global batch — the data layer already feeds
    every host different examples (data.prefetch host sharding) — and the
    leaves assemble into one global array of leading dim
    ``local_batch * process_count`` via
    ``jax.make_array_from_process_local_data``. Single-process (the common
    case and every test) is a plain ``device_put``, which would be WRONG
    across processes: it treats each host's local array as the global one,
    silently training on half-dropped, mismatched data.
    """
    s = NamedSharding(mesh, P(axis))
    if jax.process_count() == 1:
        return jax.device_put(batch, s)
    return jax.tree.map(
        lambda a: jax.make_array_from_process_local_data(
            s, np.asarray(a)), batch)
