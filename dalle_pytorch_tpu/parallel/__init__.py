"""Parallelism layer (L1.5) — mesh, shardings, collectives, ring attention.

The reference has NO distributed support (SURVEY.md §2.12: no
torch.distributed, no NCCL/MPI, single device everywhere); this layer is the
from-scratch TPU-native design the north star requires: a
``jax.sharding.Mesh`` over ICI/DCN, ``jit``/``pjit`` with NamedShardings for
data/tensor parallel training (XLA inserts the psum/all-gather collectives),
and ``shard_map`` + ``ppermute``/``all_to_all`` kernels for sequence/context
parallelism over long sequences.
"""

from dalle_pytorch_tpu.parallel.mesh import (  # noqa: F401
    make_mesh, named_sharding, replicate, shard_batch)
from dalle_pytorch_tpu.parallel.pipeline import (  # noqa: F401
    pipeline_transformer, pp_dalle_loss_fn, pp_param_specs)
from dalle_pytorch_tpu.parallel.ring import (  # noqa: F401
    ring_attention, ulysses_attention)
from dalle_pytorch_tpu.parallel.sequence import (  # noqa: F401
    sp_dalle_loss_fn, sp_transformer_apply)
from dalle_pytorch_tpu.parallel.serve_specs import (  # noqa: F401
    serve_kv_specs, serve_mesh, serve_param_specs, slice_devices)
from dalle_pytorch_tpu.parallel.train import make_train_step  # noqa: F401
