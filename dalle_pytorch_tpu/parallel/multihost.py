"""Multi-host initialization — the DCN-scale entry point of the distributed
backend.

The reference reaches no communication backend at all (SURVEY.md §5.8:
DeepSpeed is built solely for its sparse-attention op; no process groups are
ever initialized). Here multi-host is the standard JAX runtime contract:
every host runs the SAME program, ``initialize()`` wires the processes into
one cluster (coordinator + process id), after which ``jax.devices()`` is the
GLOBAL device list — every mesh/pjit/shard_map in this package then spans
hosts automatically, with XLA routing collectives over ICI within a slice
and DCN across slices (mesh.py's axis-order convention keeps only the dp
psum on DCN).

On Cloud TPU pods ``jax.distributed.initialize()`` autodetects everything
from the metadata server; elsewhere (CPU/GPU clusters, tests) pass
coordinator/process counts explicitly or via the standard env vars. The
data layer is already host-sharded (data.prefetch reads 1/process_count of
the stream per host), so the CLIs become pod-ready by calling this first.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax

_ENV_COORD = "JAX_COORDINATOR_ADDRESS"
_ENV_NPROC = "JAX_NUM_PROCESSES"
_ENV_PID = "JAX_PROCESS_ID"

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None,
               deadline_s: Optional[float] = None,
               max_attempts: int = 3,
               on_event=None) -> bool:
    """Join (or form) the multi-host cluster. Returns True iff distributed
    mode was initialized.

    Resolution order per field: explicit argument, then the standard env
    var (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID),
    then TPU-pod autodetection (when no coordinator is known but jax was
    launched on a pod, ``jax.distributed.initialize()`` with no arguments
    resolves from the metadata server). With neither arguments, env vars,
    nor a pod environment this is a single-process no-op returning False.

    ``deadline_s`` bounds the cluster join (a wedged coordinator otherwise
    pends it indefinitely — the round-5 failure mode): each of
    ``max_attempts`` attempts runs under the deadline with jittered
    exponential backoff between them (resilience.retry), retry records
    flowing to ``on_event``; exhausted attempts raise
    ``resilience.BringupError`` carrying the structured failure record
    instead of hanging. None (default) keeps the legacy unbounded join.

    Caveat: a deadline-cut attempt ABANDONS its daemon thread, which may
    still be blocked inside ``jax.distributed.initialize``; a retry then
    races it against a fresh call. That is acceptable for the wedge this
    defends against (the abandoned call is stuck in connect and never
    mutates the client), but a retried init that merely *straggles* can
    interleave with its successor — bench avoids this by re-exec'ing a
    fresh process per attempt (claim_backend), which is the right model
    for anything beyond a launcher; see ROADMAP open items.

    Idempotent: a second call (same process) is a no-op returning True.
    """
    global _initialized
    if _initialized:
        return True
    coord = coordinator_address or os.environ.get(_ENV_COORD)
    nproc = num_processes if num_processes is not None else (
        int(os.environ[_ENV_NPROC]) if _ENV_NPROC in os.environ else None)
    pid = process_id if process_id is not None else (
        int(os.environ[_ENV_PID]) if _ENV_PID in os.environ else None)

    if coord is None and nproc is None:
        # bare single-process run (the common laptop/test case): stay local
        # unless we're visibly on a multi-worker pod (TPU pod env
        # autodetects). A single entry in TPU_WORKER_HOSTNAMES is one host
        # (some runtimes set it to "localhost" even on a single chip) —
        # nothing to join.
        hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        if len([h for h in hosts.split(",") if h.strip()]) <= 1:
            return False

    def _join(attempt: int = 0):
        from dalle_pytorch_tpu.resilience import faults
        faults.on_backend_init(attempt)
        try:
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=nproc,
                                       process_id=pid,
                                       local_device_ids=local_device_ids)
        except RuntimeError as e:
            # someone initialized jax.distributed without going through
            # this module ("distributed.initialize should only be called
            # once")
            msg = str(e).lower()
            if "already" not in msg and "only be called once" not in msg:
                raise

    if deadline_s and deadline_s > 0:
        from dalle_pytorch_tpu.resilience import retry as rretry
        policy = rretry.RetryPolicy(max_attempts=max(max_attempts, 1),
                                    deadline_s=deadline_s)
        rretry.retry_with_backoff(_join, policy, label="multihost_init",
                                  on_event=on_event)
    else:
        _join()
    _initialized = True
    return True


def is_primary() -> bool:
    """True on the process that should write checkpoints/logs (process 0 —
    the multi-host analogue of the reference's single-process scripts
    writing unconditionally)."""
    return jax.process_index() == 0


def fetch_local(x):
    """Materialize a (possibly cross-host-sharded) array as numpy on EVERY
    process — a collective in multi-host mode (all processes must call it
    together), a plain ``np.asarray`` otherwise.

    For epoch-end diagnostics (recon grids, samples) that need concrete
    values: ``np.asarray`` on a dp-sharded global array raises on shards
    owned by other hosts, and feeding per-host-different data into a jit
    over the global mesh would break SPMD consistency — allgathering first
    solves both."""
    import numpy as np
    if jax.process_count() == 1:
        return np.asarray(x)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))
