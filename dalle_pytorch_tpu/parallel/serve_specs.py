"""Serve-side partition rules: sharding a DECODE program over an ICI mesh.

``parallel/train.py``'s specs shard for training throughput (Megatron
tp: column/row-parallel pairs whose row halves psum partial matmul
results). The serving engine cannot use those rules, because serving
carries a stricter contract than throughput: the mesh-sharded engine
(``serve/mesh_engine.py``) must emit tokens BYTE-IDENTICAL to the
single-device engine — the same equality the whole serving stack is
built on (paged-vs-dense, kernel-vs-gather, failover replay). A psum
reassociates a floating-point sum (partial products added in a
different order than the unsharded dot), which breaks bit-equality in
exactly the way a tolerance test hides and a token-equality test
catches.

So the serve rules shard only NON-CONTRACTED dimensions, making every
collective a data movement (all-gather / gather / dynamic-slice), never
an arithmetic reassociation:

  * transformer layer stacks ``(depth, ...)`` shard the DEPTH axis
    (ZeRO-style): the per-layer ``lax.scan`` slice all-gathers one
    layer's weights per step, and the math on the gathered values is
    the single-device math, bit for bit. Params HBM scales 1/m;
  * the KV store — the dense slot cache ``(depth, slots, heads, len,
    dh)`` or the paged page pool ``(depth, num_pages, heads,
    page_size, dh)`` and its int8 scale pages — shards the HEADS axis:
    per-head attention (scores, softmax, weighted sum) is data-
    independent across heads, so each shard computes its heads exactly
    as the single device would. KV HBM scales 1/m — the term that caps
    serving concurrency;
  * embedding tables and the logits head shard their VOCAB axis
    (gathers and column-parallel projection: elementwise-exact), and
    the engine re-replicates logits BEFORE sampling so softmax/cumsum
    reductions never run over a sharded axis;
  * everything the host touches — per-slot decode state, block tables,
    the emit ring — stays replicated, so the engine's host protocol
    (one explicit device_get per chunk, explicit device_puts at
    admission) is unchanged.

The one seam this needs inside the model math is ``ops.decode``'s
``out_sync`` hook: the per-head attention output is constrained back to
replicated BEFORE the output projection, forcing GSPMD to all-gather
the heads (data movement) instead of partial-summing the projection
(reassociation). ``head_sync``/``replicate_sync`` build that constraint.

Divisibility is checked per leaf: a dimension the mesh size does not
divide falls back to replicated for that leaf (same policy as
``train.dalle_param_specs``), so an odd config degrades in memory
footprint, never in correctness.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dalle_pytorch_tpu.parallel.mesh import make_mesh

# the serving model-parallel mesh axis: one axis is enough because every
# sharded tensor shards exactly one dim over it (depth for params, heads
# for KV, vocab for the embedding/logits tables)
SERVE_AXIS = "mp"


def serve_mesh(devices: Sequence, axis: str = SERVE_AXIS) -> Mesh:
    """One-axis device mesh for a mesh-sharded serving engine. On a pod
    slice the devices should be ICI neighbours (a contiguous slice of
    ``jax.devices()`` — ``slice_devices`` below), so the per-layer
    all-gathers ride ICI, never DCN."""
    return make_mesh({axis: len(devices)}, devices)


def slice_devices(devices: Sequence, index: int,
                  per_replica: int) -> Tuple:
    """Replica ``index``'s device slice — the replica=slice composition
    rule (a ReplicaSet replica becomes a mesh SLICE instead of one
    chip). The host's devices divide into ``len(devices) // m``
    non-overlapping slices and replica ``index`` takes slice ``index %
    n_slices`` — the exact generalization of the single-chip placement
    ``devices[i % len(devices)]`` (``per_replica=1`` reproduces it), so
    more replicas than slices SHARE slices (slower, never wrong), and a
    remote worker serving replica 7 on a 2-chip host still gets a valid
    local slice. Raises only when the host cannot hold even one slice."""
    m = int(per_replica)
    if m < 1:
        raise ValueError(f"devices_per_replica must be >= 1, got {m}")
    n_slices = len(devices) // m
    if n_slices < 1:
        raise ValueError(
            f"a {m}-device mesh slice does not fit this host: only "
            f"{len(devices)} device(s) visible")
    lo = (index % n_slices) * m
    return tuple(devices[lo:lo + m])


def replicated(mesh: Mesh) -> NamedSharding:
    """The replicated placement every host-visible array gets."""
    return NamedSharding(mesh, P())


def _div(leaf_dim: int, mesh: Mesh, axis: str) -> bool:
    return leaf_dim % mesh.shape[axis] == 0


def serve_param_specs(params, cfg, mesh: Mesh, axis: str = SERVE_AXIS):
    """NamedSharding tree for a DALLE param tree under the serve rules
    (module docstring): transformer stacks depth-sharded, embedding /
    logits-head tables vocab-sharded, the rest replicated. ``cfg`` is
    the DALLEConfig (``cfg.transformer.depth`` identifies the stacked
    leaves; int8-quantized stacks keep their leading depth dim, so the
    shape test covers them too)."""
    depth = cfg.transformer.depth

    def rule(path, leaf):
        keys = [getattr(k, "key", None) for k in path]
        shape = getattr(leaf, "shape", ())
        if "transformer" in keys and len(shape) >= 1 \
                and shape[0] == depth and _div(depth, mesh, axis):
            return P(axis)
        if "proj" in keys and keys[-1] in ("w", "wq") \
                and len(shape) == 2 and _div(shape[1], mesh, axis):
            # logits head, column-parallel: the contraction (model dim)
            # stays replicated — elementwise-exact shards of the logits,
            # re-replicated by the engine's logits_sync before sampling
            return P(None, axis)
        if "proj" in keys and len(shape) == 1 \
                and _div(shape[0], mesh, axis):
            return P(axis)          # head bias / int8 scale, vocab-long
        if len(keys) >= 2 and keys[-2] in ("text_emb", "image_emb") \
                and keys[-1] == "w" and len(shape) == 2 \
                and _div(shape[0], mesh, axis):
            return P(axis)          # row-sharded table: gathers only
        return P()

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, rule(path, leaf)), params)


def kv_heads_shard(heads: int, mesh_size: int) -> bool:
    """THE policy predicate for sharding a KV store: heads shard iff the
    mesh size divides them. One definition shared by ``serve_kv_specs``
    (which places the live pool) and the replica set's config-only HBM
    model (``ReplicaSet._kv_bytes_per_shard`` — a parent fronting
    remote workers has no pool to measure), so the modeled per-shard
    bytes can never drift from what placement actually does."""
    return int(mesh_size) > 0 and heads % int(mesh_size) == 0


def serve_kv_specs(cache: dict, mesh: Mesh, axis: str = SERVE_AXIS) -> dict:
    """NamedSharding dict for a KV store — the dense slot cache or the
    paged page pool (``serve/kv_pool.py``), int8 scale pages included.
    Both layouts carry heads at dim 2 (``(depth, slots|pages, heads,
    rows[, dh])``), the one axis whose shards attend independently."""
    out = {}
    for k, buf in cache.items():
        shard = kv_heads_shard(buf.shape[2], mesh.shape[axis])
        out[k] = NamedSharding(
            mesh, P(None, None, axis) if shard else P())
    return out


def kv_is_sharded(specs: dict) -> bool:
    """True when the KV store actually sharded (heads divisible) — what
    per-shard HBM accounting divides by the mesh size on."""
    return any(s.spec != P() for s in specs.values())


def replicate_sync(mesh: Mesh) -> Callable:
    """A ``with_sharding_constraint`` closure pinning a value replicated
    — the engine applies it to logits before sampling (reductions over
    the vocab axis must never run sharded) and ``ops.decode`` applies it
    to the per-head attention output via the ``out_sync`` seam (the out
    projection must see gathered heads, not partial-sum them)."""
    sharding = NamedSharding(mesh, P())

    def sync(x):
        return jax.lax.with_sharding_constraint(x, sharding)

    return sync


def per_shard_bytes(tree) -> int:
    """Resident bytes ONE device of the mesh stores for ``tree`` —
    replicated leaves count whole, sharded leaves count their shard
    (``sharding.shard_shape``). Host/numpy leaves (no sharding) count
    whole: one copy somewhere is the honest model. The /stats
    ``*_per_shard`` fields and bench's ``mesh_compare`` HBM-budget
    assertion read this."""
    import numpy as np
    total = 0
    for x in jax.tree_util.tree_leaves(tree):
        sharding = getattr(x, "sharding", None)
        if sharding is None or not hasattr(sharding, "shard_shape"):
            total += int(getattr(x, "nbytes", 0))
        else:
            total += int(np.prod(sharding.shard_shape(x.shape))
                         * x.dtype.itemsize)
    return total


def param_bytes(params) -> int:
    """Total parameter bytes (the modeled-HBM term next to the KV pool
    in the mesh HBM budget math — bench's ``mesh_compare`` and the
    /stats surface read it)."""
    return int(sum(x.nbytes for x in jax.tree_util.tree_leaves(params)))


def mesh_shape_desc(mesh: Mesh) -> dict:
    """``{axis: size}`` — the /stats ``mesh_shape`` field."""
    return {str(k): int(v) for k, v in mesh.shape.items()}


def mesh_device_ids(mesh: Mesh) -> List[int]:
    return [int(d.id) for d in mesh.devices.flat]
