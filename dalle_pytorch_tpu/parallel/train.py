"""Sharded training steps: jit + NamedShardings, collectives by XLA.

The idiomatic TPU recipe (scaling-book style): pick a mesh, place params and
batch with NamedShardings, jit the step — XLA/GSPMD inserts the gradient
psum over `dp`, the all-gathers/reduce-scatters implied by `fsdp`, and the
activation collectives implied by `tp`, all riding ICI. There is no userland
communication library to port (the reference has none anyway, SURVEY.md
§2.12); the mesh IS the backend.

Usage:
    mesh = make_mesh({'dp': 4, 'tp': 2})
    specs = dalle_param_specs(params, tp='tp')           # or fsdp='dp'
    params, opt_state = setup_sharded(params, optimizer, mesh, specs)
    step = make_train_step(loss_fn, optimizer)
    batch = shard_batch(mesh, batch)
    params, opt_state, loss = step(params, opt_state, batch, rng)
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dalle_pytorch_tpu.parallel import _compat



def make_train_step(loss_fn: Callable, optimizer,
                    grad_accum: int = 1) -> Callable:
    """jit step: (params, opt_state, batch, rng) -> (params, opt_state, loss).

    ``loss_fn(params, batch, rng) -> scalar``. Shardings are dictated by the
    inputs (set up with ``setup_sharded``/``shard_batch``); params and opt
    state buffers are donated.

    ``grad_accum > 1`` splits the batch's leading dim into that many
    microbatches and accumulates their mean gradient in a ``lax.scan``
    before the single optimizer update — the same update as the full batch
    when the loss is deterministic (it is an example mean); with RNG in the
    loss (dropout, Gumbel noise) each microbatch gets an independent
    ``fold_in``-derived key, so noise stays decorrelated across the
    accumulated batch (not bitwise the full-batch draw). Activation memory
    is 1/N. The batch must be a dict; scalar entries (e.g. a traced
    temperature) pass through unsplit, array entries' leading dim must
    divide.

    An optional scalar ``batch['lr_scale']`` multiplies the optimizer
    updates (for Adam, exactly an LR scale) — the resilience supervisor's
    post-rollback re-warm rides it as a traced input, so the ramp never
    recompiles. Absent key = scale 1.
    """

    # donation frees the old params/opt-state in place; CPU ignores it
    donate = _compat.donate_if_accelerator(0, 1)

    @functools.partial(jax.jit, donate_argnums=donate)
    def step(params, opt_state, batch, rng):
        batch = dict(batch)
        lr_scale = batch.pop("lr_scale", None)
        if grad_accum <= 1:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch, rng)
        else:
            loss, grads = accumulate_grads(loss_fn, params, batch, rng,
                                           grad_accum)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if lr_scale is not None:
            updates = jax.tree.map(
                lambda u: (u * lr_scale).astype(u.dtype), updates)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def accumulate_grads(loss_fn: Callable, params, batch: dict, rng,
                     grad_accum: int):
    """(mean loss, mean grads) over ``grad_accum`` microbatches, scanned so
    only one microbatch's activations are live at a time. ``batch`` is a
    dict; entries with ndim >= 1 split on their leading dim, scalars are
    closed over unchanged. Each microbatch's loss sees a distinct
    ``fold_in(rng, i)`` key — identical keys would correlate dropout/noise
    across the whole accumulated batch."""
    import jax.numpy as jnp
    if not isinstance(batch, dict):
        raise TypeError("grad accumulation expects a dict batch")
    split = {k: v for k, v in batch.items()
             if getattr(v, "ndim", 0) >= 1}
    rest = {k: v for k, v in batch.items() if k not in split}
    micro = jax.tree.map(
        lambda a: a.reshape(grad_accum, a.shape[0] // grad_accum,
                            *a.shape[1:]), split)

    def body(carry, xs):
        i, mb = xs
        loss_acc, grads_acc = carry
        loss_i, grads_i = jax.value_and_grad(loss_fn)(
            params, {**mb, **rest}, jax.random.fold_in(rng, i))
        grads_acc = jax.tree.map(
            lambda a, g: a + g.astype(jnp.float32), grads_acc, grads_i)
        return (loss_acc + loss_i, grads_acc), None

    # accumulate in f32 even under --param_dtype bfloat16: bf16 summation
    # across microbatches compounds rounding error as grad_accum grows
    zeros = jax.tree.map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)
    (loss, grads), _ = jax.lax.scan(
        body, (jnp.float32(0.0), zeros), (jnp.arange(grad_accum), micro))
    inv = 1.0 / grad_accum
    return loss * inv, jax.tree.map(
        lambda g, p: (g * inv).astype(p.dtype), grads, params)


def setup_sharded(params, optimizer, mesh: Mesh, param_specs=None,
                  opt_state=None):
    """Place params per ``param_specs`` (replicated when None) and build the
    optimizer state THROUGH jit so its moment buffers inherit the param
    shardings (the standard GSPMD propagation trick). A restored
    ``opt_state`` (checkpoint resume) is placed like the params instead of
    re-initialized."""
    if param_specs is None:
        shardings = NamedSharding(mesh, P())
        params = jax.device_put(params, shardings)
        if opt_state is not None:
            opt_state = jax.device_put(opt_state, shardings)
    else:
        shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), param_specs,
            is_leaf=lambda x: isinstance(x, P))
        params = jax.tree.map(jax.device_put, params, shardings)
        if opt_state is not None:
            # moment buffers mirror the param TREE (optax mu/nu subtrees have
            # the params' exact structure): place each such subtree with the
            # params' own sharding tree — matched positionally by path, never
            # by array shape (two equal-shaped params with different specs
            # must not collide) — and replicate everything else (counters).
            p_struct = jax.tree.structure(params)
            p_leaves = jax.tree.leaves(params)

            def is_param_tree(x):
                if jax.tree.structure(x) != p_struct:
                    return False
                return all(getattr(a, "shape", None) == b.shape
                           for a, b in zip(jax.tree.leaves(x), p_leaves))

            opt_state = jax.tree.map(
                lambda sub: (jax.tree.map(jax.device_put, sub, shardings)
                             if is_param_tree(sub)
                             else jax.device_put(
                                 sub, NamedSharding(mesh, P()))),
                opt_state, is_leaf=is_param_tree)
    if opt_state is None:
        opt_state = jax.jit(optimizer.init)(params)
    return params, opt_state


# ---------------------------------------------------------------------------
# partition-spec rules for the framework's parameter trees
# ---------------------------------------------------------------------------

def _dalle_rule(tp: Optional[str], fsdp: Optional[str]):
    """Spec by (sub-module, leaf) name for DALLE/transformer params.

    Transformer layer params are depth-stacked (leading depth axis) — that
    axis shards over ``fsdp`` (ZeRO-style: each device stores a slice of
    every layer stack, all-gathered per scan step). ``tp`` follows the
    Megatron pattern: qkv/w1 column-parallel, out/w2 row-parallel, so each
    layer needs exactly one psum on the attention output and one on the FF
    output — inserted by XLA from the shardings alone.
    """
    def rule(path, leaf):
        keys = [getattr(k, "key", None) for k in path]
        # layer-stack params are recognized by their attn/ff sub-keys, so a
        # BARE transformer tree (no 'transformer' ancestor) shards the same
        # as one nested inside DALLE/CLIP params
        if "attn" in keys or "ff" in keys:
            sub, name = keys[-2], keys[-1]
            if name == "w":
                if sub in ("qkv", "w1"):
                    return P(fsdp, None, tp)      # column parallel
                if sub in ("out", "w2"):
                    return P(fsdp, tp, None)      # row parallel
            if name == "b" and sub == "w1":
                return P(fsdp, tp)
            return P(fsdp)                         # ln params, out/w2 bias
        if keys[-2] == "proj":                     # to_logits
            return P(None, tp) if keys[-1] == "w" else P(tp)
        return P()                                 # embeddings replicated
    return rule


def dalle_param_specs(params, tp: Optional[str] = None,
                      fsdp: Optional[str] = None,
                      mesh: Optional[Mesh] = None):
    """PartitionSpec tree for a DALLE (or bare transformer) param tree.

    With ``mesh``, any axis whose dimension is not divisible by the mesh
    axis size is dropped back to replicated for that dim (e.g. the
    total_tokens logits dim with an odd vocab size).
    """
    rule = _dalle_rule(tp, fsdp)

    def checked(path, leaf):
        spec = rule(path, leaf)
        if mesh is None:
            return spec
        fixed = tuple(
            a if (a is None or leaf.shape[i] % mesh.shape[a] == 0) else None
            for i, a in enumerate(spec))
        return P(*fixed)

    return jax.tree_util.tree_map_with_path(checked, params)


def dalle_moe_param_specs(params, axis: str = "ep"):
    """PartitionSpecs sharding the MoE expert axis over ``axis``: the
    depth-stacked expert weights (depth, E, ...) get P(None, axis); the
    router and everything else replicate. Feed to
    ``setup_sharded(param_specs=...)`` on a dp x ep mesh — GSPMD inserts
    the token->expert collectives."""
    specs = jax.tree.map(lambda _: P(), params)
    moe = specs["transformer"]["ff"]["moe"]
    moe["w1"] = P(None, axis)
    moe["w2"] = P(None, axis)
    return specs


# ---------------------------------------------------------------------------
# model-specific loss closures
# ---------------------------------------------------------------------------

def vae_loss_fn(cfg, *, smooth_l1: bool = False, temperature=None):
    """Batch = {'images': (b, H, W, C)}. The training scripts' loss is
    smooth_l1 + mse (reference trainVAE.py:87) while the model's built-in is
    mse-only (reference dalle_pytorch.py:156); ``smooth_l1`` selects the
    script behavior."""
    from dalle_pytorch_tpu.models import vae as V
    import jax.numpy as jnp

    def loss(params, batch, rng):
        imgs = batch["images"]
        recon = V.vae_apply(params, imgs, cfg=cfg, rng=rng,
                            temperature=temperature)
        mse = jnp.mean(jnp.square(imgs - recon))
        if not smooth_l1:
            return mse
        d = jnp.abs(imgs - recon)
        huber = jnp.mean(jnp.where(d < 1.0, 0.5 * d * d, d - 0.5))
        return huber + mse

    return loss


def dalle_loss_fn(cfg, vae_params=None):
    """Batch = {'text': (b, t), 'image': ids (b, n) or raw images,
    'mask': optional (b, t)}."""
    from dalle_pytorch_tpu.models import dalle as D

    def loss(params, batch, rng):
        return D.dalle_apply(params, batch["text"], batch["image"], cfg=cfg,
                             mask=batch.get("mask"), vae_params=vae_params,
                             rng=rng, train=True, return_loss=True)

    return loss


def clip_loss_fn(cfg):
    from dalle_pytorch_tpu.models import clip as C

    def loss(params, batch, rng):
        return C.clip_apply(params, batch["text"], batch["images"], cfg=cfg,
                            text_mask=batch.get("mask"), return_loss=True)

    return loss
