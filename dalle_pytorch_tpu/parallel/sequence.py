"""Sequence-parallel transformer: the full stack with the TOKEN axis
sharded over a mesh axis — long-context training the reference cannot do at
all (SURVEY.md §5.7: its only sequence-cost levers are single-device).

Layout: activations are (batch, seq/sp, dim) per device; parameters are
replicated over ``sp`` (shard them over dp/fsdp outside). LayerNorm, the
qkv/out projections, and the GEGLU FF are position-local, so they need no
communication; only attention mixes positions and it runs as either

  * ``impl='ring'``   — K/V shards rotate neighbor-to-neighbor with
    ``ppermute`` (bandwidth-optimal on an ICI ring) into an online-softmax
    accumulator (parallel.ring.ring_attention_local), or
  * ``impl='ulysses'`` — one all-to-all re-shards sequence -> heads, local
    dense attention over the full sequence, all-to-all back.

The whole stack is ONE ``shard_map`` (collectives inside a single compiled
program, one ``lax.scan`` over the depth-stacked layer params) rather than
a shard_map per attention call.

Pad masks are supported with dense-path semantics: mask blocks rotate
around the ring with k/v (pad pairs fill with the finite -fmax, so padded
rows degrade to a causal-prefix average exactly like
ops.attention.dense_attention_weights). Dropout is supported and
sp-degree-invariant: both dropout sites (post-attention projection, FF
hidden) are position-local, so their masks are drawn from PER-POSITION
keys (``core.positional_dropout`` with offset = shard start) — the same
rng gives bit-identical masks on every sp degree, and the flagship
dropout-0.1 config trains under ``--sp``. ``cfg.remat`` composes (the
checkpointed body re-runs its ring/all-to-all collectives in the
backward), as do extra GSPMD mesh axes: only sp/batch are manual
(``shard_map(axis_names=...)``), so tp/fsdp param shardings ride through
— dp x tp x sp with remat is the long-context training recipe.
Restrictions (asserted): dense attention only, no reversible engine.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dalle_pytorch_tpu.ops import attention as attn_ops
from dalle_pytorch_tpu.ops import core
from dalle_pytorch_tpu.ops import transformer as T
from dalle_pytorch_tpu.parallel.ring import (ring_attention_local,
                                             ulysses_attention_local)

# jax >= 0.8 required: this module leans on shard_map(axis_names=...)
# (partial-manual lowering) which the old experimental shard_map lacks —
# a silent fallback would only defer the failure to every call site
from dalle_pytorch_tpu.parallel._compat import shard_map


def _check_cfg(cfg: T.TransformerConfig) -> None:
    if any(cfg.sparse_pattern):
        raise ValueError("sequence parallelism supports dense attention "
                         "only (sparse_attn must be False)")
    if cfg.reversible:
        raise ValueError("sequence parallelism and reversible execution "
                         "are mutually exclusive engines")
    if cfg.moe_experts:
        raise ValueError("sequence parallelism does not yet compose with "
                         "MoE layers (route tokens before sharding them)")


def sp_transformer_apply(params, x, *, cfg: T.TransformerConfig, mesh: Mesh,
                         sp_axis: str = "sp",
                         batch_axis: Optional[str] = None,
                         impl: str = "ring", mask=None,
                         rng=None, train: bool = False):
    """Run the stack with x (b, n, dim) sequence-sharded over ``sp_axis``.

    Numerics match ``ops.transformer.transformer_apply`` (same prenorm
    residual bodies, same ``cfg.scale``, same pad-mask semantics — ``mask``
    is the (b, n) GLOBAL pad mask, sharded like the tokens); only the
    attention communication pattern differs. ``batch_axis`` optionally
    shards the batch dim too (dp x sp in one mesh). Dropout masks are drawn
    per GLOBAL token position (core.positional_dropout), so the same
    ``rng`` yields identical masks on every sp degree.
    """
    _check_cfg(cfg)
    if impl not in ("ring", "ulysses"):
        raise ValueError(f"unknown sp impl {impl!r}")
    dropout_on = train and (cfg.attn_dropout > 0 or cfg.ff_dropout > 0)
    if dropout_on and rng is None:
        raise ValueError(
            "sp_transformer_apply(train=True) with nonzero dropout requires "
            "an explicit `rng` key — JAX has no global RNG state")
    size = mesh.shape[sp_axis]
    if x.shape[1] % size != 0:
        raise ValueError(f"seq len {x.shape[1]} not divisible by "
                         f"{sp_axis} axis ({size})")
    n_local = x.shape[1] // size
    keys = T._layer_keys(rng, cfg.depth)

    def attend(q, k, v, mb):
        if impl == "ring":
            return ring_attention_local(q, k, v, axis=sp_axis, size=size,
                                        causal=cfg.causal, scale=cfg.scale,
                                        mask=mb)
        return ulysses_attention_local(q, k, v, axis=sp_axis,
                                       causal=cfg.causal, scale=cfg.scale,
                                       mask=mb)

    def stack(params, keys, x, mb):
        # absolute position of this shard's first token — the dropout keys
        # depend on it, not on the shard index count, hence sp-invariance
        offset = lax.axis_index(sp_axis) * n_local

        def body(h, xs):
            lp, lkeys = xs
            a_in = core.layernorm(lp["attn"]["ln"], h)
            q, k, v = attn_ops.qkv_project(lp["attn"], a_in, cfg.heads)
            o = attend(q, k, v, mb)
            a_out = attn_ops.output_tail(lp["attn"], o)
            a_out = core.positional_dropout(lkeys[0], a_out,
                                            cfg.attn_dropout, train,
                                            offset=offset)
            h = h + a_out
            h = h + T.ff_branch(
                lp, h, cfg, lkeys[1], train,
                dropout_fn=lambda k, t: core.positional_dropout(
                    k, t, cfg.ff_dropout, train, offset=offset))
            return h, None

        # remat composes with sequence sharding: jax.checkpoint inside the
        # shard_map body re-runs the layer (including the ring ppermutes /
        # the ulysses all-to-alls) in the backward — activation thrift and
        # sequence sharding together are exactly the long-context recipe
        out, _ = lax.scan(T._maybe_remat(body, cfg.remat), x, (params, keys))
        return out

    x_spec = P(batch_axis, sp_axis, None)
    m_spec = P(batch_axis, sp_axis)
    # Only the token/batch axes are MANUAL (ring ppermutes / all-to-alls
    # written by hand); every other mesh axis stays auto, so e.g. a
    # dp x tp x sp mesh runs Megatron tp INSIDE this shard_map with
    # GSPMD-placed collectives — the 3-axis long-context recipe — without
    # this file knowing tp exists. Params use in_specs P(): replicated
    # over the manual axes, while any auto-axis sharding (tp/fsdp) rides
    # through untouched.
    manual = frozenset(a for a in (sp_axis, batch_axis) if a is not None)
    if mask is None:
        return shard_map(lambda p, k, x: stack(p, k, x, None), mesh=mesh,
                         in_specs=(P(), P(), x_spec),
                         out_specs=x_spec,
                         axis_names=manual)(params, keys, x)
    return shard_map(stack, mesh=mesh, in_specs=(P(), P(), x_spec, m_spec),
                     out_specs=x_spec, axis_names=manual)(params, keys, x,
                                                          mask)


def sp_dalle_loss_fn(cfg, mesh: Mesh, *, sp_axis: str = "sp",
                     batch_axis: Optional[str] = None, impl: str = "ring"):
    """DALLE training loss with the transformer sequence-sharded.

    Batch = {'text': (b, t) ids, 'image': (b, n_img) token ids, 'mask':
    optional (b, t) text pad mask — extended all-True over the image span
    exactly like the dense path (reference dalle_pytorch.py:384-388)}.
    Embedding lookups and the CE head run under GSPMD (the embeddings
    inherit the sequence sharding from the concat; use ``cfg.loss_chunk``
    to also cap the head's logits memory). Signature matches
    ``parallel.train.make_train_step``'s ``loss_fn(params, batch, rng)``.
    """
    from dalle_pytorch_tpu.models import dalle as D
    _check_cfg(cfg.transformer)

    def loss(params, batch, rng):
        text, image_ids = batch["text"], batch["image"]
        tokens = D.embed_prompt(params, cfg, text, image_ids)
        tokens = jax.lax.with_sharding_constraint(
            tokens, NamedSharding(mesh, P(batch_axis, sp_axis, None)))
        mask = batch.get("mask")
        if mask is not None:
            pad = jnp.ones((mask.shape[0], image_ids.shape[1]), bool)
            mask = jnp.concatenate([mask, pad], axis=1)
        h = sp_transformer_apply(params["transformer"], tokens,
                                 cfg=cfg.transformer, mesh=mesh,
                                 sp_axis=sp_axis, batch_axis=batch_axis,
                                 impl=impl, mask=mask, rng=rng, train=True)
        # same loss tail as dalle_apply — one definition of the contract
        return D.ce_from_hidden(params, h, text, image_ids, cfg=cfg)

    return loss
