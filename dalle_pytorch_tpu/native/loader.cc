// Native data loader: threaded JPEG/PNG decode + bilinear resize +
// normalize, producing the float32 NHWC [-1, 1] batches the models consume.
//
// This is the TPU-host runtime equivalent of the native IO path the
// reference reaches through torchvision (`torchvision.io.read_image`,
// reference trainDALLE.py:185-187, and the ImageFolder/transforms stack,
// reference trainVAE.py:59-67): image decode there is libjpeg/libpng C++
// inside torchvision; here it is the same C libraries driven directly, plus
// a std::thread pool so a many-core TPU host can decode a global batch
// while the chip runs the previous step (the reference's loop decodes
// serially on the Python side, SURVEY.md §3.2 "data-pipeline bottleneck").
//
// C ABI (ctypes-friendly, no CPython dependency):
//   dtl_load_images(paths, n, image_size, threads, out, err, errlen) -> int
//     paths       : array of n NUL-terminated file paths
//     image_size  : output side S (square); 0 = no resize (files must then
//                   all match the first file's dimensions)
//     out         : caller-allocated n*S*S*3 float32, filled NHWC in [-1,1]
//     returns 0 on success; on failure, a negative count of failed files
//     with the first error message in err.
//
// Build: g++ -O3 -shared -fPIC loader.cc -o _loader.so -ljpeg -lpng -pthread
// (driven by dalle_pytorch_tpu/native/build.py).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <png.h>

namespace {

struct Decoded {
  std::vector<unsigned char> rgb;  // HWC, 3 channels
  int w = 0, h = 0;
};

// ---------------------------------------------------------------------------
// JPEG (libjpeg with longjmp error trap — its default handler exit()s)
// ---------------------------------------------------------------------------

struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jump;
  char msg[JMSG_LENGTH_MAX];
};

void jpeg_err_exit(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<JpegErr*>(cinfo->err);
  (*cinfo->err->format_message)(cinfo, err->msg);
  longjmp(err->jump, 1);
}

bool decode_jpeg(FILE* f, Decoded* out, std::string* err) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jump)) {
    *err = jerr.msg;
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;  // libjpeg expands grayscale/YCbCr
  jpeg_start_decompress(&cinfo);
  out->w = cinfo.output_width;
  out->h = cinfo.output_height;
  out->rgb.resize(size_t(out->w) * out->h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row = out->rgb.data() +
        size_t(cinfo.output_scanline) * out->w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// ---------------------------------------------------------------------------
// PNG (libpng, transformed to 8-bit RGB: palette/gray expanded, alpha
// stripped, 16-bit reduced)
// ---------------------------------------------------------------------------

bool decode_png(FILE* f, Decoded* out, std::string* err) {
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr,
                                           nullptr, nullptr);
  if (!png) { *err = "png_create_read_struct failed"; return false; }
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    *err = "png_create_info_struct failed";
    return false;
  }
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    *err = "libpng decode error";
    return false;
  }
  png_init_io(png, f);
  png_read_info(png, info);
  png_set_expand(png);            // palette -> rgb, gray<8 -> 8, tRNS -> alpha
  png_set_strip_16(png);
  png_set_strip_alpha(png);
  png_set_gray_to_rgb(png);
  png_read_update_info(png, info);
  out->w = png_get_image_width(png, info);
  out->h = png_get_image_height(png, info);
  if (png_get_rowbytes(png, info) != size_t(out->w) * 3) {
    png_destroy_read_struct(&png, &info, nullptr);
    *err = "unexpected png row size after transforms";
    return false;
  }
  out->rgb.resize(size_t(out->w) * out->h * 3);
  std::vector<png_bytep> rows(out->h);
  for (int y = 0; y < out->h; ++y)
    rows[y] = out->rgb.data() + size_t(y) * out->w * 3;
  png_read_image(png, rows.data());
  png_read_end(png, nullptr);
  png_destroy_read_struct(&png, &info, nullptr);
  return true;
}

bool decode_file(const char* path, Decoded* out, std::string* err) {
  FILE* f = std::fopen(path, "rb");
  if (!f) { *err = std::string("cannot open ") + path; return false; }
  unsigned char magic[8] = {0};
  size_t got = std::fread(magic, 1, 8, f);
  std::rewind(f);
  bool ok = false;
  if (got >= 8 && png_sig_cmp(magic, 0, 8) == 0) {
    ok = decode_png(f, out, err);
  } else if (got >= 2 && magic[0] == 0xFF && magic[1] == 0xD8) {
    ok = decode_jpeg(f, out, err);
  } else {
    *err = std::string("unsupported format (not JPEG/PNG): ") + path;
  }
  std::fclose(f);
  if (!ok && !err->empty() && err->find(path) == std::string::npos)
    *err += std::string(" (") + path + ")";
  return ok;
}

// ---------------------------------------------------------------------------
// Separable triangle-filter resize (the PIL/torchvision BILINEAR resample:
// filter support scales with the downscale ratio, so minification
// area-averages instead of aliasing like 2-tap bilinear) + [-1,1] normalize.
// Computed in float32 throughout — no 8-bit intermediate, slightly *better*
// than the PIL path it replaces.
// ---------------------------------------------------------------------------

struct FilterTaps {
  std::vector<int> xmin;       // per output index: first input tap
  std::vector<int> count;      // taps per output index
  std::vector<float> weights;  // flattened [out][max_count]
  int max_count = 0;
};

FilterTaps triangle_taps(int in_size, int out_size) {
  FilterTaps t;
  const double scale = double(in_size) / out_size;
  const double fscale = std::max(scale, 1.0);
  const double radius = fscale;  // bilinear filter support = 1.0
  t.max_count = int(std::ceil(radius)) * 2 + 1;
  t.xmin.resize(out_size);
  t.count.resize(out_size);
  t.weights.assign(size_t(out_size) * t.max_count, 0.0f);
  for (int o = 0; o < out_size; ++o) {
    const double center = (o + 0.5) * scale;
    int x0 = std::max(0, int(center - radius + 0.5));
    int x1 = std::min(in_size, int(center + radius + 0.5));
    double sum = 0.0;
    for (int x = x0; x < x1; ++x) {
      double d = std::abs((x + 0.5 - center) / fscale);
      double w = d < 1.0 ? 1.0 - d : 0.0;
      t.weights[size_t(o) * t.max_count + (x - x0)] = float(w);
      sum += w;
    }
    if (sum > 0.0)
      for (int i = 0; i < x1 - x0; ++i)
        t.weights[size_t(o) * t.max_count + i] /= float(sum);
    t.xmin[o] = x0;
    t.count[o] = x1 - x0;
  }
  return t;
}

void resize_normalize(const Decoded& img, int S, float* out) {
  const FilterTaps tx = triangle_taps(img.w, S);
  const FilterTaps ty = triangle_taps(img.h, S);
  // pass 1: horizontal, uint8 (h, w, 3) -> float (h, S, 3)
  std::vector<float> tmp(size_t(img.h) * S * 3);
  for (int y = 0; y < img.h; ++y) {
    const unsigned char* row = img.rgb.data() + size_t(y) * img.w * 3;
    float* trow = tmp.data() + size_t(y) * S * 3;
    for (int ox = 0; ox < S; ++ox) {
      const float* w = &tx.weights[size_t(ox) * tx.max_count];
      const unsigned char* p = row + size_t(tx.xmin[ox]) * 3;
      float r = 0, g = 0, b = 0;
      for (int i = 0; i < tx.count[ox]; ++i, p += 3) {
        r += w[i] * p[0];
        g += w[i] * p[1];
        b += w[i] * p[2];
      }
      trow[ox * 3 + 0] = r;
      trow[ox * 3 + 1] = g;
      trow[ox * 3 + 2] = b;
    }
  }
  // pass 2: vertical, (h, S, 3) -> (S, S, 3), normalized to [-1,1]
  for (int oy = 0; oy < S; ++oy) {
    const float* w = &ty.weights[size_t(oy) * ty.max_count];
    float* orow = out + size_t(oy) * S * 3;
    std::memset(orow, 0, size_t(S) * 3 * sizeof(float));
    for (int i = 0; i < ty.count[oy]; ++i) {
      const float* trow = tmp.data() + size_t(ty.xmin[oy] + i) * S * 3;
      for (int x = 0; x < S * 3; ++x) orow[x] += w[i] * trow[x];
    }
    for (int x = 0; x < S * 3; ++x)
      orow[x] = orow[x] * (2.0f / 255.0f) - 1.0f;
  }
}

void copy_normalize(const Decoded& img, float* out) {
  const size_t n = size_t(img.w) * img.h * 3;
  for (size_t i = 0; i < n; ++i)
    out[i] = img.rgb[i] * (2.0f / 255.0f) - 1.0f;
}

}  // namespace

extern "C" {

// Returns 0 on full success, -k when k files failed (err holds the first
// failure message). Successfully decoded files are written regardless.
int dtl_load_images(const char** paths, int n, int image_size, int threads,
                    float* out, char* err, int errlen) {
  if (n <= 0) return 0;
  int S = image_size;
  Decoded first;
  std::string first_err;
  if (S <= 0) {  // no-resize mode: probe the first file for dimensions
    if (!decode_file(paths[0], &first, &first_err)) {
      if (err && errlen > 0) std::snprintf(err, errlen, "%s", first_err.c_str());
      return -n;
    }
    S = first.w;
    if (first.w != first.h) {
      if (err && errlen > 0)
        std::snprintf(err, errlen, "image_size=0 requires square images, "
                      "got %dx%d (%s)", first.w, first.h, paths[0]);
      return -n;
    }
  }

  std::atomic<int> next{0}, failures{0};
  std::mutex err_mu;
  std::string first_failure;
  const size_t stride = size_t(S) * S * 3;

  auto worker = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      Decoded img;
      std::string e;
      if (!decode_file(paths[i], &img, &e)) {
        failures.fetch_add(1);
        std::lock_guard<std::mutex> lk(err_mu);
        if (first_failure.empty()) first_failure = e;
        std::memset(out + i * stride, 0, stride * sizeof(float));
        continue;
      }
      if (image_size <= 0 && (img.w != S || img.h != S)) {
        failures.fetch_add(1);
        std::lock_guard<std::mutex> lk(err_mu);
        if (first_failure.empty())
          first_failure = std::string("size mismatch in no-resize mode: ") +
                          paths[i];
        std::memset(out + i * stride, 0, stride * sizeof(float));
        continue;
      }
      if (img.w == S && img.h == S)
        copy_normalize(img, out + i * stride);
      else
        resize_normalize(img, S, out + i * stride);
    }
  };

  int t = threads > 0 ? threads
                      : int(std::thread::hardware_concurrency());
  t = std::max(1, std::min(t, n));
  if (t == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(t);
    for (int i = 0; i < t; ++i) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }

  int fails = failures.load();
  if (fails && err && errlen > 0)
    std::snprintf(err, errlen, "%s", first_failure.c_str());
  return -fails;
}

// Decode ONE image, returning its dimensions without pixel output — used by
// the Python wrapper to validate files cheaply.
int dtl_probe(const char* path, int* w, int* h, char* err, int errlen) {
  Decoded img;
  std::string e;
  if (!decode_file(path, &img, &e)) {
    if (err && errlen > 0) std::snprintf(err, errlen, "%s", e.c_str());
    return -1;
  }
  *w = img.w;
  *h = img.h;
  return 0;
}

}  // extern "C"
