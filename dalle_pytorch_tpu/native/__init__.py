"""Native (C++) runtime components, bound over a plain C ABI via ctypes.

`load_image_batch_native` is the hot data-path entry: threaded JPEG/PNG
decode + bilinear resize + [-1,1] normalize into one float32 NHWC array —
the role torchvision's C++ IO plays for the reference (reference
trainDALLE.py:185-187 `read_image(...)/255.`, trainVAE.py:59-67 transform
stack), plus host-side parallelism the reference's serial per-image Python
loop lacks (SURVEY.md §3.2).

The library is built lazily on first use (g++, -ljpeg -lpng) and the data
layer falls back to the PIL path when unavailable, so the framework never
hard-requires a toolchain at runtime.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Sequence

import numpy as np

_lock = threading.Lock()
_lib = None
_lib_err: Optional[str] = None


def load_library(build_if_missing: bool = True):
    """dlopen the native loader, compiling it first if needed. Returns the
    ctypes library or raises RuntimeError (sticky: a failed build is
    remembered for the process)."""
    global _lib, _lib_err
    with _lock:
        if _lib is not None:
            return _lib
        if _lib_err is not None:
            raise RuntimeError(_lib_err)
        from dalle_pytorch_tpu.native.build import LIB, build
        if not build_if_missing and not os.path.exists(LIB):
            # NOT sticky: a later build_if_missing=True call (or an explicit
            # `python -m dalle_pytorch_tpu.native.build`) can still succeed
            raise RuntimeError(
                f"{LIB} not built (build_if_missing=False); run "
                "`python -m dalle_pytorch_tpu.native.build`")
        try:
            path = LIB
            if build_if_missing:
                # racelint: disable=RL003 — the lock exists precisely to
                # serialize this one-time compile (double-checked dlopen);
                # nothing else contends on it during a build
                path = build(quiet=True)  # no-op when fresh, rebuild if stale
            lib = ctypes.CDLL(path)
            lib.dtl_load_images.restype = ctypes.c_int
            lib.dtl_load_images.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.POINTER(ctypes.c_float),
                ctypes.c_char_p, ctypes.c_int]
            lib.dtl_probe.restype = ctypes.c_int
            lib.dtl_probe.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int), ctypes.c_char_p, ctypes.c_int]
            _lib = lib
            return _lib
        except Exception as e:
            _lib_err = f"native loader unavailable: {e}"
            raise RuntimeError(_lib_err) from e


def available() -> bool:
    """True when the native loader can be (or has been) loaded."""
    try:
        load_library()
        return True
    except RuntimeError:
        return False


def load_image_batch_native(paths: Sequence[str], image_size: int = 0,
                            threads: int = 0) -> np.ndarray:
    """Decode ``paths`` (JPEG/PNG) -> (n, S, S, 3) float32 in [-1, 1].

    ``image_size=0`` skips resizing (all files must be square and equal
    size). ``threads=0`` uses the host's core count. Raises RuntimeError
    with the first file's error when any decode fails — batch loading is
    all-or-nothing like the reference's loop (a bad file there raises from
    ``read_image``, reference trainDALLE.py:185).
    """
    lib = load_library()
    n = len(paths)
    if n == 0:
        return np.zeros((0, max(image_size, 0), max(image_size, 0), 3),
                        np.float32)
    size = image_size
    if size <= 0:
        w = ctypes.c_int()
        h = ctypes.c_int()
        err = ctypes.create_string_buffer(512)
        if lib.dtl_probe(paths[0].encode(), ctypes.byref(w), ctypes.byref(h),
                         err, len(err)) != 0:
            raise RuntimeError(err.value.decode(errors="replace"))
        size = w.value
    out = np.empty((n, size, size, 3), np.float32)
    c_paths = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
    err = ctypes.create_string_buffer(512)
    rc = lib.dtl_load_images(
        c_paths, n, image_size, threads,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), err, len(err))
    if rc != 0:
        raise RuntimeError(
            f"{-rc}/{n} images failed to decode: "
            f"{err.value.decode(errors='replace')}")
    return out


__all__ = ["available", "load_library", "load_image_batch_native"]
