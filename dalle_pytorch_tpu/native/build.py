"""Build the native loader shared library.

One translation unit, no CPython dependency (plain C ABI consumed via
ctypes — the sanctioned binding route in this image, no pybind11). The .so
lands next to this file; `python -m dalle_pytorch_tpu.native.build` builds
explicitly, and `native.load_library()` builds lazily on first use.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "loader.cc")
LIB = os.path.join(_DIR, "_loader.so")


def build(force: bool = False, quiet: bool = False) -> str:
    """Compile loader.cc -> _loader.so if missing/stale. Returns the path.
    Raises RuntimeError when no toolchain or libs are available."""
    if (not force and os.path.exists(LIB)
            and os.path.getmtime(LIB) >= os.path.getmtime(SRC)):
        return LIB
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        raise RuntimeError("no C++ compiler found (set CXX)")
    cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC", SRC,
           "-o", LIB + ".tmp", "-ljpeg", "-lpng", "-pthread"]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native loader build failed:\n{' '.join(cmd)}\n{proc.stderr}")
    os.replace(LIB + ".tmp", LIB)
    if not quiet:
        print(f"built {LIB}")
    return LIB


if __name__ == "__main__":
    build(force="--force" in sys.argv)
