"""Shared linter infrastructure for jaxlint and racelint.

One finding/JSON schema and ONE suppression-comment parser for every
in-repo linter: jaxlint (TPU/tracing invariants) and racelint (the
concurrency rules for the threaded serve tier) emit the same
``Finding`` record — ``{rule, slug, path, line, col, message}`` — and
honour the same in-line waiver convention,

    # <tool>: disable=RULE — reason why this one is fine

scoped to the offending line (or the comment line above it). The slug
form (``disable=rng-key-reuse``) and ``disable=all`` work for both.
Keeping the parser single-sourced is what keeps the convention
single-sourced: a waiver form that works for one linter works for the
other, and a drift between the two could silently turn a gate off.

The per-rule slug registry is shared too (rule ids are namespaced —
``JL...`` vs ``RL...`` — so one flat registry is safe), which is what
lets ``Finding`` stay a plain frozen dataclass constructed positionally
by both linters while still rendering its slug.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Dict, List, Sequence, Set, Tuple

# rule id -> slug, fed by each linter's register_rules at import time.
_SLUGS: Dict[str, str] = {}

# linter true-positive corpora must not fail the repo gate — each
# linter's fixtures deliberately violate BOTH rule sets (racelint's
# wallclock fixtures would trip jaxlint's JL007 and vice versa), so
# the default excludes are shared.
DEFAULT_EXCLUDES = ("fixtures/jaxlint", "fixtures/racelint")


def register_rules(rules: Dict[str, Tuple[str, str]]) -> None:
    """Register ``{rule_id: (slug, description)}`` so ``Finding.slug``
    resolves. Both linters call this at import."""
    for rid, (slug, _desc) in rules.items():
        _SLUGS[rid] = slug


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    @property
    def slug(self) -> str:
        return _SLUGS.get(self.rule, self.rule.lower())

    def to_dict(self) -> dict:
        return {"rule": self.rule, "slug": self.slug, "path": self.path,
                "line": self.line, "col": self.col,
                "message": self.message}

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"({self.slug}) {self.message}")


def suppressions(src: str, tool: str,
                 rules: Dict[str, Tuple[str, str]]) -> Dict[int, Set[str]]:
    """line -> set of suppressed rule ids for ``tool`` (``jaxlint`` or
    ``racelint``). A trailing comment suppresses its own line; a
    comment-only line also suppresses the next code line (for
    statements too long to share a line with their waiver)."""
    disable_re = re.compile(
        rf"{re.escape(tool)}:\s*disable=([A-Za-z0-9_,\-]+)")
    slug_to_id = {slug: rid for rid, (slug, _) in rules.items()}
    out: Dict[int, Set[str]] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except tokenize.TokenizeError:
        return out
    code_lines = set()
    for tok in tokens:
        if tok.type == tokenize.COMMENT:
            m = disable_re.search(tok.string)
            if not m:
                continue
            found: Set[str] = set()
            for part in m.group(1).split(","):
                part = part.strip()
                if part.lower() == "all":
                    found |= set(rules)
                elif part.upper() in rules:
                    found.add(part.upper())
                elif part in slug_to_id:
                    found.add(slug_to_id[part])
            out.setdefault(tok.start[0], set()).update(found)
        elif tok.type not in (tokenize.NL, tokenize.NEWLINE,
                              tokenize.INDENT, tokenize.DEDENT,
                              tokenize.ENCODING, tokenize.ENDMARKER):
            code_lines.add(tok.start[0])
    max_line = max(code_lines, default=0)
    for line in list(out):
        if line in code_lines:
            continue
        # standalone waiver: skip the rest of its comment block and
        # cover the first code line after it
        nxt = line + 1
        while nxt <= max_line and nxt not in code_lines:
            nxt += 1
        out.setdefault(nxt, set()).update(out[line])
    return out


def filter_findings(findings: List[Finding], src: str, tool: str,
                    rules: Dict[str, Tuple[str, str]]) -> List[Finding]:
    """Apply the suppression comments, sort, and dedupe (two rules can
    hit one call site; keep the first per (line, col, rule))."""
    supp = suppressions(src, tool, rules)
    findings = [f for f in findings
                if f.rule not in supp.get(f.line, set())]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    seen: Set[Tuple] = set()
    out = []
    for f in findings:
        k = (f.line, f.col, f.rule)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def iter_py_files(paths: Sequence[str],
                  excludes: Sequence[str] = DEFAULT_EXCLUDES
                  ) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        pp = Path(p)
        if pp.is_dir():
            out.extend(sorted(pp.rglob("*.py")))
        elif pp.suffix == ".py":
            out.append(pp)
    return [p for p in out
            if not any(ex in str(p) for ex in excludes)
            and "__pycache__" not in str(p)]


def dotted(node: ast.AST) -> str:
    """'jax.random.normal' for a Name/Attribute chain, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def last(node: ast.AST) -> str:
    """Final component of a Name/Attribute chain ('' otherwise)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def mod_parts(path: str) -> Tuple[str, ...]:
    """Dotted-module parts of a file path ('.../serve/engine.py' ->
    (..., 'serve', 'engine')); a package's __init__.py is the package
    itself."""
    p = Path(path)
    parts = list(p.parts)
    parts[-1] = p.stem
    if parts[-1] == "__init__":
        parts.pop()
    return tuple(parts)
