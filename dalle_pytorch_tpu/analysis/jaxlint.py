"""jaxlint — AST lint for this repo's TPU invariants (stdlib only).

``make lint`` was ``compileall`` — a syntax check — while the invariants
that actually decide whether the chip runs fast live in reviewers' heads:
no host syncs inside traced code, no recompiles of the serve decode
program, no PRNG key reused across draws, no wall-clock ``time.time()``
in duration math. Serving-stack papers (PAPERS.md: Ragged Paged
Attention; Serving Gemma on Cloud TPU) name recompiles and host-device
syncs as the silent TPU killers; both are exactly the class of defect an
AST pass can catch before anything is compiled. docs/STATIC_ANALYSIS.md
is the rule catalog with one real bug from this repo's history per rule.

Scope and philosophy: per-file analysis tuned to THIS codebase's idioms
(``jax.jit(self._method)``, ``fn = jax.jit(pre)`` caches, bench's
``run = jax.jit(...)`` timing harness), plus PROJECT MODE
(``lint_files`` — what the CLI and the repo-clean test run): JL001/JL009
traced reachability propagates across module boundaries, so a
module-level jitted program imported elsewhere is a known jitted
callable there (host round-trips on its outputs are flagged), and a
function jitted from ANOTHER module gets its body checked as traced
code (the serve replica layer driving jitted engine internals is the
motivating shape). Rules prefer missing a finding
over flagging working idioms — the gate only stays on in CI if the
merged tree lints clean. Every finding can be silenced in place with

    # jaxlint: disable=JL001 — reason why this one is fine

on the offending line (or the line above); the reason is part of the
convention, not enforced syntax.

Usage:
    jaxlint [paths...] [--json] [--select JL001,..] [--ignore JL00x,..]
    python -m dalle_pytorch_tpu.analysis.jaxlint dalle_pytorch_tpu tests

Exit status: 0 clean, 1 findings, 2 usage/parse errors.
"""

from __future__ import annotations

import argparse
import ast
import json
import sys
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import lintcore
from .lintcore import (DEFAULT_EXCLUDES, Finding, iter_py_files,
                       dotted as _dotted, last as _last,
                       mod_parts as _mod_parts)

# rule id -> (slug, one-line description). docs/STATIC_ANALYSIS.md holds
# the long-form rationale; keep the two in sync.
RULES: Dict[str, Tuple[str, str]] = {
    "JL001": ("host-sync-in-jit",
              "host-device sync (.item/.tolist/np.asarray/int()) reachable "
              "from traced code, or a host round-trip on a jitted "
              "program's output"),
    "JL002": ("traced-branch",
              "python if/while on a traced argument — trace error or "
              "silent recompile per value"),
    "JL003": ("rng-key-reuse",
              "same PRNG key consumed by two draws without an "
              "intervening split/fold_in"),
    "JL004": ("recompile-hazard",
              "jit construction that retraces per call (jit() in a loop, "
              "non-int static_argnums, static+donated overlap)"),
    "JL005": ("loop-closure-in-jit",
              "jitted def closes over a loop variable — late binding + "
              "one compile per distinct value"),
    "JL006": ("use-after-donate",
              "buffer referenced after being donated via donate_argnums"),
    "JL007": ("wallclock-timing",
              "time.time() — durations must use perf_counter; epoch "
              "stamps carry an explicit disable comment"),
    "JL008": ("effect-in-jit",
              "print/time.* side effect inside traced code — runs at "
              "trace time only (or burns a callback into the program)"),
    "JL009": ("cond-pred-sync",
              "lax.cond/switch/while_loop dispatched eagerly on a jitted "
              "program's output — the predicate implies a hidden host "
              "round-trip per call (per iteration for while_loop)"),
}

# Wrappers whose function-valued argument is traced by JAX. Used to mark
# trace roots beyond literal @jit decoration.
_TRACE_WRAPPERS = {
    "jit", "pjit", "vmap", "pmap", "grad", "value_and_grad", "remat",
    "checkpoint", "scan", "while_loop", "fori_loop", "cond", "switch",
    "map", "shard_map", "custom_vjp", "custom_jvp", "linearize", "vjp",
    "jvp", "hessian", "jacfwd", "jacrev", "associative_scan",
}
_JIT_NAMES = {"jit", "pjit"}
# jax.random consumers that burn entropy; split/fold_in/PRNGKey derive.
_RNG_DERIVE = {"split", "fold_in", "PRNGKey", "key", "key_data",
               "wrap_key_data", "clone"}
_SYNC_ATTRS = {"item", "tolist"}

# Finding, the suppression parser, DEFAULT_EXCLUDES, and iter_py_files
# live in lintcore and are shared with racelint; registering the rules
# is what makes Finding.slug resolve for JL ids.
lintcore.register_rules(RULES)


def _is_jit_expr(node: ast.AST) -> bool:
    """True for expressions that build a jitted callable: ``jax.jit``,
    ``jit``, ``pjit``, ``jax.jit(...)`` (configured), and
    ``partial(jax.jit, ...)``."""
    if _last(node) in _JIT_NAMES:
        return True
    if isinstance(node, ast.Call):
        if _last(node.func) in _JIT_NAMES:
            return True
        if _last(node.func) == "partial" and node.args \
                and _is_jit_expr(node.args[0]):
            return True
    return False


def _jit_call_of(node: ast.AST) -> Optional[ast.Call]:
    """The ``jit(...)`` Call carrying kwargs, if ``node`` is one (either
    bare or partial-wrapped)."""
    if isinstance(node, ast.Call):
        if _last(node.func) in _JIT_NAMES:
            return node
        if _last(node.func) == "partial" and node.args \
                and _is_jit_expr(node.args[0]):
            return node
    return None


def _const_ints(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """(ints,) for Constant int / tuple/list of Constant ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int) \
                    and not isinstance(el.value, bool):
                out.append(el.value)
            else:
                return None
        return tuple(out)
    return None


class _ModuleIndex(ast.NodeVisitor):
    """One pass collecting everything the rules need: import aliases,
    function defs, jit-wrapped names, and trace roots."""

    def __init__(self) -> None:
        self.functions: List[ast.FunctionDef] = []
        self.parent_fn: Dict[ast.AST, Optional[ast.AST]] = {}
        self.np_aliases: Set[str] = set()
        self.time_aliases: Set[str] = set()
        self.random_aliases: Set[str] = {"jax.random"}
        self.trace_roots: Set[ast.AST] = set()
        # names (vars or attribute leaves like ``_decode_fn``) assigned
        # from a jit expression anywhere in the module, with donated
        # positions when statically known
        self.jitted_names: Dict[str, Tuple[int, ...]] = {}
        # MODULE-LEVEL jit assignments only — the importable subset, what
        # project mode exports to other modules' jitted_names
        self.module_jitted: Dict[str, Tuple[int, ...]] = {}
        # cross-module resolution surface: `from M import n as a` ->
        # import_from[a] = (M, n); module-object aliases (`import m.x
        # as y`, `from pkg import mod`) -> module_alias[y] = dotted
        self.import_from: Dict[str, Tuple[str, str]] = {}
        self.module_alias: Dict[str, str] = {}
        self._fn_stack: List[ast.AST] = []

    # -- imports -----------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            alias = a.asname or a.name.split(".")[0]
            if a.name == "numpy":
                self.np_aliases.add(alias)
            elif a.name == "time":
                self.time_aliases.add(alias)
            elif a.name == "jax.random" and a.asname:
                self.random_aliases.add(a.asname)
            if a.asname:
                self.module_alias[a.asname] = a.name
            else:
                self.module_alias[alias] = alias
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "jax":
            for a in node.names:
                if a.name == "random":
                    self.random_aliases.add(a.asname or "random")
        mod = node.module or ""
        for a in node.names:
            if a.name == "*":
                continue
            alias = a.asname or a.name
            # a `from pkg import name` is ambiguous between a symbol
            # and a submodule — record both readings; project mode
            # resolves against what the target module actually exports
            self.import_from[alias] = (mod, a.name)
            self.module_alias[alias] = f"{mod}.{a.name}" if mod \
                else a.name
        self.generic_visit(node)

    # -- defs --------------------------------------------------------------
    def _visit_fn(self, node) -> None:
        self.functions.append(node)
        self.parent_fn[node] = self._fn_stack[-1] if self._fn_stack \
            else None
        for dec in node.decorator_list:
            if _is_jit_expr(dec) or _last(dec) in _TRACE_WRAPPERS:
                self.trace_roots.add(node)
        self._fn_stack.append(node)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- jit-wrapped names and trace roots by reference --------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        jc = node.value if _is_jit_expr(node.value) \
            and isinstance(node.value, ast.Call) else None
        if jc is not None:
            donated: Tuple[int, ...] = ()
            call = _jit_call_of(node.value)
            if call is not None:
                for kw in call.keywords:
                    if kw.arg == "donate_argnums":
                        donated = _const_ints(kw.value) or ()
            for tgt in node.targets:
                name = _last(tgt)
                if name:
                    self.jitted_names[name] = donated
                    if not self._fn_stack:
                        self.module_jitted[name] = donated
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # mark `jax.jit(fn)` / `lax.scan(body, ...)` function arguments
        # as trace roots (matched by name against defs in this module)
        if _last(node.func) in _TRACE_WRAPPERS:
            for arg in node.args:
                ref = _last(arg)
                if ref:
                    self._mark_by_name(ref)
        self.generic_visit(node)

    def _mark_by_name(self, name: str) -> None:
        for fn in self.functions:
            if fn.name == name:
                self.trace_roots.add(fn)

    def resolve(self, tree: ast.Module) -> None:
        """Late `jax.jit(name)` references may precede the def in visit
        order; re-resolve every wrapper reference."""
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and _last(node.func) in _TRACE_WRAPPERS:
                for arg in node.args:
                    ref = _last(arg)
                    if ref:
                        self._mark_by_name(ref)

    def mark_name(self, name: str) -> None:
        """Mark a function DEFINED in this module as a trace root — the
        project-mode entry for cross-module traced reachability (module
        B jits a function module A defines)."""
        self._mark_by_name(name)

    def propagate(self) -> None:
        """Propagate traced reachability through same-module calls and
        nesting (re-runnable: project mode adds cross-module roots after
        the per-module pass, then propagates again)."""
        by_name: Dict[str, List[ast.AST]] = {}
        for fn in self.functions:
            by_name.setdefault(fn.name, []).append(fn)
        changed = True
        while changed:
            changed = False
            for fn in self.functions:
                if fn in self.trace_roots:
                    continue
                parent = self.parent_fn.get(fn)
                if parent is not None and parent in self.trace_roots:
                    # a def nested in traced code is traced when called
                    self.trace_roots.add(fn)
                    changed = True
                    continue
            for root in list(self.trace_roots):
                for node in ast.walk(root):
                    if isinstance(node, ast.Call):
                        callee = _last(node.func)
                        for fn in by_name.get(callee, ()):
                            if fn not in self.trace_roots:
                                self.trace_roots.add(fn)
                                changed = True

    def finalize(self, tree: ast.Module) -> None:
        self.resolve(tree)
        self.propagate()


# ---------------------------------------------------------------------------
# suppression comments (shared parser in lintcore)
# ---------------------------------------------------------------------------

def _suppressions(src: str) -> Dict[int, Set[str]]:
    """line -> set of suppressed rule ids for `# jaxlint: disable=...`
    comments (the shared lintcore parser scoped to this tool's tag)."""
    return lintcore.suppressions(src, "jaxlint", RULES)


# ---------------------------------------------------------------------------
# per-rule checks
# ---------------------------------------------------------------------------

def _walk_no_nested_fns(node: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested defs (each
    function scope is analyzed on its own)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def _params(fn) -> Set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return {n for n in names if n not in ("self", "cls")}


_SCALAR_ANN = {"int", "float", "bool", "str", "bytes", "Optional"}


def _likely_traced_params(fn) -> Set[str]:
    """Arguments that plausibly receive tracers. Codebase idiom: traced
    arrays ride in positional, unannotated (or Array-annotated) slots;
    keyword-only args and scalar-annotated args are trace-time config
    (``causal: bool``, ``*, scale, block_k``) — python branches on them
    are legitimate specialization, not tracer reads."""
    out: Set[str] = set()
    for p in fn.args.posonlyargs + fn.args.args:
        if p.arg in ("self", "cls"):
            continue
        ann = p.annotation
        if ann is not None:
            names = {_last(n) for n in ast.walk(ann)
                     if isinstance(n, (ast.Name, ast.Attribute))}
            names |= {n.value for n in ast.walk(ann)
                      if isinstance(n, ast.Constant)
                      and isinstance(n.value, str)}
            if names & _SCALAR_ANN and not names & {"Array", "ndarray",
                                                    "ArrayLike"}:
                continue
        out.add(p.arg)
    return out


def _static_params(fn) -> Set[str]:
    """Best-effort static_argnames/static_argnums from a jit decorator —
    those arguments are concrete python values, not tracers."""
    out: Set[str] = set()
    a = fn.args
    positional = [p.arg for p in a.posonlyargs + a.args]
    for dec in fn.decorator_list:
        call = _jit_call_of(dec)
        if call is None:
            continue
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                if isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    out.add(kw.value.value)
                elif isinstance(kw.value, (ast.Tuple, ast.List)):
                    for el in kw.value.elts:
                        if isinstance(el, ast.Constant) \
                                and isinstance(el.value, str):
                            out.add(el.value)
            elif kw.arg == "static_argnums":
                for i in _const_ints(kw.value) or ():
                    if 0 <= i < len(positional):
                        out.add(positional[i])
    return out


def _check_traced_bodies(idx: _ModuleIndex, path: str,
                         findings: List[Finding]) -> None:
    """JL001 (syncs in traced code), JL002 (traced branches), JL008
    (print/time effects in traced code)."""
    for fn in idx.trace_roots:
        params = _likely_traced_params(fn) - _static_params(fn)
        for node in _walk_no_nested_fns(fn):
            if isinstance(node, ast.Call):
                self_sync = isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SYNC_ATTRS and not node.args
                if self_sync:
                    findings.append(Finding(
                        "JL001", path, node.lineno, node.col_offset,
                        f".{node.func.attr}() inside traced code blocks "
                        f"on the device and breaks the trace"))
                    continue
                fname = _last(node.func)
                base = _dotted(node.func).rsplit(".", 1)[0] \
                    if isinstance(node.func, ast.Attribute) else ""
                if fname in ("asarray", "array") \
                        and base in idx.np_aliases and node.args \
                        and not isinstance(node.args[0],
                                           (ast.Constant, ast.List,
                                            ast.Tuple)):
                    findings.append(Finding(
                        "JL001", path, node.lineno, node.col_offset,
                        f"{base}.{fname}() inside traced code forces a "
                        f"host transfer (use jnp, or hoist the constant)"))
                elif fname == "device_get":
                    findings.append(Finding(
                        "JL001", path, node.lineno, node.col_offset,
                        "device_get inside traced code is a host sync"))
                elif isinstance(node.func, ast.Name) \
                        and node.func.id in ("int", "float", "bool") \
                        and len(node.args) == 1 \
                        and isinstance(node.args[0], ast.Name) \
                        and node.args[0].id in params:
                    findings.append(Finding(
                        "JL001", path, node.lineno, node.col_offset,
                        f"{node.func.id}() on traced argument "
                        f"'{node.args[0].id}' concretizes the tracer "
                        f"(host sync / TracerError)"))
                elif isinstance(node.func, ast.Name) \
                        and node.func.id == "print":
                    findings.append(Finding(
                        "JL008", path, node.lineno, node.col_offset,
                        "print() in traced code runs at trace time only "
                        "— use jax.debug.print for runtime values"))
                elif base in idx.time_aliases:
                    findings.append(Finding(
                        "JL008", path, node.lineno, node.col_offset,
                        f"time.{fname}() in traced code is evaluated "
                        f"once at trace time, not per step"))
            elif isinstance(node, (ast.If, ast.While)):
                traced = _traced_names_in_test(node.test, params)
                if traced:
                    kind = "if" if isinstance(node, ast.If) else "while"
                    findings.append(Finding(
                        "JL002", path, node.lineno, node.col_offset,
                        f"python `{kind}` on traced argument(s) "
                        f"{', '.join(sorted(traced))} — use lax.cond/"
                        f"while_loop or mark the argument static"))


def _trace_time_compare(node: ast.Compare) -> bool:
    """Compares that read python facts, not tracer values: identity
    (`x is None`), and CONSTANT-key membership (`"k_scale" in cache` —
    pytree STRUCTURE, fixed at trace time). Membership with a non-
    constant left operand (`if x in xs:`) stays flagged: on a traced
    array that is exactly the TracerBoolConversionError JL002 exists
    to catch."""
    if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
        return True
    return all(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops) \
        and isinstance(node.left, ast.Constant)


def _traced_names_in_test(test: ast.AST, params: Set[str]) -> Set[str]:
    """Parameter names whose VALUE the test branches on. `x is None`,
    `isinstance(x, ...)`, `len(x)`, attribute access (config objects)
    and constant-key membership (`"k" in cache`) are trace-time python
    facts, not tracer reads."""
    if isinstance(test, ast.Compare) and _trace_time_compare(test):
        return set()
    skip: Set[ast.AST] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Call) \
                and _last(node.func) in ("isinstance", "len", "getattr",
                                         "hasattr", "callable"):
            for sub in ast.walk(node):
                skip.add(sub)
        elif isinstance(node, ast.Attribute):
            for sub in ast.walk(node):
                skip.add(sub)
        elif isinstance(node, ast.Compare) and _trace_time_compare(node):
            for sub in ast.walk(node):
                skip.add(sub)
    return {node.id for node in ast.walk(test)
            if isinstance(node, ast.Name) and node.id in params
            and node not in skip}


def _check_sync_on_jit_output(idx: _ModuleIndex, path: str,
                              findings: List[Finding]) -> None:
    """JL001's host-loop half: a value returned by a known jit-wrapped
    callable, fetched to the host in the same function via
    np.asarray/.item()/device_get. This is the per-step round-trip the
    ROADMAP flags in the serve decode loop — legitimate terminal fetches
    carry a disable comment saying why the value must leave the device."""
    if not idx.jitted_names:
        return
    for fn in idx.functions:
        # flow-ordered events: a sync only fires on a name that is a
        # jit output AT THAT POINT — bound from a jitted call earlier
        # and not rebound to host data in between
        events: List[Tuple[int, int, int, str, str]] = []
        for node in _walk_no_nested_fns(fn):
            if isinstance(node, ast.Assign):
                kind = "jitbind" if isinstance(node.value, ast.Call) \
                    and _last(node.value.func) in idx.jitted_names \
                    else "bind"
                for tgt in node.targets:
                    els = tgt.elts if isinstance(tgt, (ast.Tuple,
                                                       ast.List)) \
                        else [tgt]
                    for el in els:
                        if isinstance(el, ast.Name):
                            # binds sort after same-line value-side syncs
                            events.append((node.lineno, 1,
                                           el.col_offset, kind, el.id))
            elif isinstance(node, (ast.AugAssign, ast.For)):
                tgt = node.target
                for el in ast.walk(tgt):
                    if isinstance(el, ast.Name):
                        events.append((el.lineno, 1, el.col_offset,
                                       "bind", el.id))
            elif isinstance(node, ast.Call):
                fname = _last(node.func)
                base = _dotted(node.func).rsplit(".", 1)[0] \
                    if isinstance(node.func, ast.Attribute) else ""
                arg: Optional[str] = None
                if fname in ("asarray", "array") \
                        and base in idx.np_aliases and node.args:
                    arg = node.args[0].id \
                        if isinstance(node.args[0], ast.Name) else None
                elif fname == "device_get" and node.args:
                    arg = node.args[0].id \
                        if isinstance(node.args[0], ast.Name) else None
                elif fname in _SYNC_ATTRS \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name):
                    arg = node.func.value.id
                if arg is not None:
                    events.append((node.lineno, 0, node.col_offset,
                                   "sync:" + fname, arg))
        events.sort()
        jit_outputs: Set[str] = set()
        for lineno, _, col, kind, name in events:
            if kind == "jitbind":
                jit_outputs.add(name)
            elif kind == "bind":
                jit_outputs.discard(name)
            elif name in jit_outputs:
                findings.append(Finding(
                    "JL001", path, lineno, col,
                    f"host round-trip: {kind[5:]} on '{name}', the "
                    f"output of a jitted program — keep it on device or "
                    f"fetch asynchronously (ROADMAP: one round-trip per "
                    f"decode step)"))


def _check_eager_lax_control(idx: _ModuleIndex, path: str,
                             findings: List[Finding]) -> None:
    """JL009: ``lax.cond``/``lax.switch``/``lax.while_loop`` dispatched
    EAGERLY — outside any traced region — on operands derived from a
    jitted program's output. Inside jit these are free; eagerly, the
    dispatch is not transfer-clean (the predicate/carry round-trips with
    the host — measurably so under ``jax.transfer_guard("disallow")``),
    and ``while_loop`` pays it once per ITERATION. The fix is to wrap
    the control flow in jit, or branch in python on genuinely host data.
    Same flow-ordered jit-output tracking as JL001's round-trip half:
    a name rebound to host data between the jitted call and the control
    op stops being flagged."""
    # which positional argument carries device data into the eager op:
    # cond/switch take the predicate/index first; while_loop's cond_fun
    # re-evaluates against the carry (arg 2) every iteration
    ctl = {"cond": 0, "switch": 0, "while_loop": 2}
    for fn in idx.functions:
        if fn in idx.trace_roots:
            continue
        events: List[Tuple] = []
        for node in _walk_no_nested_fns(fn):
            if isinstance(node, ast.Assign):
                kind = "jitbind" if isinstance(node.value, ast.Call) \
                    and _last(node.value.func) in idx.jitted_names \
                    else "bind"
                for tgt in node.targets:
                    els = tgt.elts if isinstance(tgt, (ast.Tuple,
                                                       ast.List)) \
                        else [tgt]
                    for el in els:
                        if isinstance(el, ast.Name):
                            events.append((node.lineno, 1, el.col_offset,
                                           kind, el.id, False))
            elif isinstance(node, ast.Call) and _last(node.func) in ctl:
                op = _last(node.func)
                pos = ctl[op]
                if pos >= len(node.args):
                    continue
                arg = node.args[pos]
                names = frozenset(n.id for n in ast.walk(arg)
                                  if isinstance(n, ast.Name))
                # a jitted call INSIDE the operand expression is a device
                # value regardless of any binding flow
                direct = any(isinstance(c, ast.Call)
                             and _last(c.func) in idx.jitted_names
                             for c in ast.walk(arg))
                events.append((node.lineno, 0, node.col_offset,
                               "ctl:" + op, names, direct))
        events.sort(key=lambda e: (e[0], e[1], e[2]))
        jit_outputs: Set[str] = set()
        for lineno, _, col, kind, payload, direct in events:
            if kind == "jitbind":
                jit_outputs.add(payload)
            elif kind == "bind":
                jit_outputs.discard(payload)
            elif direct or (payload & jit_outputs):
                op = kind[4:]
                cost = ("its cond_fun syncs with the host every "
                        "iteration" if op == "while_loop"
                        else "the predicate forces a host round-trip "
                             "per call")
                findings.append(Finding(
                    "JL009", path, lineno, col,
                    f"eager lax.{op} on a jitted program's output — "
                    f"{cost}; wrap the control flow in jit or branch "
                    f"in python on host data"))


def _check_rng_reuse(idx: _ModuleIndex, path: str,
                     findings: List[Finding]) -> None:
    """JL003: straight-line reuse of a PRNG key by two draws, and reuse
    across loop iterations of a key defined outside the loop."""

    def consumer_calls(expr: ast.AST) -> List[Tuple[ast.Call, str]]:
        out = []
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                base = _dotted(node.func).rsplit(".", 1)[0]
                if base in idx.random_aliases \
                        and node.func.attr not in _RNG_DERIVE \
                        and node.args \
                        and isinstance(node.args[0], ast.Name):
                    out.append((node, node.args[0].id))
        return sorted(out, key=lambda t: (t[0].lineno, t[0].col_offset))

    def assigned_names(stmt: ast.stmt) -> Set[str]:
        out: Set[str] = set()
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.For):
            targets = [stmt.target]
        for tgt in targets:
            for node in ast.walk(tgt):
                if isinstance(node, ast.Name):
                    out.add(node.id)
        return out

    def exprs_of(stmt: ast.stmt) -> List[ast.AST]:
        if isinstance(stmt, ast.Assign):
            return [stmt.value]
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            return [stmt.value] if stmt.value is not None else []
        if isinstance(stmt, ast.Expr):
            return [stmt.value]
        if isinstance(stmt, ast.Return):
            return [stmt.value] if stmt.value is not None else []
        if isinstance(stmt, ast.For):
            return [stmt.iter]
        if isinstance(stmt, (ast.While, ast.If)):
            return [stmt.test]
        return []

    def run_block(stmts: Sequence[ast.stmt], state: Dict[str, int],
                  in_loop_retry: bool = False) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for expr in exprs_of(stmt):
                for call, key in consumer_calls(expr):
                    if key in state:
                        suffix = " (reused across loop iterations)" \
                            if in_loop_retry else ""
                        f = Finding(
                            "JL003", path, call.lineno, call.col_offset,
                            f"PRNG key '{key}' already consumed at line "
                            f"{state[key]} — split or fold_in before "
                            f"drawing again{suffix}")
                        if f not in findings:
                            findings.append(f)
                    else:
                        state[key] = call.lineno
            cleared = assigned_names(stmt)
            for name in cleared:
                state.pop(name, None)
            if isinstance(stmt, ast.If):
                s_if, s_else = dict(state), dict(state)
                run_block(stmt.body, s_if, in_loop_retry)
                run_block(stmt.orelse, s_else, in_loop_retry)
                # join = MUST-consumed: a key counts as consumed after
                # the `if` only when BOTH arms end with it consumed —
                # an arm that re-derived it (split/fold_in reassignment)
                # drops it from that arm's final state, so key-rotation
                # in every branch legally resets the key
                state.clear()
                for key in s_if.keys() & s_else.keys():
                    state[key] = min(s_if[key], s_else[key])
            elif isinstance(stmt, (ast.For, ast.While)):
                inner = dict(state)
                run_block(stmt.body, inner, in_loop_retry)
                # second pass simulates iteration 2: a key consumed in
                # pass 1 and not reassigned inside the loop trips here
                run_block(stmt.body, inner, in_loop_retry=True)
                state.update(inner)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                run_block(stmt.body, state, in_loop_retry)
            elif isinstance(stmt, ast.Try):
                run_block(stmt.body, state, in_loop_retry)
                for h in stmt.handlers:
                    run_block(h.body, dict(state), in_loop_retry)
                run_block(stmt.orelse, state, in_loop_retry)
                run_block(stmt.finalbody, state, in_loop_retry)

    for fn in idx.functions:
        run_block(fn.body, {})


def _check_recompile_hazards(idx: _ModuleIndex, path: str, tree: ast.Module,
                             findings: List[Finding]) -> None:
    """JL004: jit construction inside a loop body (a fresh wrapper per
    iteration defeats the compile cache), suspicious static_argnums, and
    arguments that are both static and donated."""
    loops = [n for n in ast.walk(tree)
             if isinstance(n, (ast.For, ast.While))]
    in_loop: Set[ast.AST] = set()
    for loop in loops:
        for stmt in loop.body + list(getattr(loop, "orelse", [])):
            # nested defs are skipped (their jits compile when THEY are
            # called — JL005's domain), but siblings after a lambda in
            # the same statement still count as in-loop
            in_loop.add(stmt)
            in_loop.update(_walk_no_nested_fns(stmt))
    for node in ast.walk(tree):
        call = _jit_call_of(node) if isinstance(node, ast.Call) else None
        if call is None:
            continue
        if node in in_loop:
            findings.append(Finding(
                "JL004", path, call.lineno, call.col_offset,
                "jit() constructed inside a loop — build the wrapper "
                "once outside (each construction risks a retrace and "
                "pays dispatch-cache misses)"))
        static = donated = None
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                static = _const_ints(kw.value)
                if static is None and isinstance(
                        kw.value, (ast.Constant, ast.Tuple, ast.List)):
                    findings.append(Finding(
                        "JL004", path, kw.value.lineno,
                        kw.value.col_offset,
                        "static_argnums must be ints — non-int static "
                        "arguments (arrays, lists) are unhashable or "
                        "retrace per value"))
            elif kw.arg == "donate_argnums":
                donated = _const_ints(kw.value)
        if static and donated and set(static) & set(donated):
            both = sorted(set(static) & set(donated))
            findings.append(Finding(
                "JL004", path, call.lineno, call.col_offset,
                f"argnums {both} are both static and donated — a "
                f"hashed-constant buffer cannot be donated"))


def _check_loop_closures(idx: _ModuleIndex, path: str, tree: ast.Module,
                         findings: List[Finding]) -> None:
    """JL005: a jitted def inside a loop body reading the loop variable
    from its closure — late binding means every def sees the LAST value,
    and each distinct value retraces."""
    for loop in ast.walk(tree):
        if not isinstance(loop, ast.For):
            continue
        loop_vars = {n.id for n in ast.walk(loop.target)
                     if isinstance(n, ast.Name)}
        for stmt in loop.body:
            for node in ast.walk(stmt):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                jitted = any(_is_jit_expr(d) for d in node.decorator_list)
                if not jitted and node in idx.trace_roots:
                    jitted = True
                if not jitted:
                    continue
                params = _params(node) | {
                    d.arg for d in node.args.defaults
                    if isinstance(d, ast.arg)}
                default_names = set()
                for d in node.args.defaults + node.args.kw_defaults:
                    if isinstance(d, ast.Name):
                        default_names.add(d.id)   # i=i rebinding is fine
                captured = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) \
                            and isinstance(sub.ctx, ast.Load) \
                            and sub.id in loop_vars \
                            and sub.id not in params:
                        captured.add(sub.id)
                captured -= {n for n in captured if n in default_names
                             and n in params}
                if captured:
                    findings.append(Finding(
                        "JL005", path, node.lineno, node.col_offset,
                        f"jitted '{node.name}' closes over loop "
                        f"variable(s) {sorted(captured)} — bind via a "
                        f"default arg or pass as input (late binding + "
                        f"retrace per value)"))


def _check_use_after_donate(idx: _ModuleIndex, path: str,
                            findings: List[Finding]) -> None:
    """JL006: positional buffers passed at a donated argnum, then read
    again later in the same function — donated device buffers are
    deallocated by XLA; the read returns garbage or raises."""
    for fn in idx.functions:
        donors: Dict[str, Tuple[int, ...]] = {}
        for node in _walk_no_nested_fns(fn):
            if isinstance(node, ast.Assign) and _is_jit_expr(node.value):
                call = _jit_call_of(node.value)
                if call is None:
                    continue
                donated: Tuple[int, ...] = ()
                for kw in call.keywords:
                    if kw.arg == "donate_argnums":
                        donated = _const_ints(kw.value) or ()
                if donated:
                    for tgt in node.targets:
                        name = _last(tgt)
                        if name:
                            donors[name] = donated
        donors.update({n: d for n, d in idx.jitted_names.items() if d})
        if not donors:
            continue
        events: List[Tuple[int, int, str, str, str]] = []
        for node in _walk_no_nested_fns(fn):
            if isinstance(node, ast.Call):
                callee = _last(node.func)
                if callee in donors:
                    for i in donors[callee]:
                        if i < len(node.args) \
                                and isinstance(node.args[i], ast.Name):
                            events.append((node.lineno, node.col_offset,
                                           "donate", node.args[i].id,
                                           callee))
            if isinstance(node, ast.Name):
                kind = "load" if isinstance(node.ctx, ast.Load) \
                    else "store"
                events.append((node.lineno, node.col_offset, kind,
                               node.id, ""))
        # within a line, the value side (loads, the donating call)
        # happens before the assignment target rebinds — `p = step(p)`
        # must clear p's donation, not trip over it
        events.sort(key=lambda e: (e[0], e[2] == "store", e[1]))
        donated_at: Dict[str, Tuple[int, str]] = {}
        for lineno, col, kind, name, callee in events:
            if kind == "donate":
                donated_at[name] = (lineno, callee)
            elif kind == "store":
                donated_at.pop(name, None)
            elif kind == "load" and name in donated_at:
                dl, callee = donated_at[name]
                if lineno > dl:   # the donating call's own args are fine
                    findings.append(Finding(
                        "JL006", path, lineno, col,
                        f"'{name}' was donated to {callee}() at line "
                        f"{dl} — its device buffer is gone; rebind the "
                        f"result instead"))
                    donated_at.pop(name, None)   # one finding per donation


def _check_wallclock(idx: _ModuleIndex, path: str, tree: ast.Module,
                     traced_spans: List[Tuple[int, int]],
                     findings: List[Finding]) -> None:
    """JL007: every time.time() call. Durations must use perf_counter
    (time.time steps under NTP slew — bench latencies went negative on
    the TPU host once); epoch timestamps in event records are the legal
    use and carry the waiver comment."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "time" \
                and _dotted(node.func.value) in idx.time_aliases:
            if any(a <= node.lineno <= b for a, b in traced_spans):
                continue                    # JL008 already reports it
            findings.append(Finding(
                "JL007", path, node.lineno, node.col_offset,
                "time.time() — use time.perf_counter() for durations; "
                "an epoch timestamp needs an explicit "
                "`# jaxlint: disable=JL007` waiver"))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _run_checks(idx: _ModuleIndex, path: str,
                tree: ast.Module) -> List[Finding]:
    findings: List[Finding] = []
    traced_spans = [(fn.lineno, max(getattr(fn, "end_lineno", fn.lineno),
                                    fn.lineno))
                    for fn in idx.trace_roots]
    _check_traced_bodies(idx, path, findings)
    _check_sync_on_jit_output(idx, path, findings)
    _check_eager_lax_control(idx, path, findings)
    _check_rng_reuse(idx, path, findings)
    _check_recompile_hazards(idx, path, tree, findings)
    _check_loop_closures(idx, path, tree, findings)
    _check_use_after_donate(idx, path, findings)
    _check_wallclock(idx, path, tree, traced_spans, findings)
    return findings


def _filter(findings: List[Finding], src: str) -> List[Finding]:
    return lintcore.filter_findings(findings, src, "jaxlint", RULES)


def lint_source(src: str, path: str = "<string>") -> List[Finding]:
    tree = ast.parse(src, filename=path)
    idx = _ModuleIndex()
    idx.visit(tree)
    idx.finalize(tree)
    return _filter(_run_checks(idx, path, tree), src)


def lint_file(path: Path) -> List[Finding]:
    src = path.read_text(encoding="utf-8")
    return lint_source(src, str(path))


# ---------------------------------------------------------------------------
# project mode: cross-module traced reachability (JL001/JL009)
# ---------------------------------------------------------------------------

class _Unit:
    __slots__ = ("path", "src", "tree", "idx", "parts")

    def __init__(self, path: str, src: str):
        self.path = path
        self.src = src
        self.tree = ast.parse(src, filename=path)
        self.idx = _ModuleIndex()
        self.idx.visit(self.tree)
        self.idx.resolve(self.tree)
        self.parts = _mod_parts(path)


def _find_unit(units: List[_Unit], modref: str,
               importer: Optional[_Unit] = None) -> Optional[_Unit]:
    """The linted module an import path refers to, by longest suffix
    match on dotted parts (absolute `pkg.sub.mod`, relative `.mod`, and
    sibling `mod` all resolve). Conservative on two fronts: ambiguity
    (two equally-specific candidates) resolves to None, and a match on
    the BARE module name alone (one component) binds only a same-
    directory sibling of the importer — `from engine import run` in an
    unrelated script must not bind to some package's engine.py and
    plant phantom trace roots there."""
    parts = tuple(p for p in modref.split(".") if p)
    if not parts:
        return None
    best: List[_Unit] = []
    best_k = 0
    for u in units:
        k = min(len(parts), len(u.parts))
        if k and parts[-k:] == u.parts[-k:]:
            if k == 1 and len(parts) == 1 and importer is not None \
                    and u.parts[:-1] != importer.parts[:-1]:
                continue
            if k > best_k:
                best, best_k = [u], k
            elif k == best_k:
                best.append(u)
    return best[0] if len(best) == 1 else None


def _cross_link(units: List[_Unit]) -> None:
    """The cross-module pass. Two propagations per importing module:

      * jitted NAMES — `from mod import fused_step` where ``fused_step``
        is a module-level jit assignment in a linted module makes the
        alias a known jitted callable here, so JL001's round-trip half
        and JL009's eager-control half see host syncs on its outputs
        across the file boundary (the replica layer calling jitted
        engine internals is exactly this shape);
      * trace ROOTS — `jax.jit(helper)` / `lax.scan(mod.fn, ...)` where
        the function is DEFINED in another linted module marks that def
        a trace root over there, so JL001/JL002/JL008 check its body as
        traced code even though the jit() lives here."""
    for u in units:
        for alias, (modref, orig) in u.idx.import_from.items():
            t = _find_unit(units, modref, importer=u)
            if t is not None and orig in t.idx.module_jitted:
                u.idx.jitted_names.setdefault(
                    alias, t.idx.module_jitted[orig])
        for node in ast.walk(u.tree):
            if not (isinstance(node, ast.Call)
                    and _last(node.func) in _TRACE_WRAPPERS):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name) \
                        and arg.id in u.idx.import_from:
                    modref, orig = u.idx.import_from[arg.id]
                    t = _find_unit(units, modref, importer=u)
                    if t is not None:
                        t.idx.mark_name(orig)
                elif isinstance(arg, ast.Attribute):
                    modref = u.idx.module_alias.get(_dotted(arg.value))
                    if modref:
                        t = _find_unit(units, modref, importer=u)
                        if t is not None:
                            t.idx.mark_name(arg.attr)


def _lint_units(units: List[_Unit]) -> List[Finding]:
    """The shared project-mode body: cross-link, propagate, check."""
    _cross_link(units)
    findings: List[Finding] = []
    for u in units:
        u.idx.propagate()
        findings.extend(_filter(_run_checks(u.idx, u.path, u.tree),
                                u.src))
    return findings


def lint_files(paths: Sequence[Path]) -> List[Finding]:
    """Project mode: lint every file with cross-module traced
    reachability (what ``main`` and the repo-clean test run). Per-file
    semantics are unchanged — the cross pass only ADDS knowledge, so a
    file clean here is clean solo plus clean against its imports. An
    unparseable file raises SyntaxError up front, before any work
    (``main`` reports parse errors per file and lints the rest)."""
    return _lint_units([_Unit(str(p), p.read_text(encoding="utf-8"))
                        for p in paths])


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="jaxlint",
        description="AST lint for this repo's TPU invariants "
                    "(docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="*", default=["dalle_pytorch_tpu"],
                    help="files or directories (default: the package)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids to run (default all)")
    ap.add_argument("--ignore", default="",
                    help="comma-separated rule ids to skip")
    ap.add_argument("--no-default-excludes", action="store_true",
                    help=f"also lint {DEFAULT_EXCLUDES} (the linter's "
                         f"own true-positive corpus)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, (slug, desc) in sorted(RULES.items()):
            print(f"{rid}  {slug:22s} {desc}")
        return 0

    select = {r.strip().upper() for r in args.select.split(",")
              if r.strip()}
    ignore = {r.strip().upper() for r in args.ignore.split(",")
              if r.strip()}
    bad = (select | ignore) - set(RULES)
    if bad:
        print(f"jaxlint: unknown rule(s): {', '.join(sorted(bad))}",
              file=sys.stderr)
        return 2

    excludes = () if args.no_default_excludes else DEFAULT_EXCLUDES
    files = iter_py_files(args.paths, excludes)
    if not files:
        print("jaxlint: no python files found", file=sys.stderr)
        return 2

    # project mode: parse everything first, then lint with cross-module
    # traced reachability (unparseable files are reported and skipped)
    units: List[_Unit] = []
    errors = 0
    for f in files:
        try:
            units.append(_Unit(str(f), f.read_text(encoding="utf-8")))
        except SyntaxError as e:
            errors += 1
            print(f"{f}:{e.lineno or 0}:0: parse error: {e.msg}",
                  file=sys.stderr)
    findings = _lint_units(units)
    if select:
        findings = [f for f in findings if f.rule in select]
    if ignore:
        findings = [f for f in findings if f.rule not in ignore]

    if args.as_json:
        print(json.dumps({"findings": [f.to_dict() for f in findings],
                          "files": len(files)}, indent=2))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"jaxlint: {n} finding{'s' if n != 1 else ''} in "
              f"{len(files)} files", file=sys.stderr)
    if errors:
        return 2
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
