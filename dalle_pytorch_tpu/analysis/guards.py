"""Runtime guards: the dynamic twin of jaxlint's static rules.

The two invariants the lint can only approximate from source — "this
region performs no implicit host-device transfer" and "this program
compiled exactly N times" — are checkable exactly at runtime, and both
already had ad-hoc open-coded versions in the tree (``bench_serve``'s
post-sweep ``decode_compiles != 1`` check, ``test_serve``'s
``engine.decode_traces == 1`` asserts). These context managers are the
one shared implementation: benches record violations, tests fail on
them, and any future kernel test gets the same contract for one line.

  * ``no_transfers()`` — ``jax.transfer_guard("disallow")``: implicit
    transfers raise; EXPLICIT ``jax.device_put``/``jax.device_get``
    still pass. That split is the point: a steady-state loop wrapped in
    ``no_transfers()`` documents every intentional round-trip as an
    explicit call at the transfer site (serve/engine.py's per-step token
    fetch is the canonical allowance — ROADMAP "keep cur_tok/pos on
    device"). Note the guard bites hardest on a real accelerator; the
    CPU backend shares one memory space, so some copies never register.
  * ``compile_count(counter, expect=N)`` — asserts a trace/compile
    counter advanced by exactly N inside the block.
  * ``counting(fn)`` — wrap a function so jit-tracing it is countable:
    ``fn2 = counting(fn); jitted = jax.jit(fn2)``; ``fn2.traces``.
  * ``LockOrderRecorder`` / ``TrackedLock`` / ``instrument_locks`` —
    racelint's dynamic twin: swap an object's ``threading.Lock`` attrs
    for wrappers that record the real acquisition order at test time.
    An ACQUISITION-ORDER INVERSION (this thread acquires B→A after
    A→B was ever observed) raises immediately — the single-threaded
    witness of a deadlock that needs two threads to actually fire —
    and ``assert_consistent_with(racelint.lock_order_edges(...))``
    asserts every runtime edge was predicted by the static graph, so
    the static analysis is validated by the test suite, not trusted.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import (Callable, Dict, Iterable, Iterator, List, Optional,
                    Set, Tuple)


class CompileCountError(AssertionError):
    """A guarded region compiled a different number of programs than its
    contract allows. Carries ``expected``/``actual`` for structured
    reporting (bench records them instead of raising)."""

    def __init__(self, label: str, expected, actual: int):
        super().__init__(
            f"{label}: expected {expected} compile(s), observed {actual}")
        self.label = label
        self.expected = expected
        self.actual = actual


class CompileCountGuard:
    """State handed back by ``compile_count`` — ``delta()`` mid-block,
    ``error`` after a non-raising exit."""

    def __init__(self, counter: Callable[[], int], label: str):
        self._counter = counter
        self.label = label
        self.start = counter()
        self.error: Optional[CompileCountError] = None

    def delta(self) -> int:
        return self._counter() - self.start


@contextlib.contextmanager
def compile_count(counter: Callable[[], int], *, expect: Optional[int]
                  = None, at_most: Optional[int] = None,
                  label: str = "compile_count",
                  raise_on_violation: bool = True
                  ) -> Iterator[CompileCountGuard]:
    """Assert that ``counter`` (a zero-arg callable returning a
    monotonically increasing trace/compile count — e.g.
    ``lambda: engine.decode_traces``) advances by exactly ``expect``
    (or by at most ``at_most``) across the block.

    ``raise_on_violation=False`` records the violation on the yielded
    guard's ``.error`` instead of raising — bench_serve's mode, where a
    recompile must land in the JSON record, not kill the sweep. A
    violation is only checked on clean exit: if the body itself raised,
    that error wins."""
    if (expect is None) == (at_most is None):
        raise ValueError("pass exactly one of expect= / at_most=")
    guard = CompileCountGuard(counter, label)
    yield guard
    actual = guard.delta()
    bad = actual != expect if expect is not None else actual > at_most
    if bad:
        want = expect if expect is not None else f"<= {at_most}"
        guard.error = CompileCountError(label, want, actual)
        if raise_on_violation:
            raise guard.error


@contextlib.contextmanager
def no_transfers(level: str = "disallow") -> Iterator[None]:
    """Forbid implicit host-device transfers inside the block
    (``jax.transfer_guard``). Explicit ``jax.device_put`` /
    ``jax.device_get`` calls still pass under the default ``disallow``
    level — intentional round-trips must be spelled at the site they
    happen. ``level="log"`` audits instead of failing;
    ``"disallow_explicit"`` forbids even the explicit escape hatch."""
    import jax
    with jax.transfer_guard(level):
        yield


def counting(fn: Callable) -> Callable:
    """Wrap ``fn`` so each trace (python execution) bumps
    ``wrapped.traces`` — the counter jit re-runs only when it compiles.
    Pair with ``compile_count``:

        traced = counting(step_fn)
        jitted = jax.jit(traced)
        with compile_count(lambda: traced.traces, expect=1):
            for batch in data:
                jitted(params, batch)
    """
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        wrapped.traces += 1
        return fn(*args, **kwargs)

    wrapped.traces = 0
    return wrapped


# ---------------------------------------------------------------------------
# Lock-order sanitizer — racelint RL002's runtime counterpart
# ---------------------------------------------------------------------------

class LockOrderError(AssertionError):
    """An acquisition-order inversion: this thread acquired ``second``
    while holding ``first``, but the opposite order ``second -> first``
    was already observed (possibly transitively). Two threads running
    those two paths concurrently can deadlock — the recorder surfaces
    the hazard from a single-threaded witness, no actual deadlock
    required."""

    def __init__(self, first: str, second: str,
                 chain: List[str]):
        path = " -> ".join(chain)
        super().__init__(
            f"lock-order inversion: acquiring {second!r} while holding "
            f"{first!r}, but the order {path} was already observed")
        self.first = first
        self.second = second
        self.chain = chain


class LockOrderRecorder:
    """Records the directed graph of observed lock-acquisition orders.

    Each thread keeps its own held-stack (thread-local); every acquire
    of ``b`` while ``a`` is held records the edge ``a -> b``. Before
    recording, the recorder checks whether ``b`` can already reach ``a``
    through observed edges — if so, the program has demonstrated both
    orders and ``LockOrderError`` is raised at the inverting acquire.

    Lock NAMES are racelint's lock ids (``ClassName.attr``), so edges
    here compare directly against ``racelint.lock_order_edges(paths)``:
    ``assert_consistent_with(static_edges)`` asserts every edge the
    program actually exercised was predicted by the static graph.
    Same-name edges are skipped — distinct instances of the same class
    share a name, and ordering within one id is an instance-level
    question the static graph deliberately doesn't model either.
    """

    def __init__(self) -> None:
        self._edges: Dict[str, Set[str]] = {}
        self._sites: Dict[Tuple[str, str], str] = {}
        self._tls = threading.local()
        self._graph_lock = threading.Lock()

    # -- per-thread held stack ------------------------------------------
    def _held(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _find_chain(self, src: str, dst: str) -> Optional[List[str]]:
        """A path src -> ... -> dst over observed edges, or None."""
        parents: Dict[str, str] = {}
        frontier = [src]
        seen = {src}
        while frontier:
            node = frontier.pop()
            for nxt in self._edges.get(node, ()):
                if nxt in seen:
                    continue
                parents[nxt] = node
                if nxt == dst:
                    chain = [dst]
                    while chain[-1] != src:
                        chain.append(parents[chain[-1]])
                    return chain[::-1]
                seen.add(nxt)
                frontier.append(nxt)
        return None

    def on_acquire(self, name: str) -> None:
        held = self._held()
        with self._graph_lock:
            for h in held:
                if h == name:
                    continue
                chain = self._find_chain(name, h)
                if chain is not None:
                    raise LockOrderError(h, name, chain)
                self._edges.setdefault(h, set()).add(name)
                self._sites.setdefault((h, name), threading.current_thread().name)
        held.append(name)

    def on_release(self, name: str) -> None:
        held = self._held()
        # release in LIFO discipline is the common case, but timed/early
        # releases may pop out of order — remove the most recent match
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    # -- inspection -----------------------------------------------------
    def edges(self) -> Set[Tuple[str, str]]:
        with self._graph_lock:
            return {(a, b) for a, succ in self._edges.items() for b in succ}

    def assert_consistent_with(
            self, static_edges: Iterable[Tuple[str, str]]) -> None:
        """Every observed runtime edge must appear in the static graph.

        ``static_edges`` is ``racelint.lock_order_edges(paths)`` — the
        set of held->acquired pairs the analyzer derived from source. A
        runtime edge the static pass missed means the call-graph
        resolution has a hole worth fixing (or a lock was taken through
        a path the analyzer cannot see, e.g. getattr indirection)."""
        static = set(static_edges)
        missing = sorted(e for e in self.edges() if e not in static)
        if missing:
            rendered = ", ".join(f"{a} -> {b}" for a, b in missing)
            raise AssertionError(
                f"runtime lock order not predicted by static graph: "
                f"{rendered}")


class TrackedLock:
    """A drop-in ``threading.Lock``/``RLock`` wrapper that reports
    acquisition order to a :class:`LockOrderRecorder`. Passthrough for
    the lock API the serve tier uses: ``with``, ``acquire(blocking=,
    timeout=)``, ``release``, ``locked``."""

    def __init__(self, name: str, recorder: LockOrderRecorder,
                 lock=None):
        self.name = name
        self._recorder = recorder
        self._lock = lock if lock is not None else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._recorder.on_acquire(self.name)
        return ok

    def release(self) -> None:
        self._lock.release()
        self._recorder.on_release(self.name)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))


def instrument_locks(obj, recorder: LockOrderRecorder,
                     cls_name: Optional[str] = None) -> List[str]:
    """Replace every ``threading.Lock``/``RLock`` attribute in
    ``vars(obj)`` with a :class:`TrackedLock` named with racelint's lock
    id (``ClassName.attr``). Returns the names installed.

    ``cls_name`` overrides the class part — needed when the lock is
    defined by a base class (racelint names locks after the DEFINING
    class, e.g. ``RequestQueue._lock`` even on a ``WeightedFairQueue``
    instance)."""
    base = cls_name or type(obj).__name__
    installed = []
    try:
        attrs = list(vars(obj))
    except TypeError:       # __slots__ classes (obs.Trace) have no __dict__
        attrs = [a for klass in type(obj).__mro__
                 for a in getattr(klass, "__slots__", ())]
    for attr in attrs:
        val = getattr(obj, attr, None)
        if isinstance(val, _LOCK_TYPES):
            name = f"{base}.{attr}"
            tracked = TrackedLock(name, recorder, lock=val)
            setattr(obj, attr, tracked)
            installed.append(name)
    return installed
