"""Runtime guards: the dynamic twin of jaxlint's static rules.

The two invariants the lint can only approximate from source — "this
region performs no implicit host-device transfer" and "this program
compiled exactly N times" — are checkable exactly at runtime, and both
already had ad-hoc open-coded versions in the tree (``bench_serve``'s
post-sweep ``decode_compiles != 1`` check, ``test_serve``'s
``engine.decode_traces == 1`` asserts). These context managers are the
one shared implementation: benches record violations, tests fail on
them, and any future kernel test gets the same contract for one line.

  * ``no_transfers()`` — ``jax.transfer_guard("disallow")``: implicit
    transfers raise; EXPLICIT ``jax.device_put``/``jax.device_get``
    still pass. That split is the point: a steady-state loop wrapped in
    ``no_transfers()`` documents every intentional round-trip as an
    explicit call at the transfer site (serve/engine.py's per-step token
    fetch is the canonical allowance — ROADMAP "keep cur_tok/pos on
    device"). Note the guard bites hardest on a real accelerator; the
    CPU backend shares one memory space, so some copies never register.
  * ``compile_count(counter, expect=N)`` — asserts a trace/compile
    counter advanced by exactly N inside the block.
  * ``counting(fn)`` — wrap a function so jit-tracing it is countable:
    ``fn2 = counting(fn); jitted = jax.jit(fn2)``; ``fn2.traces``.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Callable, Iterator, Optional


class CompileCountError(AssertionError):
    """A guarded region compiled a different number of programs than its
    contract allows. Carries ``expected``/``actual`` for structured
    reporting (bench records them instead of raising)."""

    def __init__(self, label: str, expected, actual: int):
        super().__init__(
            f"{label}: expected {expected} compile(s), observed {actual}")
        self.label = label
        self.expected = expected
        self.actual = actual


class CompileCountGuard:
    """State handed back by ``compile_count`` — ``delta()`` mid-block,
    ``error`` after a non-raising exit."""

    def __init__(self, counter: Callable[[], int], label: str):
        self._counter = counter
        self.label = label
        self.start = counter()
        self.error: Optional[CompileCountError] = None

    def delta(self) -> int:
        return self._counter() - self.start


@contextlib.contextmanager
def compile_count(counter: Callable[[], int], *, expect: Optional[int]
                  = None, at_most: Optional[int] = None,
                  label: str = "compile_count",
                  raise_on_violation: bool = True
                  ) -> Iterator[CompileCountGuard]:
    """Assert that ``counter`` (a zero-arg callable returning a
    monotonically increasing trace/compile count — e.g.
    ``lambda: engine.decode_traces``) advances by exactly ``expect``
    (or by at most ``at_most``) across the block.

    ``raise_on_violation=False`` records the violation on the yielded
    guard's ``.error`` instead of raising — bench_serve's mode, where a
    recompile must land in the JSON record, not kill the sweep. A
    violation is only checked on clean exit: if the body itself raised,
    that error wins."""
    if (expect is None) == (at_most is None):
        raise ValueError("pass exactly one of expect= / at_most=")
    guard = CompileCountGuard(counter, label)
    yield guard
    actual = guard.delta()
    bad = actual != expect if expect is not None else actual > at_most
    if bad:
        want = expect if expect is not None else f"<= {at_most}"
        guard.error = CompileCountError(label, want, actual)
        if raise_on_violation:
            raise guard.error


@contextlib.contextmanager
def no_transfers(level: str = "disallow") -> Iterator[None]:
    """Forbid implicit host-device transfers inside the block
    (``jax.transfer_guard``). Explicit ``jax.device_put`` /
    ``jax.device_get`` calls still pass under the default ``disallow``
    level — intentional round-trips must be spelled at the site they
    happen. ``level="log"`` audits instead of failing;
    ``"disallow_explicit"`` forbids even the explicit escape hatch."""
    import jax
    with jax.transfer_guard(level):
        yield


def counting(fn: Callable) -> Callable:
    """Wrap ``fn`` so each trace (python execution) bumps
    ``wrapped.traces`` — the counter jit re-runs only when it compiles.
    Pair with ``compile_count``:

        traced = counting(step_fn)
        jitted = jax.jit(traced)
        with compile_count(lambda: traced.traces, expect=1):
            for batch in data:
                jitted(params, batch)
    """
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        wrapped.traces += 1
        return fn(*args, **kwargs)

    wrapped.traces = 0
    return wrapped
