"""Static analysis + runtime guards for the repo's TPU invariants.

``jaxlint`` is the AST pass (``python -m
dalle_pytorch_tpu.analysis.jaxlint`` or the ``jaxlint`` console script);
``guards`` is its runtime twin (``no_transfers``, ``compile_count``).
Rule catalog and rationale: docs/STATIC_ANALYSIS.md.
"""

from dalle_pytorch_tpu.analysis.guards import (CompileCountError,  # noqa: F401
                                               CompileCountGuard,
                                               compile_count, counting,
                                               no_transfers)

_JAXLINT_NAMES = ("RULES", "Finding", "lint_file", "lint_source")


def __getattr__(name):
    # lazy: `python -m ...analysis.jaxlint` warns if the package
    # __init__ already imported the submodule before runpy runs it
    if name in _JAXLINT_NAMES:
        from dalle_pytorch_tpu.analysis import jaxlint
        return getattr(jaxlint, name)
    raise AttributeError(name)
